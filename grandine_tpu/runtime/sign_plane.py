"""Device-resident signing plane: the sign-side mirror of the verify
scheduler (runtime/verify_scheduler.py).

Every validator duty signature funnels into one multi-lane batch-signing
plane: `submit` coalesces (pubkey, signing_root, duty_kind) requests
from all validators under a deadline-or-max_batch policy onto
pow-2-bucketed `batch_sign` dispatches (tpu/bls.py — one G2 GLV
dual-ladder pass for the whole batch), with ticket futures handed back
to callers and pipeline_depth worker threads overlapping host prep with
device execution, two deep.

Two properties the verify side never needed:

  release gate — a faulty device must never EMIT a bad signature (a
      wrong block signature is a missed proposal; a wrong attestation
      loses rewards network-wide for the operator). Before any caller
      sees a device-produced batch, the plane batch-*verifies* it
      against the registered public keys in one RLC pass
      (`SigningDescriptor.release_verify`). Gate failure re-signs that
      batch on the host anchor and files a `verdict` fault with the
      health supervisor — zero bad signatures are ever released.

  slashing interlock — a per-pubkey monotonic (duty_kind, slot/epoch)
      low-watermark (`SignInterlock`, persisted via storage.Database
      like the reputation table) refuses a regressing block or
      attestation signing request BEFORE it reaches a kernel, counted
      in `sign_refused_total{reason}`.

Degradation: a breaker-open device, a watchdog-timed-out dispatch, or a
failed release gate all fall back to the host `sk.sign` anchor
(byte-identical by contract), so a device fault never misses a duty
deadline. Scheme resolution goes through the tpu/schemes.py table only
(`Scheme.signing` — the sign-side descriptor), never a kernel import.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

from grandine_tpu.runtime import flight as _flight
from grandine_tpu.runtime import health as _health
from grandine_tpu.runtime.thread_pool import Priority
from grandine_tpu.tpu import schemes as _schemes

#: slashing-interlock refusal reasons — the CLOSED label set of
#: `sign_refused_total{reason}` (metrics-cardinality lint)
REFUSAL_REASONS = ("block_regression", "attestation_regression")

#: duty kinds the interlock watermark applies to → refusal reason.
#: Everything else (randao, sync messages, selection proofs, aggregate
#: proofs) is not slashable and passes through uncounted.
SLASHABLE_KINDS = {
    "block": "block_regression",
    "attestation": "attestation_regression",
}


class SignRefused(Exception):
    """The slashing interlock refused this request (regressing block
    slot / attestation target epoch for the pubkey's watermark)."""

    def __init__(self, reason: str, duty_kind: str, index: int) -> None:
        super().__init__(
            f"signing refused ({reason}): {duty_kind} at {index} does "
            f"not advance the pubkey's low-watermark"
        )
        self.reason = reason
        self.duty_kind = duty_kind
        self.index = index


class SignInterlock:
    """Minimal slashing-protection interlock in front of the plane: a
    per-pubkey monotonic (duty_kind, slot/epoch) low-watermark. A
    request whose index does not strictly advance the watermark is
    refused — conservatively including re-signing the SAME slot/epoch,
    which the full SlashingProtection store would allow for identical
    data; the plane's interlock is a last-line device-side guard, not a
    replacement for validator/slashing_protection.py.

    Watermarks persist across restarts via `storage.Database` (prefix
    ``sgn:w:``, 8-byte little-endian index per (duty_kind, pubkey) key,
    the reputation-table idiom), with a write-through in-memory mirror
    so the hot path pays one dict probe. All state is guarded by
    `_lock` (submit arrives from every validator thread at once)."""

    _PREFIX = b"sgn:w:"

    def __init__(self, db=None) -> None:
        self._db = db
        self._lock = threading.Lock()
        self._marks: "dict[tuple[str, bytes], int]" = {}

    def _key(self, duty_kind: str, pubkey: bytes) -> bytes:
        return self._PREFIX + duty_kind.encode() + b":" + pubkey

    def check_and_advance(
        self, pubkey: bytes, duty_kind: str, index: "Optional[int]"
    ) -> "Optional[str]":
        """None when the request is allowed (watermark advanced and
        persisted); the refusal reason string otherwise. Non-slashable
        duty kinds and index-less requests always pass."""
        reason = SLASHABLE_KINDS.get(duty_kind)
        if reason is None or index is None:
            return None
        index = int(index)
        with self._lock:
            mark = self._marks.get((duty_kind, pubkey))
            if mark is None and self._db is not None:
                raw = self._db.get(self._key(duty_kind, pubkey))
                if raw is not None:
                    mark = int.from_bytes(raw, "little")
            if mark is not None and index <= mark:
                return reason
            self._marks[(duty_kind, pubkey)] = index
            if self._db is not None:
                self._db.put(
                    self._key(duty_kind, pubkey), index.to_bytes(8, "little")
                )
        return None

    def watermark(
        self, pubkey: bytes, duty_kind: str
    ) -> "Optional[int]":
        with self._lock:
            mark = self._marks.get((duty_kind, pubkey))
            if mark is None and self._db is not None:
                raw = self._db.get(self._key(duty_kind, pubkey))
                if raw is not None:
                    mark = int.from_bytes(raw, "little")
            return mark


class SignLaneConfig:
    """One signing lane's flush/backpressure policy (the sign-side
    LaneConfig)."""

    __slots__ = ("name", "priority", "max_batch", "max_wait_s",
                 "max_queue", "shed", "scheme", "label")

    def __init__(self, name: str, priority: Priority, max_batch: int,
                 max_wait_s: float, max_queue: int, shed: bool,
                 scheme: str = "bls") -> None:
        self.name = name
        #: metric label — prefixed so sign lanes stay distinguishable
        #: from verify lanes inside shared families (one drop family:
        #: verify_lane_dropped_total)
        self.label = "sign_" + name
        self.priority = priority
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        #: LOW lanes shed oldest-first at max_queue (a dropped ticket
        #: degrades the caller to its host path — the duty is never
        #: lost); HIGH lanes block the submitter instead
        self.shed = bool(shed)
        self.scheme = str(scheme)


#: the signing lane table (README "Device signing plane" mirrors this).
#: Block/randao flush almost immediately (a proposal is one signature on
#: a hard deadline); attestation/sync-message lanes coalesce the
#: per-slot many-validator burst into big buckets; max_batch values sit
#: on the warmed `sign` ladder so steady state never compiles.
DEFAULT_SIGN_LANES = (
    SignLaneConfig("block", Priority.HIGH, 4, 0.001, 256, shed=False),
    SignLaneConfig("randao", Priority.HIGH, 8, 0.001, 256, shed=False),
    SignLaneConfig("attestation", Priority.HIGH, 512, 0.020, 16384,
                   shed=False),
    SignLaneConfig("sync_message", Priority.HIGH, 512, 0.020, 16384,
                   shed=False),
    SignLaneConfig("aggregate", Priority.HIGH, 64, 0.010, 4096,
                   shed=False),
    SignLaneConfig("sync_contribution", Priority.HIGH, 64, 0.010, 4096,
                   shed=False),
    SignLaneConfig("selection_proof", Priority.LOW, 64, 0.010, 4096,
                   shed=True),
    SignLaneConfig("other", Priority.LOW, 64, 0.025, 4096, shed=True),
)


class SignTicket:
    """Future handed back by `submit`: resolves to the wire-encoded
    signature bytes, or `dropped=True` when the request was shed at
    shutdown/overload (the caller degrades to its own host path)."""

    __slots__ = ("lane", "enqueued_at", "settled_at", "dropped",
                 "deadline", "_sig", "_event", "_callbacks", "_lock")

    def __init__(self, lane: str,
                 deadline: "Optional[float]" = None) -> None:
        self.lane = lane
        #: absolute monotonic deadline (the duty's proposal/attestation
        #: window, stamped at submit): past it the job skips device
        #: batching and degrades straight to the host anchor — the duty
        #: is still produced, the device dispatch is not wasted
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.settled_at: "Optional[float]" = None
        self.dropped = False
        # lint: atomic=_sig: _resolve writes it under _lock before
        # _event.set(); readers gate on the Event — happens-before edge
        self._sig: "Optional[bytes]" = None
        self._event = threading.Event()
        self._callbacks: "list[Callable]" = []
        self._lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: "Optional[float]" = None) -> bytes:
        if not self._event.wait(timeout):
            raise TimeoutError(f"{self.lane} sign ticket not settled")
        # Event.wait() is the happens-before edge for the _sig write
        if self._sig is None:
            raise RuntimeError(
                f"{self.lane} sign request dropped at shutdown"
            )
        return self._sig

    def add_callback(self, fn: "Callable[[SignTicket], None]") -> None:
        """Run fn(ticket) once settled (immediately if already done)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self, sig: "Optional[bytes]",
                 dropped: bool = False) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._sig = sig
            self.dropped = dropped
            self.settled_at = time.monotonic()
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for fn in callbacks:
            try:
                fn(self)
            except Exception:
                pass  # a consumer's callback must not break settling


class _SignJob:
    __slots__ = ("signing_root", "secret_key", "public_key", "duty_kind",
                 "ticket")

    def __init__(self, signing_root: bytes, secret_key, public_key,
                 duty_kind: str, ticket: SignTicket) -> None:
        self.signing_root = bytes(signing_root)
        self.secret_key = secret_key
        self.public_key = public_key
        self.duty_kind = duty_kind
        self.ticket = ticket


class SigningPlane:
    """submit → coalesce → device batch_sign → release gate → release.

    One dispatcher thread forms batches (HIGH lanes flush first among
    due lanes); `pipeline_depth` worker threads run the blocking device
    dispatch + release gate so two batches overlap (host prep of one
    against device execute of the other). The breaker
    (`BackendHealthSupervisor`) gates device use exactly as on the
    verify side; every degradation lands on the host `sk.sign` anchor
    so a duty deadline is never missed."""

    def __init__(
        self,
        backend=None,
        lanes: "Optional[Sequence[SignLaneConfig]]" = None,
        use_device: bool = True,
        pipeline_depth: int = 2,
        metrics=None,
        health: "Optional[_health.BackendHealthSupervisor]" = None,
        settle_timeout_s: float = 5.0,
        flight: "Optional[_flight.FlightRecorder]" = None,
        interlock: "Optional[SignInterlock]" = None,
        db=None,
        release_gate: bool = True,
        deadline_margin_s: float = 0.05,
    ) -> None:
        self.metrics = metrics
        self.use_device = bool(use_device)
        #: release-gate toggle — ONLY for benches measuring the gate's
        #: overhead; production keeps it on (the plane's core promise)
        self.release_gate = bool(release_gate)
        self.lanes = {
            lane.name: lane
            for lane in (lanes if lanes is not None else DEFAULT_SIGN_LANES)
        }
        self.health = (
            health if health is not None
            else _health.BackendHealthSupervisor(
                metrics=metrics, settle_timeout_s=settle_timeout_s,
                name="sign-device",
            )
        )
        self.flight = (
            flight if flight is not None
            else _flight.FlightRecorder(metrics=metrics)
        )
        self.interlock = (
            interlock if interlock is not None else SignInterlock(db=db)
        )
        #: safety margin subtracted from a ticket's absolute deadline
        #: when computing its effective flush due-time — a near-deadline
        #: head flushes early enough to dispatch AND settle in-window
        self.deadline_margin_s = float(deadline_margin_s)
        self._injected_backend = backend
        self._backend_lock = threading.Lock()
        self._backends: "dict[str, object]" = {}
        #: pubkey-by-scalar cache for submitters that pass no
        #: public_key: deriving pk = [sk]g1 on the host costs a scalar
        #: mul, paid once per key per process. In-process only — the
        #: keys already live in this address space. All access stays
        #: inside _pk_lock.
        self._pk_lock = threading.Lock()
        self._pk_cache: "dict[int, object]" = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: "dict[str, deque]" = {
            name: deque() for name in self.lanes
        }
        self._pending = 0
        self._stop = False
        self._stats_lock = threading.Lock()
        self._stats = {
            name: {
                "submitted": 0, "batches": 0, "signed": 0, "refused": 0,
                "dropped": 0, "device_batches": 0, "degraded": 0,
                "host_batches": 0, "breaker_skips": 0, "device_faults": 0,
                "gate_failures": 0, "max_batch_items": 0, "expired": 0,
            }
            for name in self.lanes
        }
        self._inflight: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(pipeline_depth))
        )
        # threads are constructed before ANY starts so a worker can
        # never observe a half-built plane (init-escape lint)
        self._workers = [
            threading.Thread(
                target=self._worker_loop, name=f"sign-plane-worker-{i}",
                daemon=True,
            )
            for i in range(max(1, int(pipeline_depth)))
        ]
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="sign-plane-dispatch",
            daemon=True,
        )
        self._dispatcher.start()
        for t in self._workers:
            t.start()

    # ------------------------------------------------------------ submit

    def _lane_for(self, duty_kind: str) -> SignLaneConfig:
        lane = self.lanes.get(duty_kind)
        if lane is None:
            lane = self.lanes.get("other")
        if lane is None:  # custom lane tables without a catch-all
            lane = next(iter(self.lanes.values()))
        return lane

    def _public_key_for(self, secret_key, public_key):
        if public_key is not None:
            if isinstance(public_key, (bytes, bytearray)):
                from grandine_tpu.crypto import bls as A

                return A.PublicKey.from_bytes(bytes(public_key))
            return public_key
        scalar = secret_key.scalar
        with self._pk_lock:
            pk = self._pk_cache.get(scalar)
            if pk is None:
                pk = secret_key.public_key()
                if len(self._pk_cache) >= 1 << 17:
                    self._pk_cache.clear()  # bounded; refill is cheap
                self._pk_cache[scalar] = pk
            return pk

    def submit(
        self,
        signing_root: bytes,
        secret_key,
        duty_kind: str = "other",
        public_key=None,
        index: "Optional[int]" = None,
        deadline: "Optional[float]" = None,
        deadline_s: "Optional[float]" = None,
    ) -> SignTicket:
        """Enqueue one signing request; returns a SignTicket future.

        `index` is the duty's slot (block) or target epoch
        (attestation): the slashing interlock refuses a request that
        does not strictly advance the pubkey's watermark, raising
        SignRefused BEFORE anything reaches a kernel.

        `deadline` (absolute monotonic) or `deadline_s` (relative to
        now) stamps the duty's window — the slot's proposal window for
        a block, the attestation broadcast window for attestations. A
        request overtaken by its window degrades to the host anchor
        (the duty is STILL produced) instead of riding a device batch
        it can no longer benefit from."""
        public_key = self._public_key_for(secret_key, public_key)
        reason = self.interlock.check_and_advance(
            public_key.to_bytes(), duty_kind, index
        )
        lane = self._lane_for(duty_kind)
        if reason is not None:
            if self.metrics is not None:
                self.metrics.sign_refused.inc(reason)
            with self._stats_lock:
                self._stats[lane.name]["refused"] += 1
            raise SignRefused(reason, duty_kind, index)
        if deadline is None and deadline_s is not None:
            deadline = time.monotonic() + float(deadline_s)
        ticket = SignTicket(lane.name, deadline=deadline)
        job = _SignJob(signing_root, secret_key, public_key, duty_kind,
                       ticket)
        shed_job = None
        with self._lock:
            if self._stop:
                ticket._resolve(None, dropped=True)
                return ticket
            q = self._queues[lane.name]
            if len(q) >= lane.max_queue:
                if lane.shed:
                    shed_job = q.popleft()
                else:
                    # HIGH lane backpressure: bounded producer, never
                    # a dropped duty
                    while len(q) >= lane.max_queue and not self._stop:
                        self._cond.wait(0.005)
                    if self._stop:
                        ticket._resolve(None, dropped=True)
                        return ticket
            q.append(job)
            self._pending += 1
            depth = len(q)
            self._cond.notify_all()
        if shed_job is not None:
            shed_job.ticket._resolve(None, dropped=True)
            self._count_shed(lane, 1)
            with self._lock:
                self._pending -= 1
        with self._stats_lock:
            self._stats[lane.name]["submitted"] += 1
        if self.metrics is not None:
            self.metrics.sign_lane_depth.labels(lane.label).set(depth)
        return ticket

    def _count_shed(self, lane: SignLaneConfig, n: int) -> None:
        """Every shed/drop funnels through here into the ONE drop
        family (verify_lane_dropped_total — drop-counter-reuse lint),
        with the sign-lane label keeping the signal separable."""
        with self._stats_lock:
            self._stats[lane.name]["dropped"] += n
        if self.metrics is not None:
            for _ in range(n):
                self.metrics.verify_lane_dropped.labels(lane.label).inc()

    def sign_many(
        self,
        requests: "Sequence[tuple]",
        duty_kind: str = "other",
        timeout: "Optional[float]" = 30.0,
    ) -> "list[bytes]":
        """Convenience batch submit-and-wait: requests are
        (signing_root, secret_key) pairs; returns wire signatures in
        order. One plane flush covers the whole slot's duty burst."""
        tickets = [
            self.submit(root, sk, duty_kind=duty_kind)
            for root, sk in requests
        ]
        return [t.result(timeout) for t in tickets]

    # --------------------------------------------------------- scheduling

    def _effective_due(self, ticket: SignTicket,
                       lane: SignLaneConfig) -> float:
        """When a lane's head must flush: the lane's max_wait, or —
        when the ticket carries a duty-window deadline — early enough
        (deadline minus the dispatch/settle margin) that a near-
        deadline head preempts coalescing."""
        due = ticket.enqueued_at + lane.max_wait_s
        if ticket.deadline is not None:
            due = min(due, ticket.deadline - self.deadline_margin_s)
        return due

    def _pick_lane(self) -> "Optional[SignLaneConfig]":
        """Called under _lock: a lane that is full or overdue — HIGH
        priority first, then the most-overdue head."""
        now = time.monotonic()
        best = None
        best_key = None
        for lane in self.lanes.values():
            q = self._queues[lane.name]
            if not q:
                continue
            overdue = now - self._effective_due(q[0].ticket, lane)
            if len(q) >= lane.max_batch or overdue >= 0.0:
                key = (lane.priority != Priority.HIGH, -overdue)
                if best is None or key < best_key:
                    best, best_key = lane, key
        return best

    def _nearest_deadline(self) -> "Optional[float]":
        """Called under _lock: seconds until the next lane flush is due,
        or None when every queue is empty."""
        now = time.monotonic()
        nearest = None
        for lane in self.lanes.values():
            q = self._queues[lane.name]
            if not q:
                continue
            due = self._effective_due(q[0].ticket, lane) - now
            if nearest is None or due < nearest:
                nearest = due
        return nearest

    def _pop_batch(self, lane: SignLaneConfig) -> "list[_SignJob]":
        """Called under _lock."""
        q = self._queues[lane.name]
        out = []
        while q and len(out) < lane.max_batch:
            out.append(q.popleft())
        return out

    def _count_daemon_failure(self, thread: str) -> None:
        if self.metrics is not None:
            self.metrics.daemon_loop_failures.inc(thread)

    def _dispatch_loop(self) -> None:
        """Dispatcher daemon: coalesce queues into batches and hand them
        to the worker pool. Crash containment per iteration — one bad
        batch must not kill the plane."""
        while True:
            try:
                if self._dispatch_once():
                    return
            except Exception:
                self._count_daemon_failure("sign-plane-dispatch")
                time.sleep(0.005)  # containment: never spin hot

    def _dispatch_once(self) -> bool:
        """One dispatcher iteration; True means stop-drain finished."""
        to_drop = None
        batch = None
        lane = None
        with self._lock:
            # _stop is re-read under the SAME lock that guarded the
            # queue reads: a stop() landing after release cannot be
            # half-observed
            if self._stop:
                to_drop = [
                    job for q in self._queues.values() for job in q
                ]
                for q in self._queues.values():
                    q.clear()
            else:
                lane = self._pick_lane()
                if lane is None:
                    due = self._nearest_deadline()
                    self._cond.wait(
                        0.05 if due is None else max(0.0005, due)
                    )
                    return False
                batch = self._pop_batch(lane)
        if to_drop is not None:
            for job in to_drop:
                job.ticket._resolve(None, dropped=True)
            if to_drop:
                by_lane: "dict[str, int]" = {}
                for job in to_drop:
                    by_lane[job.ticket.lane] = (
                        by_lane.get(job.ticket.lane, 0) + 1
                    )
                for name, n in by_lane.items():
                    self._count_shed(self.lanes[name], n)
                with self._lock:
                    self._pending -= len(to_drop)
                    self._cond.notify_all()
            return True
        if batch:
            if self.metrics is not None:
                self.metrics.sign_pipeline_depth.inc()
            self._inflight.put((lane, batch))
        return False

    def _worker_loop(self) -> None:
        """Worker daemon: full batch life (device sign → release gate →
        resolve), one batch at a time; pipeline_depth workers give the
        two-deep overlap. Crash containment: an unexpected error
        degrades the batch to the host anchor rather than dropping it."""
        while True:
            handoff = self._inflight.get()
            if handoff is None:
                return
            lane, jobs = handoff
            try:
                self._process_batch(lane, jobs)
            except Exception:
                try:
                    self._resolve_on_host(lane, jobs, note_fault=True)
                except Exception:
                    for job in jobs:  # last resort: never hang a caller
                        job.ticket._resolve(None, dropped=True)
            finally:
                if self.metrics is not None:
                    self.metrics.sign_pipeline_depth.dec()
                with self._lock:
                    self._pending -= len(jobs)
                    self._cond.notify_all()

    # ---------------------------------------------------------- batch life

    def _backend_for(self, lane: SignLaneConfig):
        """Lazily build (once) the scheme backend; table-resolved only.
        Double-checked under _backend_lock like CachedPublicKey — two
        workers must not race a double build."""
        if self._injected_backend is not None:
            return self._injected_backend
        with self._backend_lock:
            backend = self._backends.get(lane.scheme)
            if backend is None:
                backend = _schemes.get(lane.scheme).make_backend(
                    metrics=self.metrics, lane=f"sign:{lane.name}"
                )
                self._backends[lane.scheme] = backend
            return backend

    def _host_sign_all(self, signing, jobs: "list[_SignJob]"
                       ) -> "list[bytes]":
        return [
            signing.host_sign(job.signing_root, job.secret_key)
            for job in jobs
        ]

    def _shed_expired(self, lane: SignLaneConfig, signing,
                      jobs: "list[_SignJob]") -> None:
        """Deadline-budget expiry on the sign side: the duty's window
        closed while the job sat in the lane, so it skips the device
        batch entirely — but the duty is STILL produced, on the host
        anchor (a late signature beats a missed one). The shed lands on
        the flight timeline with cause="expired"."""
        with self._stats_lock:
            self._stats[lane.name]["expired"] += len(jobs)
        if self.metrics is not None:
            for _ in jobs:
                self.metrics.verify_expired.inc(lane.label)
        self.flight.record_shed(lane.name, len(jobs), "expired")
        if signing is None:
            for job in jobs:
                job.ticket._resolve(None, dropped=True)
            return
        for job in jobs:
            job.ticket._resolve(
                signing.host_sign(job.signing_root, job.secret_key)
            )

    def _process_batch(self, lane: SignLaneConfig,
                       jobs: "list[_SignJob]") -> None:
        signing = _schemes.get(lane.scheme).signing
        now = time.monotonic()
        # deadline-budget gate: window-expired jobs resolve on the host
        # anchor here, before the batch spends a device dispatch — the
        # worker's _pending accounting still covers them (they remain
        # part of this handoff)
        live: "list[_SignJob]" = []
        expired: "list[_SignJob]" = []
        for job in jobs:
            t = job.ticket.deadline
            (expired if (t is not None and now >= t) else live).append(job)
        if expired:
            self._shed_expired(lane, signing, expired)
            if not live:
                return
            jobs = live
        queue_wait = max(
            0.0, now - min(job.ticket.enqueued_at for job in jobs)
        )
        if self.metrics is not None:
            for job in jobs:
                self.metrics.sign_lane_wait_seconds.labels(
                    lane.label
                ).observe(now - job.ticket.enqueued_at)
            self.metrics.sign_lane_depth.labels(lane.label).set(
                len(self._queues[lane.name])
            )
        result = "host"
        sigs: "Optional[list[bytes]]" = None
        fl = self.flight.begin_batch(
            lane.name, "batch_sign", len(jobs),
            queue_wait_s=queue_wait, breaker_state=self.health.state,
        )
        device_wanted = (
            self.use_device and signing is not None
        )
        if device_wanted and not self.health.allow_device():
            device_wanted = False
            with self._stats_lock:
                self._stats[lane.name]["breaker_skips"] += 1
        backend = self._backend_for(lane) if device_wanted else None
        if backend is not None:
            messages = [job.signing_root for job in jobs]
            sks = [job.secret_key for job in jobs]
            self.flight.device_enter()
            try:
                t0 = time.perf_counter()
                outcome = self.health.guard_settle(
                    lambda: signing.batch_sign(backend, messages, sks),
                    thread_name="sign-settle-watchdog",
                )
                if outcome.status == _health.OK:
                    fl.note_device(time.perf_counter() - t0)
                    produced = outcome.value
                    if self.release_gate:
                        t1 = time.perf_counter()
                        gate_ok = signing.release_verify(
                            backend, messages, produced,
                            [job.public_key for job in jobs],
                        )
                        gate_s = time.perf_counter() - t1
                        fl.note_device(gate_s)
                        if self.metrics is not None:
                            self.metrics.sign_release_gate_seconds.observe(
                                gate_s
                            )
                        if gate_ok:
                            sigs = produced
                            result = "device"
                            self.health.record_success()
                        else:
                            # the core promise: a batch that fails the
                            # gate is NEVER released — host re-sign, and
                            # the breaker hears about the bad verdict
                            self.health.record_fault("verdict")
                            fl.note_fault("verdict")
                            with self._stats_lock:
                                self._stats[lane.name]["gate_failures"] += 1
                                self._stats[lane.name]["device_faults"] += 1
                            result = "degraded"
                    else:
                        sigs = produced
                        result = "device"
                        self.health.record_success()
                elif outcome.status == _health.TIMEOUT:
                    self.health.record_fault("watchdog")
                    fl.note_fault("watchdog")
                    with self._stats_lock:
                        self._stats[lane.name]["device_faults"] += 1
                    result = "degraded"
                else:
                    self.health.record_fault("dispatch")
                    fl.note_fault("dispatch")
                    with self._stats_lock:
                        self._stats[lane.name]["device_faults"] += 1
                    result = "degraded"
            finally:
                self.flight.device_exit()
        if sigs is None:
            if signing is None:
                # no sign-side scheme row: nothing to anchor against —
                # refuse by dropping (callers keep their own host path)
                for job in jobs:
                    job.ticket._resolve(None, dropped=True)
                fl.finish(False)
                return
            t0 = time.perf_counter()
            sigs = self._host_sign_all(signing, jobs)
            fl.note_host(time.perf_counter() - t0)
        for job, sig in zip(jobs, sigs):
            job.ticket._resolve(sig)
        fl.finish(True)
        with self._stats_lock:
            st = self._stats[lane.name]
            st["batches"] += 1
            st["signed"] += len(jobs)
            st["max_batch_items"] = max(st["max_batch_items"], len(jobs))
            if result == "device":
                st["device_batches"] += 1
            elif result == "degraded":
                st["degraded"] += 1
            else:
                st["host_batches"] += 1
        if self.metrics is not None:
            self.metrics.sign_lane_batches.labels(
                lane.label, result
            ).inc()

    def _resolve_on_host(self, lane: SignLaneConfig,
                         jobs: "list[_SignJob]",
                         note_fault: bool = False) -> None:
        """Containment path: resolve every ticket on the host anchor."""
        signing = _schemes.get(lane.scheme).signing
        if signing is None:
            for job in jobs:
                job.ticket._resolve(None, dropped=True)
            return
        if note_fault:
            self.health.record_fault("dispatch")
            with self._stats_lock:
                self._stats[lane.name]["device_faults"] += 1
                self._stats[lane.name]["degraded"] += 1
        for job in jobs:
            job.ticket._resolve(
                signing.host_sign(job.signing_root, job.secret_key)
            )

    # ------------------------------------------------------------ control

    def flush(self, timeout: "Optional[float]" = None) -> bool:
        """Block until every submitted request has settled (or timeout);
        True when fully drained."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._lock:
            while self._pending > 0:
                wait = 0.05
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._cond.wait(wait)
        return True

    def stop(self, timeout: float = 5.0) -> None:
        """Drain in-flight batches, drop queued requests (tickets settle
        dropped=True), and join the plane's threads."""
        with self._lock:
            if self._stop:
                return
            self._stop = True
            self._cond.notify_all()
        self._dispatcher.join(timeout)
        for _ in self._workers:
            self._inflight.put(None)
        for t in self._workers:
            t.join(timeout)

    def stats(self) -> dict:
        with self._stats_lock:
            return {
                name: dict(st) for name, st in self._stats.items()
            }


__all__ = [
    "DEFAULT_SIGN_LANES",
    "REFUSAL_REASONS",
    "SLASHABLE_KINDS",
    "SignInterlock",
    "SignLaneConfig",
    "SignRefused",
    "SignTicket",
    "SigningPlane",
]
