"""Liveness tracker — reference: liveness_tracker crate
(liveness_tracker/src/lib.rs:30-39: per-epoch validator liveness bitvecs
fed from blocks / attestations / sync messages, served by the Beacon API's
/eth/v1/validator/liveness endpoint).
"""

from __future__ import annotations

import threading

import numpy as np


class LivenessTracker:
    """Two rolling epochs of per-validator liveness bits."""

    def __init__(self, n_validators: int = 0) -> None:
        self._epochs: "dict[int, np.ndarray]" = {}
        self._n = n_validators
        self._lock = threading.Lock()

    def _bits(self, epoch: int) -> np.ndarray:
        bits = self._epochs.get(epoch)
        if bits is None:
            bits = np.zeros(max(self._n, 1), dtype=bool)
            self._epochs[epoch] = bits
            # keep only the two most recent epochs
            for old in sorted(self._epochs)[:-2]:
                del self._epochs[old]
        return bits

    def _grow(self, bits: np.ndarray, index: int, epoch: int) -> np.ndarray:
        if index >= len(bits):
            grown = np.zeros(index + 1, dtype=bool)
            grown[: len(bits)] = bits
            self._epochs[epoch] = grown
            self._n = max(self._n, index + 1)
            return grown
        return bits

    def on_attestation(self, epoch: int, indices) -> None:
        with self._lock:
            bits = self._bits(epoch)
            for i in indices:
                bits = self._grow(bits, int(i), epoch)
                bits[int(i)] = True

    def on_block(self, epoch: int, proposer_index: int) -> None:
        self.on_attestation(epoch, [proposer_index])

    def on_sync_message(self, epoch: int, validator_index: int) -> None:
        self.on_attestation(epoch, [validator_index])

    def is_live(self, epoch: int, index: int) -> bool:
        with self._lock:
            bits = self._epochs.get(epoch)
            return bool(bits[index]) if bits is not None and index < len(bits) else False

    def liveness(self, epoch: int, indices) -> "list[dict]":
        """Beacon-API-shaped response rows."""
        return [
            {"index": str(int(i)), "is_live": self.is_live(epoch, int(i))}
            for i in indices
        ]


__all__ = ["LivenessTracker"]
