"""In-process node: clock + controller + duty engine ticking through slots
on synthetic data — the round-9 "minimal runtime skeleton" everything else
plugs into (reference runtime/src/runtime.rs:49-110 wiring, minus
networking/eth1 which enter through the same seams later).

`InProcessNode.run_slot` drives one slot's three ticks:
  PROPOSE   — produce a block on the current head (validator.rs:733,1292)
              and feed it back through the controller (own-block path)
  ATTEST    — produce one aggregate attestation per committee and submit
              them to the AttestationVerifier firehose
  AGGREGATE — flush the verifier (stand-in for aggregate publication)
"""

from __future__ import annotations

from typing import Optional

from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.runtime.attestation_verifier import AttestationVerifier
from grandine_tpu.runtime.clock import SlotClock, ticks_for_slot
from grandine_tpu.runtime.controller import Controller
from grandine_tpu.validator.duties import produce_attestations, produce_block


class InProcessNode:
    def __init__(
        self,
        genesis_state,
        cfg,
        execution_engine=None,
        verifier_factory=None,
        use_device_firehose: bool = False,
        use_verify_scheduler: bool = False,
        full_sync_participation: bool = False,
        slasher=None,
        operation_pool=None,
        metrics=None,
        tracer=None,
        mesh=None,
        use_isolation: bool = True,
        use_brownout: bool = True,
        database=None,
    ) -> None:
        from grandine_tpu.consensus.verifier import MultiVerifier

        from grandine_tpu.runtime.flight import FlightRecorder
        from grandine_tpu.runtime.health import BackendHealthSupervisor
        from grandine_tpu.runtime.isolation import (
            AdmissionController,
            ReputationTable,
        )
        from grandine_tpu.tpu.mesh import mesh_or_none

        self.cfg = cfg
        self.metrics = metrics
        self.tracer = tracer
        #: injected VerifyMesh (cli --devices → VerifyMesh.build): threaded
        #: into the scheduler and the attestation firehose, which shard
        #: the registry + kernels over it; None / 1-device is single-chip
        self.mesh = mesh_or_none(mesh)
        #: ONE flight recorder for the whole verify plane: scheduler
        #: batches, firehose batches, canary probes, and breaker
        #: transitions share a single ordered timeline (the debug
        #: endpoint GET /eth/v1/debug/grandine/flight serves it)
        self.flight = FlightRecorder(metrics=metrics)
        #: ONE kernel profiler for the whole verify plane: the flight
        #: recorder reconciles every committed batch's device seconds
        #: into it, and the dispatch seams reach the same instance via
        #: the module default so capture sessions annotate every kernel
        #: (GET /eth/v1/debug/grandine/profile serves/controls it)
        from grandine_tpu.runtime.profiler import KernelProfiler, set_profiler

        self.profiler = set_profiler(KernelProfiler(metrics=metrics))
        self.flight.profiler = self.profiler
        #: ONE health supervisor for the whole device verify plane: a
        #: breaker fault observed by either the scheduler or the
        #: attestation firehose quarantines the device for both
        self.health = BackendHealthSupervisor(
            metrics=metrics, flight=self.flight
        )
        #: ONE reputation table + admission controller for the whole
        #: node (runtime/isolation.py): the scheduler quarantines by it,
        #: the gossip plane (p2p/network.py `admission=`) sheds by it.
        #: Persisted through the node's K-V store (when one is given) so
        #: an attacker cannot reset quarantine by waiting out a reboot.
        self.database = database
        self.reputation = ReputationTable()
        if database is not None:
            try:
                self.reputation.load(database)
            except Exception:
                pass  # a corrupt reputation row must never stop the node
        # admission keys quotas off per-origin FAILURE RATES from the
        # shared reputation table (not raw submission share): a busy
        # honest aggregator is never clamped, a high-failure origin is
        self.admission = AdmissionController(
            metrics=metrics, reputation=self.reputation
        )
        self.verify_scheduler = None
        if use_verify_scheduler:
            from grandine_tpu.runtime.verify_scheduler import VerifyScheduler

            self.verify_scheduler = VerifyScheduler(
                use_device=use_device_firehose,
                metrics=metrics,
                tracer=tracer,
                health=self.health,
                flight=self.flight,
                mesh=self.mesh,
                reputation=self.reputation,
                use_isolation=use_isolation,
            )
            if verifier_factory is None:
                # block proposer-signature batches ride the HIGH lane
                verifier_factory = self.verify_scheduler.verifier_factory(
                    "block"
                )
        self.controller = Controller(
            genesis_state,
            cfg,
            execution_engine=execution_engine,
            verifier_factory=verifier_factory or MultiVerifier,
            metrics=metrics,
            tracer=tracer,
        )
        self.controller.verify_scheduler = self.verify_scheduler
        self.attestation_verifier = AttestationVerifier(
            self.controller,
            use_device=use_device_firehose,
            slasher=slasher,
            operation_pool=operation_pool,
            metrics=metrics,
            tracer=tracer,
            health=self.health,
            flight=self.flight,
            mesh=self.mesh,
        )
        if (
            self.verify_scheduler is not None
            and self.attestation_verifier.registry is not None
        ):
            # share the device-resident pubkey registry (one device
            # mirror; the firehose already hooked its staleness to
            # on_validator_set_change)
            self.verify_scheduler.registry = (
                self.attestation_verifier.registry
            )
        #: ONE brownout controller for the whole node: watches the
        #: shared flight recorder's SLO-miss stream and the scheduler's
        #: lane depths, and walks the NORMAL→…→CRITICAL ladder across
        #: the verify plane + admission quotas (runtime/brownout.py).
        #: Only meaningful when a scheduler exists to actuate on.
        self.brownout = None
        if use_brownout and self.verify_scheduler is not None:
            from grandine_tpu.runtime.brownout import BrownoutController

            self.brownout = BrownoutController(
                self.verify_scheduler,
                flight=self.flight,
                admission=self.admission,
                metrics=metrics,
            )
            self.brownout.start()
        self.clock = SlotClock(
            int(genesis_state.genesis_time), cfg.seconds_per_slot
        )
        self.full_sync_participation = full_sync_participation
        self.produced_blocks: list = []
        #: optional BuilderApi (cli --builder-url): when set, _propose
        #: tries the blinded/builder flow before local building
        self.builder_api = None
        self.builder_stats = {"blocks": 0, "fallbacks": 0, "aborts": 0}

    # ------------------------------------------------------------- driving

    def run_slot(self, slot: int, attest: bool = True) -> None:
        for tick in ticks_for_slot(slot):
            self.controller.on_tick(tick)
            if tick.kind == TickKind.PROPOSE:
                self._propose(slot)
            elif tick.kind == TickKind.ATTEST and attest:
                self._attest(slot)
            elif tick.kind == TickKind.AGGREGATE:
                self.attestation_verifier.flush()
        self.controller.wait()

    def run_until(self, slot: int, attest: bool = True) -> None:
        start = self.controller.snapshot().slot + 1
        for s in range(start, slot + 1):
            self.run_slot(s, attest=attest)

    # -------------------------------------------------------------- duties

    def _propose(self, slot: int) -> None:
        self.controller.wait()  # head must reflect everything applied
        snapshot = self.controller.snapshot()
        signed_block = None
        if self.builder_api is not None and self.builder_api.can_use_builder(
            self.controller, slot, self.cfg.preset.SLOTS_PER_EPOCH
        ):
            aborted, signed_block = self._propose_via_builder(snapshot, slot)
            if aborted:
                self.builder_stats["aborts"] += 1
                return  # post-sign failure: never sign a second block
            if signed_block is not None:
                self.builder_stats["blocks"] += 1
            else:
                self.builder_stats["fallbacks"] += 1
        if signed_block is None:
            signed_block, _post = produce_block(
                snapshot.head_state,
                slot,
                self.cfg,
                full_sync_participation=self.full_sync_participation,
                attestations=self._pool_attestations(snapshot, slot),
            )
        self.produced_blocks.append(signed_block)
        self.controller.on_own_block(signed_block)
        self.controller.wait()

    def _propose_via_builder(self, snapshot, slot: int):
        """Builder flow with the devnet's interop proposer key; returns
        (aborted, signed_block_or_None). Pre-sign failures fall back to
        local building; post-sign failures abort the slot (the relay may
        hold the signature — equivocation risk)."""
        from grandine_tpu.consensus import accessors, signing
        from grandine_tpu.transition.slots import process_slots
        from grandine_tpu.types.combined import fork_namespace, state_phase_of
        from grandine_tpu.validator import blinded as blinded_mod
        from grandine_tpu.validator.duties import _interop_keys

        p = self.cfg.preset
        state = snapshot.head_state
        try:
            if int(state.slot) < slot:
                state = process_slots(state, slot, self.cfg)
            ns = fork_namespace(self.cfg, state_phase_of(state, self.cfg))
            proposer = accessors.get_beacon_proposer_index(state, p)
            key = _interop_keys(proposer)
            pubkey = key.public_key().to_bytes()
            bid = self.builder_api.get_execution_payload_header(
                slot,
                bytes(state.latest_execution_payload_header.block_hash),
                pubkey,
                ns=ns,
            )
            header = blinded_mod.header_from_bid(ns, bid["header"])
            reveal = key.sign(
                signing.randao_signing_root(
                    state, accessors.get_current_epoch(state, p), self.cfg
                )
            ).to_bytes()
            block, pre, _post = blinded_mod.produce_blinded_block(
                state, slot, self.cfg, header, reveal,
                attestations=self._pool_attestations(snapshot, slot),
            )
        except Exception as e:
            self.builder_stats["last_error"] = repr(e)
            return False, None  # pre-sign: local fallback is safe
        try:
            sig = key.sign(
                signing.block_signing_root(pre, block, self.cfg)
            ).to_bytes()
            signed_blinded = ns.SignedBlindedBeaconBlock(
                message=block, signature=sig
            )
            response = self.builder_api.submit_blinded_block(signed_blinded)
            raw = response["execution_payload"]
            payload = ns.ExecutionPayload.deserialize(
                bytes.fromhex(raw.removeprefix("0x"))
                if isinstance(raw, str)
                else bytes(raw)
            )
            return False, blinded_mod.unblind_signed_block(
                signed_blinded, payload, self.cfg
            )
        except Exception as e:
            self.builder_stats["last_error"] = repr(e)
            return True, None  # post-sign: abort the slot

    def _pool_attestations(self, snapshot, slot: int):
        """Previous-slot attestations for inclusion (a stand-in for the
        operation pool, built against the head state)."""
        if slot <= 1 or int(snapshot.head_state.slot) < slot - 1:
            return []
        try:
            return produce_attestations(
                snapshot.head_state, self.cfg, slot=slot - 1
            )
        except ValueError:
            return []

    def _attest(self, slot: int) -> None:
        self.controller.wait()
        snapshot = self.controller.snapshot()
        if int(snapshot.head_state.slot) < slot:
            return
        atts = produce_attestations(snapshot.head_state, self.cfg, slot=slot)
        # firehose path exercises batch verification + fallback; the
        # produced attestations also flow into the proposer's next block
        # via _pool_attestations
        self.attestation_verifier.submit_many(atts)

    # ------------------------------------------------------------- control

    def head(self):
        return self.controller.snapshot()

    def stop(self) -> None:
        if self.database is not None:
            try:
                self.reputation.save(self.database)
            except Exception:
                pass  # shutdown persistence is best-effort
        # the controller stops FIRST so it reverts every brownout
        # actuation (lane configs, admission pressure) before the
        # scheduler drains
        if self.brownout is not None:
            self.brownout.stop()
        self.attestation_verifier.stop()
        if self.verify_scheduler is not None:
            self.verify_scheduler.stop()
        self.controller.stop()

    def __enter__(self) -> "InProcessNode":
        return self

    def __exit__(self, *_) -> None:
        self.stop()


__all__ = ["InProcessNode"]
