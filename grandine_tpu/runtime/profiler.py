"""Node-wide kernel profiler: per-kernel device-time attribution plus
bounded on-demand capture sessions.

Two independent planes share this module:

* **Always-on estimators** — every dispatch seam (`TpuBlsBackend.
  _run_kernel`, `Ed25519Backend.verify_batch_async`, `KzgDeviceBackend.
  verify_blobs_async`, the kzg MSM tail) counts its dispatches here, and
  the flight recorder reconciles every committed `BatchRecord`'s
  dispatch→settle delta into per-`(kernel, scheme)` device-second
  totals via `on_batch` (`FlightRecorder.profiler` hook). These feed
  `verify_device_seconds_total{kernel,scheme}` and, together with
  `jax.live_arrays`-derived per-family live-byte gauges
  (`verify_device_hbm_bytes{family}`), cost nothing but a dict bump per
  batch — no jax import, no trace machinery.

* **Capture sessions** — `start()`/`stop()` open at most one session at
  a time; while a session is active every dispatch runs inside a
  `jax.profiler.TraceAnnotation("{scheme}/{kernel}/b{bucket}")` scope
  (and bench loops may add `step()` = `StepTraceAnnotation` marks), so
  the device timeline in the resulting perfetto/Chrome trace is keyed
  by the same `(scheme, kernel, bucket)` coordinates the shape ledger
  uses. Sessions with a `trace_dir` also drive `jax.profiler.
  start_trace`/`stop_trace`; finished sessions land in a bounded ring
  of the last K. `GET /eth/v1/debug/grandine/profile` serves the
  summary and the start/stop control (http_api/routing.py).

Entering/leaving a capture session MUST NOT perturb the shape ledger or
the recompile guarantees: annotation scopes wrap the already-jitted
callable invocation — they never touch tracing-time state, so
`post_warmup_recompiles()` stays 0 across a mid-soak toggle
(tests/test_profiler.py proves it).

The `KERNEL_SCHEMES` table below is the annotation registry: every
dispatch name in the shapes manifest MUST have an entry — enforced
statically by the `profiler-scope` check in tools/shapes.

Import discipline: stdlib only at module scope. jax is reached through
`sys.modules` on the estimator paths (never imported — a host-only node
must not pay the import) and imported lazily only inside the capture /
timing helpers the tools/ shims call.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import shutil
import sys
import threading
import time
from typing import Callable, Optional

#: closed scheme-label set for verify_device_seconds_total{scheme} —
#: the tpu/schemes.py registry names plus the slasher span plane and
#: the catch-all (metrics-cardinality: no open-ended label values)
SCHEMES = ("bls", "ed25519", "blob_kzg", "slasher", "other")

#: the annotation registry: dispatch name → scheme label. Covers the
#: shapes-manifest dispatch universe (every `contract` row) plus the
#: flight-record kernel labels the runtime stamps on batches
#: (scheme.kernel_label values, the replay window kernels, the host
#: twin). The tools/shapes `profiler-scope` check asserts statically
#: that no manifest dispatch name is missing here.
KERNEL_SCHEMES = {
    # tpu/bls.py jit entry points (TpuBlsBackend ASYNC_SEAM + sync)
    "agg_fast_verify_msm": "bls",
    "agg_fast_verify_msm_idx": "bls",
    "agg_fast_verify_msm_comp": "bls",
    "agg_fast_verify_msm_idx_comp": "bls",
    "multi_verify_msm_comp": "bls",
    "g1_decompress": "bls",
    "batch_sign": "bls",
    "g2_aggregate": "bls",
    "g1_aggregate": "bls",
    "g2_subgroup_check": "bls",
    "grouped_multi_verify_msm": "bls",
    "multi_verify_msm": "bls",
    "multi_verify_msm_idx": "bls",
    "rlc_partition": "bls",
    "sharded_multi_verify": "bls",
    "sharded_multi_verify_msm": "bls",
    "make_sharded_multi_verify": "bls",
    "make_sharded_multi_verify_msm": "bls",
    # flight-record kernel labels (scheme.kernel_label / firehose /
    # replay) — the estimator sees these on BatchRecords
    "fast_aggregate": "bls",
    "fast_aggregate_fused": "bls",
    "multi_verify": "bls",
    "host": "bls",
    "pubkey_registry": "bls",
    # other schemes' dispatch names double as their flight labels
    "ed25519_verify": "ed25519",
    "kzg_blob_verify": "blob_kzg",
    "blob_kzg_verify": "blob_kzg",
    "kzg_msm": "blob_kzg",
    # slasher span plane
    "span_update_grid": "slasher",
    "span_update": "slasher",
}

#: closed family set for verify_device_hbm_bytes{family}
HBM_FAMILIES = ("registry", "kernel_io", "other")

#: field-element limb count — live arrays whose trailing dimension is
#: a limb plane belong to the verify plane (tpu/limbs.NLIMBS, kept as a
#: literal so this module never imports the kernel layer)
_NLIMBS = 26
#: rows at or above this look like registry planes, not batch operands
#: (tpu/registry.MIN_CAPACITY covers tests; production registries are
#: 2^20 rows — the boundary only needs to separate per-batch operands)
_REGISTRY_MIN_ROWS = 16384

DEFAULT_SESSION_RING = 8


def _bucket(items: int) -> int:
    """Pow-2 padding bucket, same policy as runtime/flight.bucket_of
    (duplicated two lines rather than importing the flight module from
    the annotation fast path)."""
    if items <= 1:
        return 1
    return 1 << (int(items) - 1).bit_length()


def _family_of(a) -> str:
    """Classify one live device array into an HBM family. Shape
    heuristic, documented rather than hidden: limb planes with a
    registry-scale leading dimension are "registry", any other integer/
    bool plane is per-batch "kernel_io", the rest (prng keys, tracer
    scratch) is "other"."""
    shape = tuple(getattr(a, "shape", ()) or ())
    if len(shape) >= 2 and shape[-1] == _NLIMBS:
        return "registry" if shape[0] >= _REGISTRY_MIN_ROWS else "kernel_io"
    dt = str(getattr(a, "dtype", ""))
    if dt.startswith(("int", "uint", "bool")):
        return "kernel_io"
    return "other"


class KernelProfiler:
    """See the module docstring. One instance per node (runtime/node.py
    wires it into the shared FlightRecorder and publishes it as the
    module default so the dispatch seams reach it); tests construct
    private instances freely."""

    def __init__(
        self,
        *,
        metrics=None,
        capacity: int = DEFAULT_SESSION_RING,
        trace_root: "Optional[str]" = None,
        clock: "Callable[[], float]" = time.monotonic,
    ) -> None:
        self.metrics = metrics
        self.capacity = max(1, int(capacity))
        #: root directory for capture traces (cli --profile-dir); a
        #: session without it is annotation-only (no device trace file)
        self.trace_root = trace_root
        self.clock = clock
        self._lock = threading.Lock()
        #: capture flag annotate()/step() read per dispatch (under the
        #: same lock as the dispatch bump); only start/stop write it
        self._capturing = False
        self._active: "Optional[dict]" = None
        self._ring: "list[dict]" = []  # finished sessions, newest last
        self._sessions_total = 0
        self._device_s: "dict[tuple, float]" = {}
        self._batches: "dict[tuple, int]" = {}
        self._dispatches: "dict[str, int]" = {}
        self._extra_kernels: "dict[str, str]" = {}
        self._hbm: "dict[str, int]" = {}

    # ------------------------------------------------ annotation registry

    def register_kernel(self, kernel: str, scheme: str = "other") -> None:
        """Register a dispatch name outside the static table (tests,
        experimental kernels). `scheme` must come from SCHEMES — the
        metric label set is closed."""
        if scheme not in SCHEMES:
            raise ValueError(f"unknown scheme {scheme!r} (want {SCHEMES})")
        with self._lock:
            self._extra_kernels[kernel] = scheme

    def annotation_keys(self) -> "dict[str, str]":
        with self._lock:
            extra = dict(self._extra_kernels)
        out = dict(KERNEL_SCHEMES)
        out.update(extra)
        return out

    def scheme_of(self, kernel: str) -> str:
        scheme = KERNEL_SCHEMES.get(kernel)
        if scheme is None:
            with self._lock:
                scheme = self._extra_kernels.get(kernel, "other")
        return scheme if scheme in SCHEMES else "other"

    # ------------------------------------------------- annotation scopes

    def annotate(self, kernel: str, items: int = 0):
        """The per-dispatch scope: always bumps the dispatch counter;
        only while a capture session is active does it open a
        jax.profiler.TraceAnnotation (keyed scheme/kernel/bucket) — the
        always-off path is one locked dict bump per BATCH, which is what
        keeps the overhead guard ≤5% (tests/test_profiler.py)."""
        with self._lock:
            self._dispatches[kernel] = self._dispatches.get(kernel, 0) + 1
            capturing = self._capturing
        if not capturing:
            return contextlib.nullcontext()
        jax = sys.modules.get("jax")
        if jax is None:
            return contextlib.nullcontext()
        label = f"{self.scheme_of(kernel)}/{kernel}/b{_bucket(items)}"
        try:
            return jax.profiler.TraceAnnotation(label)
        except Exception:
            return contextlib.nullcontext()

    def step(self, step_num: int):
        """Batch-iteration mark for bench/soak loops: a StepTrace
        Annotation while capturing, a no-op otherwise."""
        with self._lock:
            capturing = self._capturing
        if not capturing:
            return contextlib.nullcontext()
        jax = sys.modules.get("jax")
        if jax is None:
            return contextlib.nullcontext()
        try:
            return jax.profiler.StepTraceAnnotation(
                "verify_batch", step_num=int(step_num)
            )
        except Exception:
            return contextlib.nullcontext()

    # --------------------------------------------- always-on estimators

    def on_batch(self, rec) -> None:
        """FlightRecorder._commit hook: reconcile one committed record's
        dispatch→settle device seconds into the estimator. Accepts any
        record carrying a kernel (batches and canary probes — both are
        device time)."""
        kernel = getattr(rec, "kernel", "") or ""
        if not kernel:
            return
        dev = max(0.0, float(getattr(rec, "device_s", 0.0) or 0.0))
        scheme = self.scheme_of(kernel)
        key = (kernel, scheme)
        with self._lock:
            self._device_s[key] = self._device_s.get(key, 0.0) + dev
            self._batches[key] = self._batches.get(key, 0) + 1
            active = self._active
            if active is not None:
                active["device_s"] += dev
                active["batches"] += 1
        if self.metrics is not None and dev > 0.0:
            self.metrics.verify_device_seconds.labels(
                kernel, scheme
            ).inc(dev)

    def device_seconds(self) -> "dict[tuple, float]":
        with self._lock:
            return dict(self._device_s)

    def attributed_seconds(self) -> float:
        with self._lock:
            return sum(self._device_s.values())

    def coverage(self, flight) -> "Optional[float]":
        """Fraction of the flight recorder's device-busy integral the
        estimator attributed to named kernels — the `profiler_coverage`
        field the firehose bench reports (acceptance: ≥0.90). None when
        the recorder saw no device time."""
        if flight is None:
            return None
        busy = flight.busy_seconds()
        if busy <= 0.0:
            return None
        return min(1.0, self.attributed_seconds() / busy)

    def update_hbm(self, live_arrays=None) -> "dict[str, int]":
        """Snapshot live device bytes per family into
        verify_device_hbm_bytes. Uses the injected iterable (tests) or
        jax.live_arrays() when jax is already imported — never imports
        jax itself."""
        arrays = live_arrays
        if arrays is None:
            jax = sys.modules.get("jax")
            if jax is None:
                return {}
            try:
                arrays = jax.live_arrays()
            except Exception:
                return {}
        totals = {fam: 0 for fam in HBM_FAMILIES}
        for a in arrays:
            totals[_family_of(a)] += int(getattr(a, "nbytes", 0) or 0)
        with self._lock:
            self._hbm = dict(totals)
        if self.metrics is not None:
            for fam, nbytes in totals.items():
                self.metrics.verify_device_hbm_bytes.labels(fam).set(nbytes)
        return totals

    # --------------------------------------------------- capture sessions

    def start(self, trace_dir: "Optional[str]" = None,
              note: str = "") -> dict:
        """Open a capture session (at most one). With a trace dir —
        explicit, or derived from `trace_root` — the jax profiler writes
        a perfetto/Chrome trace there; without one the session is
        annotation-only (still ringed, still counted). Raises
        RuntimeError if a session is already active."""
        with self._lock:
            if self._active is not None:
                raise RuntimeError("profiler capture session already active")
            self._sessions_total += 1
            sid = self._sessions_total
            tdir = trace_dir
            if tdir is None and self.trace_root:
                tdir = os.path.join(self.trace_root, f"session-{sid:04d}")
            sess = {
                "id": sid,
                "started": self.clock(),
                "stopped": None,
                "trace_dir": tdir,
                "note": note,
                "device_s": 0.0,
                "batches": 0,
                "tracing": False,
                "error": None,
            }
            self._active = sess
            self._capturing = True
        if tdir is not None:
            try:
                import jax

                os.makedirs(tdir, exist_ok=True)
                jax.profiler.start_trace(tdir)
                sess["tracing"] = True
            except Exception as exc:  # host-only node: annotation-only
                sess["error"] = f"device trace unavailable: {exc!r}"
        if self.metrics is not None:
            self.metrics.verify_profile_sessions.inc()
        return dict(sess)

    def stop(self) -> dict:
        """Close the active session: stop the device trace (if any),
        stamp the duration, append to the bounded ring of the last
        `capacity` sessions. Raises RuntimeError when none is active."""
        with self._lock:
            sess = self._active
            if sess is None:
                raise RuntimeError("no active profiler capture session")
            self._active = None
            self._capturing = False
        if sess["tracing"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as exc:
                sess["error"] = f"stop_trace failed: {exc!r}"
        sess["stopped"] = self.clock()
        with self._lock:
            self._ring.append(sess)
            if len(self._ring) > self.capacity:
                del self._ring[: len(self._ring) - self.capacity]
        self.update_hbm()  # best-effort close-of-session snapshot
        return dict(sess)

    def sessions(self) -> "list[dict]":
        with self._lock:
            return [dict(s) for s in self._ring]

    def active_session(self) -> "Optional[dict]":
        with self._lock:
            return dict(self._active) if self._active is not None else None

    @property
    def sessions_total(self) -> int:
        with self._lock:
            return self._sessions_total

    # ------------------------------------------------------------ summary

    def summary(self, kernel: "Optional[str]" = None,
                scheme: "Optional[str]" = None,
                n_sessions: "Optional[int]" = None,
                flight=None) -> dict:
        """The debug-endpoint payload: estimator rows (filterable by
        kernel/scheme), dispatch counts, the session ring, the HBM
        snapshot, and coverage against the given flight recorder."""
        with self._lock:
            rows = [
                {
                    "kernel": k,
                    "scheme": s,
                    "device_s": round(v, 6),
                    "batches": self._batches.get((k, s), 0),
                }
                for (k, s), v in sorted(self._device_s.items())
            ]
            dispatches = dict(sorted(self._dispatches.items()))
            ring = [dict(x) for x in self._ring]
            active = dict(self._active) if self._active else None
            total = self._sessions_total
            hbm = dict(self._hbm)
        if kernel is not None:
            rows = [r for r in rows if r["kernel"] == kernel]
            dispatches = {k: v for k, v in dispatches.items() if k == kernel}
        if scheme is not None:
            rows = [r for r in rows if r["scheme"] == scheme]
        if n_sessions is not None:
            ring = ring[-n_sessions:] if n_sessions else []
        out = {
            "device_seconds": rows,
            "dispatches": dispatches,
            "sessions": ring,
            "active_session": active,
            "sessions_total": total,
            "hbm_bytes": hbm,
        }
        cov = self.coverage(flight)
        if cov is not None:
            out["coverage"] = round(cov, 4)
        return out


# ------------------------------------------------------- module default

_default_lock = threading.Lock()
_DEFAULT: "Optional[KernelProfiler]" = None


def get_profiler() -> KernelProfiler:
    """The process-wide profiler the dispatch seams annotate through.
    Metrics-less until a node (or bench) publishes a configured instance
    via set_profiler."""
    global _DEFAULT
    with _default_lock:
        if _DEFAULT is None:
            _DEFAULT = KernelProfiler()
        return _DEFAULT


def set_profiler(profiler: KernelProfiler) -> KernelProfiler:
    global _DEFAULT
    with _default_lock:
        _DEFAULT = profiler
    return profiler


# ------------------------------- shared helpers for the tools/ shims


def time_jit(name: str, fn, *args, iters: int = 5, jit: bool = True,
             stream=None) -> dict:
    """The stage-timing primitive the tools/profile_* scripts share:
    jit the callable, time compile+first-run, then `iters` warm runs —
    forcing a host fetch per measurement, because the axon runtime's
    block_until_ready does not wait for execution. Prints one aligned
    line and returns the numbers."""
    import jax
    import numpy as np

    f = jax.jit(fn) if jit else fn
    t0 = time.time()
    out = f(*args)
    np.asarray(jax.tree.leaves(out)[0])  # force execution
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(max(1, iters)):
        out = f(*args)
        np.asarray(jax.tree.leaves(out)[0])
    run_s = (time.time() - t0) / max(1, iters)
    print(
        f"{name:26s} compile={compile_s:7.1f}s run={run_s * 1000:9.2f}ms",
        file=stream if stream is not None else sys.stderr,
    )
    return {"name": name, "compile_s": compile_s, "run_s": run_s}


def capture_trace(fn, trace_dir: str, runs: int = 2) -> str:
    """Run `fn()` `runs` times under a KernelProfiler capture session
    writing a device trace into `trace_dir` (recreated), forcing the
    last result. The capture path the tools/trace_kernel shim rides."""
    import jax

    shutil.rmtree(trace_dir, ignore_errors=True)
    prof = KernelProfiler()
    prof.start(trace_dir=trace_dir)
    try:
        out = None
        for _ in range(max(1, runs)):
            out = fn()
        jax.block_until_ready(out)
    finally:
        prof.stop()
    return trace_dir


def summarize_trace(trace_dir: str, top: int = 40):
    """Aggregate the Chrome-trace JSON the jax profiler emitted under
    `trace_dir`: total complete-event ("X" phase) op time plus the top
    ops by self time. Returns (total_seconds, [(name, seconds, count)]);
    (0.0, []) when no trace file exists."""
    files = glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    if not files:
        return 0.0, []
    with gzip.open(files[0], "rt") as f:
        trace = json.load(f)
    durations: "dict[str, float]" = {}
    counts: "dict[str, int]" = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        durations[name] = durations.get(name, 0.0) + ev.get("dur", 0)
        counts[name] = counts.get(name, 0) + 1
    total = sum(durations.values()) / 1e6
    rows = [
        (name, dur / 1e6, counts[name])
        for name, dur in sorted(durations.items(), key=lambda kv: -kv[1])
    ]
    return total, rows[:top]


__all__ = [
    "KernelProfiler",
    "KERNEL_SCHEMES",
    "SCHEMES",
    "HBM_FAMILIES",
    "DEFAULT_SESSION_RING",
    "get_profiler",
    "set_profiler",
    "time_jit",
    "capture_trace",
    "summarize_trace",
]
