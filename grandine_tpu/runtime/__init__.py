"""Runtime assembly — reference: `runtime` crate (service wiring,
runtime/src/runtime.rs:49-597), `fork_choice_control` threading
(controller/mutator/thread pool), `clock`, and the p2p
`AttestationVerifier` batching service.

  clock.py                — slot/tick timing (clock/src/lib.rs:1-30)
  thread_pool.py          — 2-priority worker pool + WaitGroup test drain
                            (fork_choice_control/src/thread_pool.rs, wait.rs)
  controller.py           — mutator-actor Controller with snapshots and
                            delayed-object retry (controller.rs, mutator.rs)
  attestation_verifier.py — accumulate→deadline→batch→fallback firehose
                            (p2p/src/attestation_verifier.rs)
  verify_scheduler.py     — multi-lane batch-verify scheduler for every
                            OTHER signed-object kind (priority lanes,
                            deadline coalescing, shed-under-overload)
  node.py                 — in-process node: clock + controller + duties
                            ticking through slots on synthetic data
"""

from grandine_tpu.runtime.clock import SlotClock, ticks_for_slot  # noqa: F401
from grandine_tpu.runtime.controller import Controller, Snapshot  # noqa: F401
from grandine_tpu.runtime.thread_pool import (  # noqa: F401
    Priority,
    ThreadPool,
    WaitGroup,
)
from grandine_tpu.runtime.attestation_verifier import (  # noqa: F401
    AttestationVerifier,
    GossipAttestation,
)
from grandine_tpu.runtime.verify_scheduler import (  # noqa: F401
    DeferredVerifier,
    LaneConfig,
    VerifyItem,
    VerifyScheduler,
    VerifyTicket,
)
from grandine_tpu.runtime.node import InProcessNode  # noqa: F401
