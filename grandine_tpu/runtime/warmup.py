"""Manifest-driven kernel precompiler (tools/shapes contract).

First compiles of the device kernels cost minutes per bucket shape (they
land in the persistent XLA cache afterwards), and an uncompiled bucket
hit mid-chain stalls verification for the whole compile. The warmer
iterates the CHECKED-IN kernel manifest (`tools/shapes/manifest.txt`,
generated and verified by `python -m tools.shapes`) — the statically
proven universe of (kind, bucket) pairs the node's dispatch paths can
form — and runs each kernel once on shape-matched dummy inputs, in a
background thread that overlaps checkpoint sync / backfill at startup
(reference parity goal: blst needs no warmup, so the node must hide
ours).

Compilation depends only on SHAPES; the dummy inputs are valid curve
points with nonsense provenance, so every warm call returns False —
irrelevant, the compile cache is the product.

When warming finishes it SEALS the shape ledger
(`tpu.bls.declare_warmup_complete`): any novel shape signature
dispatched afterwards increments `verify_recompiles_total`, making
"zero steady-state recompiles" an assertable invariant (bench soaks,
tests/test_shapes.py). The built-in bucket ladders below are only the
fallback for a checkout whose manifest is missing.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

#: FALLBACK ladders when tools/shapes/manifest.txt is absent — kept in
#: sync with the analyzer's derived rows (firehose bound = max of
#: attestation MAX_BATCH and the widest scheduler lane max_batch).
FIREHOSE_BUCKETS = (4, 8, 16, 32, 64, 128)
MULTI_VERIFY_BUCKETS = (64, 256, 1024, 4096)
# sign-plane lanes deadline-flush at any n ≤ max_batch (512): warm the
# full pow-2 ladder so first-duty signing never compiles at slot time
SIGN_BUCKETS = (4, 8, 16, 32, 64, 128, 256, 512)
SUBGROUP_BUCKETS = (4, 8, 16, 32, 64, 128)

#: warm kinds the runner understands, in manifest order. The sharded_*
#: kinds compile the multi-chip dispatch targets (tpu/bls.py
#: sharded_multi_verify / sharded_multi_verify_msm) and are skipped with
#: a progress note on a mesh-less node — the MULTICHIP dryruns measured
#: a cold 2m51s sharded compile, which warmup must eat at startup so a
#: restart never pays it mid-chain.
WARM_KINDS = ("aggregate", "aggregate_idx", "multi_verify", "sign",
              "subgroup", "rlc_partition", "sharded_multi_verify",
              "sharded_multi_verify_msm", "span_update",
              "registry_capacity", "ed25519_verify", "kzg_blob",
              "aggregate_comp", "aggregate_idx_comp", "multi_verify_comp",
              "g1_decompress", "g2_aggregate", "g1_aggregate")


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def manifest_file_path() -> str:
    return os.path.join(_repo_root(), "tools", "shapes", "manifest.txt")


def load_manifest(
    path: "Optional[str]" = None,
) -> "Optional[list[tuple[str, int]]]":
    """(kind, bucket) pairs from the checked-in shape manifest's `warm`
    rows, or None when the file is missing/unparseable (fallback ladders
    apply)."""
    path = path or manifest_file_path()
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    out: "list[tuple[str, int]]" = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line.startswith("warm "):
            continue
        cols = [c.strip() for c in line.split("|")]
        kind = cols[0][len("warm "):].strip()
        buckets = None
        for col in cols[1:]:
            if col.startswith("buckets "):
                try:
                    buckets = [
                        int(b) for b in col[len("buckets "):].split(",")
                    ]
                except ValueError:
                    return None
        if not buckets or kind not in WARM_KINDS:
            return None
        out.extend((kind, b) for b in buckets)
    return out or None


def manifest() -> "list[tuple[str, int]]":
    loaded = load_manifest()
    if loaded is not None:
        return loaded
    out = [("aggregate", b) for b in FIREHOSE_BUCKETS]
    out += [("aggregate_idx", b) for b in FIREHOSE_BUCKETS]
    out += [("multi_verify", b) for b in MULTI_VERIFY_BUCKETS]
    out += [("sign", b) for b in SIGN_BUCKETS]
    out += [("subgroup", b) for b in SUBGROUP_BUCKETS]
    out += [("rlc_partition", b) for b in FIREHOSE_BUCKETS]
    # sharded rows are no-ops without a mesh (skipped with a note)
    out += [("sharded_multi_verify", b) for b in MULTI_VERIFY_BUCKETS]
    out += [("sharded_multi_verify_msm", b) for b in MULTI_VERIFY_BUCKETS]
    # compressed-ingest twins ride the same dispatch-bound ladders
    out += [("aggregate_comp", b) for b in FIREHOSE_BUCKETS]
    out += [("aggregate_idx_comp", b) for b in FIREHOSE_BUCKETS]
    out += [("multi_verify_comp", b) for b in MULTI_VERIFY_BUCKETS]
    out += [("g1_decompress", b) for b in (16, 64, 256, 1024)]
    # aggregate-construction sums (signing plane duty aggregation)
    out += [("g2_aggregate", b) for b in (64, 256)]
    out += [("g1_aggregate", b) for b in (64, 256)]
    return out


def enable_persistent_cache() -> "Optional[str]":
    """Point XLA's persistent compilation cache at the node cache dir
    (GRANDINE_TPU_JIT_CACHE overrides). Warm compiles land there, so a
    RESTART pays cache loads (~ms each), not fresh compiles (~minutes).
    Idempotent and best-effort; returns the cache dir or None."""
    import jax

    cache_dir = os.environ.get(
        "GRANDINE_TPU_JIT_CACHE",
        os.path.expanduser("~/.cache/grandine_tpu_jit"),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception:
        return None  # older jax / read-only FS: warm still compiles


def warm_all(
    buckets: "Optional[list[tuple[str, int]]]" = None,
    progress: "Optional[Callable[[str], None]]" = None,
    backend=None,
    registry=None,
    metrics=None,
    seal: bool = True,
    enable_cache: bool = True,
    mesh=None,
) -> int:
    """Compile-and-run every manifest entry once. Returns the number of
    entries warmed. Call from a background thread at node startup
    (`warm_in_background`'s `kernel-warmup` daemon): all state here is
    thread-local; the shared ledger/cache seams take their own locks.

    `registry` (a DevicePubkeyRegistry with at least one key) unlocks
    the aggregate_idx kind; without it those rows are skipped with a
    progress note. `mesh` (a VerifyMesh, cli --devices) unlocks the
    sharded_* kinds, warmed through a mesh-attached backend so the
    multi-chip dispatch targets compile at startup; single-device kinds
    still warm through a plain backend (they stay the fallback for
    batches the mesh gates reject). With `seal` the shape ledger is
    sealed on completion so later novel shapes count as recompiles."""
    from grandine_tpu.crypto import bls as A
    from grandine_tpu.crypto.curves import G1
    from grandine_tpu.crypto.hash_to_curve import hash_to_g2
    from grandine_tpu.tpu import bls as B
    from grandine_tpu.tpu import schemes
    from grandine_tpu.tpu.mesh import mesh_or_none

    if enable_cache:
        enable_persistent_cache()
    # pre-load the MSM autotune table (tools/shapes/msm_tune.json) so the
    # window widths baked into the warmed plans are the MEASURED ones —
    # a table loaded after warmup would re-plan, and re-compile, mid-slot
    if B.load_msm_tuning() and progress:
        progress("msm autotune table loaded (%s)" % B.msm_tune_path())
    mesh_backend = (
        backend if getattr(backend, "mesh", None) is not None else None
    )
    if mesh_backend is None and mesh_or_none(mesh) is not None:
        mesh_backend = schemes.get("bls").make_backend(
            metrics=metrics, mesh=mesh
        )
    if backend is None:
        backend = schemes.get("bls").make_backend(metrics=metrics)
    pk = A.PublicKey(G1)
    h = hash_to_g2(b"warmup")
    sig = A.Signature(h)
    sig_c = A.g2_to_bytes(h)  # compressed wire bytes (compressed-ingest)
    sk = A.SecretKey(0x1234_5678)
    #: lazily-built non-BLS scheme backends (tpu/schemes.py table),
    #: shared across that scheme's warm rows so each gets one jit cache
    scheme_backends: "dict[str, object]" = {}
    done = 0
    for kind, b in buckets if buckets is not None else manifest():
        t0 = time.time()
        try:
            if kind == "aggregate":
                backend.fast_aggregate_verify_batch(
                    [b"warm-%d" % i for i in range(b)],
                    [sig] * b,
                    [[pk]] * b,
                )
            elif kind == "aggregate_idx":
                if registry is None or registry.arrays()[0] is None:
                    if progress:
                        progress(
                            f"warm {kind}/{b} skipped: no device registry"
                        )
                    continue
                backend.fast_aggregate_verify_batch_indexed(
                    [b"warm-%d" % i for i in range(b)],
                    [sig] * b,
                    [[0]] * b,
                    registry,
                )
            elif kind == "multi_verify":
                # bm distinct messages x bk signatures each: the grouped
                # kernel's shape (bm = b//8 groups exercises the MSM path)
                n_groups = max(2, b // 8)
                backend.multi_verify(
                    [b"warm-%d" % (i % n_groups) for i in range(b)],
                    [sig] * b,
                    [pk] * b,
                )
            elif kind == "sign":
                backend.batch_sign([b"warm-%d" % i for i in range(b)],
                                   [sk] * b)
            elif kind == "subgroup":
                backend.g2_subgroup_check_batch([h] * b)
            elif kind == "rlc_partition":
                # fault localization dispatches each bucket at every
                # rung of its fixed group ladder (runtime/isolation.py);
                # warm all (bucket, groups) variants so an adversarial
                # incident never compiles mid-descent
                from grandine_tpu.runtime.isolation import ladder

                for g in ladder(b):
                    backend.rlc_partition_verify(
                        [b"warm-%d" % i for i in range(b)],
                        [sig] * b,
                        [[pk]] * b,
                        g,
                    )
            elif kind == "sharded_multi_verify":
                if mesh_backend is None:
                    if progress:
                        progress(f"warm {kind}/{b} skipped: no mesh")
                    continue
                # ALL-distinct messages defeat the grouping heuristic so
                # dispatch takes the flat sharded-RLC path
                mesh_backend.multi_verify(
                    [b"warm-%d" % i for i in range(b)],
                    [sig] * b,
                    [pk] * b,
                )
            elif kind == "sharded_multi_verify_msm":
                if mesh_backend is None:
                    if progress:
                        progress(f"warm {kind}/{b} skipped: no mesh")
                    continue
                # grouped messages route to the sharded grouped-MSM path
                # (both group axes divide any power-of-two mesh)
                n_groups = max(2, b // 8)
                mesh_backend.multi_verify(
                    [b"warm-%d" % (i % n_groups) for i in range(b)],
                    [sig] * b,
                    [pk] * b,
                )
            elif kind == "span_update":
                # slasher bulk-replay span grid (tpu/spans.py): buckets
                # are row widths; the epoch axis is fixed, so one merge
                # per bucket compiles the whole kernel surface
                import numpy as np

                from grandine_tpu.tpu import spans as SP

                plane = SP.SpanPlane(metrics=metrics)
                plane.update(
                    np.full(
                        (b, SP.SPAN_GRID_EPOCHS), SP.INT32_UNSET, np.int32
                    ),
                    np.zeros((b, SP.SPAN_GRID_EPOCHS), np.int32),
                    np.full((b,), 8, np.int32),
                    np.full((b,), 9, np.int32),
                    0,
                )
            elif kind == "registry_capacity":
                # the registry arrays' row count is part of the indexed
                # gather kernel's jit signature: one small dispatch
                # against a zeros shim at mainnet capacity compiles the
                # 2^20-row gather without holding a million real keys
                import jax
                import numpy as np

                from grandine_tpu.tpu import limbs as L

                zx = jax.device_put(np.zeros((b, L.NLIMBS), np.int32))
                zy = jax.device_put(np.zeros((b, L.NLIMBS), np.int32))
                cap_rows = b

                class _ShimRegistry:
                    @staticmethod
                    def arrays():
                        return zx, zy, cap_rows

                backend.fast_aggregate_verify_batch_indexed(
                    [b"warm-%d" % i for i in range(4)],
                    [sig] * 4,
                    [[0]] * 4,
                    _ShimRegistry(),
                )
            elif kind == "aggregate_comp":
                # compressed-ingest firehose twin: signatures stay raw
                # 96-byte wire rows, decompressed inside the kernel
                backend.fast_aggregate_verify_batch_compressed(
                    [b"warm-%d" % i for i in range(b)],
                    [sig_c] * b,
                    [[pk]] * b,
                )
            elif kind == "aggregate_idx_comp":
                if registry is None or registry.arrays()[0] is None:
                    if progress:
                        progress(
                            f"warm {kind}/{b} skipped: no device registry"
                        )
                    continue
                backend.fast_aggregate_verify_batch_indexed_compressed(
                    [b"warm-%d" % i for i in range(b)],
                    [sig_c] * b,
                    [[0]] * b,
                    registry,
                )
            elif kind == "multi_verify_comp":
                backend.multi_verify_compressed(
                    [b"warm-%d" % i for i in range(b)],
                    [sig_c] * b,
                    [pk] * b,
                )
            elif kind in ("g2_aggregate", "g1_aggregate"):
                # aggregate CONSTRUCTION (duty aggregation, signing
                # plane): the kernel signature is (flat bucket n, group
                # count g) — like rlc_partition, warm every (n, g) split
                # the contiguous-sum dispatch can form at this bucket so
                # slot-time committee mixes never compile
                g = 4
                while b // g >= 4:  # spans below the bucket floor (4)
                    span = b // g   # re-bucket to a different n
                    if kind == "g2_aggregate":
                        B.g2_aggregate_groups(
                            [[sig] * span] * g, metrics
                        )
                    else:
                        B.g1_aggregate_groups(
                            [[pk] * span] * g, metrics
                        )
                    g <<= 1
            elif kind == "g1_decompress":
                # the registry's device decompress runs at append buckets
                # and capacity shapes (tpu/registry.py _decompress_dev) —
                # warm the jit entry directly against dummy rows
                import numpy as np

                rows = np.zeros((b, 48), np.uint8)
                rows[:, 0] = 0xC0  # canonical infinity: valid, neutral
                B.g1_decompress_rows(rows, metrics)
            elif kind == "ed25519_verify":
                # the manifest bucket is the KERNEL batch (point rows
                # m = 1 + 2n for n items, pow-4 ladder): n = b//2 - 1
                # items land exactly on bucket b
                from grandine_tpu.crypto import ed25519 as ED
                from grandine_tpu.runtime.verify_scheduler import (
                    VerifyItem,
                )

                ed_backend = scheme_backends.get("ed25519")
                if ed_backend is None:
                    ed_backend = scheme_backends["ed25519"] = schemes.get(
                        "ed25519"
                    ).make_backend(metrics=metrics)
                ed_sk = b"\x42" * 32
                ed_pk = ED.secret_to_public(ed_sk)
                ed_sig = ED.sign(ed_sk, b"warmup")
                n_items = max(1, b // 2 - 1)
                status, prep = ed_backend.prepare([
                    VerifyItem(b"warmup", ed_sig, public_keys=(ed_pk,))
                ] * n_items)
                if status != "ok":
                    raise RuntimeError(f"ed25519 warm prep: {status}")
                ed_backend.verify_batch_async(prep)()
            elif kind == "kzg_blob":
                # bucket = _bucket(n_blobs, lo=4, hi=8); the kernel
                # shape is blob-width independent (width only sizes the
                # host barycentric prep), so the small dev setup warms
                # the same executable mainnet blobs dispatch to
                from grandine_tpu.kzg import eip4844 as KZ
                from grandine_tpu.kzg.setup import dev_setup
                from grandine_tpu.runtime.verify_scheduler import (
                    VerifyItem,
                )

                kzg_backend = scheme_backends.get("blob_kzg")
                if kzg_backend is None:
                    kzg_backend = scheme_backends["blob_kzg"] = (
                        schemes.get("blob_kzg").make_backend(
                            metrics=metrics
                        )
                    )
                kzg_setup = dev_setup(8)
                blob = b"\x00" * (
                    8 * KZ.BYTES_PER_FIELD_ELEMENT
                )
                commitment = KZ.blob_to_kzg_commitment(blob, kzg_setup)
                proof = KZ.compute_blob_kzg_proof(
                    blob, commitment, kzg_setup
                )
                status, prep = kzg_backend.prepare([
                    VerifyItem(blob, proof, public_keys=(commitment,))
                ] * b)
                if status != "ok":
                    raise RuntimeError(f"kzg warm prep: {status}")
                kzg_backend.verify_blobs_async(prep)()
        except Exception as e:  # a failed warm is a lost optimization only
            if progress:
                progress(f"warm {kind}/{b} FAILED: {e!r}")
            continue
        done += 1
        if progress:
            progress(f"warm {kind}/{b}: {time.time() - t0:.1f}s")
    if seal:
        B.declare_warmup_complete()
        if progress:
            progress(f"warm complete: {done} shapes, ledger sealed")
    return done


def warm_in_background(
    progress: "Optional[Callable[[str], None]]" = None,
    **kwargs,
) -> threading.Thread:
    """Fire the warmer on a daemon thread (overlaps sync at startup)."""
    t = threading.Thread(
        target=warm_all, kwargs={"progress": progress, **kwargs},
        name="kernel-warmup", daemon=True,
    )
    t.start()
    return t


__all__ = ["manifest", "load_manifest", "manifest_file_path",
           "enable_persistent_cache", "warm_all", "warm_in_background",
           "WARM_KINDS", "FIREHOSE_BUCKETS", "MULTI_VERIFY_BUCKETS",
           "SIGN_BUCKETS", "SUBGROUP_BUCKETS"]
