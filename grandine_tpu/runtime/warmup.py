"""Kernel precompile manifest + startup warmer (VERDICT r4 weak #5).

First compiles of the device kernels cost minutes per bucket shape (they
land in the persistent XLA cache afterwards), and an uncompiled bucket
hit mid-chain stalls verification for the whole compile. The warmer walks
the MANIFEST of bucket shapes the node's verification paths actually
form — firehose aggregate buckets, grouped multi-verify buckets, subgroup
checks, batch signing — and runs each kernel once on shape-matched dummy
inputs, in a background thread that overlaps checkpoint sync / backfill
at startup (reference parity goal: blst needs no warmup, so the node must
hide ours).

Compilation depends only on SHAPES; the dummy inputs are valid curve
points with nonsense provenance, so every warm call returns False —
irrelevant, the compile cache is the product.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

#: bucket sizes the firehose/aggregate plane forms (power-of-two padding
#: in TpuBlsBackend._bucket) — the default firehose max_batch is 64;
#: block verify and back-sync form the larger multi-verify buckets.
FIREHOSE_BUCKETS = (4, 8, 16, 32, 64)
MULTI_VERIFY_BUCKETS = (64, 256, 1024, 4096)
SIGN_BUCKETS = (64, 512)
SUBGROUP_BUCKETS = (64, 512)


def manifest() -> "list[tuple[str, int]]":
    out = [("aggregate", b) for b in FIREHOSE_BUCKETS]
    out += [("multi_verify", b) for b in MULTI_VERIFY_BUCKETS]
    out += [("sign", b) for b in SIGN_BUCKETS]
    out += [("subgroup", b) for b in SUBGROUP_BUCKETS]
    return out


def warm_all(
    buckets: "Optional[list[tuple[str, int]]]" = None,
    progress: "Optional[Callable[[str], None]]" = None,
) -> int:
    """Compile-and-run every manifest entry once. Returns the number of
    entries warmed. Call from a background thread at node startup."""
    from grandine_tpu.crypto import bls as A
    from grandine_tpu.crypto.curves import G1
    from grandine_tpu.crypto.hash_to_curve import hash_to_g2
    from grandine_tpu.tpu.bls import TpuBlsBackend

    backend = TpuBlsBackend()
    pk = A.PublicKey(G1)
    h = hash_to_g2(b"warmup")
    sig = A.Signature(h)
    sk = A.SecretKey(0x1234_5678)
    done = 0
    for kind, b in buckets if buckets is not None else manifest():
        t0 = time.time()
        try:
            if kind == "aggregate":
                backend.fast_aggregate_verify_batch(
                    [b"warm-%d" % i for i in range(b)],
                    [sig] * b,
                    [[pk]] * b,
                )
            elif kind == "multi_verify":
                # bm distinct messages x bk signatures each: the grouped
                # kernel's shape (bm = b//8 groups exercises the MSM path)
                n_groups = max(2, b // 8)
                backend.multi_verify(
                    [b"warm-%d" % (i % n_groups) for i in range(b)],
                    [sig] * b,
                    [pk] * b,
                )
            elif kind == "sign":
                backend.batch_sign([b"warm-%d" % i for i in range(b)],
                                   [sk] * b)
            elif kind == "subgroup":
                backend.g2_subgroup_check_batch([h] * b)
        except Exception as e:  # a failed warm is a lost optimization only
            if progress:
                progress(f"warm {kind}/{b} FAILED: {e!r}")
            continue
        done += 1
        if progress:
            progress(f"warm {kind}/{b}: {time.time() - t0:.1f}s")
    return done


def warm_in_background(
    progress: "Optional[Callable[[str], None]]" = None,
) -> threading.Thread:
    """Fire the warmer on a daemon thread (overlaps sync at startup)."""
    t = threading.Thread(
        target=warm_all, kwargs={"progress": progress},
        name="kernel-warmup", daemon=True,
    )
    t.start()
    return t


__all__ = ["manifest", "warm_all", "warm_in_background",
           "FIREHOSE_BUCKETS", "MULTI_VERIFY_BUCKETS"]
