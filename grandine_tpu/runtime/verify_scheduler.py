"""Unified verify scheduler: every signed-object kind funnels into one
multi-lane batch-verification plane — the generalization of the
attestation firehose (runtime/attestation_verifier.py) to sync-committee
messages, contributions, slashings, exits, BLS changes, blob-sidecar
headers, and block proposer signatures.

Shape (reference: fork_choice_control/src/thread_pool.rs's 2-priority
split + p2p/src/attestation_verifier.rs's accumulate→deadline→batch):

  lanes     — each signed-object kind gets a LaneConfig: priority class
              (HIGH: blocks, blob headers, contributions; LOW: sync
              messages, slashings, exits, BLS changes), a flush policy
              (max_batch or max_wait, whichever first), and a bounded
              queue. Under overload LOW lanes shed oldest-first with a
              counted drop (`verify_lane_dropped_total`); HIGH lanes
              backpressure the producer instead — block import is never
              starved by a saturated gossip lane.
  tickets   — `submit` returns a VerifyTicket future; callers wait
              (`result`) or attach a callback. Shed tickets resolve
              False with `dropped=True` so gossip accounting can tell
              "ignored under load" from "rejected as invalid".
  batches   — a dispatcher thread coalesces each lane into ONE padded
              device batch on the fast-aggregate kernels in tpu/bls.py,
              gathering pubkeys on-device via the shared
              DevicePubkeyRegistry when items carry validator indices.
              Dispatch is async (two-deep, like the attestation
              pipeline); a completion thread settles verdicts.
  failure   — a failed batch bisects down to a SingleVerifier-checked
              leaf, quarantining only the bad items; a faulted device
              backend degrades the batch to the eager host path (the
              pre-scheduler behavior) without dropping anything.

`DeferredVerifier` adapts the scheduler to the existing `Verifier` seam
(consensus/verifier.py), so transition/fork-choice code can route block
signature batches through a lane with zero changes.

Schemes: a lane serves ONE verification scheme (`LaneConfig.scheme`),
resolved through the tpu/schemes.py dispatch table — BLS for the
consensus lanes, Ed25519 for execution-layer/non-Ethereum traffic,
blob_kzg for the EIP-4844 sidecar proof check. Backend construction,
device dispatch, the bisection leaf, and the host degradation pass all
route through the table; cross-lane merging only combines same-scheme
lanes.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

from grandine_tpu.consensus.verifier import (
    SignatureInvalid,
    SingleVerifier,
    Verifier,
)
from grandine_tpu.crypto import bls as A
from grandine_tpu.runtime import flight as _flight
from grandine_tpu.runtime import health as _health
from grandine_tpu.runtime import isolation as _isolation
from grandine_tpu.runtime.thread_pool import Priority
from grandine_tpu.tpu import schemes as _schemes
from grandine_tpu.tracing import NULL_TRACER


class LaneConfig:
    """One lane's flush/backpressure policy."""

    __slots__ = ("name", "priority", "max_batch", "max_wait_s",
                 "max_queue", "shed", "scheme")

    def __init__(self, name: str, priority: Priority, max_batch: int,
                 max_wait_s: float, max_queue: int, shed: bool,
                 scheme: str = "bls") -> None:
        self.name = name
        self.priority = priority
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        #: LOW lanes shed oldest-first at max_queue; HIGH lanes block
        #: the submitter (bounded producer) and never drop
        self.shed = bool(shed)
        #: verification scheme served by this lane — a key into the
        #: tpu/schemes.py dispatch table (backend factory, device
        #: dispatch, host twin, kernel label all resolve through it)
        self.scheme = str(scheme)


#: the lane table (README "Verify scheduler" section mirrors this)
DEFAULT_LANES = (
    LaneConfig("block", Priority.HIGH, 64, 0.002, 8192, shed=False),
    LaneConfig("blob_header", Priority.HIGH, 32, 0.005, 4096, shed=False),
    LaneConfig("sync_contribution", Priority.HIGH, 32, 0.025, 4096,
               shed=False),
    LaneConfig("sync_message", Priority.LOW, 128, 0.050, 2048, shed=True),
    LaneConfig("slashing", Priority.LOW, 16, 0.100, 512, shed=True),
    LaneConfig("exit", Priority.LOW, 16, 0.100, 512, shed=True),
    LaneConfig("bls_change", Priority.LOW, 32, 0.100, 1024, shed=True),
    # quarantined-origin traffic: small batches so one forgery poisons
    # little, sheddable so a hostile origin only backpressures itself
    LaneConfig("quarantine", Priority.LOW, 8, 0.050, 512, shed=True),
    # non-BLS schemes (tpu/schemes.py): execution-layer / non-Ethereum
    # Ed25519 traffic and the blob-sidecar KZG-proof gossip check.
    # max_batch 63 keeps the Ed25519 MSM inside the 128-point ladder
    # bucket (2·63+1 = 127); sheddable — a dropped ticket degrades the
    # caller to its host path, it never loses the object.
    LaneConfig("ed25519", Priority.LOW, 63, 0.050, 2048, shed=True,
               scheme="ed25519"),
    LaneConfig("blob_kzg", Priority.LOW, 8, 0.025, 1024, shed=True,
               scheme="blob_kzg"),
)


class VerifyItem:
    """One signature check in fast-aggregate geometry: a 32-byte signing
    root, a 96-byte compressed signature, and the signer set — either
    materialized `public_keys`, or `member_indices` into the state's
    compressed `pubkey_columns` so the device path can gather pubkeys
    from the registry without the host ever decompressing them."""

    __slots__ = ("message", "signature", "public_keys", "member_indices",
                 "pubkey_columns")

    def __init__(self, message: bytes, signature: bytes,
                 public_keys: "Optional[Sequence]" = None,
                 member_indices: "Optional[Sequence[int]]" = None,
                 pubkey_columns=None) -> None:
        self.message = bytes(message)
        self.signature = bytes(signature)
        self.public_keys = (
            tuple(public_keys) if public_keys is not None else None
        )
        self.member_indices = (
            tuple(int(i) for i in member_indices)
            if member_indices is not None else None
        )
        self.pubkey_columns = pubkey_columns

    def resolve_keys(self) -> list:
        """Materialize the signer keys (host fallback / bisection leaf);
        raises SignatureInvalid when the item carries no usable keys."""
        if self.public_keys is not None:
            if not self.public_keys:
                raise SignatureInvalid("aggregate with no public keys")
            return list(self.public_keys)
        if self.member_indices is None or self.pubkey_columns is None:
            raise SignatureInvalid("verify item has no key material")
        if not self.member_indices:
            raise SignatureInvalid("aggregate with no public keys")
        from grandine_tpu.consensus import keys as _keys

        try:
            return [
                _keys.decompress_pubkey(self.pubkey_columns[i], trusted=True)
                for i in self.member_indices
            ]
        except (IndexError, A.BlsError) as e:
            raise SignatureInvalid(f"bad member index/pubkey: {e}") from e


def host_check_item(item: VerifyItem) -> bool:
    """The eager host path — SingleVerifier semantics (full decompression
    + subgroup checks), the bisection leaf and the degradation target."""
    sv = SingleVerifier()
    try:
        resolved = item.resolve_keys()
        if len(resolved) == 1:
            sv.verify_singular(item.message, item.signature, resolved[0])
        else:
            sv.verify_aggregate(item.message, item.signature, resolved)
    except SignatureInvalid:
        return False
    return True


class VerifyTicket:
    """Future handed back by `submit`: resolves True (all the job's items
    verified), or False (some item invalid — or `dropped` when the job
    was shed under overload / at shutdown, so callers can count an
    "ignore" rather than a "reject")."""

    __slots__ = ("lane", "origin", "enqueued_at", "settled_at", "dropped",
                 "deadline", "_ok", "_event", "_callbacks", "_lock")

    def __init__(self, lane: str, origin: "Optional[str]" = None,
                 deadline: "Optional[float]" = None) -> None:
        self.lane = lane
        #: gossip peer / validator attribution ("peer:<id>",
        #: "validator:<index>", …) — a rejected job files it into the
        #: flight recorder's bounded top-K failing-origin table (the
        #: quarantine lane's feed); NEVER a Prometheus label value
        self.origin = origin
        #: absolute monotonic deadline (end-to-end budget, stamped at
        #: submit): past it the ticket sheds BEFORE any device dispatch
        #: is spent on it; None = only the lane's max_wait governs
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.settled_at: "Optional[float]" = None
        self.dropped = False
        # lint: atomic=_ok: _resolve writes it under _lock before
        # _event.set(); readers gate on the Event — happens-before edge
        self._ok = False
        self._event = threading.Event()
        self._callbacks: "list[Callable]" = []
        self._lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def ok(self) -> bool:
        """The settled verdict (False until resolved). Safe bare read:
        _resolve writes _ok before _event.set(), and the advertised
        contract is done()-then-ok."""
        return self._ok

    def result(self, timeout: "Optional[float]" = None) -> bool:
        if not self._event.wait(timeout):
            raise TimeoutError(f"{self.lane} verify ticket not settled")
        # Event.wait() is the happens-before edge for the _ok write
        return self._ok

    def add_callback(self, fn: "Callable[[VerifyTicket], None]") -> None:
        """Run fn(ticket) once settled (immediately if already done)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _resolve(self, ok: bool, dropped: bool = False) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._ok = bool(ok)
            self.dropped = dropped
            self.settled_at = time.monotonic()
            callbacks, self._callbacks = self._callbacks, []
            self._event.set()
        for fn in callbacks:
            try:
                fn(self)
            except Exception:
                pass  # a consumer's callback must not break settling


class _Job:
    __slots__ = ("items", "ticket")

    def __init__(self, items, ticket) -> None:
        self.items = tuple(items)
        self.ticket = ticket


class VerifyScheduler:
    """The central lane scheduler: submit → coalesce → device batch →
    settle. One dispatcher thread forms batches (HIGH-priority lanes flush
    first among due lanes); a completion thread forces async device
    verdicts so dispatch overlaps execution, two deep."""

    def __init__(
        self,
        backend=None,
        registry=None,
        lanes: "Optional[Sequence[LaneConfig]]" = None,
        use_device: bool = True,
        pipeline_depth: int = 2,
        metrics=None,
        tracer=None,
        health: "Optional[_health.BackendHealthSupervisor]" = None,
        settle_timeout_s: float = 5.0,
        flight: "Optional[_flight.FlightRecorder]" = None,
        mesh=None,
        reputation: "Optional[_isolation.ReputationTable]" = None,
        use_isolation: bool = True,
        merge_window_s: float = 0.0,
        merge_max_items: int = 128,
        deadline_margin_s: float = 0.05,
    ) -> None:
        from grandine_tpu.tpu.mesh import mesh_or_none

        self.metrics = metrics
        self.tracer = tracer or NULL_TRACER
        self.use_device = use_device
        #: cross-lane batch merging: when > 0, a due lane's flush also
        #: collects other lanes whose head deadline falls within the
        #: window, collapsing them into ONE RLC dispatch (one Miller
        #: loop, one final exp) with per-lane verdict slices and
        #: per-lane flight records. 0 disables (per-lane batches only).
        #: The quarantine lane never merges — either side — so forgeries
        #: cannot share a batch (nor a localization descent) with
        #: honest traffic.
        self.merge_window_s = float(merge_window_s)
        #: cap on a merged dispatch's total items, keeping merged
        #: batches inside the pow-2 buckets the warmup manifest compiled
        self.merge_max_items = int(merge_max_items)
        #: brownout plane (runtime/brownout.py pokes these, always as
        #: whole-object frozenset swaps — a torn read sees either the
        #: old or the new set): lanes routed to the host twin at B3 so
        #: the device serves HIGH only, and lanes whose submits resolve
        #: dropped at the door under CRITICAL
        self.brownout_route_host: "frozenset[str]" = frozenset()
        self.brownout_shed_lanes: "frozenset[str]" = frozenset()
        #: safety margin subtracted from a ticket's absolute deadline
        #: when computing its effective flush due-time, so a near-
        #: deadline head still has a chance to dispatch AND settle
        self.deadline_margin_s = float(deadline_margin_s)
        #: injected VerifyMesh (tpu/mesh.py) threaded into every per-lane
        #: backend; None / 1-device collapses to the single-chip plane
        self.mesh = mesh_or_none(mesh)
        #: flight recorder — always-on (a private ring when none is
        #: injected; node.py shares one across the whole verify plane)
        self.flight = (
            flight if flight is not None
            else _flight.FlightRecorder(metrics=metrics)
        )
        #: breaker + settle watchdog + canary gating; node.py shares one
        #: supervisor with the attestation pipeline so a fault on either
        #: plane quarantines the device for both
        self.health = (
            health if health is not None
            else _health.BackendHealthSupervisor(
                metrics=metrics, settle_timeout_s=settle_timeout_s,
                flight=self.flight,
            )
        )
        if self.health.flight is None:
            # an injected supervisor without its own recorder joins this
            # scheduler's timeline (breaker + canary events interleave
            # with the batches that provoked them)
            self.health.flight = self.flight
            self.health.breaker.flight = self.flight
        #: a shared injected backend (tests: fault injection) or one
        #: lazily-built TpuBlsBackend per lane, so device stage spans
        #: attribute to the dispatching lane (kernels stay shared via
        #: the global jit cache)
        #: decaying per-origin quarantine state (runtime/isolation.py);
        #: node.py shares one table between scheduler and gossip plane
        self.reputation = (
            reputation if reputation is not None
            else _isolation.ReputationTable()
        )
        #: on-device fault localization of failed batches; None reverts
        #: _isolate to the legacy host bisection (--no-isolation knob)
        self._localizer = (
            # host_check unset → the localizer resolves this module's
            # host_check_item per call, so monkeypatched truth tables
            # reach the leaves the same way they reach _bisect
            _isolation.FaultLocalizer(health=self.health, metrics=metrics)
            if use_isolation else None
        )
        self._shared_backend = backend
        self._backends: dict = {}
        self._backend_lock = threading.Lock()  # lazy per-lane build
        self.registry = registry
        self.lanes = {l.name: l for l in (lanes or DEFAULT_LANES)}
        self._queues = {n: deque() for n in self.lanes}
        self._item_counts = {n: 0 for n in self.lanes}
        self._cond = threading.Condition()
        self._stop = False
        self._pending = 0  # submitted jobs not yet settled (flush barrier)
        self.stats = {
            n: {
                "submitted": 0, "batches": 0, "accepted": 0,
                "rejected": 0, "shed": 0, "device_faults": 0,
                "breaker_skips": 0, "retries": 0,
                "max_batch_items": 0, "merged": 0,
            }
            for n in self.lanes
        }
        #: guards every `stats` counter bump — the caller (submit/shed),
        #: dispatcher, settle, and watchdog threads all mutate them
        self._stats_lock = threading.Lock()

        self.pipeline_depth = max(1, int(pipeline_depth))
        self._sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._completion: "queue.Queue" = queue.Queue()
        # construct BOTH threads before starting either: a started
        # thread must never observe a half-initialized scheduler
        self._completion_thread = threading.Thread(
            target=self._complete, name="verify-settle", daemon=True
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="verify-scheduler", daemon=True
        )
        self._completion_thread.start()
        self._dispatcher.start()

    # ------------------------------------------------------------ submit

    def submit(self, lane_name: str, items: "Sequence[VerifyItem]",
               callback=None, origin: "Optional[str]" = None,
               deadline: "Optional[float]" = None,
               deadline_s: "Optional[float]" = None) -> VerifyTicket:
        """Queue one job (all `items` must verify for the ticket to
        resolve True). Returns immediately; LOW lanes shed oldest-first
        at capacity, HIGH lanes block the caller until there is room.
        `origin` attributes a rejected job to its gossip peer/validator
        in the flight recorder's failing-origin table.

        `deadline` (absolute monotonic) or `deadline_s` (relative to
        now) stamps an end-to-end budget on the ticket: past it the job
        sheds before any device dispatch is spent on it, and a near-
        deadline head preempts max_wait/merge-window batching.

        A quarantined origin's SHEDDABLE traffic is rerouted into the
        small-batch quarantine lane so it never shares a batch (nor a
        localization descent) with honest traffic; HIGH lanes are never
        rerouted — block import correctness beats isolation."""
        lane = self.lanes[lane_name]
        # feed the failure-rate denominator: admission quotas trust an
        # origin by its attributed-failure RATE, which needs the
        # submission count alongside _deliver's failure count
        self.reputation.note_submitted(origin)
        if (
            origin is not None and lane.shed
            and lane_name != "quarantine" and "quarantine" in self.lanes
            and self.reputation.is_quarantined(origin)
        ):
            lane_name = "quarantine"
            lane = self.lanes[lane_name]
        if deadline is None and deadline_s is not None:
            deadline = time.monotonic() + float(deadline_s)
        ticket = VerifyTicket(lane_name, origin=origin, deadline=deadline)
        if callback is not None:
            ticket.add_callback(callback)
        if lane.shed and lane_name in self.brownout_shed_lanes:
            # CRITICAL brownout: sheddable lanes drop at the door, with
            # full accounting — HIGH lanes (shed=False) never take this
            # path, the device keeps serving them
            with self._stats_lock:
                self.stats[lane_name]["submitted"] += 1
            self._count_shed(lane_name)
            self.flight.record_shed(lane_name, len(items), "brownout")
            ticket._resolve(False, dropped=True)
            return ticket
        job = _Job(items, ticket)
        shed: "list[_Job]" = []
        with self._cond:
            if self._stop:
                ticket._resolve(False, dropped=True)
                return ticket
            q = self._queues[lane_name]
            if lane.shed:
                while len(q) >= lane.max_queue:
                    old = q.popleft()
                    self._item_counts[lane_name] -= len(old.items)
                    self._pending -= 1
                    shed.append(old)
            else:
                while len(q) >= lane.max_queue and not self._stop:
                    self._cond.wait(0.05)
                if self._stop:
                    ticket._resolve(False, dropped=True)
                    return ticket
            q.append(job)
            self._item_counts[lane_name] += len(job.items)
            self._pending += 1
            with self._stats_lock:
                self.stats[lane_name]["submitted"] += 1
            self._set_depth(lane_name)
            self._cond.notify_all()
        for old in shed:
            self._count_shed(lane_name)
            # shed-oldest is the overload-control valve: the timeline
            # attributes it to the brownout plane at whatever level is
            # in force (level "normal" = plain pre-controller overflow)
            self.flight.record_shed(lane_name, len(old.items), "brownout")
            old.ticket._resolve(False, dropped=True)
        return ticket

    def deferred(self, lane: str = "block",
                 timeout: float = 30.0) -> "DeferredVerifier":
        return DeferredVerifier(self, lane=lane, timeout=timeout)

    def verifier_factory(self, lane: str = "block", timeout: float = 30.0):
        """A `Controller(verifier_factory=...)`-shaped callable routing
        block signature batches through `lane`."""
        return lambda: DeferredVerifier(self, lane=lane, timeout=timeout)

    # -------------------------------------------------------- dispatcher

    def _effective_due(self, ticket: VerifyTicket,
                       lane: LaneConfig) -> float:
        """When a lane's head must flush: the lane's max_wait, or —
        when the ticket carries an absolute deadline budget — early
        enough (deadline minus the dispatch/settle margin) that a
        near-deadline head preempts max_wait/merge-window batching."""
        due = ticket.enqueued_at + lane.max_wait_s
        if ticket.deadline is not None:
            due = min(due, ticket.deadline - self.deadline_margin_s)
        return due

    def _pick_lane(self, now: float) -> "Optional[str]":
        """The due lane to flush next: full (max_batch) or overdue
        (past its head's effective due-time); HIGH priority wins, then
        the most-overdue lane."""
        best, best_key = None, None
        for name, lane in self.lanes.items():
            q = self._queues[name]
            if not q:
                continue
            overdue = now - self._effective_due(q[0].ticket, lane)
            if self._item_counts[name] >= lane.max_batch or overdue >= 0:
                key = (int(lane.priority), -overdue)
                if best_key is None or key < best_key:
                    best, best_key = name, key
        return best

    def _nearest_deadline(self, now: float) -> "Optional[float]":
        soonest = None
        for name, lane in self.lanes.items():
            q = self._queues[name]
            if not q:
                continue
            wait = self._effective_due(q[0].ticket, lane) - now
            if soonest is None or wait < soonest:
                soonest = wait
        if soonest is None:
            return None
        return max(soonest, 0.0)

    def _pop_batch(self, lane: LaneConfig, cap: "Optional[int]" = None,
                   allow_oversize: bool = True) -> "list[_Job]":
        q = self._queues[lane.name]
        jobs, n_items = [], 0
        limit = lane.max_batch if cap is None else min(lane.max_batch, cap)
        # peek before popping: taking a job that would push the batch
        # past max_batch overflows into the NEXT pow-2 device bucket —
        # a shape outside the warmed manifest, i.e. a mid-slot XLA
        # recompile. An oversized single job still goes alone (the
        # backend chunks it) — except under a merge cap, where it stays
        # queued for its own flush instead.
        while q and n_items + len(q[0].items) <= limit:
            jobs.append(q.popleft())
            n_items += len(jobs[-1].items)
        if q and not jobs and allow_oversize:
            jobs.append(q.popleft())
            n_items += len(jobs[-1].items)
        self._item_counts[lane.name] -= n_items
        self._set_depth(lane.name)
        return jobs

    def _collect_merge(self, primary: LaneConfig, n_primary: int,
                       now: float) -> "list[tuple]":
        """Cross-lane batch merging (runs under _cond, dispatcher thread
        only): other non-quarantine lanes whose OLDEST job's deadline
        falls inside the merge window join the primary lane's dispatch —
        their Miller loops and the shared final exponentiation ride one
        device pass instead of flushing separately moments later.
        Returns [(lane, jobs), ...]; per-lane verdict slices and flight
        records are preserved downstream (_deliver_segments)."""
        merged: "list[tuple]" = []
        if self.merge_window_s <= 0 or primary.name == "quarantine":
            return merged
        room = self.merge_max_items - n_primary
        for name, lane in self.lanes.items():
            if room <= 0:
                break
            if name == primary.name or name == "quarantine":
                continue
            # cross-SCHEME merging is meaningless: the batches run on
            # different kernels (an Ed25519 lane cannot ride a BLS RLC
            # dispatch) — only same-scheme lanes share a device pass
            if lane.scheme != primary.scheme:
                continue
            # a brownout-routed lane runs on the host twin: merging it
            # into a device dispatch would defeat the routing
            if name in self.brownout_route_host:
                continue
            q = self._queues[name]
            if not q:
                continue
            deadline = self._effective_due(q[0].ticket, lane)
            if deadline > now + self.merge_window_s:
                continue
            jobs = self._pop_batch(lane, cap=room, allow_oversize=False)
            if jobs:
                merged.append((lane, jobs))
                room -= sum(len(j.items) for j in jobs)
        return merged

    def _dispatch_loop(self) -> None:
        """Runs ONLY on the dispatcher thread: owns lane queues (under
        _cond), batch formation, and device dispatch."""
        while True:
            # crash containment: one poisoned batch must not kill the
            # dispatcher — resolve its tickets dropped, account the
            # failure, keep scheduling (thread-crash-containment rule)
            jobs: "list[_Job]" = []
            merged: "list[tuple]" = []
            try:
                with self._cond:
                    while not self._stop:
                        name = self._pick_lane(time.monotonic())
                        if name is not None:
                            break
                        self._cond.wait(
                            self._nearest_deadline(time.monotonic())
                        )
                    if self._stop:
                        # drain: everything still queued resolves
                        # dropped=True — no result() caller hangs to its
                        # full timeout during shutdown, and no verify
                        # work runs against torn-down state
                        to_drop = []
                        for lname in self.lanes:
                            q = self._queues[lname]
                            to_drop.extend(q)
                            q.clear()
                            self._item_counts[lname] = 0
                            self._set_depth(lname)
                    else:
                        to_drop = None
                        lane = self.lanes[name]
                        jobs = self._pop_batch(lane)
                        if jobs:
                            merged = self._collect_merge(
                                lane,
                                sum(len(j.items) for j in jobs),
                                time.monotonic(),
                            )
                        # wake HIGH-lane submitters blocked on a full
                        # queue
                        self._cond.notify_all()
                # decide from the state observed UNDER the lock:
                # re-reading self._stop bare here could see a stop()
                # that landed after the lock was released, with
                # `to_drop` never built
                if to_drop is not None:
                    # tickets resolve outside _cond: a resolve callback
                    # may re-enter the scheduler
                    for job in to_drop:
                        job.ticket._resolve(False, dropped=True)
                    with self._cond:
                        self._pending -= len(to_drop)
                        self._cond.notify_all()
                    return
                if jobs:
                    self._flush(lane, jobs, merged)
            except Exception:
                self._count_daemon_failure("verify-scheduler")
                self._abandon_jobs(
                    jobs + [j for _, mjobs in merged for j in mjobs]
                )

    def _abandon_jobs(self, jobs: "list[_Job]") -> None:
        """Containment cleanup: resolve a failed batch's unsettled
        tickets dropped and release their flush barrier."""
        undelivered = [j for j in jobs if not j.ticket.done()]
        for job in undelivered:
            job.ticket._resolve(False, dropped=True)
        if undelivered:
            with self._cond:
                self._pending -= len(undelivered)
                self._cond.notify_all()

    # ------------------------------------------------------------- flush

    @contextmanager
    def _stage(self, lane: LaneConfig, stage: str, **attrs):
        """PR-1 stage-span vocabulary, lane-attributed."""
        t0 = time.perf_counter()
        with self.tracer.span(stage, attrs or None):
            yield
        if self.metrics is not None:
            self.metrics.verify_stage_seconds.labels(
                stage, lane.name
            ).observe(time.perf_counter() - t0)

    def _set_depth(self, lane_name: str) -> None:
        if self.metrics is not None:
            depth = len(self._queues[lane_name])
            self.metrics.verify_lane_depth.labels(lane_name).set(depth)
            if lane_name == "quarantine":
                self.metrics.verify_quarantine_lane_depth.set(depth)

    def _count_batch(self, lane: LaneConfig, result: str) -> None:
        if self.metrics is not None:
            self.metrics.verify_lane_batches.labels(lane.name, result).inc()

    def _count_shed(self, lane_name: str) -> None:
        with self._stats_lock:
            self.stats[lane_name]["shed"] += 1
        if self.metrics is not None:
            self.metrics.verify_lane_dropped.labels(lane_name).inc()

    def _count_watchdog(self, lane_name: str) -> None:
        if self.metrics is not None:
            self.metrics.verify_watchdog_fired.inc(lane_name)

    def _count_retry(self, lane_name: str) -> None:
        if self.metrics is not None:
            self.metrics.verify_retry.inc(lane_name)

    def _count_daemon_failure(self, thread: str) -> None:
        if self.metrics is not None:
            self.metrics.daemon_loop_failures.inc(thread)

    def _scheme_for(self, lane: LaneConfig) -> "_schemes.Scheme":
        return _schemes.get(getattr(lane, "scheme", "bls"))

    def _backend_for(self, lane: LaneConfig):
        if self._shared_backend is not None:
            return self._shared_backend
        # dispatcher AND settle-thread bisection both build lazily; the
        # lock keeps the per-lane backend a singleton (no double compile
        # cache, no torn publication)
        with self._backend_lock:
            backend = self._backends.get(lane.name)
            if backend is None:
                scheme = self._scheme_for(lane)
                backend = self._backends[lane.name] = scheme.make_backend(
                    metrics=self.metrics, tracer=self.tracer, lane=lane.name,
                    mesh=self.mesh,
                )
                # the first real canary-capable backend also answers
                # probes for HALF_OPEN re-promotion (injected backends
                # keep whatever probe the caller wired — tests drive
                # their own canaries)
                if scheme.canary:
                    self.health.ensure_probe(_health.make_canary_probe(
                        backend, timeout_s=self.health.settle_timeout_s
                    ))
        return backend

    def _retry_dispatch(self, lane: LaneConfig, items, fl=None):
        """Bounded transient retry: ONE immediate re-dispatch after a
        dispatch/settle fault, breaker permitting. The retry's faults
        feed the breaker but not the per-lane `device_faults` stat (the
        batch's first failure already counted)."""
        if not self.health.allow_device():
            return None
        with self._stats_lock:
            self.stats[lane.name]["retries"] += 1
        self._count_retry(lane.name)
        if fl is not None:
            fl.note_retry()
        t0 = time.perf_counter()
        try:
            return self._device_dispatch(lane, items)
        except Exception:
            self.health.record_fault("dispatch")
            if fl is not None:
                fl.note_fault("dispatch")
            return None
        finally:
            if fl is not None:
                fl.note_device(time.perf_counter() - t0)

    def _shed_expired(self, lane: LaneConfig, jobs: "list[_Job]") -> None:
        """Deadline-budget enforcement: jobs whose absolute deadline
        already passed resolve dropped BEFORE the batch spends a device
        dispatch on them; the shed lands on the flight timeline with
        cause="expired" and the in-force brownout level stamped on."""
        n_items = sum(len(j.items) for j in jobs)
        for job in jobs:
            self._count_shed(lane.name)
            if self.metrics is not None:
                self.metrics.verify_expired.inc(lane.name)
            job.ticket._resolve(False, dropped=True)
        self.flight.record_shed(lane.name, n_items, "expired")
        with self._cond:
            self._pending -= len(jobs)
            self._cond.notify_all()

    def _flush(self, lane: LaneConfig, jobs: "list[_Job]",
               merged: "list[tuple]" = ()) -> None:
        now = time.monotonic()
        # deadline-budget gate: already-expired jobs shed here, before
        # the batch spends a device dispatch (or a host pass) on them.
        # Merged lanes are same-scheme, so any surviving segment can be
        # promoted to primary when the original primary fully expired.
        live_pairs: "list[tuple]" = []
        for seg_lane, seg_jobs in [(lane, jobs)] + list(merged):
            live, expired = [], []
            for j in seg_jobs:
                t = j.ticket.deadline
                (expired if (t is not None and now >= t) else live).append(j)
            if expired:
                self._shed_expired(seg_lane, expired)
            if live:
                live_pairs.append((seg_lane, live))
        if not live_pairs:
            return
        (lane, jobs), merged = live_pairs[0], live_pairs[1:]
        # segments: the primary lane's batch first, then any merged
        # lanes' batches. Each keeps its own flight record so per-lane
        # SLO/failure attribution survives the shared device pass.
        segments = []
        for seg_lane, seg_jobs in [(lane, jobs)] + list(merged):
            seg_items = [it for j in seg_jobs for it in j.items]
            if self.metrics is not None:
                waits = self.metrics.verify_lane_wait_seconds.labels(
                    seg_lane.name
                )
                for j in seg_jobs:
                    waits.observe(now - j.ticket.enqueued_at)
            with self._stats_lock:
                st = self.stats[seg_lane.name]
                st["batches"] += 1
                st["max_batch_items"] = max(
                    st["max_batch_items"], len(seg_items)
                )
                if merged:
                    st["merged"] += 1
            # jobs pop FIFO, so jobs[0] is the oldest: its wait is the
            # batch's queue_wait component for SLO attribution
            seg_fl = self.flight.begin_batch(
                seg_lane.name, "", len(seg_items),
                queue_wait_s=now - seg_jobs[0].ticket.enqueued_at,
                breaker_state=self.health.state if self.use_device else "",
                devices=(
                    self.mesh.device_count if self.mesh is not None else 1
                ),
                quarantined=(seg_lane.name == "quarantine"),
            )
            if seg_lane.name == "quarantine" and self.metrics is not None:
                self.metrics.verify_quarantine_batches.inc()
            segments.append((seg_lane, seg_jobs, seg_items, seg_fl))
        items = [it for _, _, seg_items, _ in segments for it in seg_items]
        fl = segments[0][3]
        with self._stats_lock:
            st = self.stats[lane.name]
        settle = None
        device_allowed = False
        with self.tracer.span(
            "verify_lane_flush",
            {"lane": lane.name, "jobs": len(jobs), "items": len(items)},
        ):
            if self.use_device:
                if lane.name in self.brownout_route_host:
                    # B3 brownout routing: this lane runs on the host
                    # twin so the device serves HIGH lanes only — this
                    # is policy, not a fault, so no breaker accounting
                    pass
                elif not (device_allowed := self.health.allow_device()):
                    # breaker OPEN: no per-batch device fault tax —
                    # straight to the host path, zero dispatch attempts
                    with self._stats_lock:
                        st["breaker_skips"] += 1
                else:
                    t0 = time.perf_counter()
                    try:
                        settle = self._device_dispatch(lane, items)
                        fl.note_device(time.perf_counter() - t0)
                    except Exception:
                        fl.note_device(time.perf_counter() - t0)
                        with self._stats_lock:
                            st["device_faults"] += 1
                        fl.note_fault("dispatch")
                        self.health.record_fault("dispatch")
                        # bounded transient retry: one immediate
                        # re-dispatch before paying a full host pass
                        settle = self._retry_dispatch(lane, items, fl)
            if settle is None:
                # graceful degradation: brownout host routing, breaker-
                # open, no device/async seam, or a faulted dispatch →
                # the eager host path
                if self.use_device:
                    routed = lane.name in self.brownout_route_host
                    for seg_lane, _, _, _ in segments:
                        self._count_batch(
                            seg_lane,
                            "degraded" if device_allowed
                            else ("brownout" if routed else "breaker_open"),
                        )
                t0 = time.perf_counter()
                verdicts = self._host_check_all(lane, items)
                fl.note_host(time.perf_counter() - t0)
                if not self.use_device:
                    i = 0
                    for seg_lane, _, seg_items, _ in segments:
                        seg_v = verdicts[i:i + len(seg_items)]
                        i += len(seg_items)
                        self._count_batch(
                            seg_lane, "ok" if all(seg_v) else "invalid"
                        )
                self._deliver_segments(segments, verdicts)
                return
            ctx = self.tracer.capture()
        backend = self._backend_for(lane)
        kernel = self._scheme_for(lane).kernel_label(backend)
        for _, _, _, seg_fl in segments:
            seg_fl.record.kernel = kernel
        # two-deep pipelined handoff (backpressure bounds device
        # residency); the slot is released on the settle thread in
        # _complete's finally, so a `with` cannot express it
        self._sem.acquire()  # lint: disable=thread-affinity
        self.flight.device_enter()
        self._completion.put((lane, segments, items, settle, ctx, fl))

    def _device_dispatch(self, lane: LaneConfig, items):
        """Host prep + async device dispatch of one coalesced batch;
        returns a zero-arg settle callable (the batch verdict) or None
        when no async device seam is available. The per-scheme body
        lives in the tpu/schemes.py dispatch table (`_dispatch_bls` is
        the former body of this method, moved verbatim); this method is
        only the lane → scheme route."""
        return self._scheme_for(lane).device_dispatch(
            self, lane, self._backend_for(lane), items
        )

    def _sync_registry(self, lane: LaneConfig, items):
        """The shared device pubkey registry, brought up to date against
        the batch's pubkey columns (identity hit when unchanged); None →
        indexed items fall back to host key resolution + upload path."""
        registry = self.registry
        if registry is None:
            return None
        cols = next(
            (it.pubkey_columns for it in items
             if it.member_indices is not None
             and it.pubkey_columns is not None),
            None,
        )
        if cols is None:
            return None
        try:
            with self._stage(lane, "host_prep", op="registry_sync"):
                if registry.ensure(cols):
                    return registry
        except A.BlsError:
            pass
        return None

    # ------------------------------------------------------------ settle

    def _complete(self) -> None:
        """Runs ONLY on the completion thread: forces device verdicts in
        dispatch order, settles tickets, releases the pipeline slot."""
        while True:
            entry = self._completion.get()
            if entry is None:
                return
            lane, segments, items, settle, ctx, fl = entry
            try:
                with self.tracer.attach(ctx):
                    self._settle_batch(lane, segments, items, settle, fl)
            except Exception:
                # the settle thread must survive anything; no ticket may
                # hang — degrade the whole batch to the host path
                try:
                    self._deliver_segments(
                        segments, self._host_check_all(lane, items)
                    )
                except Exception:
                    for _, seg_jobs, _, _ in segments:
                        for j in seg_jobs:
                            j.ticket._resolve(False, dropped=True)
                for _, _, _, seg_fl in segments:
                    seg_fl.finish(None)
            finally:
                self.flight.device_exit()
                self._sem.release()

    def _guarded_settle(self, lane: LaneConfig, settle, fl=None,
                        count_stats: bool = True) -> "_health.SettleOutcome":
        """One watchdog-bounded settle with breaker accounting: OK
        records a success; a fault or watchdog expiry files the breaker
        fault (and, for the batch's FIRST failure, the per-lane stat)."""
        t0 = time.perf_counter()
        outcome = self.health.guard_settle(settle)
        if fl is not None:
            fl.note_device(time.perf_counter() - t0)
        if outcome.status == _health.OK:
            self.health.record_success()
            return outcome
        if outcome.status == _health.TIMEOUT:
            # abandon the hung settle: its daemon thread is expendable,
            # the pipeline slot is released by _complete's finally
            self._count_watchdog(lane.name)
            self.health.record_fault("watchdog")
            if fl is not None:
                fl.note_fault("watchdog")
        else:
            self.health.record_fault("settle")
            if fl is not None:
                fl.note_fault("settle")
        if count_stats:
            with self._stats_lock:
                self.stats[lane.name]["device_faults"] += 1
        return outcome

    def _settle_batch(self, lane, segments, items, settle,
                      fl=None) -> None:
        if fl is None:
            fl = self.flight.begin_batch(lane.name, "", len(items))
        outcome = self._guarded_settle(lane, settle, fl)
        if outcome.status == _health.FAULT:
            # fast fault: one bounded re-dispatch before degrading. A
            # TIMEOUT never retries — the ticket already spent its
            # watchdog budget, the host pass must start now.
            retry = self._retry_dispatch(lane, items, fl)
            if retry is not None:
                outcome = self._guarded_settle(lane, retry, fl,
                                               count_stats=False)
        if outcome.status != _health.OK:
            for seg_lane, _, _, _ in segments:
                self._count_batch(seg_lane, "degraded")
            t0 = time.perf_counter()
            verdicts = self._host_check_all(lane, items)
            fl.note_host(time.perf_counter() - t0)
            self._deliver_segments(segments, verdicts)
            return
        if bool(outcome.value):
            for seg_lane, _, _, _ in segments:
                self._count_batch(seg_lane, "ok")
            self._deliver_segments(segments, [True] * len(items))
            return
        with self._stage(lane, "fallback", items=len(items)):
            # the bisection shares ONE watchdog budget so a failed
            # batch still meets the deadline + one-host-pass bound
            deadline = time.monotonic() + self.health.settle_timeout_s
            t0 = time.perf_counter()
            verdicts = self._isolate(lane, list(items), deadline, fl)
            fl.note_bisect(time.perf_counter() - t0)
        if verdicts and all(verdicts):
            # device said "invalid", host verified every item: a
            # wrong-verdict device — the fault kind only canary probes
            # catch at re-promotion time
            self.health.record_fault("verdict")
            fl.note_fault("verdict")
        i = 0
        for seg_lane, _, seg_items, _ in segments:
            seg_v = verdicts[i:i + len(seg_items)]
            i += len(seg_items)
            self._count_batch(seg_lane, "ok" if all(seg_v) else "invalid")
        self._deliver_segments(segments, verdicts)

    def _isolate(self, lane: LaneConfig, items,
                 deadline: "Optional[float]" = None,
                 fl=None) -> "list[bool]":
        """Per-item verdicts for a failed batch. Preferred path: the
        on-device fault localizer (runtime/isolation.py) — O(log n)
        RLC-partition passes, host work bounded by named-bad leaves.
        Fallback (no localizer, no partition seam, breaker open): the
        legacy recursive host bisection."""
        if (
            self._localizer is not None and self.use_device
            # the RLC-partition localizer is a BLS seam (its host leaves
            # are SingleVerifier semantics); other schemes bisect, with
            # their own host twin at the leaf
            and self._scheme_for(lane).name == "bls"
            and self.health.allow_device()
        ):
            backend = self._backend_for(lane)
            if _isolation.FaultLocalizer.supports(backend):
                return self._localizer.localize(
                    backend, items, deadline=deadline, fl=fl
                )
        return self._bisect(lane, items, deadline, fl, 1)

    def _bisect(self, lane: LaneConfig, items,
                deadline: "Optional[float]" = None, fl=None,
                depth: int = 1) -> "list[bool]":
        """Recursive bisection of a failed batch — batch-check halves,
        descend only into failing halves, SingleVerifier at the leaf —
        so k bad items cost O(k·log n) checks, not n."""
        if fl is not None:
            fl.note_bisect(0.0, depth)
        if len(items) == 1:
            return [self._scheme_for(lane).host_check(items[0])]
        mid = len(items) // 2
        out: "list[bool]" = []
        for half in (items[:mid], items[mid:]):
            try:
                ok = self._batch_check(lane, half, deadline)
            except Exception:
                with self._stats_lock:
                    self.stats[lane.name]["device_faults"] += 1
                ok = False  # descend; leaves verify on the host
            out.extend(
                [True] * len(half)
                if ok else self._bisect(lane, half, deadline, fl, depth + 1)
            )
        return out

    def _batch_check(self, lane: LaneConfig, items,
                     deadline: "Optional[float]" = None) -> bool:
        """Bisection probe of one half: device when the breaker allows
        and the shared time budget has room, host otherwise."""
        if self.use_device and self.health.allow_device():
            budget = self.health.settle_timeout_s
            if deadline is not None:
                budget = min(budget, deadline - time.monotonic())
            if budget > 0:
                try:
                    settle = self._device_dispatch(lane, items)
                except Exception:
                    self.health.record_fault("dispatch")
                    raise
                if settle is not None:
                    outcome = self.health.guard_settle(
                        settle, timeout_s=budget
                    )
                    if outcome.status == _health.OK:
                        self.health.record_success()
                        return bool(outcome.value)
                    if outcome.status == _health.TIMEOUT:
                        self._count_watchdog(lane.name)
                        self.health.record_fault("watchdog")
                    else:
                        self.health.record_fault("settle")
                    # fall through: host verdict for this half
        hc = self._scheme_for(lane).host_check
        return all(hc(it) for it in items)

    def _host_check_all(self, lane: LaneConfig, items) -> "list[bool]":
        hc = self._scheme_for(lane).host_check
        with self._stage(lane, "execute", path="host", items=len(items)):
            return [hc(it) for it in items]

    def _deliver_segments(self, segments, verdicts) -> None:
        """Slice one merged dispatch's verdict vector back into its
        per-lane segments: each lane's jobs settle against its own
        slice and its own flight record — attribution is never blurred
        by the shared device pass."""
        i = 0
        for seg_lane, seg_jobs, seg_items, seg_fl in segments:
            seg_v = verdicts[i:i + len(seg_items)]
            i += len(seg_items)
            self._deliver(seg_lane, seg_jobs, seg_v)
            seg_fl.finish(all(seg_v))

    def _deliver(self, lane: LaneConfig, jobs, verdicts) -> None:
        i = 0
        for job in jobs:
            n = len(job.items)
            ok = all(verdicts[i:i + n])
            i += n
            with self._stats_lock:
                self.stats[lane.name]["accepted" if ok else "rejected"] += 1
            if not ok and job.ticket.origin is not None:
                # localization named this job's items bad: attribute the
                # failure to its gossip origin (bounded top-K table) and
                # quarantine it
                self.flight.note_origin_failure(job.ticket.origin)
                self.reputation.note_failure(job.ticket.origin)
            elif (
                ok and lane.name == "quarantine"
                and job.ticket.origin is not None
            ):
                # a clean quarantine batch steps the origin toward exit
                self.reputation.note_clean_batch(job.ticket.origin)
            job.ticket._resolve(ok)
        with self._cond:
            self._pending -= len(jobs)
            self._cond.notify_all()

    # ----------------------------------------------------------- control

    def device_degraded(self) -> bool:
        """True while the device plane is quarantined (breaker not
        CLOSED) — lets gossip shed accounting (p2p/network.py) tell
        overload-under-degradation from plain overload."""
        return self.use_device and self.health.state != _health.CLOSED

    def lane_pressure(self) -> "dict[str, float]":
        """Queue fullness per lane (queued jobs over max_queue) — the
        brownout controller's depth feed, read under _cond so the
        snapshot is coherent with in-flight shed decisions."""
        with self._cond:
            return {
                n: (len(self._queues[n]) / lane.max_queue
                    if lane.max_queue else 0.0)
                for n, lane in self.lanes.items()
            }

    def flush(self, timeout: float = 30.0) -> None:
        """Test barrier: wait until every submitted job has settled.
        Condition-variable wait, no polling: every _pending decrement
        (_deliver, stop-drain, containment) notifies _cond."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._cond.notify_all()  # nudge the dispatcher awake
            while self._pending != 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("verify scheduler did not drain")
                self._cond.wait(remaining)

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._dispatcher.join(timeout=10)
        # sentinel queues BEHIND pending settles so they drain first
        self._completion.put(None)
        self._completion_thread.join(timeout=10)


class DeferredVerifier(Verifier):
    """The `Verifier`-seam adapter: accumulate items, then `finish()`
    submits ONE job to the configured lane and waits the ticket
    (`finish_async` returns the zero-arg settle, preserving the
    verify-∥-process overlap). Aggregates keep their signer sets so the
    device kernel — not the host — does the key aggregation."""

    def __init__(self, scheduler: VerifyScheduler, lane: str = "block",
                 timeout: float = 30.0) -> None:
        self.scheduler = scheduler
        self.lane = lane
        self.timeout = timeout
        self.items: "list[VerifyItem]" = []

    def verify_singular(self, message, signature, public_key) -> None:
        self.items.append(
            VerifyItem(message, signature, public_keys=(public_key,))
        )

    def verify_aggregate(self, message, signature, public_keys) -> None:
        if not public_keys:
            raise SignatureInvalid("aggregate with no public keys")
        self.items.append(
            VerifyItem(message, signature, public_keys=public_keys)
        )

    def verify_aggregate_indexed(
        self, message, signature, member_indices, pubkey_columns
    ) -> None:
        if not member_indices:
            raise SignatureInvalid("aggregate with no public keys")
        self.items.append(
            VerifyItem(message, signature, member_indices=member_indices,
                       pubkey_columns=pubkey_columns)
        )

    def extend(self, triples) -> None:
        for t in triples:
            self.verify_singular(t.message, t.signature, t.public_key)

    def finish(self) -> None:
        self.finish_async()()

    def finish_async(self):
        if not self.items:
            return lambda: None
        items, self.items = self.items, []
        n = len(items)
        lane = self.lane
        ticket = self.scheduler.submit(lane, items)
        timeout = self.timeout

        def settle() -> None:
            if not ticket.result(timeout):
                raise SignatureInvalid(
                    f"batch of {n} failed {lane}-lane verification"
                )

        return settle


__all__ = [
    "DEFAULT_LANES",
    "DeferredVerifier",
    "LaneConfig",
    "VerifyItem",
    "VerifyScheduler",
    "VerifyTicket",
    "host_check_item",
]
