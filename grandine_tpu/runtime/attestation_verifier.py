"""The gossip-attestation firehose service — reference:
p2p/src/attestation_verifier.rs (`AttestationVerifier` :39: accumulate up
to 64 per batch :37, bounded concurrent batch tasks :44-45,68, spawn on the
low-priority executor :142-163, prevalidate + build triples :352-457, ONE
batch verification :396-417, and on batch failure fall back to per-item
verification so a single bad signature can't stall the stream :231-239,
:377-386).

TPU shape: each batch becomes ONE `fast_aggregate_verify_batch` launch
(M aggregates × K committee members — the aggregate_fast_verify_kernel's
native geometry). The deadline keeps latency bounded when gossip is slow;
the batch bound keeps device launches dense when it's fast.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

from grandine_tpu.consensus import accessors, keys, signing
from grandine_tpu.consensus.verifier import SignatureInvalid
from grandine_tpu.crypto import bls as A
from grandine_tpu.fork_choice.store import ForkChoiceError, ValidAttestation
from grandine_tpu.runtime import flight as _flight
from grandine_tpu.runtime import health as _health
from grandine_tpu.runtime.thread_pool import Priority
from grandine_tpu.tracing import NULL_TRACER

MAX_BATCH = 64  # attestation_verifier.rs:37


class GossipAttestation:
    """One attestation off the wire, pre-verification. `origin` is the
    gossip peer attribution ("peer:<id>") for the flight recorder's
    failing-origin table — never a metrics label."""

    __slots__ = ("attestation", "received_at", "origin")

    def __init__(self, attestation, received_at: "Optional[float]" = None,
                 origin: "Optional[str]" = None) -> None:
        self.attestation = attestation
        self.received_at = received_at if received_at is not None else time.time()
        self.origin = origin


class AttestationVerifier:
    """Accumulate → deadline/size-bound batch → device verify → feedback.

    `submit` is called from gossip (any thread); a collector thread forms
    batches; verification tasks run on the controller's LOW-priority pool;
    verified attestations flow to `controller.on_valid_attestation_batch`.
    """

    def __init__(
        self,
        controller,
        backend=None,
        max_batch: int = MAX_BATCH,
        deadline_s: float = 0.050,
        max_active: "Optional[int]" = None,
        use_device: bool = True,
        use_registry: bool = True,
        pipeline_depth: int = 2,
        slasher=None,
        operation_pool=None,
        metrics=None,
        tracer=None,
        health: "Optional[_health.BackendHealthSupervisor]" = None,
        settle_timeout_s: float = 5.0,
        flight: "Optional[_flight.FlightRecorder]" = None,
        mesh=None,
    ) -> None:
        from grandine_tpu.tpu.mesh import mesh_or_none

        self.controller = controller
        self.cfg = controller.cfg
        self.backend = backend
        self.use_device = use_device
        #: injected VerifyMesh (tpu/mesh.py) threaded into the backend and
        #: the pubkey registry; None / 1-device collapses to single-chip
        self.mesh = mesh_or_none(mesh)
        #: observability: default to whatever the controller carries so
        #: node wiring stays one assignment; NULL_TRACER keeps span calls
        #: branch-free when tracing is off
        self.metrics = (
            metrics if metrics is not None
            else getattr(controller, "metrics", None)
        )
        self.tracer = (
            tracer or getattr(controller, "tracer", None) or NULL_TRACER
        )
        self.max_batch = max_batch
        self.deadline_s = deadline_s
        self.max_active = max_active or controller.pool.n_threads
        #: optional slasher fed with every ACCEPTED attestation; detected
        #: offenses become AttesterSlashing ops in the operation pool
        #: (the reference's slasher → validator proposer pipeline)
        self.slasher = slasher
        self.operation_pool = operation_pool

        #: target_epoch -> {data_root: (attestation, indices)} for recent
        #: epochs — the evidence store that turns a slasher hit into a
        #: full AttesterSlashing op (the reference's indexed-attestation
        #: DB keyed by target+root); epoch-bucketed so pruning is one
        #: dict-pop per stale epoch, not a rebuild
        self._recent_attestations: "dict[int, dict]" = {}
        #: serializes slasher spans + the evidence store across the
        #: concurrent batch-verify pool threads
        self._slasher_lock = threading.Lock()
        #: breaker + settle watchdog + canary gating; node.py passes the
        #: scheduler's supervisor so both verify planes quarantine the
        #: device together
        #: flight recorder — always-on (a private ring when none is
        #: injected; node.py shares one across the whole verify plane)
        self.flight = (
            flight if flight is not None
            else _flight.FlightRecorder(metrics=self.metrics)
        )
        self.health = (
            health if health is not None
            else _health.BackendHealthSupervisor(
                metrics=self.metrics, settle_timeout_s=settle_timeout_s,
                flight=self.flight,
            )
        )
        if self.health.flight is None:
            # an injected supervisor without its own recorder joins this
            # pipeline's timeline
            self.health.flight = self.flight
            self.health.breaker.flight = self.flight
        self._queue: "deque[GossipAttestation]" = deque()
        self._cond = threading.Condition()
        self._active = 0
        self._stop = False
        self.stats = {
            "batches": 0, "accepted": 0, "rejected": 0, "fallbacks": 0,
            "breaker_skips": 0, "retries": 0,
        }
        #: guards every `stats` bump — concurrent pool workers, the
        #: completion thread, and the slasher feed all mutate them
        self._stats_lock = threading.Lock()
        #: guards the lazy TpuBlsBackend build (pool workers race to it)
        self._backend_lock = threading.Lock()

        #: device-resident pubkey registry (tpu/registry.py): the verify
        #: plane's warm path gathers committee pubkeys on-device by
        #: validator index instead of re-uploading 208 B/member per batch.
        #: Kept fresh via the controller's validator-set-change hook
        #: (deposits / finalization → mark_stale → prefix re-check).
        self.use_registry = use_registry
        self.registry = None
        if use_device and use_registry:
            from grandine_tpu.tpu.registry import DevicePubkeyRegistry

            self.registry = DevicePubkeyRegistry(
                metrics=self.metrics, mesh=self.mesh
            )
            hooks = getattr(controller, "on_validator_set_change", None)
            if hooks is not None:
                hooks.append(lambda old, new: self.registry.mark_stale())

        #: two-deep dispatch pipeline: batch tasks hand their device
        #: dispatch a zero-arg settle callable and return immediately, so
        #: batch N+1's host_prep/upload overlaps batch N's device execute
        #: (JAX async dispatch). The semaphore bounds device residency;
        #: the completion thread forces results in dispatch order.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._dispatch_sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._inflight = 0
        self._completion: "Optional[queue.Queue]" = None
        self._completion_thread: "Optional[threading.Thread]" = None
        if use_device:
            self._completion = queue.Queue()
            self._completion_thread = threading.Thread(
                target=self._complete, name="attestation-settle", daemon=True
            )
        # construct every thread before starting any: a started thread
        # must never observe a half-initialized verifier
        self._collector = threading.Thread(
            target=self._collect, name="attestation-verifier", daemon=True
        )
        if self._completion_thread is not None:
            self._completion_thread.start()
        self._collector.start()

    # ----------------------------------------------------------- ingestion

    def submit(self, attestation, origin: "Optional[str]" = None) -> None:
        with self._cond:
            self._queue.append(GossipAttestation(attestation, origin=origin))
            self._cond.notify()

    def submit_many(self, attestations: "Sequence",
                    origin: "Optional[str]" = None) -> None:
        with self._cond:
            self._queue.extend(
                GossipAttestation(a, origin=origin) for a in attestations
            )
            self._cond.notify()

    # ----------------------------------------------------------- collector

    def _collect(self) -> None:
        """Runs ONLY on the collector thread: owns the pending queue
        (under _cond) and batch formation; pool workers run the host
        fallback, the completion thread settles device batches."""
        while True:
            # crash containment: the collector must outlive any single
            # batch-forming failure (thread-crash-containment rule) —
            # account it and keep collecting
            try:
                if self._collect_once():
                    return
            except Exception:
                self._count_daemon_failure("attestation-verifier")
                with self._cond:
                    if self._stop:
                        return
                time.sleep(0.01)

    def _collect_once(self) -> bool:
        """One accumulate→spawn round; True when the collector should
        exit (stop() with an empty queue)."""
        with self._cond:
            # wait for the first item
            while not self._stop and not self._queue:
                self._cond.wait()
            if self._stop and not self._queue:
                return True
            # accumulate: dispatch when the batch bound is reached, the
            # deadline since the first item expires, or on shutdown —
            # this is what makes device launches dense under load
            deadline = time.monotonic() + self.deadline_s
            while (
                not self._stop
                and len(self._queue) < self.max_batch
                and (remaining := deadline - time.monotonic()) > 0
            ):
                self._cond.wait(remaining)
            # respect the concurrent-batch bound before dispatching
            while not self._stop and self._active >= self.max_active:
                self._cond.wait()
            if self._stop and not self._queue:
                return True
            batch = [
                self._queue.popleft()
                for _ in range(min(self.max_batch, len(self._queue)))
            ]
            if not batch:
                return False
            self._active += 1
        try:
            self.controller.pool.spawn(
                lambda b=batch: self._verify_batch(b), Priority.LOW
            )
        except Exception:
            # pool stopped / spawn failure: release the active slot so
            # the collector cannot wedge on max_active
            with self._cond:
                self._active -= 1
                self._cond.notify_all()
            raise
        return False

    # ------------------------------------------------------------- verify

    #: lane label on verify_stage_seconds — the attestation firehose is
    #: the scheduler's sibling "attestation" lane
    lane = "attestation"

    @contextmanager
    def _stage(self, stage: str, **attrs):
        """One pipeline stage: a child span under the current trace
        context plus a `verify_stage_seconds{stage=...,lane=...}`
        observation."""
        t0 = time.perf_counter()
        with self.tracer.span(stage, attrs or None):
            yield
        if self.metrics is not None:
            self.metrics.verify_stage_seconds.labels(
                stage, self.lane
            ).observe(time.perf_counter() - t0)

    def _verify_batch(self, batch: "Sequence[GossipAttestation]") -> None:
        t_batch = time.perf_counter()
        try:
            with self.tracer.span("verify_batch", {"batch": len(batch)}):
                self._verify_batch_traced(batch)
        finally:
            with self._cond:
                self._active -= 1
                self._cond.notify()
            with self._stats_lock:
                self.stats["batches"] += 1
            if self.metrics is not None:
                self.metrics.att_batches.inc()
                self.metrics.att_batch_times.observe(
                    time.perf_counter() - t_batch
                )

    def _verify_batch_traced(self, batch: "Sequence[GossipAttestation]") -> None:
        snapshot = self.controller.snapshot()
        state = snapshot.head_state
        prepared = []
        with self._stage("host_prep", items=len(batch)):
            for item in batch:
                try:
                    prepared.append(
                        self._prevalidate(state, item.attestation)
                        + (item.origin,)
                    )
                except (ForkChoiceError, ValueError, KeyError):
                    # KeyError: raced the mutator's finalization prune (the
                    # same race the block task path catches)
                    with self._stats_lock:
                        self.stats["rejected"] += 1
        if not prepared:
            return
        # accumulate-wait of the OLDEST attestation in the batch is its
        # queue_wait component for flight SLO attribution
        fl = self.flight.begin_batch(
            self.lane, "", len(prepared),
            queue_wait_s=max(
                0.0, time.time() - min(it.received_at for it in batch)
            ),
            breaker_state=self.health.state if self.use_device else "",
            devices=self.mesh.device_count if self.mesh is not None else 1,
        )
        skipped = False
        if self.use_device and self._completion is not None:
            if not self.health.allow_device():
                # breaker OPEN: zero device dispatch attempts — straight
                # to the host anchor below, no per-batch fault tax
                with self._stats_lock:
                    self.stats["breaker_skips"] += 1
                skipped = True
            else:
                t0 = time.perf_counter()
                try:
                    settle = self._device_dispatch(prepared)
                    fl.note_device(time.perf_counter() - t0)
                except Exception:
                    fl.note_device(time.perf_counter() - t0)
                    fl.note_fault("dispatch")
                    self.health.record_fault("dispatch")
                    # bounded transient retry: one immediate re-dispatch
                    settle = self._retry_dispatch(prepared, fl)
                if settle is not None:
                    # pipelined path: readback is deferred to the
                    # completion thread so this pool thread (and the
                    # collector behind it) can start the NEXT batch's
                    # host_prep while the device executes this one
                    fl.record.kernel = "fast_aggregate"
                    self._enqueue_settle(settle, prepared, fl)
                    return
        messages = [p[0] for p in prepared]
        signatures = [p[1] for p in prepared]
        members = [p[2] for p in prepared]
        t0 = time.perf_counter()
        ok = self._batch_check(messages, signatures, members)
        dt = time.perf_counter() - t0
        if self.use_device and not skipped:
            fl.note_device(dt)
        else:
            fl.note_host(dt)
        self._resolve_batch(prepared, ok, fl)

    def _resolve_batch(self, prepared, ok: bool, fl=None) -> None:
        """Deliver a settled batch verdict: feedback on success, bisection
        on failure. Runs on the pool thread (sync path) or the completion
        thread (pipelined path)."""
        if fl is None:
            fl = self.flight.begin_batch(
                self.lane, "", len(prepared),
                devices=(
                    self.mesh.device_count if self.mesh is not None else 1
                ),
            )
        if ok:
            with self._stats_lock:
                self.stats["accepted"] += len(prepared)
            with self._stage("feedback", items=len(prepared)):
                self.controller.on_valid_attestation_batch(
                    [p[3] for p in prepared]
                )
                # AFTER delivery: a slasher problem must never cost fork
                # choice its verified votes
                self._feed_slasher([(p[4], p[3]) for p in prepared])
            fl.finish(True)
            return
        # batch failed: BISECT to the bad items with batch checks —
        # O(k·log n) verifies for k bad signatures instead of n
        # singular host pairings. The singular-per-item fallback
        # (attestation_verifier.rs:231-239) costs ~0.7 s/item on the
        # host anchor; at the adversarial operating point of ~1 bad
        # signature per batch that re-verifies EVERY item and blows
        # the 4 s deadline — this is the DoS surface of batch
        # verification, and bisection caps it.
        with self._stats_lock:
            self.stats["fallbacks"] += 1
        if self.metrics is not None:
            self.metrics.att_fallbacks.inc()
        with self._stage("fallback", items=len(prepared)):
            t0 = time.perf_counter()
            good_items, bad_count = self._isolate(prepared)
            fl.note_bisect(
                time.perf_counter() - t0,
                depth=max(1, len(prepared).bit_length()),
            )
        if bad_count == 0:
            # the batch verdict said "invalid" yet bisection cleared
            # every item: a wrong-verdict device — file the fault kind
            # only canary probes catch at re-promotion
            self.health.record_fault("verdict")
            fl.note_fault("verdict")
        else:
            # attribute each bisection-named bad item to its gossip
            # origin (bounded top-K — the quarantine lane's feed)
            good_ids = {id(p) for p in good_items}
            for p in prepared:
                if id(p) not in good_ids:
                    fl.note_origin_failure(p[7])
        with self._stats_lock:
            self.stats["accepted"] += len(good_items)
            self.stats["rejected"] += bad_count
        if good_items:
            with self._stage("feedback", items=len(good_items)):
                self.controller.on_valid_attestation_batch(
                    [p[3] for p in good_items]
                )
                self._feed_slasher([(p[4], p[3]) for p in good_items])
        fl.finish(bad_count == 0)

    # ------------------------------------------------------------ pipeline

    def _device_dispatch(self, prepared):
        """Host prep + async device dispatch for one prepared batch.
        Returns a zero-arg settle callable producing the batch verdict, or
        None when the backend lacks the async seam (foreign backends keep
        the synchronous `_batch_check` path)."""
        backend = self._ensure_backend()
        if not _health.has_async_seam(backend):
            return None
        messages = [p[0] for p in prepared]
        try:
            # decompress WITHOUT the per-signature host subgroup
            # scalar-mul; the device checks the whole batch in one ψ
            # ladder (see _batch_check for the rationale)
            with self._stage("host_prep", op="g2_decompress"):
                points = [
                    A.g2_from_bytes(bytes(p[1]), subgroup_check=False)
                    for p in prepared
                ]
        except A.BlsError:
            return lambda: False
        if any(p.is_infinity() for p in points):
            return lambda: False
        # Fused backends fold the ψ-ladder membership check into the
        # verify kernel itself (check_subgroup static): ONE device
        # dispatch per batch. Two-pass backends stack both dispatches
        # before any readback: subgroup ladder and verify kernel queue
        # back-to-back on the device. Verifying a not-yet-subgroup-
        # checked (but on-curve) point is safe either way — if the
        # membership check fails the batch verdict is False and the
        # items fall to bisection, whose singular path is fully checked.
        fused = getattr(backend, "fuse_subgroup", False)
        sub_settle = (
            None if fused else backend.g2_subgroup_check_batch_async(points)
        )
        sigs = [A.Signature(p) for p in points]
        if self.metrics is not None:
            self.metrics.device_batch_sigs.inc(len(sigs))
        registry = self._sync_registry(prepared)
        if registry is not None:
            ver_settle = backend.fast_aggregate_verify_batch_indexed_async(
                messages, sigs, [p[5] for p in prepared], registry
            )
        else:
            ver_settle = backend.fast_aggregate_verify_batch_async(
                messages, sigs, [p[2] for p in prepared]
            )

        def settle() -> bool:
            if sub_settle is not None and not bool(sub_settle().all()):
                return False
            return bool(ver_settle())

        return settle

    def _ensure_backend(self):
        """The verify backend, lazily building the real TpuBlsBackend
        (which then also answers the supervisor's canary probes;
        injected backends keep whatever probe the caller wired).
        Concurrent pool workers race to the first build: the lock keeps
        the backend a singleton (one jit cache, one canary probe)."""
        with self._backend_lock:
            backend = self.backend
            if backend is None:
                from grandine_tpu.tpu import schemes

                backend = self.backend = schemes.get("bls").make_backend(
                    metrics=self.metrics, tracer=self.tracer, mesh=self.mesh
                )
                self.health.ensure_probe(_health.make_canary_probe(
                    backend, timeout_s=self.health.settle_timeout_s
                ))
        return backend

    def _retry_dispatch(self, prepared, fl=None):
        """Bounded transient retry: ONE immediate re-dispatch after a
        dispatch fault, breaker permitting."""
        if not self.health.allow_device():
            return None
        with self._stats_lock:
            self.stats["retries"] += 1
        if self.metrics is not None:
            self.metrics.verify_retry.inc(self.lane)
        if fl is not None:
            fl.note_retry()
        t0 = time.perf_counter()
        try:
            return self._device_dispatch(prepared)
        except Exception:
            self.health.record_fault("dispatch")
            if fl is not None:
                fl.note_fault("dispatch")
            return None
        finally:
            if fl is not None:
                fl.note_device(time.perf_counter() - t0)

    def _count_daemon_failure(self, thread: str) -> None:
        if self.metrics is not None:
            self.metrics.daemon_loop_failures.inc(thread)

    def _sync_registry(self, prepared):
        """Bring the registry up to date with the batch's head-state
        pubkey columns (identity hit when nothing changed); None → take
        the upload path."""
        registry = self.registry
        if registry is None:
            return None
        try:
            with self._stage("host_prep", op="registry_sync"):
                if registry.ensure(prepared[0][6]):
                    return registry
        except A.BlsError:
            # corrupted registry bytes: keep the upload path (and its
            # per-key validation) rather than poisoning the device mirror
            pass
        return None

    def _enqueue_settle(self, settle, prepared, fl=None) -> None:
        """Hand a dispatched batch to the completion thread. Blocks when
        `pipeline_depth` batches are already in flight — backpressure that
        bounds device residency."""
        # the slot is released on the completion thread in _complete's
        # finally, so a `with` cannot express this handoff
        self._dispatch_sem.acquire()  # lint: disable=thread-affinity
        with self._cond:
            self._inflight += 1
            depth = self._inflight
        if self.metrics is not None:
            self.metrics.verify_pipeline_depth.set(depth)
        self.flight.device_enter()
        self._completion.put((settle, prepared, self.tracer.capture(), fl))

    def _complete(self) -> None:
        """Completion thread: force settled batch verdicts in dispatch
        order and deliver feedback. Readback happens HERE, off the
        dispatch path, so the pool threads never block on the device."""
        while True:
            item = self._completion.get()
            if item is None:
                return
            settle, prepared, span_ctx, fl = item
            try:
                with self.tracer.attach(span_ctx):
                    self._settle_one(settle, prepared, fl)
            except Exception:
                # the completion thread must survive backend faults; the
                # batch is dropped (counted), not silently accepted
                with self._stats_lock:
                    self.stats["settle_errors"] = (
                        self.stats.get("settle_errors", 0) + 1
                    )
                if fl is not None:
                    fl.finish(None)
            finally:
                self.flight.device_exit()
                self._dispatch_sem.release()
                with self._cond:
                    self._inflight -= 1
                    depth = self._inflight
                    self._cond.notify_all()
                if self.metrics is not None:
                    self.metrics.verify_pipeline_depth.set(depth)

    def _settle_one(self, settle, prepared, fl=None) -> None:
        """Force one batch verdict under the settle watchdog. A fault or
        watchdog expiry files a breaker fault and DEGRADES the batch to a
        fresh (breaker-gated device or host) re-check — honest votes are
        never dropped on a backend hiccup."""
        t0 = time.perf_counter()
        outcome = self.health.guard_settle(
            settle, thread_name="attestation-settle-watchdog"
        )
        if fl is not None:
            fl.note_device(time.perf_counter() - t0)
        if outcome.status == _health.OK:
            self.health.record_success()
            self._resolve_batch(prepared, bool(outcome.value), fl)
            return
        if outcome.status == _health.TIMEOUT:
            # abandon the hung settle (its thread is an expendable
            # daemon); the pipeline slot is released by the caller's
            # finally, so backpressure clears immediately
            if self.metrics is not None:
                self.metrics.verify_watchdog_fired.inc(self.lane)
            self.health.record_fault("watchdog")
            if fl is not None:
                fl.note_fault("watchdog")
        else:
            self.health.record_fault("settle")
            if fl is not None:
                fl.note_fault("settle")
        with self._stats_lock:
            self.stats["settle_errors"] = (
                self.stats.get("settle_errors", 0) + 1
            )
        t0 = time.perf_counter()
        ok = self._batch_check(
            [p[0] for p in prepared],
            [p[1] for p in prepared],
            [p[2] for p in prepared],
        )
        if fl is not None:
            fl.note_host(time.perf_counter() - t0)
        self._resolve_batch(prepared, ok, fl)

    def _isolate(self, prepared):
        """Recursive bisection over a FAILED batch: re-check halves as
        batches, descend only into failing halves. Returns
        (good_items, bad_count)."""
        if len(prepared) == 1:
            try:
                ok = bool(
                    self._batch_check(
                        [prepared[0][0]], [prepared[0][1]], [prepared[0][2]]
                    )
                )
            except ValueError:
                ok = False  # malformed signature (BlsError): drop the item
            return (list(prepared), 0) if ok else ([], 1)
        mid = len(prepared) // 2
        good, bad = [], 0
        for half in (prepared[:mid], prepared[mid:]):
            # non-crypto errors (device/runtime faults) PROPAGATE — honest
            # votes must not be silently rejected on a backend hiccup; the
            # pool's task catch surfaces the failure like the old fallback
            try:
                half_ok = bool(
                    self._batch_check(
                        [p[0] for p in half],
                        [p[1] for p in half],
                        [p[2] for p in half],
                    )
                )
            except ValueError:
                half_ok = False  # a malformed signature inside: descend
            if half_ok:
                good.extend(half)
            else:
                g, b = self._isolate(half)
                good.extend(g)
                bad += b
        return good, bad

    def _prevalidate(self, state, attestation):
        """Committee lookup + fork-choice windows; returns
        (signing_root, signature_bytes, member_keys, ValidAttestation,
        attestation, member_indices, state_pubkey_columns) — the index
        list and the state's compressed-pubkey tuple ride along so the
        registry path can gather on-device without touching the keys."""
        p = self.cfg.preset
        data = attestation.data
        indices = accessors.get_attesting_indices(
            state, data, attestation.aggregation_bits, p
        )
        if len(indices) == 0:
            raise ValueError("empty attestation")
        idx_list = [int(i) for i in indices]
        valid = self.controller.store.validate_attestation(
            int(data.slot),
            int(data.index),
            int(data.target.epoch),
            bytes(data.beacon_block_root),
            bytes(data.target.root),
            idx_list,
        )
        root = signing.attestation_signing_root(state, data, self.cfg)
        cols = accessors.registry_columns(state)
        members = [
            keys.decompress_pubkey(cols.pubkeys[i], trusted=True)
            for i in idx_list
        ]
        return (
            root, bytes(attestation.signature), members, valid, attestation,
            idx_list, cols.pubkeys,
        )

    #: evidence retention window (epochs) for building slashing ops
    SLASHER_EVIDENCE_EPOCHS = 64

    def _feed_slasher(self, accepted_pairs) -> None:
        """Run every ACCEPTED attestation through the slasher; a hit is
        turned into a full AttesterSlashing op for the proposer pipeline
        when the conflicting attestation is still in the evidence window
        (slasher.rs → validator slashing forwarding). Serialized by
        _slasher_lock (the slasher's span chunks are not thread-safe) and
        exception-isolated — detection must never break verification."""
        if self.slasher is None:
            return
        try:
            with self._slasher_lock:
                # pass 1: evidence-window bookkeeping + normalization
                batch = []  # (attestation, indices, source, target, root)
                for attestation, valid in accepted_pairs:
                    data = attestation.data
                    source = int(data.source.epoch)
                    target = int(data.target.epoch)
                    data_root = bytes(data.hash_tree_root())
                    indices = [int(i) for i in valid.indices]
                    bucket = self._recent_attestations.get(target)
                    if bucket is None:
                        bucket = self._recent_attestations[target] = {}
                        # a NEW epoch appeared: drop stale epoch buckets
                        # (one pop per epoch, not a rebuild per item)
                        floor = target - self.SLASHER_EVIDENCE_EPOCHS
                        for e in [
                            e
                            for e in self._recent_attestations
                            if e < floor
                        ]:
                            del self._recent_attestations[e]
                    # keep up to a few aggregates per data root: a later
                    # NARROWER aggregate must not evict the one holding
                    # the offender (each op's signature must match its
                    # own indices, so entries cannot be union-merged)
                    entries = bucket.setdefault(data_root, [])
                    idx_set = set(indices)
                    if not any(idx_set <= set(i) for _a, i in entries):
                        entries.append((attestation, indices))
                        del entries[:-4]
                    batch.append(
                        (attestation, indices, source, target, data_root)
                    )
                # pass 2: one bulk slasher call for the whole accepted
                # batch — span updates merge across aggregates instead
                # of walking chunks per attesting index
                hit_lists = self.slasher.on_attestations_bulk(
                    [(ix, s, t, r) for _a, ix, s, t, r in batch]
                )
                for (attestation, indices, _s, _t, _r), hits in zip(
                    batch, hit_lists
                ):
                    # a committee-wide equivocation yields one hit per
                    # validator with (usually) shared evidence: skip a
                    # hit only when an ALREADY-BUILT op's index
                    # intersection covers that validator — never on the
                    # evidence key alone (validators may live in
                    # disjoint stored aggregates)
                    covered: "set[int]" = set()
                    for hit in hits:
                        if hit.validator_index in covered:
                            continue
                        newly = self._build_slashing_op(
                            hit, attestation, indices
                        )
                        if newly:
                            covered |= newly
        except Exception:
            with self._stats_lock:
                self.stats["slasher_errors"] = (
                    self.stats.get("slasher_errors", 0) + 1
                )

    def _build_slashing_op(self, hit, attestation, indices):
        """Build + pool one AttesterSlashing for `hit`; returns the set
        of validator indices the op's intersection covers (None if no op
        could be built)."""
        if self.operation_pool is None:
            return None
        if hit.kind == "double_vote":
            prior_target = int(hit.evidence["target_epoch"])
            prior_root = bytes.fromhex(hit.evidence["roots"][0])
        elif hit.kind in ("surround_vote", "surrounded_vote"):
            prior_target = int(hit.evidence["existing"][1])
            rec = self.slasher.record_for(hit.validator_index, prior_target)
            if rec is None:
                return None  # evidence pruned
            prior_root = rec[1]
        else:
            return None
        entries = self._recent_attestations.get(prior_target, {}).get(
            prior_root, []
        )
        if not entries:
            return None  # conflicting attestation no longer retrievable
        # prefer evidence that contains the offending validator (the op
        # slashes the INTERSECTION of the two index sets)
        prev_att, prev_indices = entries[0]
        for att_i, idx_i in entries:
            if hit.validator_index in idx_i:
                prev_att, prev_indices = att_i, idx_i
                break
        from grandine_tpu.types.combined import fork_namespace, state_phase_of

        snap = self.controller.snapshot()
        tns = fork_namespace(
            self.cfg, state_phase_of(snap.head_state, self.cfg)
        )
        prev_indexed = tns.IndexedAttestation(
            attesting_indices=sorted(prev_indices),
            data=prev_att.data,
            signature=bytes(prev_att.signature),
        )
        cur_indexed = tns.IndexedAttestation(
            attesting_indices=sorted(indices),
            data=attestation.data,
            signature=bytes(attestation.signature),
        )
        # spec is_slashable_attestation_data(data_1, data_2) surrounds
        # as data_1.source < data_2.source AND data_2.target <
        # data_1.target: the SURROUNDING attestation must be
        # attestation_1. For a "surround_vote" hit the NEW attestation
        # surrounds the existing one.
        if hit.kind == "surround_vote":
            att1, att2 = cur_indexed, prev_indexed
        else:
            att1, att2 = prev_indexed, cur_indexed
        slashing = tns.AttesterSlashing(
            attestation_1=att1, attestation_2=att2
        )
        if self.operation_pool.insert_attester_slashing(slashing):
            with self._stats_lock:
                self.stats["slashings_emitted"] = (
                    self.stats.get("slashings_emitted", 0) + 1
                )
        return set(prev_indices) & set(indices)

    def _batch_check(self, messages, signatures, members) -> bool:
        if self.use_device and self.health.allow_device():
            try:
                ok = self._device_batch_check(messages, signatures, members)
            except ValueError:
                # crypto-malformed input (BlsError): the item's problem,
                # not the device's — no breaker fault
                raise
            except Exception:
                # device/runtime fault: feed the breaker, then PROPAGATE
                # (see _isolate — honest votes are not silently rejected)
                self.health.record_fault("settle")
                raise
            self.health.record_success()
            return ok
        # host anchor path (small batches / tests / breaker OPEN): all
        # host work, so the whole check is the "execute" stage
        with self._stage("execute", path="host", items=len(messages)):
            try:
                return all(
                    A.Signature.from_bytes(sig).fast_aggregate_verify(msg, mems)
                    for msg, sig, mems in zip(messages, signatures, members)
                )
            except A.BlsError:
                return False

    def _device_batch_check(self, messages, signatures, members) -> bool:
        backend = self._ensure_backend()
        try:
            # decompress WITHOUT the per-signature host subgroup
            # scalar-mul (~9 ms each — it dominated batch latency);
            # the device checks the whole batch in one ψ ladder.
            # A failed batch falls to the singular path, which uses
            # the fully-checked from_bytes and isolates the item.
            with self._stage("host_prep", op="g2_decompress"):
                points = [
                    A.g2_from_bytes(bytes(s), subgroup_check=False)
                    for s in signatures
                ]
        except A.BlsError:
            return False
        if any(p.is_infinity() for p in points):
            return False
        # fused backends check membership inside the verify kernel —
        # no separate subgroup dispatch
        if not getattr(backend, "fuse_subgroup", False):
            if not bool(backend.g2_subgroup_check_batch(points).all()):
                return False
        sigs = [A.Signature(p) for p in points]
        if self.metrics is not None:
            self.metrics.device_batch_sigs.inc(len(sigs))
        return backend.fast_aggregate_verify_batch(messages, sigs, members)

    # ------------------------------------------------------------ control

    def flush(self, timeout: float = 30.0) -> None:
        """Drain the queue, all in-flight batches, and the pipelined
        settle queue (test barrier)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._cond.notify()
        while time.monotonic() < deadline:
            with self._cond:
                if not self._queue and self._active == 0 and self._inflight == 0:
                    return
                self._cond.notify()
            time.sleep(0.01)
        raise TimeoutError("attestation verifier did not drain")

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._collector.join(timeout=5)
        if self._completion is not None:
            # sentinel queues BEHIND any still-pending settles, so they
            # drain before the thread exits
            self._completion.put(None)
            if self._completion_thread is not None:
                self._completion_thread.join(timeout=10)


__all__ = ["AttestationVerifier", "GossipAttestation", "MAX_BATCH"]
