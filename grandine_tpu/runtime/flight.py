"""Verify-plane flight recorder: one timeline for every dispatch surface.

The verify plane spans five dispatch surfaces — the attestation firehose
(runtime/attestation_verifier.py), the scheduler lanes
(runtime/verify_scheduler.py), bulk replay windows (runtime/replay.py),
canary probes and breaker transitions (runtime/health.py) — but the
aggregate histograms can't answer "which batch missed its deadline and
why", "how much device capacity is padding waste", or "which peer's
traffic keeps poisoning batches". This module records a bounded ring of
per-batch `BatchRecord` events (plus canary and breaker events, in the
SAME timeline, so a fault → breaker-open → probe → re-close sequence
reads as consecutive records) and derives four things on top:

  SLO tracker      — each settled batch is compared against its lane's
                     deadline budget; a miss increments
                     `verify_slo_miss_total{lane,cause}` where `cause`
                     names the dominant component: queue_wait (sat in
                     the lane queue), device (device execute + the host
                     pass a device fault forced), bisection (failed-
                     batch isolation), or breaker_open (dispatch was
                     skipped with the breaker open). The cause set is a
                     closed enum (SLO_CAUSES) — the metrics-cardinality
                     lint rule rejects values outside it.
  fill histograms  — items vs the pow-2 device bucket actually
                     compiled: `verify_bucket_fill_ratio{kernel}` and
                     `verify_padding_waste_total{kernel}` are the
                     capacity-planning input for multi-chip promotion
                     (ROADMAP item 1).
  origin table     — failing jobs attribute their gossip peer/validator
                     origin (threaded through `VerifyTicket`) into a
                     bounded top-K table (space-saving eviction, so k
                     counters survive adversarial origin churn). This
                     is the attribution feed the quarantine lane
                     (ROADMAP item 2) consumes. Origins appear ONLY in
                     the flight ring and the debug endpoint — never as
                     Prometheus label values (unbounded cardinality;
                     the lint rule enforces this too).
  duty cycle       — device_enter/device_exit bracket on-device work;
                     the recorder integrates busy time and in-flight
                     depth into `verify_device_duty_cycle` and
                     `verify_pipeline_occupancy`, the real measure of
                     the two-deep overlap.

Lock-light by design: one short-hold lock guards the ring index and the
duty-cycle accumulators; records are built outside it. Recording is
always-on (the scheduler and firehose construct a recorder when none is
injected) and must stay inside the ≤5% instrumentation-overhead guard
(tests/test_flight.py).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Optional

# ------------------------------------------------------------------ enums

#: record kinds sharing the flight timeline
BATCH = "batch"
CANARY = "canary"
BREAKER = "breaker"
RECORD_KINDS = (BATCH, CANARY, BREAKER)

#: the CLOSED cause enum on verify_slo_miss_total — the metrics-
#: cardinality lint rule parses this tuple and rejects any literal
#: `cause` outside it, and `_slo_cause` below can only return members.
#: `expired` = a ticket's absolute deadline passed before dispatch and
#: it was shed un-dispatched; `brownout` = the overload controller
#: (runtime/brownout.py) shed it — shed-oldest overflow, or a
#: CRITICAL-level submit refusal.
SLO_CAUSES = ("queue_wait", "device", "bisection", "breaker_open",
              "expired", "brownout")

#: per-lane deadline budgets (seconds, enqueue→settle). HIGH scheduler
#: lanes sit on the block-import path; the attestation budget is the
#: spec's 4 s gossip propagation window; replay windows are wall-time
#: bounded only by throughput targets.
DEFAULT_SLO_BUDGETS = {
    "block": 0.5,
    "blob_header": 0.5,
    "sync_contribution": 0.5,
    "sync_message": 1.0,
    "slashing": 2.0,
    "exit": 2.0,
    "bls_change": 2.0,
    # suspect-origin traffic: correctness matters, latency does not —
    # small batches from quarantined origins may wait behind every
    # honest lane
    "quarantine": 5.0,
    "attestation": 4.0,
    "replay": 120.0,
    # slasher span ingestion: keep-up is throughput-gated (span-update
    # rate ≥ attestation arrival rate), but any single batch blowing the
    # gossip window means detections lag the chain
    "slasher": 4.0,
}
DEFAULT_SLO_BUDGET_S = 4.0  # unknown lanes


def bucket_of(items: int) -> int:
    """The pow-2 device bucket `items` pads into (the shape the kernel
    manifest compiles; tools/shapes bucketing)."""
    n = max(1, int(items))
    return 1 << (n - 1).bit_length()


def _recompile_count() -> "Optional[int]":
    """The shape ledger's post-warmup recompile counter — read only when
    tpu/bls is ALREADY imported (never import jax from the recorder)."""
    mod = sys.modules.get("grandine_tpu.tpu.bls")
    if mod is None:
        return None
    try:
        return int(mod.post_warmup_recompiles())
    except Exception:
        return None


# ---------------------------------------------------------------- records


class BatchRecord:
    """One flight-timeline event. `kind=BATCH` rows carry the full
    per-batch story; CANARY/BREAKER rows reuse the shape (lane="health",
    fault/verdict describing the probe or the entered state) so the
    whole verify plane reads as one ordered sequence."""

    __slots__ = (
        "seq", "t", "kind", "lane", "kernel", "items", "bucket", "fill",
        "queue_wait_s", "device_s", "host_s", "bisect_s", "verdict",
        "fault", "retries", "bisect_depth", "breaker_state", "recompile",
        "slo_miss", "slo_cause", "origin", "note", "devices",
        "quarantined", "brownout",
    )

    def __init__(self, kind: str, lane: str) -> None:
        self.seq = 0
        self.t = 0.0
        self.kind = kind
        self.lane = lane
        self.kernel = ""
        self.items = 0
        self.bucket = 0
        self.fill = 0.0
        self.queue_wait_s = 0.0
        self.device_s = 0.0
        self.host_s = 0.0
        self.bisect_s = 0.0
        self.verdict: "Optional[bool]" = None
        self.fault: "Optional[str]" = None
        self.retries = 0
        self.bisect_depth = 0
        self.breaker_state = ""
        self.recompile = False
        self.slo_miss = False
        self.slo_cause: "Optional[str]" = None
        self.origin: "Optional[str]" = None
        self.note = ""
        #: mesh width the batch dispatched over (a record FIELD, never a
        #: Prometheus label — per-device label cardinality is forbidden)
        self.devices = 1
        #: True for quarantine-lane batches (suspect-origin traffic
        #: isolated from honest batches — runtime/isolation.py)
        self.quarantined = False
        #: the brownout level (runtime/brownout.py LEVELS) in force when
        #: the record committed — every shed reads its causing level
        #: straight off the timeline
        self.brownout = "normal"

    def total_s(self) -> float:
        return self.queue_wait_s + self.device_s + self.host_s + self.bisect_s

    def as_dict(self) -> dict:
        """JSON-ready row for the debug endpoint / bench summary."""
        return {
            "seq": self.seq,
            "t": round(self.t, 6),
            "kind": self.kind,
            "lane": self.lane,
            "kernel": self.kernel,
            "items": self.items,
            "bucket": self.bucket,
            "fill": round(self.fill, 4),
            "queue_wait_s": round(self.queue_wait_s, 6),
            "device_s": round(self.device_s, 6),
            "host_s": round(self.host_s, 6),
            "bisect_s": round(self.bisect_s, 6),
            "verdict": self.verdict,
            "fault": self.fault,
            "retries": self.retries,
            "bisect_depth": self.bisect_depth,
            "breaker_state": self.breaker_state,
            "recompile": self.recompile,
            "slo_miss": self.slo_miss,
            "slo_cause": self.slo_cause,
            "origin": self.origin,
            "note": self.note,
            "devices": self.devices,
            "quarantined": self.quarantined,
            "brownout": self.brownout,
        }


class BatchFlight:
    """Mutable per-batch accumulator the emission sites thread through a
    batch's life (dispatch → settle → bisection → deliver); `finish`
    hands the completed record to the recorder exactly once. All methods
    are called from the single thread that owns the batch at that stage,
    so no locking here."""

    __slots__ = ("record", "_recorder", "_done", "_recompiles_before")

    def __init__(self, recorder: "FlightRecorder", record: BatchRecord) -> None:
        self.record = record
        self._recorder = recorder
        self._done = False
        self._recompiles_before = _recompile_count()

    def note_device(self, seconds: float) -> None:
        self.record.device_s += max(0.0, seconds)

    def note_host(self, seconds: float) -> None:
        self.record.host_s += max(0.0, seconds)

    def note_bisect(self, seconds: float, depth: int = 0) -> None:
        self.record.bisect_s += max(0.0, seconds)
        self.record.bisect_depth = max(self.record.bisect_depth, int(depth))

    def note_retry(self) -> None:
        self.record.retries += 1

    def note_fault(self, kind: str) -> None:
        # first fault wins the record's `fault` field (it names what
        # pushed the batch off the fast path); a secondary fault — a
        # hang on the RETRY of an already-faulted batch — stays visible
        # in the note and in the recorder's aggregate fault counts
        if self.record.fault is None:
            self.record.fault = kind
        else:
            note = self.record.note
            self.record.note = f"{note}+{kind}" if note else f"also_{kind}"
        self._recorder._count_fault(kind)

    def note_origin_failure(self, origin: "Optional[str]") -> None:
        if origin:
            self.record.origin = origin
            self._recorder.note_origin_failure(origin)

    def finish(self, verdict: "Optional[bool]") -> None:
        if self._done:
            return
        self._done = True
        rec = self.record
        rec.verdict = verdict
        if self._recompiles_before is not None:
            after = _recompile_count()
            rec.recompile = bool(after is not None
                                 and after > self._recompiles_before)
        self._recorder._commit(rec)


class OriginTable:
    """Bounded top-K failing-origin counters with space-saving (Misra-
    Gries) eviction: a NEW origin arriving at capacity replaces the
    minimum-count entry and inherits its count (+1), so the true
    heaviest offenders survive adversarial churn of one-shot origins and
    the table never exceeds `capacity` entries. `error` on a snapshot
    row bounds the inherited over-count."""

    __slots__ = ("capacity", "_counts", "_errors", "_lock")

    def __init__(self, capacity: int = 32) -> None:
        self.capacity = max(1, int(capacity))
        self._counts: "dict[str, int]" = {}
        self._errors: "dict[str, int]" = {}
        self._lock = threading.Lock()

    def note_failure(self, origin: str, count: int = 1) -> None:
        origin = str(origin)
        with self._lock:
            if origin in self._counts:
                self._counts[origin] += count
                return
            if len(self._counts) < self.capacity:
                self._counts[origin] = count
                self._errors[origin] = 0
                return
            victim = min(self._counts, key=self._counts.__getitem__)
            floor = self._counts.pop(victim)
            self._errors.pop(victim, None)
            self._counts[origin] = floor + count
            self._errors[origin] = floor

    def snapshot(self) -> "list[dict]":
        with self._lock:
            rows = [
                {"origin": o, "failures": c, "error": self._errors.get(o, 0)}
                for o, c in self._counts.items()
            ]
        rows.sort(key=lambda r: (-r["failures"], r["origin"]))
        return rows

    def __len__(self) -> int:
        with self._lock:
            return len(self._counts)


# --------------------------------------------------------------- recorder


class FlightRecorder:
    """The bounded flight-timeline ring plus the SLO/fill/origin/duty
    derivations. One recorder per node (runtime/node.py wires the same
    instance into the scheduler, the firehose, the replay pipeline, and
    the health supervisor); components construct a private one when none
    is injected so recording is always-on."""

    def __init__(
        self,
        capacity: int = 4096,
        metrics=None,
        slo_budgets: "Optional[dict]" = None,
        default_budget_s: float = DEFAULT_SLO_BUDGET_S,
        origin_top_k: int = 32,
        clock=time.monotonic,
    ) -> None:
        self.capacity = max(16, int(capacity))
        self.metrics = metrics
        self.clock = clock
        self.slo_budgets = dict(DEFAULT_SLO_BUDGETS)
        if slo_budgets:
            self.slo_budgets.update(
                {str(k): float(v) for k, v in slo_budgets.items()}
            )
        self.default_budget_s = float(default_budget_s)
        self.origins = OriginTable(origin_top_k)
        #: the brownout level stamped on every committed record — poked
        #: by the BrownoutController on each transition (a torn read
        #: only mis-stamps one record's level by one tick)
        self.brownout_level = "normal"
        #: runtime.profiler.KernelProfiler hook: every committed record
        #: carrying a kernel feeds its dispatch→settle device seconds to
        #: the profiler's always-on estimator (node.py wires the node's
        #: profiler here; None = no attribution, recording unchanged)
        self.profiler = None
        #: ring storage: preallocated slots, one short-hold lock around
        #: index bumps and duty-cycle accounting — record assembly and
        #: SLO attribution happen outside it
        self._ring: "list[Optional[BatchRecord]]" = [None] * self.capacity
        self._lock = threading.Lock()
        self._seq = 0
        #: duty cycle / occupancy integrals
        self._t0 = self.clock()
        self._inflight = 0
        self._busy_since = 0.0
        self._busy_total = 0.0
        self._occ_mark = self._t0
        self._occ_integral = 0.0
        #: running aggregates for summary() (cheap dict bumps, also
        #: under the one lock so snapshots are coherent)
        self._slo_miss: "dict[tuple, int]" = {}
        self._fill_sum: "dict[str, float]" = {}
        self._fill_n: "dict[str, int]" = {}
        self._waste: "dict[str, int]" = {}
        self._batches = 0
        self._faults: "dict[str, int]" = {}

    # ------------------------------------------------------------ batches

    def begin_batch(self, lane: str, kernel: str, items: int,
                    queue_wait_s: float = 0.0,
                    breaker_state: str = "",
                    devices: int = 1,
                    quarantined: bool = False) -> BatchFlight:
        """Open one batch's flight context at dispatch time. Fill/waste
        are derived from the pow-2 bucket the device actually pads to."""
        rec = BatchRecord(BATCH, lane)
        rec.kernel = kernel
        rec.items = int(items)
        rec.bucket = bucket_of(items)
        rec.fill = rec.items / rec.bucket if rec.bucket else 0.0
        rec.queue_wait_s = max(0.0, float(queue_wait_s))
        rec.breaker_state = breaker_state
        rec.devices = max(1, int(devices))
        rec.quarantined = bool(quarantined)
        return BatchFlight(self, rec)

    def _slo_cause(self, rec: BatchRecord) -> str:
        """Attribute a miss to its dominant component. Breaker-open
        skips win outright (the batch never had a device chance); a
        device fault's forced host pass charges to "device" (the device
        caused it), bisection time to "bisection"."""
        if rec.breaker_state == "open" and rec.device_s == 0.0:
            return "breaker_open"
        exec_s = rec.device_s + rec.host_s
        if rec.bisect_s > exec_s and rec.bisect_s > rec.queue_wait_s:
            return "bisection"
        if exec_s >= rec.queue_wait_s:
            return "device"
        return "queue_wait"

    def _commit(self, rec: BatchRecord) -> None:
        """Finalize one batch record: SLO attribution, fill/waste
        accounting, metrics, and the ring append."""
        budget = self.slo_budgets.get(rec.lane, self.default_budget_s)
        if rec.total_s() > budget:
            rec.slo_miss = True
            rec.slo_cause = self._slo_cause(rec)
        m = self.metrics
        if m is not None:
            if rec.slo_miss:
                m.verify_slo_miss.inc(rec.lane, rec.slo_cause)
            if rec.kernel:
                m.verify_bucket_fill.observe(rec.kernel, value=rec.fill)
                m.verify_padding_waste.inc(
                    rec.kernel, amount=rec.bucket - rec.items
                )
        prof = self.profiler
        if prof is not None and rec.kernel:
            prof.on_batch(rec)
        waste = rec.bucket - rec.items
        with self._lock:
            self._batches += 1
            if rec.slo_miss:
                key = (rec.lane, rec.slo_cause)
                self._slo_miss[key] = self._slo_miss.get(key, 0) + 1
            # faults already aggregated by note_fault (every noted fault
            # counts, not just the record's primary)
            if rec.kernel:
                self._fill_sum[rec.kernel] = (
                    self._fill_sum.get(rec.kernel, 0.0) + rec.fill
                )
                self._fill_n[rec.kernel] = self._fill_n.get(rec.kernel, 0) + 1
                self._waste[rec.kernel] = (
                    self._waste.get(rec.kernel, 0) + waste
                )
            self._append_locked(rec)

    def _count_fault(self, kind: str) -> None:
        with self._lock:
            self._faults[kind] = self._faults.get(kind, 0) + 1

    # ------------------------------------------------- health-plane events

    def record_canary(self, backend: str, passed: bool,
                      duration_s: float = 0.0,
                      fault: "Optional[str]" = None) -> None:
        """A HALF_OPEN canary probe, in the same timeline as the batches
        whose faults provoked it."""
        rec = BatchRecord(CANARY, "health")
        rec.kernel = backend
        rec.device_s = max(0.0, float(duration_s))
        rec.verdict = bool(passed)
        rec.fault = fault
        rec.note = "probe_pass" if passed else "probe_fail"
        with self._lock:
            if fault is not None:
                self._faults[fault] = self._faults.get(fault, 0) + 1
            self._append_locked(rec)

    def record_breaker(self, backend: str, state: str) -> None:
        """A breaker state transition (entered `state`)."""
        rec = BatchRecord(BREAKER, "health")
        rec.kernel = backend
        rec.breaker_state = state
        rec.note = f"breaker_{state}"
        with self._lock:
            self._append_locked(rec)

    def note_origin_failure(self, origin: str, count: int = 1) -> None:
        self.origins.note_failure(origin, count)

    def record_shed(self, lane: str, items: int, cause: str) -> None:
        """One shed event: jobs that never reached a device dispatch —
        a deadline expiry (`cause="expired"`) or an overload-control
        drop (`cause="brownout"`). The record joins the timeline with
        the brownout level stamped on, so every shed is attributable,
        and feeds the SLO-miss aggregates (the brownout controller's
        own escalation feed) — but not the dispatched-batch count."""
        rec = BatchRecord(BATCH, lane)
        rec.items = int(items)
        rec.verdict = False
        rec.slo_miss = True
        rec.slo_cause = cause if cause in SLO_CAUSES else "brownout"
        rec.note = "shed"
        m = self.metrics
        if m is not None:
            m.verify_slo_miss.inc(rec.lane, rec.slo_cause)
        with self._lock:
            key = (rec.lane, rec.slo_cause)
            self._slo_miss[key] = self._slo_miss.get(key, 0) + 1
            self._append_locked(rec)

    # -------------------------------------------------- duty cycle gauges

    def device_enter(self) -> None:
        """One batch entered the device (dispatch handed off)."""
        now = self.clock()
        with self._lock:
            self._occ_integral += self._inflight * (now - self._occ_mark)
            self._occ_mark = now
            if self._inflight == 0:
                self._busy_since = now
            self._inflight += 1

    def device_exit(self) -> None:
        """One batch left the device (settle forced)."""
        now = self.clock()
        with self._lock:
            self._occ_integral += self._inflight * (now - self._occ_mark)
            self._occ_mark = now
            if self._inflight > 0:
                self._inflight -= 1
                if self._inflight == 0:
                    self._busy_total += now - self._busy_since
            duty = self._duty_locked(now)
            occ = self._occupancy_locked(now)
        if self.metrics is not None:
            self.metrics.verify_device_duty_cycle.set(duty)
            self.metrics.verify_pipeline_occupancy.set(occ)

    def _duty_locked(self, now: float) -> float:
        elapsed = now - self._t0
        if elapsed <= 0.0:
            return 0.0
        busy = self._busy_total
        if self._inflight > 0:
            busy += now - self._busy_since
        return min(1.0, busy / elapsed)

    def _occupancy_locked(self, now: float) -> float:
        elapsed = now - self._t0
        if elapsed <= 0.0:
            return 0.0
        return (
            self._occ_integral + self._inflight * (now - self._occ_mark)
        ) / elapsed

    def duty_cycle(self) -> float:
        with self._lock:
            return self._duty_locked(self.clock())

    def busy_seconds(self) -> float:
        """Total wall seconds with at least one batch on the device —
        the denominator of the profiler's coverage metric."""
        now = self.clock()
        with self._lock:
            busy = self._busy_total
            if self._inflight > 0:
                busy += now - self._busy_since
        return busy

    def occupancy(self) -> float:
        with self._lock:
            return self._occupancy_locked(self.clock())

    # ----------------------------------------------------------- the ring

    def _append_locked(self, rec: BatchRecord) -> None:
        rec.seq = self._seq
        rec.t = self.clock() - self._t0
        rec.brownout = self.brownout_level
        self._ring[self._seq % self.capacity] = rec
        self._seq += 1

    def snapshot(self, lane: "Optional[str]" = None,
                 n: "Optional[int]" = None,
                 kind: "Optional[str]" = None) -> "list[BatchRecord]":
        """The newest records, oldest-first, optionally filtered by lane
        and/or kind and truncated to the newest `n` AFTER filtering.
        Safe against concurrent recording: the slot list is copied under
        the lock; records are immutable once committed."""
        with self._lock:
            seq = self._seq
            ring = list(self._ring)
        count = min(seq, self.capacity)
        out: "list[BatchRecord]" = []
        for s in range(seq - count, seq):
            rec = ring[s % self.capacity]
            # a slot being overwritten mid-copy shows a newer seq; skip
            # anything that does not match its expected position
            if rec is None or rec.seq != s:
                continue
            if lane is not None and rec.lane != lane:
                continue
            if kind is not None and rec.kind != kind:
                continue
            out.append(rec)
        if n is not None:
            n = max(0, int(n))
            out = out[-n:] if n else []
        return out

    # ------------------------------------------------------------ summary

    def slo_misses(self) -> "dict[str, dict[str, int]]":
        """{lane: {cause: count}} of recorded SLO misses."""
        with self._lock:
            items = list(self._slo_miss.items())
        out: "dict[str, dict[str, int]]" = {}
        for (lane, cause), count in items:
            out.setdefault(lane, {})[cause] = count
        return out

    def summary(self) -> dict:
        """The bench JSON-line payload: fill ratio and padding waste per
        kernel, duty cycle / occupancy, SLO misses by lane and cause,
        fault counts, and the origin top-K."""
        now = self.clock()
        with self._lock:
            batches = self._batches
            recorded = min(self._seq, self.capacity)
            total = self._seq
            fills = {
                k: self._fill_sum[k] / n
                for k, n in self._fill_n.items() if n
            }
            waste = dict(self._waste)
            faults = dict(self._faults)
            duty = self._duty_locked(now)
            occ = self._occupancy_locked(now)
        return {
            "batches": batches,
            "records": recorded,
            "records_total": total,
            "fill_ratio": {k: round(v, 4) for k, v in sorted(fills.items())},
            "padding_waste": dict(sorted(waste.items())),
            "device_duty_cycle": round(duty, 4),
            "pipeline_occupancy": round(occ, 4),
            "slo_miss": self.slo_misses(),
            "faults": dict(sorted(faults.items())),
            "failing_origins": self.origins.snapshot()[:8],
        }


__all__ = [
    "BATCH",
    "BREAKER",
    "CANARY",
    "BatchFlight",
    "BatchRecord",
    "DEFAULT_SLO_BUDGETS",
    "FlightRecorder",
    "OriginTable",
    "RECORD_KINDS",
    "SLO_CAUSES",
    "bucket_of",
]
