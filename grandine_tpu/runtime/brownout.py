"""Adaptive overload control: the brownout controller.

Every robustness seam so far (breakers, chaos, isolation, quarantine)
reacts to *faults*; this module defends the verify/sign planes against
*overload* — arrival rate exceeding device capacity. The controller
consumes three feeds the plane already produces — the flight recorder's
per-lane SLO-miss stream, the scheduler's lane depths, and the device
duty cycle — and walks a hysteretic degradation ladder:

  NORMAL    — nothing engaged.
  B1        — stop waiting for fill: `merge_window_s` goes to zero and
              sheddable-lane `max_wait_s` shrinks, so batches flush at
              whatever size they have instead of padding the queue wait.
  B2        — shed harder: sheddable-lane `max_queue` shrinks (the
              existing shed-oldest valve fires earlier) and admission
              quotas squeeze toward `min_quota` through the
              AdmissionController's brownout-pressure hook, which the
              ReputationTable failure-rate feed already modulates —
              distrusted origins are clamped first.
  B3        — the device serves HIGH lanes only: bulk replay / slasher
              backfill pauses on its run gate and LOW lanes route to
              the host twin (`VerifyScheduler.brownout_route_host`).
  CRITICAL  — HIGH lanes exclusively; every sheddable lane's submits
              resolve dropped at the door, with full accounting (shed
              stat, drop metric, a flight-timeline record attributing
              the shed to the brownout).

Escalation moves ONE level per evaluation tick whenever the window saw
new SLO misses or a lane queue crossed its high-water mark. Recovery is
hysteretic: stepping DOWN one level requires a sustained clean window —
no misses and no depth pressure for `recovery_window_s`, re-armed at
every level — so the controller never flaps between adjacent levels.

End-to-end deadline budgets ride with the controller: `VerifyTicket`
and `SignTicket` carry an absolute deadline stamped at submit, the
scheduler/sign plane shed already-expired tickets before wasting a
device dispatch, and every shed lands on the flight timeline with an
`expired`/`brownout` SLO cause plus the brownout level stamped on the
record (flight.py).

Threading: all mutable controller state lives under one lock; actuator
pokes (scheduler knobs, lane configs, admission pressure, the replay
gate) happen under it too — none of those acquire the scheduler's
condition or the flight lock, so there is no ordering hazard. Feed
reads (which DO take those locks) happen before the controller lock is
taken. `evaluate()` is deterministic given its feeds and an injected
clock; `start()` runs it on a crash-contained daemon thread.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from grandine_tpu.runtime.thread_pool import Priority

#: the CLOSED brownout-level enum, in escalation order. The metrics-
#: cardinality lint rule parses this tuple (like flight.SLO_CAUSES) and
#: rejects any literal `from`/`to` label outside it on
#: `verify_brownout_transitions_total`.
LEVELS = ("normal", "b1", "b2", "b3", "critical")

NORMAL, B1, B2, B3, CRITICAL = LEVELS


class BrownoutController:
    """The hysteretic ladder walker. One per node (runtime/node.py); the
    bench (`bench.py --overload`) drives `evaluate()` directly at a
    fixed cadence for determinism, production uses `start()`."""

    def __init__(
        self,
        scheduler,
        flight=None,
        sign_plane=None,
        admission=None,
        replay=None,
        metrics=None,
        clock=time.monotonic,
        interval_s: float = 0.25,
        escalate_misses: int = 1,
        depth_high_water: float = 0.5,
        recovery_window_s: float = 5.0,
        escalate_dwell_s: float = 0.0,
        b1_wait_factor: float = 0.25,
        b2_queue_factor: float = 0.25,
        b2_admission_pressure: float = 0.75,
    ) -> None:
        self.scheduler = scheduler
        self.flight = (
            flight if flight is not None
            else getattr(scheduler, "flight", None)
        )
        self.sign_plane = sign_plane
        self.admission = admission
        self.replay = replay
        self.metrics = metrics
        self.clock = clock
        #: controller-thread tick period (start()); immutable after init
        self.interval_s = float(interval_s)
        #: new SLO misses in one window that count as pressure
        self.escalate_misses = max(1, int(escalate_misses))
        #: lane fullness (jobs / max_queue) that counts as pressure even
        #: before the queue wait materializes as an SLO miss
        self.depth_high_water = float(depth_high_water)
        #: the sustained clean window a ONE-level recovery step needs,
        #: re-armed at every level — the anti-flap hysteresis
        self.recovery_window_s = float(recovery_window_s)
        #: minimum dwell at a level before escalating again (0 = one
        #: step per evaluation tick)
        self.escalate_dwell_s = float(escalate_dwell_s)
        self.b1_wait_factor = float(b1_wait_factor)
        self.b2_queue_factor = float(b2_queue_factor)
        self.b2_admission_pressure = float(b2_admission_pressure)

        self._lock = threading.Lock()
        self._idx = 0
        self._since = float(clock())
        #: clean-window arming: recovery may only fire once the clock
        #: passes this mark (re-pushed by every hot observation)
        self._hot_until = float(clock())
        self._miss_seen = 0
        self._transitions: "list[tuple[float, str, str]]" = []
        #: per-level saved baselines, restored on de-escalation
        self._baselines: "dict[str, dict]" = {}
        self._daemon_failures = 0
        self._stop_evt = threading.Event()
        self._thread: "Optional[threading.Thread]" = None

    # ------------------------------------------------------------- feeds

    def _miss_total(self) -> int:
        fl = self.flight
        if fl is None:
            return 0
        misses = fl.slo_misses()
        return sum(c for causes in misses.values() for c in causes.values())

    def _depth_pressure(self) -> float:
        pressure = getattr(self.scheduler, "lane_pressure", None)
        if pressure is None:
            return 0.0
        depths = pressure()
        return max(depths.values()) if depths else 0.0

    def _duty(self) -> float:
        fl = self.flight
        if fl is None:
            return 0.0
        try:
            return float(fl.duty_cycle())
        except Exception:
            return 0.0

    # ---------------------------------------------------------- evaluate

    def evaluate(self, now: "Optional[float]" = None) -> str:
        """One deterministic control tick: read the feeds, walk the
        ladder at most one step, apply/revert actuators. Returns the
        level after the tick. Callers serialize through the controller
        lock, so concurrent ticks cannot tear a transition."""
        now = float(self.clock()) if now is None else float(now)
        misses = self._miss_total()
        pressure = self._depth_pressure()
        with self._lock:
            new = misses - self._miss_seen
            self._miss_seen = misses
            hot = (
                new >= self.escalate_misses
                or pressure >= self.depth_high_water
            )
            if hot:
                self._hot_until = now + self.recovery_window_s
            if hot and self._idx < len(LEVELS) - 1:
                if now - self._since >= self.escalate_dwell_s:
                    self._shift_locked(self._idx + 1, now)
            elif (
                not hot
                and self._idx > 0
                and now >= self._hot_until
                and now - self._since >= self.recovery_window_s
            ):
                self._shift_locked(self._idx - 1, now)
            return LEVELS[self._idx]

    def _shift_locked(self, new_idx: int, now: float) -> None:
        """Move to `new_idx` (always ±1 from the current level),
        engaging or reverting each level's actuators in order."""
        frm = LEVELS[self._idx]
        to = LEVELS[new_idx]
        if new_idx > self._idx:
            for k in range(self._idx + 1, new_idx + 1):
                self._engage_locked(LEVELS[k])
        else:
            for k in range(self._idx, new_idx, -1):
                self._revert_locked(LEVELS[k])
        self._idx = new_idx
        self._since = now
        self._transitions.append((now, frm, to))
        fl = self.flight
        if fl is not None:
            fl.brownout_level = to
        m = self.metrics
        if m is not None:
            m.verify_brownout_level.set(float(new_idx))
            m.verify_brownout_transitions.inc(frm, to)

    # --------------------------------------------------------- actuators

    def _engage_locked(self, level: str) -> None:
        sched = self.scheduler
        if level == B1:
            base: dict = {
                "merge_window_s": getattr(sched, "merge_window_s", 0.0),
                "max_wait_s": {},
            }
            if hasattr(sched, "merge_window_s"):
                sched.merge_window_s = 0.0
            for name, lane in getattr(sched, "lanes", {}).items():
                if lane.shed:
                    base["max_wait_s"][name] = lane.max_wait_s
                    lane.max_wait_s = lane.max_wait_s * self.b1_wait_factor
            self._baselines[B1] = base
        elif level == B2:
            base = {"max_queue": {}}
            for name, lane in getattr(sched, "lanes", {}).items():
                if lane.shed and name != "quarantine":
                    base["max_queue"][name] = lane.max_queue
                    lane.max_queue = max(
                        1, int(lane.max_queue * self.b2_queue_factor)
                    )
            self._baselines[B2] = base
            if self.admission is not None:
                self.admission.set_brownout_pressure(
                    self.b2_admission_pressure
                )
        elif level == B3:
            gate = getattr(self.replay, "run_gate", None)
            if gate is not None:
                gate.clear()
            if hasattr(sched, "brownout_route_host"):
                sched.brownout_route_host = frozenset(
                    n for n, l in sched.lanes.items()
                    if l.priority != Priority.HIGH
                )
        elif level == CRITICAL:
            if hasattr(sched, "brownout_shed_lanes"):
                sched.brownout_shed_lanes = frozenset(
                    n for n, l in sched.lanes.items() if l.shed
                )

    def _revert_locked(self, level: str) -> None:
        sched = self.scheduler
        if level == B1:
            base = self._baselines.pop(B1, None)
            if base is not None:
                if hasattr(sched, "merge_window_s"):
                    sched.merge_window_s = base["merge_window_s"]
                for name, wait in base["max_wait_s"].items():
                    lane = sched.lanes.get(name)
                    if lane is not None:
                        lane.max_wait_s = wait
        elif level == B2:
            base = self._baselines.pop(B2, None)
            if base is not None:
                for name, cap in base["max_queue"].items():
                    lane = sched.lanes.get(name)
                    if lane is not None:
                        lane.max_queue = cap
            if self.admission is not None:
                self.admission.set_brownout_pressure(0.0)
        elif level == B3:
            gate = getattr(self.replay, "run_gate", None)
            if gate is not None:
                gate.set()
            if hasattr(sched, "brownout_route_host"):
                sched.brownout_route_host = frozenset()
        elif level == CRITICAL:
            if hasattr(sched, "brownout_shed_lanes"):
                sched.brownout_shed_lanes = frozenset()

    # ----------------------------------------------------------- queries

    @property
    def level(self) -> str:
        with self._lock:
            return LEVELS[self._idx]

    def transitions(self) -> "list[tuple[float, str, str]]":
        with self._lock:
            return list(self._transitions)

    def status(self) -> dict:
        """Debug-endpoint / bench-summary payload."""
        duty = self._duty()
        pressure = self._depth_pressure()
        with self._lock:
            return {
                "level": LEVELS[self._idx],
                "level_index": self._idx,
                "since": self._since,
                "transitions": len(self._transitions),
                "misses_seen": self._miss_seen,
                "engaged": sorted(self._baselines),
                "daemon_failures": self._daemon_failures,
                "duty_cycle": round(duty, 4),
                "depth_pressure": round(pressure, 4),
            }

    # ------------------------------------------------------------ thread

    def start(self) -> None:
        """Run `evaluate` every `interval_s` on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="brownout", daemon=True
            )
            t = self._thread
        t.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            # crash containment: one bad tick (a feed raising mid-
            # teardown) must not kill the controller — account it and
            # keep walking the ladder
            try:
                self.evaluate()
            except Exception:
                with self._lock:
                    self._daemon_failures += 1
                if self.metrics is not None:
                    self.metrics.daemon_loop_failures.inc("brownout")

    def stop(self) -> None:
        """Stop the tick thread and revert every engaged level, so a
        node shutdown (or a --no-brownout restart) never strands shrunk
        lane configs or a cleared replay gate."""
        self._stop_evt.set()
        with self._lock:
            t, self._thread = self._thread, None
            while self._idx > 0:
                self._shift_locked(self._idx - 1, float(self.clock()))
        if t is not None:
            t.join(timeout=5)


__all__ = [
    "B1",
    "B2",
    "B3",
    "CRITICAL",
    "LEVELS",
    "NORMAL",
    "BrownoutController",
]
