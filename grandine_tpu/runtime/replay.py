"""Bulk replay pipeline — cross-block batched signature verification for
back-sync, checkpoint catch-up, and historical slashing surveillance.

`verify_block_batch` historically built one fresh verifier and one RLC
device dispatch PER BLOCK (CONFIG3: 16.8 signature-sets/s), and
`back_sync` skipped signature re-verification entirely (the reference's
`TrustBackSyncBlocks` escape hatch). Replay is the one verify workload
whose batch size is NOT bounded by gossip deadlines, so the right shape
is the opposite of the firehose's: run `custom_state_transition` over a
WINDOW of N blocks with a `CollectingVerifier` (consensus/verifier.py)
that defers every signature — proposer, randao, attestation aggregates,
sync aggregates, operations — into ONE shared pow-2-bucketed RLC batch
on the device multi_verify kernel (one Miller loop per signature set
and one final exponentiation per WINDOW, vs one kernel dispatch and one
padded bucket per block in the legacy path).

Stages (two-deep dispatch overlap, mirroring attestation_verifier.py):

  transition_collect  optimistic state transition over the window; all
                      signature checks accumulate into the window sink
  dispatch            host prep + async device dispatch of the combined
                      batch (readback stays in the settle closure)
  settle              force the batch verdict; window W+1's transition
                      ran while window W's batch was on the device
  commit              feed every replayed attestation and block header
                      through the Slasher (historical surround/double-
                      vote surveillance) — only for VERIFIED blocks

A failed window batch triggers O(log n) split-in-half re-dispatch at
block granularity (the verify scheduler's bisection shape — never a
linear per-signature host walk): each probe re-dispatches half the
remaining item range as one batch, descending into the failing half
until one block remains, whose items are then checked individually to
name the offending signature.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional, Sequence

from grandine_tpu.consensus import accessors
from grandine_tpu.consensus.verifier import (
    CollectingVerifier,
    SignatureInvalid,
)
from grandine_tpu.crypto import bls as A
from grandine_tpu.runtime import flight as _flight
from grandine_tpu.runtime.verify_scheduler import VerifyItem, host_check_item
from grandine_tpu.tracing import NULL_TRACER

logger = logging.getLogger("grandine.replay")

#: default blocks per window — two epochs of minimal preset / a quarter
#: epoch of mainnet; the sweet spot where per-dispatch overhead amortizes
#: without holding more than a few thousand signature sets per batch
DEFAULT_WINDOW_BLOCKS = 32
#: windows in flight (dispatched, not settled): the same two-deep bound
#: the firehose uses — window W+1 transitions while W is on the device
DEFAULT_PIPELINE_DEPTH = 2


class ReplayInvalidBlock(SignatureInvalid):
    """A window batch failed and bisection localized the offending block.
    `index` is the position in the replayed sequence, `verified_posts`
    the post-states of every block BEFORE it (all verified)."""

    def __init__(self, index: int, slot: int, root: bytes, reason: str,
                 verified_posts: "Sequence" = ()) -> None:
        super().__init__(
            f"replay block {index} (slot {slot}, root {root.hex()[:16]}…) "
            f"failed verification: {reason}"
        )
        self.index = index
        self.slot = slot
        self.root = bytes(root)
        self.verified_posts = list(verified_posts)


class _WindowSink:
    """CollectingVerifier sink for one window: VerifyItems in collection
    order (per-block contiguous, so a (lo, hi) slice names one block's
    signature sets)."""

    __slots__ = ("items",)

    def __init__(self) -> None:
        self.items: "list[VerifyItem]" = []

    def add(self, message, signature, public_keys=None,
            member_indices=None, pubkey_columns=None) -> None:
        self.items.append(VerifyItem(
            message, signature, public_keys=public_keys,
            member_indices=member_indices, pubkey_columns=pubkey_columns,
        ))


class _Window:
    """One window's optimistic results, held until the batch settles."""

    __slots__ = ("blocks", "posts", "items", "slices", "slasher_feed",
                 "start_index", "t0")

    def __init__(self, blocks, start_index: int) -> None:
        self.blocks = list(blocks)
        self.start_index = start_index
        self.posts: list = []
        self.items: "list[VerifyItem]" = []
        #: per-block [lo, hi) into `items`
        self.slices: "list[tuple[int, int]]" = []
        #: per-block (proposer, slot, root, [(indices, src, tgt, droot)])
        self.slasher_feed: list = []
        self.t0 = time.perf_counter()


class BulkReplayPipeline:
    """Verify a historical block sequence with cross-block device batches.

    `replay(anchor_state, blocks)` returns the post-state of every block,
    raising `ReplayInvalidBlock` (bisection-localized) on a bad signature
    or the underlying `TransitionError`/`StateRootMismatch` on a
    structurally invalid block. With `slasher` set, every verified
    block's attestations and header feed the slashing database, so
    back-fill doubles as historical surveillance.

    Thread ownership: `replay` drives everything on the CALLING thread;
    window state is single-owned and only the injected scheduler's own
    threads run concurrently behind the ticket API."""

    def __init__(
        self,
        cfg,
        *,
        use_device: bool = False,
        backend=None,
        window_size: int = DEFAULT_WINDOW_BLOCKS,
        pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
        slasher=None,
        metrics=None,
        tracer=None,
        flight=None,
        state_root_policy: str = "verify",
    ) -> None:
        self.cfg = cfg
        self.use_device = use_device
        if use_device and backend is None:
            from grandine_tpu.tpu import schemes

            backend = schemes.get("bls").make_backend(
                metrics=metrics, tracer=tracer, lane="replay"
            )
        self.backend = backend
        #: flight recorder: one record per window in the "replay" lane
        self.flight = (
            flight if flight is not None
            else _flight.FlightRecorder(metrics=metrics)
        )
        self.window_size = max(1, int(window_size))
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.slasher = slasher
        #: brownout gate: cleared by the BrownoutController at B3 to
        #: pause bulk replay between windows (live duties outrank
        #: catch-up); set again on recovery. Starts open.
        self.run_gate = threading.Event()
        self.run_gate.set()
        self.metrics = metrics
        self.tracer = tracer or NULL_TRACER
        self.state_root_policy = state_root_policy
        self.stats = {
            "windows": 0, "blocks": 0, "sigsets": 0, "localizations": 0,
            "slasher_attestations": 0, "slasher_hits": 0,
            "slasher_errors": 0,
        }

    # ------------------------------------------------------------- driver

    def replay(self, anchor_state, blocks) -> list:
        """Replay `blocks` (a parent→child chain extending `anchor_state`)
        through windowed batch verification; returns all post-states."""
        blocks = list(blocks)
        posts: list = []
        pending: "deque[tuple[_Window, object, object]]" = deque()
        state = anchor_state
        device = self.use_device and self.backend is not None
        kernel = "multi_verify" if device else "host"
        try:
            for w0 in range(0, len(blocks), self.window_size):
                # brownout B3 pauses catch-up at window granularity —
                # in-flight windows still settle, new ones wait here
                self.run_gate.wait()
                chunk = blocks[w0 : w0 + self.window_size]
                window, state = self._transition_and_collect(
                    state, chunk, w0
                )
                fl = self.flight.begin_batch(
                    "replay", kernel, len(window.items)
                )
                t0 = time.perf_counter()
                settle = self._dispatch_batch(window.items)
                (fl.note_device if device else fl.note_host)(
                    time.perf_counter() - t0
                )
                if device:
                    self.flight.device_enter()
                pending.append((window, settle, fl))
                self._note_depth(len(pending))
                while len(pending) > self.pipeline_depth:
                    self._settle_window(*pending.popleft(), posts=posts)
                    self._note_depth(len(pending))
        except Exception:
            # a bad signature in an ALREADY-DISPATCHED window outranks
            # whatever just went wrong downstream of it: settle the
            # in-flight windows first (their failure replaces this one)
            while pending:
                self._settle_window(*pending.popleft(), posts=posts)
            raise
        while pending:
            self._settle_window(*pending.popleft(), posts=posts)
            self._note_depth(len(pending))
        return posts

    def _note_depth(self, depth: int) -> None:
        if self.metrics is not None:
            self.metrics.replay_pipeline_depth.set(depth)

    def _stage(self, stage: str, **attrs):
        return _StageTimer(self, stage, attrs)

    # --------------------------------------------------- transition+collect

    def _transition_and_collect(self, state, chunk, start_index: int):
        """Optimistically transition the window, deferring every signature
        into the window sink; records per-block item slices (for the
        bisection) and the slasher feed entries (committed after the
        batch verdict)."""
        from grandine_tpu.transition.combined import custom_state_transition

        sink = _WindowSink()
        verifier = CollectingVerifier(sink)
        window = _Window(chunk, start_index)
        window.items = sink.items
        with self._stage("transition_collect", blocks=len(chunk)):
            for blk in chunk:
                lo = len(sink.items)
                post = custom_state_transition(
                    state, blk, self.cfg, verifier,
                    state_root_policy=self.state_root_policy,
                )
                window.slices.append((lo, len(sink.items)))
                window.posts.append(post)
                if self.slasher is not None:
                    window.slasher_feed.append(
                        self._slasher_entries(post, blk)
                    )
                state = post
        return window, state

    def _slasher_entries(self, post, signed_block):
        """(proposer, slot, root, [(indices, source, target, data_root)])
        for one block, resolved against the post-state (its committees
        cover the attestations' current-and-previous-epoch slots)."""
        block = signed_block.message
        atts = []
        p = self.cfg.preset
        for att in block.body.attestations:
            try:
                indices = accessors.get_attesting_indices(
                    post, att.data, att.aggregation_bits, p
                )
            except Exception:
                self.stats["slasher_errors"] += 1
                continue
            atts.append((
                [int(i) for i in indices],
                int(att.data.source.epoch),
                int(att.data.target.epoch),
                bytes(att.data.hash_tree_root()),
            ))
        return (
            int(block.proposer_index),
            int(block.slot),
            bytes(block.hash_tree_root()),
            atts,
        )

    # ----------------------------------------------------------- dispatch

    def _dispatch_batch(self, items: "Sequence[VerifyItem]"):
        """Host prep + async dispatch of one cross-block batch; returns a
        zero-arg settle callable producing the batch verdict. Readback
        happens only inside the settle closures."""
        if not items:
            return lambda: True
        if self.use_device and self.backend is not None:
            settle = self._device_dispatch(items)
            if settle is not None:
                return settle
        return self._host_dispatch(items)

    def _device_dispatch(self, items: "Sequence[VerifyItem]"):
        """ONE RLC multi_verify kernel dispatch for the whole window.

        The firehose needs per-item verdicts (gossip attribution), so it
        pays the fast-aggregate kernels' two pairings per item. Replay
        does not: a window wants a single combined verdict — attribution
        comes from the bisection, not the kernel — so the RLC batch
        kernel (one Miller loop per item, one final exponentiation per
        WINDOW) is the right shape, exactly the per-block TpuVerifier
        kernel but dispatched once per window instead of once per block.
        Signatures decompress WITHOUT the per-item host subgroup
        scalar-mul; the device ψ-ladder batch check covers them."""
        backend = self.backend
        if not (
            hasattr(backend, "multi_verify_async")
            and hasattr(backend, "g2_subgroup_check_batch_async")
        ):
            return None
        try:
            points = [
                A.g2_from_bytes(it.signature, subgroup_check=False)
                for it in items
            ]
        except A.BlsError:
            return lambda: False
        if any(p.is_infinity() for p in points):
            return lambda: False
        try:
            pks = [
                resolved[0] if len(resolved) == 1
                else A.PublicKey.aggregate(resolved)
                for resolved in (it.resolve_keys() for it in items)
            ]
        except SignatureInvalid:
            return lambda: False
        sub_settle = backend.g2_subgroup_check_batch_async(points)
        sigs = [A.Signature(p) for p in points]
        if self.metrics is not None:
            self.metrics.device_batch_sigs.inc(len(sigs))
        mv_settle = backend.multi_verify_async(
            [it.message for it in items], sigs, pks
        )

        def settle() -> bool:
            if not bool(sub_settle().all()):
                return False
            return bool(mv_settle())

        return settle

    def _host_dispatch(self, items: "Sequence[VerifyItem]"):
        """MultiVerifier semantics over the whole window: aggregate each
        item's signer set host-side, one anchor RLC multi_verify. The
        work is deferred into the settle closure so the dispatch stage
        stays cheap on the host path too."""

        def settle() -> bool:
            messages, signatures, pks = [], [], []
            try:
                for it in items:
                    signatures.append(A.Signature.from_bytes(it.signature))
                    resolved = it.resolve_keys()
                    messages.append(it.message)
                    pks.append(
                        resolved[0] if len(resolved) == 1
                        else A.PublicKey.aggregate(resolved)
                    )
            except (A.BlsError, SignatureInvalid):
                return False
            return A.multi_verify(messages, signatures, pks)

        return settle

    # ------------------------------------------------------------- settle

    def _settle_window(self, window: _Window, settle, fl,
                       posts: list) -> None:
        device = self.use_device and self.backend is not None
        with self._stage("settle", blocks=len(window.blocks)):
            t0 = time.perf_counter()
            try:
                ok = bool(settle())
            finally:
                (fl.note_device if device else fl.note_host)(
                    time.perf_counter() - t0
                )
                if device:
                    self.flight.device_exit()
        if not ok:
            self.stats["localizations"] += 1
            t0 = time.perf_counter()
            k, reason = self._localize(window)
            fl.note_bisect(
                time.perf_counter() - t0,
                depth=max(1, len(window.blocks).bit_length()),
            )
            fl.finish(False)
            posts.extend(window.posts[:k])
            self._commit(window, upto=k)
            blk = window.blocks[k]
            raise ReplayInvalidBlock(
                window.start_index + k,
                int(blk.message.slot),
                blk.message.hash_tree_root(),
                reason,
                posts,
            )
        fl.finish(True)
        self._commit(window, upto=len(window.blocks))
        posts.extend(window.posts)
        self.stats["windows"] += 1
        self.stats["blocks"] += len(window.blocks)
        self.stats["sigsets"] += len(window.items)
        if self.metrics is not None:
            self.metrics.replay_blocks.inc(len(window.blocks))
            self.metrics.replay_sigsets.inc(len(window.items))
            self.metrics.replay_window_seconds.observe(
                time.perf_counter() - window.t0
            )

    def _localize(self, window: _Window) -> "tuple[int, str]":
        """First invalid block of a failed window: split-in-half
        re-dispatch (O(log n) batch probes, the scheduler's `_isolate`
        shape), then an item-level host check of the single remaining
        block to name the offending signature."""

        def batch_ok(b_lo: int, b_hi: int) -> bool:
            i_lo = window.slices[b_lo][0]
            i_hi = window.slices[b_hi - 1][1]
            half = window.items[i_lo:i_hi]
            if not half:
                return True
            return bool(self._dispatch_batch(half)())

        lo, hi = 0, len(window.blocks)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if batch_ok(lo, mid):
                # the left half verifies as a batch → the FIRST invalid
                # block is in the right half
                lo = mid
            else:
                hi = mid
        s_lo, s_hi = window.slices[lo]
        for j in range(s_lo, s_hi):
            if not host_check_item(window.items[j]):
                return lo, (
                    f"signature set {j - s_lo + 1} of {s_hi - s_lo} invalid"
                )
        # every item of the leaf passes individually: the batch verdict
        # came from a device fault/wrong verdict, not this block's data
        return lo, "window batch rejected (leaf items verify individually)"

    # ------------------------------------------------------------- commit

    def _commit(self, window: _Window, upto: int) -> None:
        """Feed the slasher the VERIFIED prefix of the window: every
        replayed attestation (surround/double-vote surveillance over
        history) and every block header (double-proposal)."""
        if self.slasher is None or upto == 0 or not window.slasher_feed:
            return
        with self._stage("commit", blocks=upto):
            # block headers stay per-block (double-proposal checks are a
            # single K-V probe each); the window's attestations feed the
            # slasher in ONE bulk call so span updates merge into a
            # handful of vectorized chunk passes — or one device grid
            # dispatch — instead of a Python walk per attesting index
            flat: "list[tuple]" = []   # (slot, att) per attestation
            for proposer, slot, root, atts in window.slasher_feed[:upto]:
                try:
                    if self.slasher.on_block(proposer, slot, root):
                        self.stats["slasher_hits"] += 1
                except Exception:
                    # surveillance is best-effort: a slasher fault must
                    # not abort an otherwise verified replay
                    self.stats["slasher_errors"] += 1
                for att in atts:
                    flat.append((slot, att))
            if not flat:
                return
            try:
                hit_lists = self.slasher.on_attestations_bulk(
                    [att for _slot, att in flat]
                )
            except Exception:
                self.stats["slasher_errors"] += 1
                return
            for (slot, att), hits in zip(flat, hit_lists):
                target = att[2]
                self.stats["slasher_attestations"] += 1
                self.stats["slasher_hits"] += len(hits)
                for hit in hits:
                    rec = self.slasher.record_for(
                        hit.validator_index, target
                    )
                    logger.warning(
                        "historical %s by validator %d at slot %d"
                        " (recorded vote: %s)", hit.kind,
                        hit.validator_index, slot,
                        rec and (rec[0], rec[1].hex()[:16]),
                    )


class _StageTimer:
    """Span + verify_stage_seconds{stage,lane="replay"} per stage, the
    attestation pipeline's observability contract."""

    __slots__ = ("pipe", "stage", "attrs", "t0", "_span")

    def __init__(self, pipe: BulkReplayPipeline, stage: str, attrs) -> None:
        self.pipe = pipe
        self.stage = stage
        self.attrs = attrs

    def __enter__(self):
        self.t0 = time.perf_counter()
        self._span = self.pipe.tracer.span(self.stage, self.attrs or None)
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        if self.pipe.metrics is not None:
            self.pipe.metrics.verify_stage_seconds.labels(
                self.stage, "replay"
            ).observe(time.perf_counter() - self.t0)
        return False


__all__ = [
    "BulkReplayPipeline",
    "ReplayInvalidBlock",
    "DEFAULT_WINDOW_BLOCKS",
    "DEFAULT_PIPELINE_DEPTH",
]
