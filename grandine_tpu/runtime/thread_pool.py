"""Two-priority thread pool + WaitGroup drain barrier.

Reference: fork_choice_control/src/thread_pool.rs (one OS thread per core,
high-priority VecDeque for blocks/blobs/checkpoint states, low-priority for
attestations; both behind one mutex + condvar — :47-64,90-141,202-232) and
wait.rs:1-41 (`WaitGroup` so tests block until all spawned tasks drain,
with poisoning on panic so tests fail instead of hanging).

Python threads still buy real parallelism here: the heavy work inside
tasks (numpy, native SHA, JAX dispatch) releases the GIL.
"""

from __future__ import annotations

import enum
import os
import threading
from collections import deque
from typing import Callable, Optional


class Priority(enum.IntEnum):
    HIGH = 0  # blocks, blob sidecars, checkpoint states
    LOW = 1   # attestations, aggregates, slashings


class PoolPoisoned(RuntimeError):
    """A pool task panicked; the WaitGroup refuses to report quiescence."""


class WaitGroup:
    """Counts in-flight tasks; `wait()` blocks until all complete. A task
    that raises poisons the group (reference wait.rs Wait::poison +
    controller.rs:158-170)."""

    def __init__(self) -> None:
        self._count = 0
        self._cond = threading.Condition()
        self._poison: "Optional[BaseException]" = None

    def add(self) -> None:
        with self._cond:
            self._count += 1

    def done(self, error: "Optional[BaseException]" = None) -> None:
        with self._cond:
            self._count -= 1
            if error is not None and self._poison is None:
                self._poison = error
            if self._count <= 0:
                self._cond.notify_all()

    def wait(self, timeout: "Optional[float]" = None) -> None:
        with self._cond:
            if not self._cond.wait_for(lambda: self._count <= 0, timeout):
                raise TimeoutError(f"{self._count} tasks still in flight")
            if self._poison is not None:
                raise PoolPoisoned(repr(self._poison)) from self._poison

    def idle(self) -> bool:
        with self._cond:
            return self._count <= 0


class ThreadPool:
    """Fixed worker pool; spawns take a priority. High-priority tasks are
    always dequeued before low-priority ones (strict, like the reference's
    two VecDeques under one mutex)."""

    def __init__(self, n_threads: "Optional[int]" = None,
                 wait_group: "Optional[WaitGroup]" = None,
                 tracer=None) -> None:
        self.n_threads = n_threads or max(1, (os.cpu_count() or 2))
        self.wait_group = wait_group or WaitGroup()
        #: optional grandine_tpu.tracing.Tracer — when set, the spawning
        #: thread's current span is captured at spawn() and re-installed
        #: on the worker so task spans nest under their submitter
        self.tracer = tracer
        self._queues = {Priority.HIGH: deque(), Priority.LOW: deque()}
        self._cond = threading.Condition()
        self._stop = False
        self._threads = [
            threading.Thread(
                target=self._run, name=f"store-worker-{i}", daemon=True
            )
            for i in range(self.n_threads)
        ]
        for t in self._threads:
            t.start()

    def spawn(self, fn: Callable[[], None],
              priority: Priority = Priority.HIGH) -> None:
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            parent = tracer.capture()
            task, fn = fn, lambda: self._traced(task, parent)
        self.wait_group.add()
        with self._cond:
            if self._stop:
                self.wait_group.done()
                raise RuntimeError("pool stopped")
            self._queues[priority].append(fn)
            self._cond.notify()

    def _traced(self, task: Callable[[], None], parent) -> None:
        with self.tracer.attach(parent):
            task()

    def _next_task(self):
        for prio in (Priority.HIGH, Priority.LOW):
            q = self._queues[prio]
            if q:
                return q.popleft()
        return None

    def _run(self) -> None:
        """Runs on EVERY pool worker thread: all shared state (queues,
        counters, stop flag) is touched only under _cond."""
        while True:
            with self._cond:
                task = self._next_task()
                while task is None and not self._stop:
                    self._cond.wait()
                    task = self._next_task()
                if task is None:
                    return
            error = None
            try:
                task()
            except BaseException as e:  # poison, never kill the worker
                error = e
            finally:
                self.wait_group.done(error)

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            # release the WaitGroup counts of tasks that will never run
            abandoned = sum(len(q) for q in self._queues.values())
            for q in self._queues.values():
                q.clear()
            self._cond.notify_all()
        for _ in range(abandoned):
            self.wait_group.done()
        for t in self._threads:
            t.join(timeout=5)

    def __enter__(self) -> "ThreadPool":
        return self

    def __exit__(self, *_) -> None:
        self.stop()


__all__ = ["Priority", "ThreadPool", "WaitGroup", "PoolPoisoned"]
