"""Slot clock — reference: `clock` crate (clock/src/lib.rs:1-30: a Stream
of Ticks, 3 per slot at 0, 1/3 and 2/3 of the slot, driving propose /
attest / aggregate duties).

Pure time math here; the driving loop (sleep-until-next-tick) lives in the
node. Everything is testable without wall time by feeding ticks manually.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional

from grandine_tpu.fork_choice.store import Tick, TickKind

INTERVALS_PER_SLOT = 3


class SlotClock:
    """Maps wall time <-> (slot, interval)."""

    def __init__(self, genesis_time: int, seconds_per_slot: int) -> None:
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def current_slot(self, now: "Optional[float]" = None) -> int:
        now = time.time() if now is None else now
        if now < self.genesis_time:
            return 0
        return int(now - self.genesis_time) // self.seconds_per_slot

    def tick_at(self, now: "Optional[float]" = None) -> Tick:
        now = time.time() if now is None else now
        slot = self.current_slot(now)
        into = (now - self.genesis_time) - slot * self.seconds_per_slot
        interval = min(
            INTERVALS_PER_SLOT - 1,
            max(0, int(into * INTERVALS_PER_SLOT / self.seconds_per_slot)),
        )  # clamped at 0: before genesis `into` is negative
        return Tick(slot, TickKind(interval))

    def time_of(self, tick: Tick) -> float:
        return (
            self.genesis_time
            + tick.slot * self.seconds_per_slot
            + int(tick.kind) * self.seconds_per_slot / INTERVALS_PER_SLOT
        )

    def next_tick(self, now: "Optional[float]" = None) -> Tick:
        now = time.time() if now is None else now
        cur = self.tick_at(now)
        if int(cur.kind) + 1 < INTERVALS_PER_SLOT:
            return Tick(cur.slot, TickKind(int(cur.kind) + 1))
        return Tick(cur.slot + 1, TickKind.PROPOSE)


def ticks_for_slot(slot: int) -> "Iterator[Tick]":
    """The three duty ticks of one slot, in order."""
    for kind in TickKind:
        yield Tick(slot, kind)


__all__ = ["SlotClock", "ticks_for_slot", "INTERVALS_PER_SLOT", "Tick", "TickKind"]
