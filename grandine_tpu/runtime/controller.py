"""Controller + mutator actor — reference: fork_choice_control/src/
controller.rs (facade :62-72, spawn_block_task :199-201), mutator.rs (the
single-writer thread owning the Store :1-15,167, delayed-object retry maps
:84-104), tasks.rs (Block/Attestation task types with panic catching).

Threading model (the reference's, kept):
  - expensive validation (state transition + signature batches) runs on a
    2-priority ThreadPool, many tasks in parallel, reading the store
    without locks (insert-only BlockNode graph; a read racing a prune is
    caught and surfaces as a retryable ForkChoiceError);
  - ALL mutation flows through one mutator thread via a queue (actor);
  - readers get an immutable `Snapshot` swapped atomically after each
    mutation (ArcSwap equivalent: Python attribute store is atomic);
  - blocks with unknown parents are delayed and retried when the parent
    arrives (mutator.rs delayed_until_block).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence

from grandine_tpu.consensus.verifier import (
    MultiVerifier,
    SignatureInvalid,
    Verifier,
)
from grandine_tpu.fork_choice.store import (
    ForkChoiceError,
    Store,
    Tick,
    ValidAttestation,
    ValidBlock,
)
from grandine_tpu.runtime.thread_pool import Priority, ThreadPool, WaitGroup
from grandine_tpu.transition.block import TransitionError


class Snapshot:
    """Immutable post-mutation view for lock-free readers
    (controller.rs:62-72 `Snapshot` over ArcSwap)."""

    __slots__ = (
        "head_root",
        "head_state",
        "slot",
        "justified_checkpoint",
        "finalized_checkpoint",
        "block_count",
        "is_optimistic",
        "validator_count",
    )

    def __init__(self, store: Store) -> None:
        self.head_root = store.get_head()
        self.head_state = store.blocks[self.head_root].state
        self.slot = store.slot
        self.justified_checkpoint = store.justified_checkpoint
        self.finalized_checkpoint = store.finalized_checkpoint
        self.block_count = len(store)
        # head chain contains an EL-unjudged payload (optimistic sync)
        self.is_optimistic = store.is_optimistic(self.head_root)
        #: registry size of the head state — drives the device pubkey
        #: registry's staleness hook (tpu/registry.py)
        self.validator_count = len(self.head_state.validators.items)


class Controller:
    """Public API is callable from any thread; everything mutating is
    marshalled onto the store-mutator thread."""

    def __init__(
        self,
        anchor_state,
        cfg,
        execution_engine=None,
        verifier_factory: "Callable[[], Verifier]" = MultiVerifier,
        pool: "Optional[ThreadPool]" = None,
        wait_group: "Optional[WaitGroup]" = None,
        storage=None,
        metrics=None,
        tracer=None,
    ) -> None:
        self.cfg = cfg
        self.verifier_factory = verifier_factory
        self.storage = storage
        #: optional central VerifyScheduler (runtime/verify_scheduler.py):
        #: when set, blob-sidecar header signatures ride its HIGH
        #: "blob_header" lane instead of verifying eagerly on the pool
        #: thread (which still blocks on the ticket — semantics unchanged)
        self.verify_scheduler = None
        self.metrics = metrics
        #: optional tracing.Tracer — handed to the pool so task spans nest
        #: under whatever span spawned them
        self.tracer = tracer
        self._persisted_finalized = -1
        self.store = Store(anchor_state, cfg, execution_engine=execution_engine)
        if storage is not None:
            # persist the finalized chain BEFORE the store prunes it away
            self.store.pre_prune_hook = self._persist_finalized
        self.wait_group = wait_group or WaitGroup()
        self.pool = pool or ThreadPool(wait_group=self.wait_group, tracer=tracer)
        self._owns_pool = pool is None

        self._delayed_by_parent: "dict[bytes, list]" = {}
        self._delayed_by_slot: "dict[int, list]" = {}
        # deneb blob plane (mutator-owned; mutator.rs:84-104
        # delayed_until_blobs + the store blob cache): a block with
        # commitments imports only when all its sidecars have arrived
        self._delayed_by_blobs: "dict[bytes, object]" = {}
        self._blob_cache: "dict[bytes, dict[int, object]]" = {}
        self._blob_seen: "set[tuple[bytes, int]]" = set()
        #: KZG trusted setup override (tests inject dev_setup)
        self.kzg_setup = None
        #: called (on the mutator thread — spawn, don't block) with the
        #: missing parent root whenever a block is delayed on an unknown
        #: parent; the sync layer resolves it via BlocksByRoot
        self.on_unknown_parent: "list[Callable[[bytes], None]]" = []
        #: called on the mutator thread with (block_root, sidecar) for
        #: every NEW validated sidecar (the SSE blob_sidecar event point)
        self.on_blob_sidecar: "list[Callable]" = []
        self._delayed_attestations: "list[ValidAttestation]" = []
        self._rejected: "list[tuple[bytes, str]]" = []
        self._state_cache: "dict[tuple, object]" = {}
        #: called on the mutator thread with (old_head_root, snapshot)
        #: whenever ANY mutation (block, attestation batch, tick) moves
        #: the head — the head/chain_reorg event publication point
        self.on_head_change: "list[Callable]" = []
        #: called on the mutator thread after EVERY applied block with
        #: (valid_block, old_head_root, snapshot) — the event-stream
        #: publication point (http_api events.rs)
        self.on_block_applied: "list[Callable]" = []
        #: called on the mutator thread with (old_snapshot, new_snapshot)
        #: when the head state's validator count or the finalized epoch
        #: changes — the device pubkey registry's staleness hook
        #: (deposits extend the set; finalization is the natural
        #: re-check point for everything else)
        self.on_validator_set_change: "list[Callable]" = []

        # on every head change, notify the EL (engine_forkchoiceUpdated)
        # off-thread and feed its verdict back as a payload-status mutation
        # (the reference's ExecutionService loop; controller.rs:242-247).
        # Null engines are consensus-only — skip the round trip.
        from grandine_tpu.execution import NullExecutionEngine

        if not isinstance(self.store.execution_engine, NullExecutionEngine):
            self.on_head_change.append(self._notify_forkchoice)

        self._snapshot = Snapshot(self.store)
        self._mutations: "queue.Queue" = queue.Queue()
        self._mutator = threading.Thread(
            target=self._mutator_run, name="store-mutator", daemon=True
        )
        self._mutator.start()

    # -------------------------------------------------------------- restore

    @classmethod
    def restore(cls, storage, cfg, anchor_state=None, **kwargs):
        """Rebuild a controller from persisted storage: load the anchor
        (finalized) state, then replay unfinalized blocks through normal
        validation (controller.rs:140 process_unfinalized_blocks)."""
        state, unfinalized = storage.load(anchor_state=anchor_state)
        ctrl = cls(state, cfg, storage=storage, **kwargs)
        if unfinalized:
            from grandine_tpu.fork_choice.store import Tick, TickKind

            max_slot = max(int(b.message.slot) for b in unfinalized)
            ctrl.on_tick(Tick(max_slot, TickKind.AGGREGATE))
            for blk in unfinalized:
                ctrl.on_requested_block(blk)
            ctrl.wait()
        return ctrl

    # ---------------------------------------------------------------- reads

    def snapshot(self) -> Snapshot:
        return self._snapshot

    def state_at_slot(self, slot: int, snapshot: "Snapshot | None" = None):
        """Head state advanced through empty slots to `slot`, memoized —
        the StateCache slot-advancer (fork_choice_control/src/
        state_cache.rs:25-135): duties at tick boundaries all need the
        same advanced state; compute it once per (head, slot).

        Pass the `snapshot` you already hold to keep (head_root, state)
        coherent under concurrent head changes — the mutator thread may
        swap `self._snapshot` between a caller's snapshot() read and
        this call."""
        from grandine_tpu.transition.slots import process_slots

        snap = snapshot if snapshot is not None else self._snapshot
        state = snap.head_state
        if int(state.slot) >= slot:
            return state
        key = (snap.head_root, slot)
        cached = self._state_cache.get(key)
        if cached is not None:
            return cached
        advanced = process_slots(state, slot, self.cfg)
        # bounded: keep only the latest few advanced states (eviction is
        # best-effort under concurrent callers — losing the race is fine)
        try:
            if len(self._state_cache) >= 4:
                self._state_cache.pop(next(iter(self._state_cache)), None)
        except (StopIteration, RuntimeError):
            pass
        self._state_cache[key] = advanced
        return advanced

    # --------------------------------------------------------------- inputs

    def on_tick(self, tick: Tick) -> None:
        self._send(("tick", tick))

    @staticmethod
    def _blob_commitment_count(signed_block) -> int:
        body = getattr(signed_block.message, "body", None)
        comms = getattr(body, "blob_kzg_commitments", None) if body else None
        return len(comms) if comms is not None else 0

    def on_gossip_block(self, signed_block) -> None:
        """Untrusted block: full verification on the high-priority pool
        (controller.rs spawn_block_task → tasks.rs BlockTask). Deneb blocks
        carrying blob commitments first pass the mutator's blob gate —
        import waits until every sidecar has arrived
        (mutator.rs delayed_until_blobs)."""
        if self._blob_commitment_count(signed_block):
            self._send(("block_with_blobs", signed_block))
        else:
            self._spawn_block_task(signed_block, trusted=False)

    def on_gossip_blob_sidecar(self, sidecar) -> None:
        """Untrusted sidecar: inclusion-proof + KZG validation on the
        low-priority pool, then into the mutator's blob cache (dedup by
        (block_root, index)); completes any block delayed on its blobs.
        The KZG proof leg rides the scheduler's `blob_kzg` lane (device
        batch) when available, with the host check as the degradation
        target — see _check_sidecar_kzg. Reference: BlobSidecarTask
        (fork_choice_control/src/tasks.rs) + mutator delayed_until_blobs."""
        header_root = sidecar.signed_block_header.message.hash_tree_root()
        if (header_root, int(sidecar.index)) in self._blob_seen:
            return  # cheap racy pre-check; the mutator dedups authoritatively

        def task() -> None:
            from grandine_tpu.kzg.sidecar import (
                validate_blob_sidecar_structure,
            )
            from grandine_tpu.types.containers import spec_types

            ns = spec_types(self.cfg.preset).deneb
            try:
                validate_blob_sidecar_structure(
                    sidecar, ns.BeaconBlockBody, self.cfg.preset
                )
                self._check_sidecar_header(sidecar)
            except Exception:
                return  # invalid sidecar: drop (gossip penalty is P2P-level)
            if not self._check_sidecar_kzg(sidecar):
                return  # proof definitively false on SOME path: drop
            self._send(("blob_sidecar", (header_root, sidecar)))

        self.pool.spawn(task, Priority.LOW)

    def _check_sidecar_kzg(self, sidecar) -> bool:
        """The sidecar's KZG proof verdict. Routed through the verify
        scheduler's `blob_kzg` lane (device-batched with other in-flight
        sidecars) when one is attached; the host proof check is the
        degradation target. A device/lane FAULT — timeout, shed ticket,
        scheduler exception — never drops a sidecar: only a definitive
        False verdict (from either path) rejects. Origin/quarantine
        plumbing is untouched: sidecar jobs carry no origin, so they are
        never rerouted into the quarantine lane."""
        blob = bytes(sidecar.blob)
        commitment = bytes(sidecar.kzg_commitment)
        proof = bytes(sidecar.kzg_proof)
        sched = self.verify_scheduler
        if sched is not None and "blob_kzg" in getattr(sched, "lanes", {}):
            route = True
            if self.kzg_setup is not None:
                # the lane resolves its trusted setup by blob width; only
                # route when that resolution lands on the injected setup
                try:
                    from grandine_tpu.kzg.eip4844 import (
                        BYTES_PER_FIELD_ELEMENT,
                        _setup_for_width,
                    )

                    width = len(blob) // BYTES_PER_FIELD_ELEMENT
                    route = _setup_for_width(width) is self.kzg_setup
                except Exception:
                    route = False
            if route:
                try:
                    from grandine_tpu.runtime.verify_scheduler import (
                        VerifyItem,
                    )

                    ticket = sched.submit(
                        "blob_kzg",
                        [VerifyItem(blob, proof, public_keys=(commitment,))],
                    )
                    ok = ticket.result(30.0)
                    if not ticket.dropped:
                        return bool(ok)
                except Exception:
                    pass  # lane fault: degrade to the host check below
        from grandine_tpu.kzg import eip4844

        try:
            return bool(
                eip4844.verify_blob_kzg_proof(
                    blob, commitment, proof, self.kzg_setup
                )
            )
        except eip4844.KzgError:
            return False

    def _check_sidecar_header(self, sidecar) -> None:
        """The inclusion proof binds the commitment to the header, but
        nothing binds the header to its claimed proposer — verify the
        proposer signature on `signed_block_header` (and bound the slot)
        before the sidecar can enter the cache, so a peer can't fill
        `_blob_cache` with sidecars for headers nobody signed (spec
        blob_sidecar gossip condition [REJECT] proposer signature)."""
        from grandine_tpu.consensus import accessors, keys, signing
        from grandine_tpu.crypto import bls as A

        header = sidecar.signed_block_header.message
        state = self._snapshot.head_state
        horizon = self.store.slot + 2 * self.cfg.preset.SLOTS_PER_EPOCH
        if int(header.slot) > horizon:
            raise ForkChoiceError("sidecar header slot beyond horizon")
        cols = accessors.registry_columns(state)
        idx = int(header.proposer_index)
        if idx >= len(cols.pubkeys):
            raise ForkChoiceError("sidecar proposer index out of range")
        root = signing.header_signing_root(state, header, self.cfg)
        sched = self.verify_scheduler
        if sched is not None:
            from grandine_tpu.runtime.verify_scheduler import VerifyItem

            ticket = sched.submit(
                "blob_header",
                [VerifyItem(
                    root, bytes(sidecar.signed_block_header.signature),
                    member_indices=(idx,), pubkey_columns=cols.pubkeys,
                )],
            )
            if not ticket.result(30.0):
                raise SignatureInvalid("sidecar header signature invalid")
            return
        pk = keys.decompress_pubkey(cols.pubkeys[idx], trusted=True)
        sig = A.Signature.from_bytes(
            bytes(sidecar.signed_block_header.signature)
        )
        if not sig.verify(root, pk):
            raise SignatureInvalid("sidecar header signature invalid")

    def blob_sidecars_for(self, block_root: bytes) -> "list":
        """Validated sidecars for a block (ordered by index) — the
        BlobsByRange/BlobsByRoot serving source."""
        have = self._blob_cache.get(bytes(block_root), {})
        return [have[i] for i in sorted(have)]

    def on_requested_block(self, signed_block) -> None:
        self.on_gossip_block(signed_block)

    def on_own_block(self, signed_block) -> None:
        """Own (just produced) block: signatures are trusted, the state
        root is still checked (tasks.rs:103-118 TrustOwnBlockSignatures)."""
        self._spawn_block_task(signed_block, trusted=True)

    def on_verified_block(self, signed_block) -> None:
        """Block whose signatures were already verified out-of-band (the
        bulk replay pipeline re-ran the full transition with batch
        verification): skip the per-block verifier, keep the state-root
        check."""
        self._spawn_block_task(signed_block, trusted=True)

    def on_valid_attestation_batch(
        self, valids: "Sequence[ValidAttestation]"
    ) -> None:
        """Prevalidated attestations (from the AttestationVerifier service)."""
        self._send(("attestations", list(valids)))

    def on_gossip_attestation(
        self, data_slot, committee_index, target_epoch, beacon_block_root,
        target_root, attesting_indices,
    ) -> None:
        """Single fork-choice vote, validated on the low-priority pool."""

        def task() -> None:
            try:
                valid = self.store.validate_attestation(
                    data_slot, committee_index, target_epoch,
                    beacon_block_root, target_root, attesting_indices,
                )
            except ForkChoiceError:
                return
            self._send(("attestations", [valid]))

        self.pool.spawn(task, Priority.LOW)

    def on_attester_slashing(self, indices: "Sequence[int]") -> None:
        self._send(("attester_slashing", list(indices)))

    def on_notified_new_payload(
        self, execution_block_hash: bytes, status,
        latest_valid_hash: "Optional[bytes]" = None,
    ) -> None:
        """Asynchronous engine_newPayload verdict (the EL caught up after
        an optimistic import) — controller.rs:236-241
        on_notified_new_payload. VALID promotes the chain out of optimistic
        status; INVALID prunes the branch and retreats the head."""
        self._send(
            ("payload_status",
             (bytes(execution_block_hash), status, latest_valid_hash))
        )

    def on_notified_forkchoice_updated(
        self, head_block_hash: bytes, status,
        latest_valid_hash: "Optional[bytes]" = None,
    ) -> None:
        """Asynchronous engine_forkchoiceUpdated verdict for the head we
        advertised — controller.rs:242-247 on_notified_fork_choice_update.
        Same store application as a newPayload verdict."""
        self._send(
            ("payload_status",
             (bytes(head_block_hash), status, latest_valid_hash))
        )

    # ---------------------------------------------------------- test hooks

    def wait(self, timeout: "Optional[float]" = 30.0) -> None:
        """Block until every spawned task AND every queued mutation drained
        (the WaitGroup test barrier, wait.rs). Loops because applying a
        block can re-spawn delayed children (new pool tasks)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = (
                None if deadline is None else max(0.0, deadline - _time.monotonic())
            )
            self.wait_group.wait(remaining)
            self._mutations.join()
            if self.wait_group.idle() and self._mutations.unfinished_tasks == 0:
                return

    def rejected(self) -> "list[tuple[bytes, str]]":
        return list(self._rejected)

    def stop(self) -> None:
        self._send(("stop", None))
        self._mutator.join(timeout=5)
        if self._owns_pool:
            self.pool.stop()

    # ------------------------------------------------------------ internals

    def _send(self, msg) -> None:
        self._mutations.put(msg)

    def _spawn_block_task(self, signed_block, trusted: bool) -> None:
        def task() -> None:
            from grandine_tpu.consensus.verifier import NullVerifier

            verifier = NullVerifier() if trusted else self.verifier_factory()
            try:
                valid = self.store.validate_block(signed_block, verifier)
            except ForkChoiceError as e:
                if "unknown parent" in str(e):
                    self._send(("delay_block", signed_block))
                elif "future slot" in str(e):
                    # mutator.rs delayed_until_slot: a block may arrive (or
                    # race the tick mutation) before its slot starts
                    self._send(("delay_block_slot", signed_block))
                else:
                    self._send(("reject", (signed_block, str(e))))
                return
            except (SignatureInvalid, TransitionError, KeyError) as e:
                # KeyError: raced a prune — the block is pre-finalized
                self._send(("reject", (signed_block, repr(e))))
                return
            self._send(("block", valid))

        self.pool.spawn(task, Priority.HIGH)

    # ------------------------------------------------------- mutator thread

    def _mutator_run(self) -> None:
        while True:
            kind, payload = self._mutations.get()
            try:
                if kind == "stop":
                    return
                elif kind == "tick":
                    self.store.apply_tick(payload)
                    self._apply_matured_attestations()
                    self._respawn_matured_blocks()
                elif kind == "block":
                    self._handle_block(payload)
                elif kind == "attestations":
                    for valid in payload:
                        if valid.earliest_slot > self.store.slot:
                            # spec: votes count from data.slot + 1
                            self._delayed_attestations.append(valid)
                        else:
                            self.store.apply_attestation(valid)
                elif kind == "attester_slashing":
                    self.store.apply_attester_slashing(payload)
                elif kind == "payload_status":
                    block_hash, status, latest_valid = payload
                    self.store.apply_payload_status(
                        block_hash, status, latest_valid
                    )
                    self._refresh_snapshot()  # fires on_head_change itself
                elif kind == "block_with_blobs":
                    self._gate_block_on_blobs(payload)
                elif kind == "blob_sidecar":
                    self._accept_blob_sidecar(*payload)
                elif kind == "delay_block_slot":
                    slot = int(payload.message.slot)
                    if slot <= self.store.slot:
                        self._spawn_block_task(payload, trusted=False)
                    else:
                        pending = self._delayed_by_slot.setdefault(slot, [])
                        if len(pending) < 64:  # per-slot bound (spam guard)
                            pending.append(payload)
                        while len(self._delayed_by_slot) > 64:
                            # drop the furthest-future slots under spam
                            self._delayed_by_slot.pop(max(self._delayed_by_slot))
                elif kind == "delay_block":
                    parent = bytes(payload.message.parent_root)
                    if parent in self.store.blocks:
                        # parent landed between the failed validation and
                        # this message: retry immediately instead of filing
                        # under an already-applied parent (would be lost)
                        self._spawn_block_task(payload, trusted=False)
                    else:
                        newly_missing = parent not in self._delayed_by_parent
                        self._delayed_by_parent.setdefault(parent, []).append(
                            payload
                        )
                        self._prune_delayed()
                        if newly_missing:
                            for cb in self.on_unknown_parent:
                                cb(parent)
                elif kind == "reject":
                    signed_block, reason = payload
                    self._rejected.append(
                        (signed_block.message.hash_tree_root(), reason)
                    )
                    del self._rejected[: -self.MAX_REJECTED]
                # snapshot refresh only for mutating kinds ("block" refreshes
                # inside _handle_block; delay/reject mutate nothing) — the
                # head computation is the mutator's main cost
                if kind in ("tick", "attestations", "attester_slashing"):
                    self._refresh_snapshot()
            except BaseException as e:  # poison so tests fail loudly
                self.wait_group.add()
                self.wait_group.done(e)
            finally:
                self._mutations.task_done()

    def _handle_block(self, valid: ValidBlock) -> None:
        old_head = self._snapshot.head_root
        self.store.apply_block(valid)
        # retry children that were waiting for this parent
        for delayed in self._delayed_by_parent.pop(valid.root, []):
            self._spawn_block_task(delayed, trusted=False)
        # persistence (runs on the mutator thread like the reference):
        # every applied block immediately; the finalized chain is promoted
        # by the store's pre-prune hook (_persist_finalized)
        if self.storage is not None:
            self.storage.persist_unfinalized_block(
                valid.root, valid.signed_block
            )
        self._refresh_snapshot()
        if self.metrics is not None:
            self.metrics.fc_blocks_applied.inc()
            self.metrics.head_slot.set(int(self._snapshot.head_state.slot))
            self.metrics.finalized_epoch.set(
                int(self.store.finalized_checkpoint.epoch)
            )
        for cb in self.on_block_applied:
            cb(valid, old_head, self._snapshot)

    #: caps for the retry/reject books (delayed blocks from parents that
    #: never arrive would otherwise grow without bound under gossip spam)
    MAX_DELAYED_PARENTS = 256
    MAX_REJECTED = 256

    def _prune_delayed(self) -> None:
        # drop pre-finalized delays, then oldest parents over the cap
        fin_epoch = int(self.store.finalized_checkpoint.epoch)
        fin_slot = fin_epoch * self.cfg.preset.SLOTS_PER_EPOCH
        for parent in list(self._delayed_by_parent):
            kept = [
                b
                for b in self._delayed_by_parent[parent]
                if int(b.message.slot) > fin_slot
            ]
            if kept:
                self._delayed_by_parent[parent] = kept
            else:
                del self._delayed_by_parent[parent]
        while len(self._delayed_by_parent) > self.MAX_DELAYED_PARENTS:
            self._delayed_by_parent.pop(next(iter(self._delayed_by_parent)))
        del self._rejected[: -self.MAX_REJECTED]

    def _persist_finalized(self, store) -> None:
        fin = int(store.finalized_checkpoint.epoch)
        if fin > self._persisted_finalized:
            self.storage.persist_finalized_chain(store)
            self._persisted_finalized = fin

    def _respawn_matured_blocks(self) -> None:
        for slot in [s for s in self._delayed_by_slot if s <= self.store.slot]:
            for blk in self._delayed_by_slot.pop(slot):
                self._spawn_block_task(blk, trusted=False)

    def _apply_matured_attestations(self) -> None:
        if not self._delayed_attestations:
            return
        still = []
        for valid in self._delayed_attestations:
            if valid.earliest_slot <= self.store.slot:
                self.store.apply_attestation(valid)
            else:
                still.append(valid)
        self._delayed_attestations = still

    MAX_BLOB_ROOTS = 128

    def _gate_block_on_blobs(self, signed_block) -> None:
        """Mutator: spawn the block task only when every committed sidecar
        is in the cache; otherwise file under delayed_until_blobs."""
        root = signed_block.message.hash_tree_root()
        need = self._blob_commitment_count(signed_block)
        have = self._blob_cache.get(root, {})
        if all(i in have for i in range(need)):
            self._spawn_block_task(signed_block, trusted=False)
        else:
            self._delayed_by_blobs[root] = signed_block
            while len(self._delayed_by_blobs) > self.MAX_BLOB_ROOTS:
                self._delayed_by_blobs.pop(next(iter(self._delayed_by_blobs)))

    def _accept_blob_sidecar(self, header_root: bytes, sidecar) -> None:
        """Mutator: dedup, cache, and retry a blob-delayed block."""
        key = (header_root, int(sidecar.index))
        if key in self._blob_seen:
            return
        self._blob_seen.add(key)
        self._blob_cache.setdefault(header_root, {})[int(sidecar.index)] = (
            sidecar
        )
        for cb in self.on_blob_sidecar:
            cb(header_root, sidecar)
        while len(self._blob_cache) > self.MAX_BLOB_ROOTS:
            # prefer evicting roots no delayed block is waiting on — FIFO
            # would let sidecar spam evict exactly the blobs that gate an
            # import; fall back to oldest only when everything is referenced
            evicted = next(
                (r for r in self._blob_cache if r not in self._delayed_by_blobs),
                next(iter(self._blob_cache)),
            )
            for idx in self._blob_cache.pop(evicted):
                self._blob_seen.discard((evicted, idx))
        delayed = self._delayed_by_blobs.get(header_root)
        if delayed is not None:
            need = self._blob_commitment_count(delayed)
            have = self._blob_cache.get(header_root, {})
            if all(i in have for i in range(need)):
                del self._delayed_by_blobs[header_root]
                self._spawn_block_task(delayed, trusted=False)

    def _notify_forkchoice(self, old_head, snap) -> None:
        """Head moved: send engine_forkchoiceUpdated on the pool (HTTP to
        the EL must not block the mutator) and route the verdict back
        through on_notified_forkchoice_updated."""
        node = self.store.blocks.get(snap.head_root)
        if node is None or node.execution_block_hash is None:
            return
        head_hash = node.execution_block_hash
        zero = b"\x00" * 32

        def exec_hash_of(checkpoint):
            n = self.store.blocks.get(bytes(checkpoint.root))
            return (n.execution_block_hash if n else None) or zero

        safe_hash = exec_hash_of(snap.justified_checkpoint)
        fin_hash = exec_hash_of(snap.finalized_checkpoint)

        def task() -> None:
            try:
                status = self.store.execution_engine.notify_forkchoice_updated(
                    head_hash, safe_hash, fin_hash
                )
            except Exception:
                return  # EL unreachable: stay optimistic, retry on next head
            if status is not None:
                self.on_notified_forkchoice_updated(head_hash, status)

        self.pool.spawn(task, Priority.LOW)

    def _refresh_snapshot(self) -> None:
        old = self._snapshot
        self._snapshot = Snapshot(self.store)
        if self._snapshot.head_root != old.head_root:
            if self.metrics is not None:
                self.metrics.fc_head_changes.inc()
            for cb in self.on_head_change:
                cb(old.head_root, self._snapshot)
        if (
            self._snapshot.validator_count != old.validator_count
            or int(self._snapshot.finalized_checkpoint.epoch)
            != int(old.finalized_checkpoint.epoch)
        ):
            for cb in self.on_validator_set_change:
                cb(old, self._snapshot)


__all__ = ["Controller", "Snapshot"]
