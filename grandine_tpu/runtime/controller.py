"""Controller + mutator actor — reference: fork_choice_control/src/
controller.rs (facade :62-72, spawn_block_task :199-201), mutator.rs (the
single-writer thread owning the Store :1-15,167, delayed-object retry maps
:84-104), tasks.rs (Block/Attestation task types with panic catching).

Threading model (the reference's, kept):
  - expensive validation (state transition + signature batches) runs on a
    2-priority ThreadPool, many tasks in parallel, reading the store
    without locks (insert-only BlockNode graph; a read racing a prune is
    caught and surfaces as a retryable ForkChoiceError);
  - ALL mutation flows through one mutator thread via a queue (actor);
  - readers get an immutable `Snapshot` swapped atomically after each
    mutation (ArcSwap equivalent: Python attribute store is atomic);
  - blocks with unknown parents are delayed and retried when the parent
    arrives (mutator.rs delayed_until_block).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence

from grandine_tpu.consensus.verifier import (
    MultiVerifier,
    SignatureInvalid,
    Verifier,
)
from grandine_tpu.fork_choice.store import (
    ForkChoiceError,
    Store,
    Tick,
    ValidAttestation,
    ValidBlock,
)
from grandine_tpu.runtime.thread_pool import Priority, ThreadPool, WaitGroup
from grandine_tpu.transition.block import TransitionError


class Snapshot:
    """Immutable post-mutation view for lock-free readers
    (controller.rs:62-72 `Snapshot` over ArcSwap)."""

    __slots__ = (
        "head_root",
        "head_state",
        "slot",
        "justified_checkpoint",
        "finalized_checkpoint",
        "block_count",
    )

    def __init__(self, store: Store) -> None:
        self.head_root = store.get_head()
        self.head_state = store.blocks[self.head_root].state
        self.slot = store.slot
        self.justified_checkpoint = store.justified_checkpoint
        self.finalized_checkpoint = store.finalized_checkpoint
        self.block_count = len(store)


class Controller:
    """Public API is callable from any thread; everything mutating is
    marshalled onto the store-mutator thread."""

    def __init__(
        self,
        anchor_state,
        cfg,
        execution_engine=None,
        verifier_factory: "Callable[[], Verifier]" = MultiVerifier,
        pool: "Optional[ThreadPool]" = None,
        wait_group: "Optional[WaitGroup]" = None,
        storage=None,
        metrics=None,
    ) -> None:
        self.cfg = cfg
        self.verifier_factory = verifier_factory
        self.storage = storage
        self.metrics = metrics
        self._persisted_finalized = -1
        self.store = Store(anchor_state, cfg, execution_engine=execution_engine)
        if storage is not None:
            # persist the finalized chain BEFORE the store prunes it away
            self.store.pre_prune_hook = self._persist_finalized
        self.wait_group = wait_group or WaitGroup()
        self.pool = pool or ThreadPool(wait_group=self.wait_group)
        self._owns_pool = pool is None

        self._delayed_by_parent: "dict[bytes, list]" = {}
        self._delayed_by_slot: "dict[int, list]" = {}
        self._delayed_attestations: "list[ValidAttestation]" = []
        self._rejected: "list[tuple[bytes, str]]" = []
        self._state_cache: "dict[tuple, object]" = {}
        #: called on the mutator thread with (old_head_root, snapshot)
        #: whenever ANY mutation (block, attestation batch, tick) moves
        #: the head — the head/chain_reorg event publication point
        self.on_head_change: "list[Callable]" = []
        #: called on the mutator thread after EVERY applied block with
        #: (valid_block, old_head_root, snapshot) — the event-stream
        #: publication point (http_api events.rs)
        self.on_block_applied: "list[Callable]" = []

        self._snapshot = Snapshot(self.store)
        self._mutations: "queue.Queue" = queue.Queue()
        self._mutator = threading.Thread(
            target=self._mutator_run, name="store-mutator", daemon=True
        )
        self._mutator.start()

    # -------------------------------------------------------------- restore

    @classmethod
    def restore(cls, storage, cfg, anchor_state=None, **kwargs):
        """Rebuild a controller from persisted storage: load the anchor
        (finalized) state, then replay unfinalized blocks through normal
        validation (controller.rs:140 process_unfinalized_blocks)."""
        state, unfinalized = storage.load(anchor_state=anchor_state)
        ctrl = cls(state, cfg, storage=storage, **kwargs)
        if unfinalized:
            from grandine_tpu.fork_choice.store import Tick, TickKind

            max_slot = max(int(b.message.slot) for b in unfinalized)
            ctrl.on_tick(Tick(max_slot, TickKind.AGGREGATE))
            for blk in unfinalized:
                ctrl.on_requested_block(blk)
            ctrl.wait()
        return ctrl

    # ---------------------------------------------------------------- reads

    def snapshot(self) -> Snapshot:
        return self._snapshot

    def state_at_slot(self, slot: int, snapshot: "Snapshot | None" = None):
        """Head state advanced through empty slots to `slot`, memoized —
        the StateCache slot-advancer (fork_choice_control/src/
        state_cache.rs:25-135): duties at tick boundaries all need the
        same advanced state; compute it once per (head, slot).

        Pass the `snapshot` you already hold to keep (head_root, state)
        coherent under concurrent head changes — the mutator thread may
        swap `self._snapshot` between a caller's snapshot() read and
        this call."""
        from grandine_tpu.transition.slots import process_slots

        snap = snapshot if snapshot is not None else self._snapshot
        state = snap.head_state
        if int(state.slot) >= slot:
            return state
        key = (snap.head_root, slot)
        cached = self._state_cache.get(key)
        if cached is not None:
            return cached
        advanced = process_slots(state, slot, self.cfg)
        # bounded: keep only the latest few advanced states (eviction is
        # best-effort under concurrent callers — losing the race is fine)
        try:
            if len(self._state_cache) >= 4:
                self._state_cache.pop(next(iter(self._state_cache)), None)
        except (StopIteration, RuntimeError):
            pass
        self._state_cache[key] = advanced
        return advanced

    # --------------------------------------------------------------- inputs

    def on_tick(self, tick: Tick) -> None:
        self._send(("tick", tick))

    def on_gossip_block(self, signed_block) -> None:
        """Untrusted block: full verification on the high-priority pool
        (controller.rs spawn_block_task → tasks.rs BlockTask)."""
        self._spawn_block_task(signed_block, trusted=False)

    def on_requested_block(self, signed_block) -> None:
        self.on_gossip_block(signed_block)

    def on_own_block(self, signed_block) -> None:
        """Own (just produced) block: signatures are trusted, the state
        root is still checked (tasks.rs:103-118 TrustOwnBlockSignatures)."""
        self._spawn_block_task(signed_block, trusted=True)

    def on_valid_attestation_batch(
        self, valids: "Sequence[ValidAttestation]"
    ) -> None:
        """Prevalidated attestations (from the AttestationVerifier service)."""
        self._send(("attestations", list(valids)))

    def on_gossip_attestation(
        self, data_slot, committee_index, target_epoch, beacon_block_root,
        target_root, attesting_indices,
    ) -> None:
        """Single fork-choice vote, validated on the low-priority pool."""

        def task() -> None:
            try:
                valid = self.store.validate_attestation(
                    data_slot, committee_index, target_epoch,
                    beacon_block_root, target_root, attesting_indices,
                )
            except ForkChoiceError:
                return
            self._send(("attestations", [valid]))

        self.pool.spawn(task, Priority.LOW)

    def on_attester_slashing(self, indices: "Sequence[int]") -> None:
        self._send(("attester_slashing", list(indices)))

    # ---------------------------------------------------------- test hooks

    def wait(self, timeout: "Optional[float]" = 30.0) -> None:
        """Block until every spawned task AND every queued mutation drained
        (the WaitGroup test barrier, wait.rs). Loops because applying a
        block can re-spawn delayed children (new pool tasks)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = (
                None if deadline is None else max(0.0, deadline - _time.monotonic())
            )
            self.wait_group.wait(remaining)
            self._mutations.join()
            if self.wait_group.idle() and self._mutations.unfinished_tasks == 0:
                return

    def rejected(self) -> "list[tuple[bytes, str]]":
        return list(self._rejected)

    def stop(self) -> None:
        self._send(("stop", None))
        self._mutator.join(timeout=5)
        if self._owns_pool:
            self.pool.stop()

    # ------------------------------------------------------------ internals

    def _send(self, msg) -> None:
        self._mutations.put(msg)

    def _spawn_block_task(self, signed_block, trusted: bool) -> None:
        def task() -> None:
            from grandine_tpu.consensus.verifier import NullVerifier

            verifier = NullVerifier() if trusted else self.verifier_factory()
            try:
                valid = self.store.validate_block(signed_block, verifier)
            except ForkChoiceError as e:
                if "unknown parent" in str(e):
                    self._send(("delay_block", signed_block))
                elif "future slot" in str(e):
                    # mutator.rs delayed_until_slot: a block may arrive (or
                    # race the tick mutation) before its slot starts
                    self._send(("delay_block_slot", signed_block))
                else:
                    self._send(("reject", (signed_block, str(e))))
                return
            except (SignatureInvalid, TransitionError, KeyError) as e:
                # KeyError: raced a prune — the block is pre-finalized
                self._send(("reject", (signed_block, repr(e))))
                return
            self._send(("block", valid))

        self.pool.spawn(task, Priority.HIGH)

    # ------------------------------------------------------- mutator thread

    def _mutator_run(self) -> None:
        while True:
            kind, payload = self._mutations.get()
            try:
                if kind == "stop":
                    return
                elif kind == "tick":
                    self.store.apply_tick(payload)
                    self._apply_matured_attestations()
                    self._respawn_matured_blocks()
                elif kind == "block":
                    self._handle_block(payload)
                elif kind == "attestations":
                    for valid in payload:
                        if valid.earliest_slot > self.store.slot:
                            # spec: votes count from data.slot + 1
                            self._delayed_attestations.append(valid)
                        else:
                            self.store.apply_attestation(valid)
                elif kind == "attester_slashing":
                    self.store.apply_attester_slashing(payload)
                elif kind == "delay_block_slot":
                    slot = int(payload.message.slot)
                    if slot <= self.store.slot:
                        self._spawn_block_task(payload, trusted=False)
                    else:
                        pending = self._delayed_by_slot.setdefault(slot, [])
                        if len(pending) < 64:  # per-slot bound (spam guard)
                            pending.append(payload)
                        while len(self._delayed_by_slot) > 64:
                            # drop the furthest-future slots under spam
                            self._delayed_by_slot.pop(max(self._delayed_by_slot))
                elif kind == "delay_block":
                    parent = bytes(payload.message.parent_root)
                    if parent in self.store.blocks:
                        # parent landed between the failed validation and
                        # this message: retry immediately instead of filing
                        # under an already-applied parent (would be lost)
                        self._spawn_block_task(payload, trusted=False)
                    else:
                        self._delayed_by_parent.setdefault(parent, []).append(
                            payload
                        )
                        self._prune_delayed()
                elif kind == "reject":
                    signed_block, reason = payload
                    self._rejected.append(
                        (signed_block.message.hash_tree_root(), reason)
                    )
                    del self._rejected[: -self.MAX_REJECTED]
                # snapshot refresh only for mutating kinds ("block" refreshes
                # inside _handle_block; delay/reject mutate nothing) — the
                # head computation is the mutator's main cost
                if kind in ("tick", "attestations", "attester_slashing"):
                    self._refresh_snapshot()
            except BaseException as e:  # poison so tests fail loudly
                self.wait_group.add()
                self.wait_group.done(e)
            finally:
                self._mutations.task_done()

    def _handle_block(self, valid: ValidBlock) -> None:
        old_head = self._snapshot.head_root
        self.store.apply_block(valid)
        # retry children that were waiting for this parent
        for delayed in self._delayed_by_parent.pop(valid.root, []):
            self._spawn_block_task(delayed, trusted=False)
        # persistence (runs on the mutator thread like the reference):
        # every applied block immediately; the finalized chain is promoted
        # by the store's pre-prune hook (_persist_finalized)
        if self.storage is not None:
            self.storage.persist_unfinalized_block(
                valid.root, valid.signed_block
            )
        self._refresh_snapshot()
        if self.metrics is not None:
            self.metrics.fc_blocks_applied.inc()
            self.metrics.head_slot.set(int(self._snapshot.head_state.slot))
            self.metrics.finalized_epoch.set(
                int(self.store.finalized_checkpoint.epoch)
            )
        for cb in self.on_block_applied:
            cb(valid, old_head, self._snapshot)

    #: caps for the retry/reject books (delayed blocks from parents that
    #: never arrive would otherwise grow without bound under gossip spam)
    MAX_DELAYED_PARENTS = 256
    MAX_REJECTED = 256

    def _prune_delayed(self) -> None:
        # drop pre-finalized delays, then oldest parents over the cap
        fin_epoch = int(self.store.finalized_checkpoint.epoch)
        fin_slot = fin_epoch * self.cfg.preset.SLOTS_PER_EPOCH
        for parent in list(self._delayed_by_parent):
            kept = [
                b
                for b in self._delayed_by_parent[parent]
                if int(b.message.slot) > fin_slot
            ]
            if kept:
                self._delayed_by_parent[parent] = kept
            else:
                del self._delayed_by_parent[parent]
        while len(self._delayed_by_parent) > self.MAX_DELAYED_PARENTS:
            self._delayed_by_parent.pop(next(iter(self._delayed_by_parent)))
        del self._rejected[: -self.MAX_REJECTED]

    def _persist_finalized(self, store) -> None:
        fin = int(store.finalized_checkpoint.epoch)
        if fin > self._persisted_finalized:
            self.storage.persist_finalized_chain(store)
            self._persisted_finalized = fin

    def _respawn_matured_blocks(self) -> None:
        for slot in [s for s in self._delayed_by_slot if s <= self.store.slot]:
            for blk in self._delayed_by_slot.pop(slot):
                self._spawn_block_task(blk, trusted=False)

    def _apply_matured_attestations(self) -> None:
        if not self._delayed_attestations:
            return
        still = []
        for valid in self._delayed_attestations:
            if valid.earliest_slot <= self.store.slot:
                self.store.apply_attestation(valid)
            else:
                still.append(valid)
        self._delayed_attestations = still

    def _refresh_snapshot(self) -> None:
        old = self._snapshot
        self._snapshot = Snapshot(self.store)
        if self._snapshot.head_root != old.head_root:
            if self.metrics is not None:
                self.metrics.fc_head_changes.inc()
            for cb in self.on_head_change:
                cb(old.head_root, self._snapshot)


__all__ = ["Controller", "Snapshot"]
