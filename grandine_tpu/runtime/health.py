"""Backend health supervision for the device verify plane.

The reference client's availability stance — quarantine the bad input,
never the whole node — needs a device-side counterpart: an accelerator
backend can fault on dispatch, fault on readback, hang a settle forever,
or (worst) return garbage verdicts while raising nothing. This module
supervises the tpu/bls async seam with three cooperating pieces:

  circuit breaker — per-backend CLOSED → OPEN (consecutive-fault
      threshold or full-window fault rate) → HALF_OPEN (after a capped,
      jittered exponential backoff) → CLOSED. While OPEN the verify
      plane skips device dispatch entirely and goes straight to the
      host path, so a sick device costs zero per-batch fault tax.
  canary probes — HALF_OPEN re-promotion is gated on known-answer
      batches containing BOTH a valid and a forged specimen, run
      through the same async seam as real traffic. A device that
      returns wrong verdicts (not just raises) fails the forged-side
      expectation and stays quarantined.
  settle watchdog — `run_with_deadline` bounds every in-flight device
      settle with a per-batch deadline on an expendable daemon thread;
      on expiry the caller abandons the hung settle, degrades to the
      host path, and files a breaker fault. No ticket waits longer
      than the watchdog deadline plus one host pass.

The scheduler (runtime/verify_scheduler.py) and the attestation
pipeline (runtime/attestation_verifier.py) share one
`BackendHealthSupervisor` per node (runtime/node.py wires it), so a
fault observed on either plane quarantines the device for both.

Deliberately import-light: no jax, no tpu/bls import at module load —
the canary builds its specimens lazily so this module stays usable in
host-only deployments and under fault-injection tests
(grandine_tpu/testing/chaos.py).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

# ---------------------------------------------------------------- states

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: gauge encoding for verify_breaker_state (README "Fault tolerance")
STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

#: breaker fault taxonomy (the `kind` label on verify_breaker_faults)
FAULT_KINDS = ("dispatch", "settle", "watchdog", "verdict")

# ------------------------------------------------------- async-seam shape

#: the canonical async device seam: a backend offering BOTH of these is
#: device-dispatchable by the verify plane (tpu/bls.py TpuBlsBackend
#: declares the same names in its ASYNC_SEAM attribute; test fakes and
#: the chaos wrapper implement them structurally)
REQUIRED_SEAM_METHODS = (
    "fast_aggregate_verify_batch_async",
    "g2_subgroup_check_batch_async",
)


def has_async_seam(backend) -> bool:
    """True when `backend` structurally implements the async device
    seam the verify plane dispatches through."""
    return backend is not None and all(
        hasattr(backend, m) for m in REQUIRED_SEAM_METHODS
    )


# -------------------------------------------------------- settle watchdog

OK = "ok"
FAULT = "fault"
TIMEOUT = "timeout"


class SettleOutcome:
    """Result of a deadline-bounded settle: OK carries the value, FAULT
    carries the exception, TIMEOUT carries neither (the settle thread was
    abandoned and may still be running)."""

    __slots__ = ("status", "value", "error")

    def __init__(self, status: str, value=None, error=None) -> None:
        self.status = status
        self.value = value
        self.error = error

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SettleOutcome({self.status!r}, {self.value!r}, {self.error!r})"


def run_with_deadline(fn: Callable[[], object],
                      timeout_s: "Optional[float]",
                      thread_name: str = "settle-watchdog") -> SettleOutcome:
    """Run zero-arg `fn` with a hard deadline on an expendable daemon
    thread. On expiry the thread is ABANDONED (a hung device readback
    cannot be interrupted from Python) — it stays a daemon so it never
    blocks interpreter exit, and the caller gets TIMEOUT immediately.

    `timeout_s=None` runs inline with no watchdog (still converting an
    exception into a FAULT outcome)."""
    if timeout_s is None:
        try:
            return SettleOutcome(OK, value=fn())
        except Exception as e:
            return SettleOutcome(FAULT, error=e)
    box: dict = {}
    settled = threading.Event()

    def _run() -> None:
        # watchdog thread: sole writer of `box`; the caller reads it
        # only after `settled` fires (or abandons it on timeout)
        try:
            box["value"] = fn()
        except BaseException as e:
            box["error"] = e
        finally:
            settled.set()

    t = threading.Thread(target=_run, name=thread_name, daemon=True)
    t.start()
    if not settled.wait(timeout_s):
        return SettleOutcome(TIMEOUT)
    if "error" in box:
        return SettleOutcome(FAULT, error=box["error"])
    return SettleOutcome(OK, value=box["value"])


# ---------------------------------------------------------- canary probes


class CanarySpecimen:
    """One known-answer check: a message, a signature, the signer set,
    and the verdict a HEALTHY device must return. Probes always pair a
    valid specimen (expected True) with a forged one (expected False) so
    a stuck-at-True device fails re-promotion."""

    __slots__ = ("message", "signature", "public_keys", "expected")

    def __init__(self, message: bytes, signature, public_keys,
                 expected: bool) -> None:
        self.message = bytes(message)
        self.signature = signature
        self.public_keys = list(public_keys)
        self.expected = bool(expected)


def default_specimens() -> "list[CanarySpecimen]":
    """A real (interop-key) valid/forged specimen pair, built lazily so
    importing this module never touches the crypto stack."""
    from grandine_tpu.crypto import bls as A
    from grandine_tpu.transition.genesis import interop_secret_key

    sk = interop_secret_key(0)
    pk = sk.public_key()
    good_msg = b"\x2a" * 32
    sig_bytes = sk.sign(good_msg).to_bytes()
    # decompress WITHOUT the host subgroup check — the same geometry the
    # scheduler hands the device seam (verify_scheduler._device_dispatch)
    sig = A.Signature(A.g2_from_bytes(sig_bytes, subgroup_check=False))
    return [
        CanarySpecimen(good_msg, sig, [pk], expected=True),
        # same (valid, in-subgroup) signature against a different
        # message: a pairing-skipping or stuck-verdict device answers
        # True here and fails the probe
        CanarySpecimen(b"\x2b" * 32, sig, [pk], expected=False),
    ]


def run_canary_detail(backend, specimens: "Sequence[CanarySpecimen]",
                      timeout_s: float = 5.0) -> "tuple[bool, Optional[str]]":
    """`run_canary` plus the FAULT_KINDS attribution of the first
    failure: (passed, None) on success, else (False, kind) where kind
    names what broke — dispatch exception, settle fault, watchdog
    expiry, or a wrong verdict. The flight recorder files the kind so a
    failed probe reads like the batch faults that provoked it."""
    if not has_async_seam(backend):
        return False, "dispatch"
    for spec in specimens:
        try:
            settle = backend.fast_aggregate_verify_batch_async(
                [spec.message], [spec.signature], [spec.public_keys]
            )
        except Exception:
            return False, "dispatch"
        outcome = run_with_deadline(settle, timeout_s, "canary-probe")
        if outcome.status == TIMEOUT:
            return False, "watchdog"
        if outcome.status != OK:
            return False, "settle"
        if bool(outcome.value) != spec.expected:
            return False, "verdict"
    return True, None


def run_canary(backend, specimens: "Sequence[CanarySpecimen]",
               timeout_s: float = 5.0) -> bool:
    """Dispatch each specimen through the backend's async seam and
    require the exact expected verdict within the deadline. Any dispatch
    exception, settle fault, timeout, or wrong verdict fails the probe."""
    return run_canary_detail(backend, specimens, timeout_s=timeout_s)[0]


def make_canary_probe(backend, specimens=None,
                      timeout_s: float = 5.0) -> Callable[[], bool]:
    """A zero-arg probe closure for CircuitBreaker(probe=...). Specimen
    construction is deferred to first probe so wiring a probe at
    scheduler construction costs nothing until the breaker half-opens.
    The closure exposes `last_fault` (a FAULT_KINDS member or None) so
    the breaker can attribute a failed probe in the flight timeline."""
    state: dict = {"specimens": specimens}

    def probe() -> bool:
        if state["specimens"] is None:
            state["specimens"] = default_specimens()
        passed, fault = run_canary_detail(
            backend, state["specimens"], timeout_s=timeout_s
        )
        probe.last_fault = fault
        return passed

    probe.last_fault = None
    return probe


# --------------------------------------------------------- circuit breaker


class CircuitBreaker:
    """CLOSED → OPEN → HALF_OPEN → CLOSED with canary-gated
    re-promotion.

    Opens on `fault_threshold` consecutive faults, or when a FULL
    sliding window of the last `window` outcomes shows a fault rate of
    at least `fault_rate` (a partial window never opens the breaker — a
    single early fault is not a rate). While OPEN, `allow()` is False
    until the capped, jittered exponential backoff expires; the first
    `allow()` after that moves to HALF_OPEN and runs the canary probe
    (pass → CLOSED, fail → re-OPEN with doubled backoff). With no probe
    configured, HALF_OPEN grants exactly one trial dispatch whose
    record_success/record_fault closes or re-opens the breaker.

    `clock` and `rng` are injectable for deterministic tests."""

    def __init__(
        self,
        name: str = "device",
        fault_threshold: int = 3,
        window: int = 16,
        fault_rate: float = 0.5,
        backoff_initial_s: float = 1.0,
        backoff_max_s: float = 60.0,
        jitter_frac: float = 0.1,
        probe: "Optional[Callable[[], bool]]" = None,
        metrics=None,
        flight=None,
        clock: Callable[[], float] = time.monotonic,
        rng: "Optional[random.Random]" = None,
    ) -> None:
        self.name = name
        self.fault_threshold = int(fault_threshold)
        self.window_size = int(window)
        self.fault_rate = float(fault_rate)
        self.backoff_initial_s = float(backoff_initial_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter_frac = float(jitter_frac)
        self.probe = probe
        self.metrics = metrics
        #: optional FlightRecorder: breaker transitions and canary
        #: probes land in the same timeline as the batches around them
        self.flight = flight
        self.clock = clock
        self.rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._window: deque = deque(maxlen=self.window_size)
        self._backoff_s = 0.0
        self._retry_at = 0.0
        self._probing = False  # one prober at a time
        self._trial = False  # probe-less HALF_OPEN: one trial dispatch
        self.stats = {
            "opens": 0, "closes": 0, "probes_passed": 0,
            "probes_failed": 0,
            "faults": {k: 0 for k in FAULT_KINDS},
        }
        self._publish_state(CLOSED, transition=False)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the caller dispatch to the device right now? Runs the
        canary probe (outside the lock) when the breaker is due for
        HALF_OPEN re-promotion."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() < self._retry_at:
                    return False
                self._enter(HALF_OPEN)
            # HALF_OPEN from here on
            if self.probe is None:
                if self._trial:
                    return False
                self._trial = True
                return True
            if self._probing:
                return False
            self._probing = True
            probe = self.probe
        t_probe = time.perf_counter()
        try:
            passed = bool(probe())
        except Exception:
            passed = False
        if self.flight is not None:
            self.flight.record_canary(
                self.name, passed,
                duration_s=time.perf_counter() - t_probe,
                fault=None if passed else getattr(
                    probe, "last_fault", None
                ),
            )
        with self._lock:
            self._probing = False
            if self._state != HALF_OPEN:
                # a concurrent record_fault re-opened us mid-probe
                return False
            if passed:
                self.stats["probes_passed"] += 1
                self._count_probe("pass")
                self._close()
                return True
            self.stats["probes_failed"] += 1
            self._count_probe("fail")
            self._reopen()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._window.append(False)
            if self._state == HALF_OPEN:
                self._close()

    def ensure_probe(self, probe: "Callable[[], bool]") -> None:
        """Install `probe` if none is configured yet — first writer wins,
        atomically. Concurrent lazy backend builds race to register their
        canary; `allow()` reads `probe` under the same lock."""
        with self._lock:
            if self.probe is None:
                self.probe = probe

    def record_fault(self, kind: str = "settle") -> None:
        with self._lock:
            faults = self.stats["faults"]
            faults[kind] = faults.get(kind, 0) + 1
            if self.metrics is not None:
                self.metrics.verify_breaker_faults.inc(self.name, kind)
            self._consecutive += 1
            self._window.append(True)
            if self._state == HALF_OPEN:
                self._reopen()
                return
            if self._state != CLOSED:
                return
            full = len(self._window) == self.window_size
            rate = (
                sum(self._window) / len(self._window) if self._window else 0.0
            )
            if self._consecutive >= self.fault_threshold or (
                full and rate >= self.fault_rate
            ):
                self._reopen()

    # ------------------------------------------------- internal (locked)

    def _close(self) -> None:
        self._consecutive = 0
        self._window.clear()
        self._backoff_s = 0.0
        self._trial = False
        self.stats["closes"] += 1
        self._enter(CLOSED)

    def _reopen(self) -> None:
        if self._backoff_s <= 0.0:
            self._backoff_s = self.backoff_initial_s
        else:
            self._backoff_s = min(self._backoff_s * 2.0, self.backoff_max_s)
        jitter = 1.0 + self.jitter_frac * (2.0 * self.rng.random() - 1.0)
        self._retry_at = self.clock() + self._backoff_s * jitter
        self._trial = False
        self.stats["opens"] += 1
        self._enter(OPEN)

    def _enter(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        self._publish_state(state, transition=True)
        if self.flight is not None:
            self.flight.record_breaker(self.name, state)

    def _publish_state(self, state: str, transition: bool) -> None:
        if self.metrics is None:
            return
        name = self.name
        self.metrics.verify_breaker_state.set(
            name, value=STATE_CODES[state]
        )
        if transition:
            self.metrics.verify_breaker_transitions.inc(name, state)

    def _count_probe(self, result: str) -> None:
        if self.metrics is not None:
            self.metrics.verify_canary_probes.inc(self.name, result)


# ----------------------------------------------------- health supervisor


class BackendHealthSupervisor:
    """The one object the verify plane talks to: breaker gating
    (`allow_device`), fault/success accounting, and deadline-bounded
    settles (`guard_settle`). Shared node-wide so the scheduler and the
    attestation pipeline quarantine the same device together."""

    def __init__(
        self,
        metrics=None,
        settle_timeout_s: float = 5.0,
        probe: "Optional[Callable[[], bool]]" = None,
        name: str = "device",
        fault_threshold: int = 3,
        window: int = 16,
        fault_rate: float = 0.5,
        backoff_initial_s: float = 1.0,
        backoff_max_s: float = 60.0,
        jitter_frac: float = 0.1,
        flight=None,
        clock: Callable[[], float] = time.monotonic,
        rng: "Optional[random.Random]" = None,
    ) -> None:
        self.metrics = metrics
        self.flight = flight
        self.settle_timeout_s = float(settle_timeout_s)
        self.breaker = CircuitBreaker(
            name=name,
            fault_threshold=fault_threshold,
            window=window,
            fault_rate=fault_rate,
            backoff_initial_s=backoff_initial_s,
            backoff_max_s=backoff_max_s,
            jitter_frac=jitter_frac,
            probe=probe,
            metrics=metrics,
            flight=flight,
            clock=clock,
            rng=rng,
        )

    @property
    def state(self) -> str:
        return self.breaker.state

    def allow_device(self) -> bool:
        return self.breaker.allow()

    def record_success(self) -> None:
        self.breaker.record_success()

    def record_fault(self, kind: str = "settle") -> None:
        self.breaker.record_fault(kind)

    def ensure_probe(self, probe: Callable[[], bool]) -> None:
        """Install a canary probe if none is configured yet (the lazily
        built real backend registers itself here; injected test backends
        keep whatever the test wired). Delegates to the breaker so the
        check-then-set is atomic under the breaker's lock."""
        self.breaker.ensure_probe(probe)

    def guard_settle(self, settle: Callable[[], object],
                     timeout_s: "Optional[float]" = None,
                     thread_name: str = "verify-settle-watchdog"
                     ) -> SettleOutcome:
        """Run a device settle under the watchdog deadline."""
        if timeout_s is None:
            timeout_s = self.settle_timeout_s
        return run_with_deadline(settle, timeout_s, thread_name)


__all__ = [
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
    "STATE_CODES",
    "FAULT_KINDS",
    "REQUIRED_SEAM_METHODS",
    "OK",
    "FAULT",
    "TIMEOUT",
    "BackendHealthSupervisor",
    "CanarySpecimen",
    "CircuitBreaker",
    "SettleOutcome",
    "default_specimens",
    "has_async_seam",
    "make_canary_probe",
    "run_canary",
    "run_canary_detail",
    "run_with_deadline",
]
