"""Adversarial isolation plane: fault localization, quarantine
reputation, and per-origin admission control.

BENCH_CONFIG4 showed the verify plane collapsing under 1.5% forged
signatures (121 → 13 atts/s, item p50 0.7s → 56s): a poisoned batch fell
back to recursive host bisection whose leaves are single host verifies,
so a trickle of forgeries bought the attacker a host-bound plane. This
module makes adversarial traffic a bounded tax with three cooperating
pieces, all fed by attribution the flight recorder already keeps:

  FaultLocalizer — on-device localization of a failed batch. ONE device
      pass of the RLC-partition kernel (tpu/bls.py
      rlc_partition_verify_kernel) yields per-sub-batch verdicts; a
      fixed-fanout descent (groups = F, F², … capped at the bucket)
      names the bad items in at most ⌈log_F(bucket)⌉ device passes plus
      one per-item subgroup pass. Every pass dispatches the SAME padded
      bucket with a coarser-to-finer group ladder, so the shape set is
      finite and warmable (tools/shapes manifest `rlc_partition` rows) —
      localization never recompiles at incident time. The host verifies
      only device-named-bad leaves (host verdict wins per item, exactly
      the old bisection-leaf semantics).
  ReputationTable — decaying per-origin quarantine state. An origin
      named bad by localization enters quarantine; the scheduler then
      routes its sheddable traffic into the small-batch `quarantine`
      lane so honest traffic never shares a batch (and therefore never
      shares a localization descent) with a known-bad origin. K
      consecutive clean quarantine batches — or time decay — exit it.
  AdmissionController — sliding-window fair-share quotas at gossip
      submit time (p2p/network.py), so one hot or hostile origin cannot
      starve the rest of the verify plane no matter how fast it sends.

Origin identities (peer ids, validator indices) are NEVER Prometheus
label values — metrics carry only closed `kernel`/`lane` label sets;
per-origin attribution lives in the bounded tables here and in the
flight recorder.

Deliberately import-light: no jax / tpu.bls at module load (host-only
deployments, fault-injection tests); the device seam is the injected
backend's `rlc_partition_verify_async` ASYNC_SEAM method,
feature-detected via `FaultLocalizer.supports`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from grandine_tpu.consensus.verifier import SignatureInvalid
from grandine_tpu.crypto import bls as A
from grandine_tpu.runtime import health as _health

#: descent fanout: each device pass splits every still-suspect group
#: into F sub-groups. 8 keeps the warm-shape ladder tiny (≤3 rungs for
#: the widest scheduler lane) while staying within the ⌈log2(bucket)⌉+1
#: pass bound.
FANOUT = 8

#: quarantine exits after this many consecutive clean batches
DEFAULT_EXIT_CLEAN = 3
#: …or after this long without a new failure (decay), whichever first
DEFAULT_DECAY_S = 60.0

#: admission window + fair-share cap + absolute per-origin floor: an
#: origin is rejected only when it already holds `max_share` of the
#: whole window AND is over the floor — a lone origin on a quiet node
#: is never throttled.
DEFAULT_WINDOW_S = 1.0
DEFAULT_MAX_SHARE = 0.5
DEFAULT_MIN_QUOTA = 256

#: reputation-fed admission: an origin whose attributed failure rate is
#: at or under this (over ≥ TRUST_MIN_OBSERVED submitted jobs) has
#: PROVEN itself honest — its quota is not share-clamped, so a busy
#: honest aggregator is never throttled for being busy. Above it, the
#: quota shrinks toward the floor as the rate climbs.
DEFAULT_TRUST_FAILURE_RATE = 0.05
#: minimum submitted jobs before a failure rate is trusted at all — an
#: unknown or low-volume origin stays on the plain share quota
TRUST_MIN_OBSERVED = 32
#: rolling-rate horizon: both traffic counters halve when `submitted`
#: reaches this, so the rate tracks recent behaviour, not ancient sins
_TRAFFIC_HALF_AT = 4096


def _bucket(n: int, lo: int = 4) -> int:
    """The pow-2 device bucket a batch of n pads into — must mirror
    tpu/bls._bucket (lo=4) WITHOUT importing jax here."""
    b = lo
    while b < n:
        b <<= 1
    return b


def ladder(bucket: int, fanout: int = FANOUT) -> "list[int]":
    """The group-count ladder one localization runs: fanout, fanout², …
    capped at (and always ending with) the bucket — the final rung is
    per-item. This is ALSO the warm-shape contract: tools/shapes emits a
    `warm rlc_partition` row per (bucket, groups) pair of this ladder."""
    out: "list[int]" = []
    g = fanout if fanout < bucket else bucket
    while True:
        out.append(g)
        if g >= bucket:
            return out
        g = g * fanout if g * fanout < bucket else bucket


def max_device_passes(items: int, fanout: int = FANOUT) -> int:
    """Upper bound on device passes one localization may take (the
    subgroup pass plus the full group ladder) — asserted ≤
    ⌈log2(bucket)⌉+1 by the adversarial soak gate."""
    return 1 + len(ladder(_bucket(max(1, int(items))), fanout))


class FaultLocalizer:
    """On-device localization of a failed verify batch.

    Stateless between calls (config + injected seams only), so one
    instance serves every scheduler thread without locking. `localize`
    runs on the scheduler's completion thread inside the same watchdog
    budget the old host bisection shared."""

    def __init__(
        self,
        health: "Optional[_health.BackendHealthSupervisor]" = None,
        metrics=None,
        host_check: "Optional[Callable]" = None,
        fanout: int = FANOUT,
    ) -> None:
        assert fanout >= 2 and fanout & (fanout - 1) == 0
        self.health = health
        self.metrics = metrics
        self.fanout = fanout
        #: None → resolve verify_scheduler.host_check_item PER CALL, so
        #: test/bench monkeypatches of that module global keep working
        #: exactly as they do for the legacy bisection path
        self.host_check = host_check

    def _leaf_check(self, item) -> bool:
        if self.host_check is not None:
            return bool(self.host_check(item))
        from grandine_tpu.runtime import verify_scheduler as _vs
        return bool(_vs.host_check_item(item))

    @staticmethod
    def supports(backend) -> bool:
        """True when `backend` offers the RLC-partition ASYNC_SEAM
        method (feature detection — test fakes and older backends fall
        back to host bisection in the scheduler)."""
        return backend is not None and hasattr(
            backend, "rlc_partition_verify_async"
        )

    # ------------------------------------------------------- device seam

    def _device_dispatch(self, backend, messages, signatures,
                         member_keys, groups: int):
        """The one isolation→device crossing for partition verdicts
        (tools/shapes seam check pins this to ASYNC_SEAM methods)."""
        return backend.rlc_partition_verify_async(
            messages, signatures, member_keys, groups
        )

    def _subgroup_dispatch(self, backend, points):
        """Per-item ψ-ladder subgroup verdicts (the whole-batch dispatch
        only learns a single ANDed bool; localization needs each)."""
        return backend.g2_subgroup_check_batch_async(points)

    # ------------------------------------------------------- bookkeeping

    def _count_pass(self, kernel: str) -> None:
        if self.metrics is not None:
            self.metrics.verify_isolation_passes.labels(kernel).inc()

    def _budget(self, deadline: "Optional[float]") -> "Optional[float]":
        budget = (
            self.health.settle_timeout_s if self.health is not None else None
        )
        if deadline is not None:
            remaining = deadline - time.monotonic()
            budget = remaining if budget is None else min(budget, remaining)
        return budget

    def _guard(self, settle, budget: "Optional[float]"):
        """Watchdog-bounded settle with breaker fault accounting; the
        (status, value) shape of health.guard_settle with or without a
        supervisor."""
        if self.health is not None:
            outcome = self.health.guard_settle(settle, timeout_s=budget)
            if outcome.status == _health.OK:
                self.health.record_success()
            elif outcome.status == _health.TIMEOUT:
                self.health.record_fault("watchdog")
            else:
                self.health.record_fault("settle")
            return outcome.status, outcome.value
        try:
            return _health.OK, settle()
        except Exception:
            return _health.FAULT, None

    def _device_ok(self) -> bool:
        return self.health is None or self.health.allow_device()

    # -------------------------------------------------------- localization

    def localize(self, backend, items, deadline: "Optional[float]" = None,
                 fl=None) -> "list[bool]":
        """Per-item verdicts for a batch the device called invalid.

        Host pre-pass names items that cannot even reach the device
        (undecodable signature, no key material) via the eager host
        check; one device pass yields per-item subgroup verdicts; then
        the fixed-fanout partition descent narrows suspects until the
        per-item rung, whose named-bad leaves the host confirms. Any
        device fault / watchdog expiry / breaker-open mid-descent sweeps
        the remaining suspects on the host — the same degradation target
        as the plane everywhere else."""
        n = len(items)
        verdicts: "list[Optional[bool]]" = [None] * n
        points: list = [None] * n
        keys: list = [None] * n
        for i, it in enumerate(items):
            try:
                p = A.g2_from_bytes(it.signature, subgroup_check=False)
                if p.is_infinity():
                    raise A.BlsError("infinity signature")
                keys[i] = it.resolve_keys()
                points[i] = p
            except (A.BlsError, SignatureInvalid):
                # host-named leaf: the eager host path is the verdict of
                # record for anything the device cannot represent
                verdicts[i] = self._leaf_check(it)

        live = [i for i in range(n) if verdicts[i] is None]
        if not live:
            return [bool(v) for v in verdicts]

        if not self._device_ok():
            return self._host_sweep(items, verdicts, live)

        # device pass 0: per-item subgroup verdicts (the failed batch's
        # own subgroup dispatch only reported the ANDed bool)
        budget = self._budget(deadline)
        if budget is not None and budget <= 0:
            return self._host_sweep(items, verdicts, live)
        try:
            sub_settle = self._subgroup_dispatch(
                backend, [points[i] for i in live]
            )
        except Exception:
            if self.health is not None:
                self.health.record_fault("dispatch")
            return self._host_sweep(items, verdicts, live)
        status, flags = self._guard(sub_settle, budget)
        if status != _health.OK:
            return self._host_sweep(items, verdicts, live)
        self._count_pass("g2_subgroup")
        if fl is not None:
            fl.note_bisect(0.0, 1)
        flags = np.asarray(flags, bool)
        for pos, idx in enumerate(live):
            if not flags[pos]:
                # device-named-bad leaf — host verdict wins per item
                verdicts[idx] = self._leaf_check(items[idx])
        live = [i for i in live if verdicts[i] is None]
        if not live:
            return [bool(v) for v in verdicts]

        # partition descent: same padded bucket every pass, group ladder
        # fanout → … → per-item; only bad groups stay suspect
        messages = [items[i].message for i in live]
        signatures = [A.Signature(points[i]) for i in live]
        member_keys = [keys[i] for i in live]
        b = _bucket(len(live))
        suspects = set(range(len(live)))
        for depth, groups in enumerate(ladder(b, self.fanout), start=2):
            if not suspects:
                break
            budget = self._budget(deadline)
            if (budget is not None and budget <= 0) or not self._device_ok():
                return self._host_sweep(
                    items, verdicts, [live[p] for p in sorted(suspects)]
                )
            try:
                settle = self._device_dispatch(
                    backend, messages, signatures, member_keys, groups
                )
            except Exception:
                if self.health is not None:
                    self.health.record_fault("dispatch")
                return self._host_sweep(
                    items, verdicts, [live[p] for p in sorted(suspects)]
                )
            status, group_verdicts = self._guard(settle, budget)
            if status != _health.OK:
                return self._host_sweep(
                    items, verdicts, [live[p] for p in sorted(suspects)]
                )
            self._count_pass("rlc_partition")
            if fl is not None:
                fl.note_bisect(0.0, depth)
            group_verdicts = np.asarray(group_verdicts, bool)
            span = b // groups
            for p in sorted(suspects):
                if group_verdicts[p // span]:
                    verdicts[live[p]] = True
                    suspects.discard(p)
            if groups >= b:
                # per-item rung: whatever is still suspect was named bad
                # by the device — host-confirm exactly those leaves
                for p in sorted(suspects):
                    verdicts[live[p]] = self._leaf_check(items[live[p]])
                suspects.clear()
        for p in range(n):
            if verdicts[p] is None:  # cleared mid-ladder
                verdicts[p] = True
        return [bool(v) for v in verdicts]

    def _host_sweep(self, items, verdicts, remaining) -> "list[bool]":
        """Degradation target: host-verify every still-undecided item
        (breaker-open, device fault, or budget exhausted mid-descent)."""
        self._count_pass("host")
        for i in remaining:
            verdicts[i] = self._leaf_check(items[i])
        return [bool(v) if v is not None else False for v in verdicts]


class ReputationTable:
    """Bounded, decaying per-origin quarantine state.

    Entry: any localization-attributed failure. Exit: `exit_clean`
    CONSECUTIVE clean quarantine batches, or `decay_s` without a new
    failure. Bounded at `capacity` origins — at capacity a new offender
    evicts the entry with the stalest failure (closest to decaying out
    anyway), so adversarial origin churn cannot grow the table."""

    def __init__(self, capacity: int = 256,
                 exit_clean: int = DEFAULT_EXIT_CLEAN,
                 decay_s: float = DEFAULT_DECAY_S,
                 clock=time.monotonic) -> None:
        self.capacity = max(1, int(capacity))
        self.exit_clean = max(1, int(exit_clean))
        self.decay_s = float(decay_s)
        self.clock = clock
        self._lock = threading.Lock()
        #: origin -> [failures, consecutive_clean, last_bad_t]
        self._entries: "dict[str, list]" = {}
        #: origin -> [submitted, failed] rolling job counters feeding
        #: `failure_rate` (admission quotas key off the RATE, not raw
        #: submission share — a high-volume honest aggregator stays
        #: unclamped). Bounded like _entries; at capacity the lowest-
        #: volume origin is evicted, so sybil churn cannot displace the
        #: heavy hitters whose rates matter.
        self._traffic: "dict[str, list]" = {}

    def _traffic_entry(self, origin: str) -> list:
        # caller holds self._lock
        t = self._traffic.get(origin)
        if t is None:
            if len(self._traffic) >= self.capacity:
                victim = min(
                    self._traffic, key=lambda o: self._traffic[o][0]
                )
                del self._traffic[victim]
            t = self._traffic[origin] = [0, 0]
        return t

    def note_submitted(self, origin: "Optional[str]",
                       jobs: int = 1) -> None:
        """One (or `jobs`) verify job(s) submitted by `origin` — the
        denominator of its failure rate."""
        if not origin:
            return
        with self._lock:
            t = self._traffic_entry(str(origin))
            t[0] += max(1, int(jobs))
            if t[0] >= _TRAFFIC_HALF_AT:
                t[0] //= 2
                t[1] //= 2

    def failure_rate(self, origin: "Optional[str]",
                     min_observed: int = TRUST_MIN_OBSERVED
                     ) -> "Optional[float]":
        """Attributed-failure fraction of `origin`'s submitted jobs, or
        None while the origin has fewer than `min_observed` submissions
        (too little evidence to trust the rate either way)."""
        if not origin:
            return None
        with self._lock:
            t = self._traffic.get(str(origin))
            if t is None or t[0] < min_observed:
                return None
            return min(1.0, t[1] / t[0])

    def note_failure(self, origin: "Optional[str]") -> None:
        if not origin:
            return
        origin = str(origin)
        now = self.clock()
        with self._lock:
            self._traffic_entry(origin)[1] += 1
            ent = self._entries.get(origin)
            if ent is not None:
                ent[0] += 1
                ent[1] = 0
                ent[2] = now
                return
            if len(self._entries) >= self.capacity:
                victim = min(self._entries, key=lambda o: self._entries[o][2])
                del self._entries[victim]
            self._entries[origin] = [1, 0, now]

    def note_clean_batch(self, origin: "Optional[str]") -> None:
        """One quarantine-lane batch from `origin` settled fully valid."""
        if not origin:
            return
        with self._lock:
            ent = self._entries.get(str(origin))
            if ent is None:
                return
            ent[1] += 1
            if ent[1] >= self.exit_clean:
                del self._entries[str(origin)]

    def is_quarantined(self, origin: "Optional[str]") -> bool:
        if not origin:
            return False
        now = self.clock()
        with self._lock:
            ent = self._entries.get(str(origin))
            if ent is None:
                return False
            if now - ent[2] > self.decay_s:
                del self._entries[str(origin)]
                return False
            return True

    def snapshot(self) -> "list[dict]":
        with self._lock:
            rows = [
                {"origin": o, "failures": e[0], "clean": e[1],
                 "age_s": round(self.clock() - e[2], 3)}
                for o, e in self._entries.items()
            ]
        rows.sort(key=lambda r: (-r["failures"], r["origin"]))
        return rows

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------- persistence

    #: K-V row holding the serialized table (storage.Database seam)
    DB_KEY = b"rep:table"

    def save(self, db) -> None:
        """Serialize quarantine + traffic state through the node's K-V
        store so a reboot does not hand every quarantined origin a clean
        slate. `last_bad_t` rides as AGE (the clock is time.monotonic,
        meaningless across processes) and is rebased on load."""
        import json

        now = self.clock()
        with self._lock:
            blob = {
                "v": 1,
                "entries": {
                    o: [e[0], e[1], round(now - e[2], 3)]
                    for o, e in self._entries.items()
                },
                "traffic": {o: list(t) for o, t in self._traffic.items()},
            }
        db.put(self.DB_KEY, json.dumps(blob, sort_keys=True).encode())

    def load(self, db) -> int:
        """Restore state saved by `save`; returns the number of
        quarantine entries restored (0 on missing/corrupt rows — a fresh
        table, never a crash at node start). Ages past `decay_s` are
        dropped on the spot rather than resurrected."""
        import json

        raw = db.get(self.DB_KEY)
        if raw is None:
            return 0
        try:
            blob = json.loads(bytes(raw).decode())
            entries = blob.get("entries", {})
            traffic = blob.get("traffic", {})
        except (ValueError, AttributeError):
            return 0
        now = self.clock()
        restored = 0
        with self._lock:
            for o, row in entries.items():
                try:
                    failures, clean, age = (
                        int(row[0]), int(row[1]), float(row[2])
                    )
                except (TypeError, ValueError, IndexError):
                    continue
                if age > self.decay_s or len(self._entries) >= self.capacity:
                    continue
                self._entries[str(o)] = [failures, clean, now - age]
                restored += 1
            for o, row in traffic.items():
                if len(self._traffic) >= self.capacity:
                    break
                try:
                    self._traffic[str(o)] = [int(row[0]), int(row[1])]
                except (TypeError, ValueError, IndexError):
                    continue
        return restored


class AdmissionController:
    """Sliding-window per-origin fair-share quotas at submit time.

    An origin is rejected only when its items in the current window
    already exceed max(min_quota, max_share × window total) — so honest
    origins under their fair share are never rejected regardless of how
    hard one hostile origin pushes, and a lone origin on an idle node is
    never throttled (the absolute floor). Unattributed submissions
    (origin None — local work, tests) are always admitted. The per-origin
    window map is bounded: at `capacity` tracked origins a NEW origin is
    admitted but untracked (it is necessarily under the floor), so sybil
    churn cannot grow the table or evict the heavy hitters.

    With a `reputation` table wired, the quota keys off the origin's
    attributed FAILURE RATE rather than raw submission share: an origin
    whose rate is at or under `trust_failure_rate` (over enough observed
    jobs) bypasses the share clamp entirely — a high-rate honest
    aggregator is never clamped for being busy — while a high-failure
    origin's quota shrinks toward `min_quota` as its rate climbs.
    Unknown / low-volume origins stay on the plain share quota, and
    `reputation=None` is exactly the legacy share-only behaviour."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 max_share: float = DEFAULT_MAX_SHARE,
                 min_quota: int = DEFAULT_MIN_QUOTA,
                 capacity: int = 1024,
                 metrics=None, clock=time.monotonic,
                 reputation: "Optional[ReputationTable]" = None,
                 trust_failure_rate: float = DEFAULT_TRUST_FAILURE_RATE,
                 ) -> None:
        self.window_s = float(window_s)
        self.max_share = float(max_share)
        self.min_quota = max(1, int(min_quota))
        self.capacity = max(1, int(capacity))
        self.metrics = metrics
        self.clock = clock
        self.reputation = reputation
        self.trust_failure_rate = float(trust_failure_rate)
        self._lock = threading.Lock()
        #: 0.0..1.0 quota squeeze applied under brownout (B2+): every
        #: clamped origin's quota shrinks toward min_quota by this
        #: fraction. Set via set_brownout_pressure() by the
        #: BrownoutController; reverts to 0.0 on recovery.
        self.brownout_pressure = 0.0
        #: origin -> list[(t, items)] (window entries, oldest first)
        self._windows: "dict[str, list]" = {}
        #: origin -> current window sum (kept in lockstep with _windows)
        self._totals: "dict[str, int]" = {}
        self._global_total = 0

    def set_brownout_pressure(self, pressure: float) -> None:
        """Squeeze every clamped origin's quota toward ``min_quota``
        by ``pressure`` (0.0 = no squeeze, 1.0 = floor). Called by the
        brownout controller at B2 and reverted on recovery."""
        with self._lock:
            self.brownout_pressure = min(1.0, max(0.0, float(pressure)))

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        dead = []
        for origin, entries in self._windows.items():
            drop = 0
            for t, count in entries:
                if t >= horizon:
                    break
                drop += 1
                self._totals[origin] -= count
                self._global_total -= count
            if drop:
                del entries[:drop]
            if not entries:
                dead.append(origin)
        for origin in dead:
            del self._windows[origin]
            del self._totals[origin]

    def admit(self, origin: "Optional[str]", items: int = 1,
              lane: str = "") -> bool:
        """True → caller may submit; False → shed at the door (callers
        count a gossip "ignore" and `verify_admission_rejected_total`)."""
        if not origin:
            return True
        origin = str(origin)
        items = max(1, int(items))
        now = self.clock()
        clamped = True
        if self.reputation is not None:
            rate = self.reputation.failure_rate(origin)
            if rate is not None and rate <= self.trust_failure_rate:
                # proven honest over enough jobs: no share clamp. The
                # submission still lands in the window so OTHER origins'
                # fair shares stay computed against true load.
                clamped = False
        else:
            rate = None
        with self._lock:
            self._prune(now)
            quota = max(
                self.min_quota, int(self.max_share * self._global_total)
            )
            if rate is not None and rate > self.trust_failure_rate:
                # distrusted: quota shrinks toward the floor as the
                # attributed failure rate climbs
                quota = max(self.min_quota, int(quota * (1.0 - rate)))
            if self.brownout_pressure > 0.0:
                # brownout squeeze (B2+): shrink everyone's headroom
                # above the floor, trusted origins included — overload
                # is a node-wide condition, not a per-origin verdict,
                # so the reputation exemption is suspended too
                quota = max(
                    self.min_quota,
                    int(quota * (1.0 - self.brownout_pressure)),
                )
                clamped = True
            used = self._totals.get(origin, 0)
            if clamped and used + items > quota:
                rejected = True
            else:
                rejected = False
                self._global_total += items
                if origin in self._windows:
                    self._windows[origin].append((now, items))
                    self._totals[origin] += items
                elif len(self._windows) < self.capacity:
                    self._windows[origin] = [(now, items)]
                    self._totals[origin] = items
                # at capacity: admitted-but-untracked (under the floor
                # by construction; sybil churn cannot evict heavy
                # hitters). _global_total still drains via a shadow
                # window under the reserved key below.
                else:
                    shadow = self._windows.setdefault("", [])
                    shadow.append((now, items))
                    self._totals[""] = self._totals.get("", 0) + items
        if rejected and self.metrics is not None:
            self.metrics.verify_admission_rejected.labels(lane).inc()
        return not rejected

    def window_share(self, origin: "Optional[str]") -> float:
        """origin's admitted fraction of the current window (debug)."""
        if not origin:
            return 0.0
        now = self.clock()
        with self._lock:
            self._prune(now)
            if not self._global_total:
                return 0.0
            return self._totals.get(str(origin), 0) / self._global_total


__all__ = [
    "FANOUT",
    "FaultLocalizer",
    "ReputationTable",
    "AdmissionController",
    "ladder",
    "max_device_passes",
    "DEFAULT_EXIT_CLEAN",
    "DEFAULT_DECAY_S",
    "DEFAULT_WINDOW_S",
    "DEFAULT_MAX_SHARE",
    "DEFAULT_MIN_QUOTA",
    "DEFAULT_TRUST_FAILURE_RATE",
    "TRUST_MIN_OBSERVED",
]
