"""Consensus-spec-tests plumbing — reference: spec_test_utils crate
(`Case` loader, spec_test_utils/src/lib.rs:50-168) and the
`#[test_resources]` glob binding.

`case.py` mirrors the official directory layout
(`tests/<preset>/<fork>/<runner>/<handler>/<suite>/<case>/` with
`meta.yaml` / `*.yaml` / `*.ssz_snappy` files) so the official vectors
drop in unchanged; `snappy.py` is a dependency-free snappy codec for the
`.ssz_snappy` encoding.
"""

from grandine_tpu.spec_tests.case import Case, iter_cases  # noqa: F401
from grandine_tpu.spec_tests.snappy import (  # noqa: F401
    frame_compress,
    frame_decompress,
)
