"""Case loader mirroring spec_test_utils/src/lib.rs:50-168.

A `Case` wraps one on-disk case directory; accessors read `*.yaml` (parsed)
and `*.ssz_snappy` (decompressed bytes) files, raising if a required file
is absent — the same surface the reference's suites consume. `iter_cases`
is the `#[test_resources(glob)]` equivalent: every matching directory is
one case, so pytest parametrization mirrors the reference's one-test-per-
case generation.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Iterator, Optional

import yaml

from grandine_tpu.spec_tests.snappy import frame_decompress


class Case:
    def __init__(self, directory: str) -> None:
        self.directory = directory

    def __repr__(self) -> str:
        return f"Case({self.directory})"

    @property
    def name(self) -> str:
        return os.path.basename(self.directory)

    def path(self, relative: str) -> str:
        return os.path.join(self.directory, relative)

    def exists(self, relative: str) -> bool:
        return os.path.exists(self.path(relative))

    def bytes(self, relative: str) -> bytes:
        with open(self.path(relative), "rb") as f:
            return f.read()

    def ssz_bytes(self, relative: str) -> bytes:
        """Decompressed payload of a `.ssz_snappy` file."""
        return frame_decompress(self.bytes(relative))

    def ssz(self, relative: str, typ):
        """Deserialize a `.ssz_snappy` file with an SSZ type descriptor /
        container class."""
        return typ.deserialize(self.ssz_bytes(relative))

    def yaml(self, relative: str) -> Any:
        with open(self.path(relative)) as f:
            return yaml.safe_load(f)

    def meta(self) -> dict:
        return self.yaml("meta.yaml") if self.exists("meta.yaml") else {}


def iter_cases(pattern: str, root: "Optional[str]" = None) -> "Iterator[Case]":
    """All case directories matching `pattern` (a glob over directories),
    sorted for stable test ordering."""
    if root is not None:
        pattern = os.path.join(root, pattern)
    for directory in sorted(_glob.glob(pattern)):
        if os.path.isdir(directory):
            yield Case(directory)


__all__ = ["Case", "iter_cases"]
