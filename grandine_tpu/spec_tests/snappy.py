"""Dependency-free snappy codec (framing format + raw block decode).

The consensus-spec-tests store SSZ payloads as `.ssz_snappy` (snappy
framing format, RFC-less but specified in google/snappy framing_format.txt).
Decoding handles compressed and uncompressed chunks; encoding emits
uncompressed chunks (valid framing, no compressor needed — we only encode
our own generated vectors).

CRC32-C checksums are verified on decode (the masked CRC of the framing
spec), computed with a small table-driven implementation.
"""

from __future__ import annotations

import struct

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_CHUNK_PADDING = 0xFE

_MAX_CHUNK = 65536


def _crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _crc32c_table()


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _crc32c(data: bytes) -> int:
    """CRC-32C via the native extension (SSE4.2 hardware instruction)
    when available; table-driven Python fallback otherwise. Every DB put
    runs through here, so the native path is load-bearing at scale."""
    from grandine_tpu import native

    if native.lib is not None:
        return native.lib.gt_crc32c(bytes(data), len(data))
    return _crc32c_py(data)


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------ raw decoding


def raw_decompress(data: bytes) -> bytes:
    """Decode one raw snappy block."""
    # varint uncompressed length
    n = 0
    shift = 0
    pos = 0
    while True:
        if pos >= len(data):
            raise ValueError("snappy: truncated varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            out += data[pos : pos + length]
            pos += length
        else:
            if kind == 1:  # copy, 1-byte offset
                length = ((tag >> 2) & 0x07) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:  # copy, 2-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:  # copy, 4-byte offset
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: bad copy offset")
            start = len(out) - offset
            for i in range(length):  # may overlap (run-length semantics)
                out.append(out[start + i])
    if len(out) != n:
        raise ValueError(f"snappy: expected {n} bytes, got {len(out)}")
    return bytes(out)


# --------------------------------------------------------------- framing


def frame_decompress(data: bytes) -> bytes:
    if not data.startswith(_STREAM_ID):
        raise ValueError("snappy: missing stream identifier")
    pos = len(_STREAM_ID)
    out = bytearray()
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("snappy: truncated chunk header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        chunk = data[pos : pos + length]
        if len(chunk) != length:
            raise ValueError("snappy: truncated chunk")
        pos += length
        if kind == _CHUNK_COMPRESSED or kind == _CHUNK_UNCOMPRESSED:
            crc = struct.unpack("<I", chunk[:4])[0]
            payload = chunk[4:]
            if kind == _CHUNK_COMPRESSED:
                payload = raw_decompress(payload)
            if _masked_crc(payload) != crc:
                raise ValueError("snappy: checksum mismatch")
            out += payload
        elif kind >= 0x80 or kind == _CHUNK_PADDING:
            continue  # skippable
        else:
            raise ValueError(f"snappy: unknown chunk type {kind:#x}")
    return bytes(out)


def frame_compress(data: bytes) -> bytes:
    """Encode with uncompressed chunks (valid framing, zero compression)."""
    out = bytearray(_STREAM_ID)
    for i in range(0, max(len(data), 1), _MAX_CHUNK):
        chunk = data[i : i + _MAX_CHUNK]
        body = struct.pack("<I", _masked_crc(chunk)) + chunk
        out += bytes([_CHUNK_UNCOMPRESSED]) + len(body).to_bytes(3, "little")
        out += body
    return bytes(out)


__all__ = ["frame_compress", "frame_decompress", "raw_decompress"]
