"""Slasher — reference: `slasher` crate (slasher/src/slasher.rs:50:
surround/double-vote detection over mdbx DBs of indexed attestations and
min/max target spans, plus proposer double-block detection; emits
slashings toward the proposer pipeline).

Detection model (per validator):
  - double vote:    two distinct attestation data with the same target epoch
  - surround vote:  recorded (s,t) surrounds or is surrounded by a new one
  - double block:   two distinct block roots signed for the same slot

Backed by the Database layer; bounded history window like the reference's
pruned span DBs.
"""

from __future__ import annotations

import json
from typing import Optional

from grandine_tpu.storage.database import Database

_PREFIX_ATT = b"sl:a:"    # validator_index_be8 -> json {target: [source, data_root, sig?]}
_PREFIX_BLOCK = b"sl:b:"  # validator_index_be8 + slot_be8 -> header root


class Slashing:
    """A detected offense with the evidence needed to build the on-chain
    operation."""

    __slots__ = ("kind", "validator_index", "evidence")

    def __init__(self, kind: str, validator_index: int, evidence: dict) -> None:
        self.kind = kind
        self.validator_index = validator_index
        self.evidence = evidence

    def __repr__(self) -> str:
        return f"Slashing({self.kind}, validator={self.validator_index})"


class Slasher:
    def __init__(self, database: "Optional[Database]" = None,
                 history_epochs: int = 4096) -> None:
        self.db = database or Database.in_memory()
        self.history_epochs = history_epochs
        self.detected: "list[Slashing]" = []

    # -------------------------------------------------------- attestations

    def _key(self, index: int) -> bytes:
        return _PREFIX_ATT + int(index).to_bytes(8, "big")

    def _records(self, index: int) -> dict:
        raw = self.db.get(self._key(index))
        return json.loads(raw) if raw else {}

    def on_attestation(
        self, attesting_indices, source_epoch: int, target_epoch: int,
        data_root: bytes,
    ) -> "list[Slashing]":
        """Record one indexed attestation; returns any detected offenses."""
        out = []
        for i in attesting_indices:
            i = int(i)
            records = self._records(i)
            hit = self._check(i, records, source_epoch, target_epoch, data_root)
            if hit is not None:
                out.append(hit)
            records[str(target_epoch)] = [source_epoch, data_root.hex()]
            # prune outside the history window
            floor = target_epoch - self.history_epochs
            for k in [k for k in records if int(k) < floor]:
                del records[k]
            self.db.put(self._key(i), json.dumps(records).encode())
        self.detected.extend(out)
        return out

    def _check(self, index, records, source, target, data_root):
        existing = records.get(str(target))
        if existing is not None and existing[1] != data_root.hex():
            return Slashing("double_vote", index, {
                "target_epoch": target,
                "roots": [existing[1], data_root.hex()],
            })
        for t_str, (s, root_hex) in records.items():
            t = int(t_str)
            if s < source and target < t:
                return Slashing("surrounded_vote", index, {
                    "existing": [s, t], "new": [source, target],
                })
            if source < s and t < target:
                return Slashing("surround_vote", index, {
                    "existing": [s, t], "new": [source, target],
                })
        return None

    # -------------------------------------------------------------- blocks

    def on_block(self, proposer_index: int, slot: int,
                 header_root: bytes) -> "Optional[Slashing]":
        key = _PREFIX_BLOCK + int(proposer_index).to_bytes(8, "big") \
            + int(slot).to_bytes(8, "big")
        existing = self.db.get(key)
        if existing is not None and bytes(existing) != bytes(header_root):
            hit = Slashing("double_block", int(proposer_index), {
                "slot": slot,
                "roots": [bytes(existing).hex(), bytes(header_root).hex()],
            })
            self.detected.append(hit)
            return hit
        self.db.put(key, bytes(header_root))
        return None

    def drain(self) -> "list[Slashing]":
        out = self.detected
        self.detected = []
        return out


__all__ = ["Slasher", "Slashing"]
