"""Slasher — reference: `slasher` crate (slasher/src/slasher.rs:50:
surround/double-vote detection over mdbx DBs of indexed attestations and
chunked min/max target spans, plus proposer double-block detection; emits
slashings toward the proposer pipeline).

Scale design (the reference's chunked span scheme, numpy-native):
for validator v and epoch e,
  min_targets[v][e] = min target among v's attestations with source > e
  max_targets[v][e] = max target among v's attestations with source < e
Both are MONOTONE non-decreasing in e (larger e → smaller source set for
min / larger for max), which makes range updates amortized O(1): walking
away from the new attestation's source, the update stops at the first
chunk it doesn't change.

Detection per new attestation (s, t) of validator v is O(1) chunk reads:
  min_targets[v][s] < t  →  the new vote SURROUNDS a recorded one
  max_targets[v][s] > t  →  the new vote IS SURROUNDED by a recorded one
  a recorded (v, t) with a different data root  →  double vote

Batched ingest: span chunks are indexed [row=validator] and records are
keyed (validator, target), so one attestation's effect on validator v
depends only on prior updates to v itself. Within one aggregate (shared
source/target/root) distinct indices therefore commute: `on_attestation`
groups an aggregate's indices by vchunk and applies one vectorized
min/max range-update and one vectorized surround/double-vote gather per
touched chunk instead of a Python loop per validator. The same argument
lets `on_attestations_bulk` merge a whole replay window's solo
validators in one chunk-aligned epoch grid — on the device through
`tpu.spans.SpanPlane` when wired, through its numpy twin otherwise —
while validators that appear more than once in the window (or twice in
one aggregate: re-recording a double vote changes what the next
occurrence sees) fall back to the sequential reference path. The
original per-validator loop survives as `on_attestation_reference`, the
oracle for the differential tests and the bench's batched-vs-loop
diagnostic.

Storage: (VALIDATORS_PER_CHUNK × CHUNK_EPOCHS) uint64 arrays in the K-V
store (the reference's mdbx chunk tables), an in-memory LRU chunk cache
flushed per call, per-(validator, target) attestation records for
evidence retrieval, and epoch-ordered index rows (`sl:e:`, `sl:t:`) so
`prune()` walks only the doomed prefix instead of scanning every key
per finalization.
"""

from __future__ import annotations

import time
from collections import Counter as _Counter
from collections import OrderedDict
from typing import Optional

import numpy as np

from grandine_tpu.storage.database import Database

CHUNK_EPOCHS = 16
VALIDATORS_PER_CHUNK = 256
_UNSET_MIN = np.uint64(0xFFFF_FFFF_FFFF_FFFF)

#: epoch values at or above this stay on the host path — the device grid
#: carries epochs as int32 and needs headroom below the sentinel
_GRID_EPOCH_LIMIT = 1 << 30

_PREFIX_MIN = b"sl:m:"    # vchunk_be8 + echunk_be8 -> uint64[VPC, CE]
_PREFIX_MAX = b"sl:x:"
_PREFIX_REC = b"sl:r:"    # validator_be8 + target_be8 -> source_be8 + root32
_PREFIX_BLOCK = b"sl:b:"  # validator_be8 + slot_be8 -> header root
#: prune indexes, ascending in the pruned dimension so finalization
#: walks exactly the doomed prefix: echunk_be8 + kind(m/x) + vchunk_be8
_PREFIX_ECHUNK_IDX = b"sl:e:"
#: target_be8 + validator_be8 (record prune index)
_PREFIX_TGT_IDX = b"sl:t:"


class Slashing:
    """A detected offense with the evidence needed to build the on-chain
    operation."""

    __slots__ = ("kind", "validator_index", "evidence")

    def __init__(self, kind: str, validator_index: int, evidence: dict) -> None:
        self.kind = kind
        self.validator_index = validator_index
        self.evidence = evidence

    def __repr__(self) -> str:
        return f"Slashing({self.kind}, validator={self.validator_index})"


class Slasher:
    def __init__(self, database: "Optional[Database]" = None,
                 history_epochs: int = 4096, metrics=None,
                 span_plane=None, cache_chunks: int = 4096) -> None:
        self.db = database or Database.in_memory()
        self.history_epochs = history_epochs
        self.metrics = metrics
        #: optional tpu.spans.SpanPlane for the bulk-replay grid merge;
        #: None keeps the merge on the numpy twin
        self.span_plane = span_plane
        self.cache_chunks = cache_chunks
        self.detected: "list[Slashing]" = []
        #: (kind, vchunk, echunk) -> uint64[VPC, CE]; LRU-ordered, dirty
        #: entries flushed to the K-V store at the end of every mutating
        #: call and pinned against eviction until then
        self._chunks: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._dirty: "set[tuple]" = set()

    # ------------------------------------------------------------- chunks

    def _cache_event(self, event: str) -> None:
        if self.metrics is not None:
            self.metrics.slasher_chunk_cache_events.labels(event).inc()

    def _sync_cache_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.slasher_chunk_cache_size.set(len(self._chunks))

    def _chunk_key(self, kind: str, vchunk: int, echunk: int) -> bytes:
        prefix = _PREFIX_MIN if kind == "min" else _PREFIX_MAX
        return prefix + vchunk.to_bytes(8, "big") + echunk.to_bytes(8, "big")

    def _chunk(self, kind: str, vchunk: int, echunk: int) -> np.ndarray:
        key = (kind, vchunk, echunk)
        arr = self._chunks.get(key)
        if arr is not None:
            self._chunks.move_to_end(key)
            self._cache_event("hit")
            return arr
        self._cache_event("miss")
        raw = self.db.get(self._chunk_key(kind, vchunk, echunk))
        if raw is not None:
            arr = (
                np.frombuffer(bytes(raw), dtype=np.uint64)
                .reshape(VALIDATORS_PER_CHUNK, CHUNK_EPOCHS)
                .copy()
            )
        else:
            fill = _UNSET_MIN if kind == "min" else np.uint64(0)
            arr = np.full(
                (VALIDATORS_PER_CHUNK, CHUNK_EPOCHS), fill, np.uint64
            )
        self._chunks[key] = arr
        if len(self._chunks) > self.cache_chunks:
            # LRU: oldest clean entries first; dirty chunks are pinned
            # until flush writes them back
            for k in list(self._chunks.keys()):
                if len(self._chunks) <= self.cache_chunks:
                    break
                if k in self._dirty or k == key:
                    continue
                del self._chunks[k]
                self._cache_event("evict")
        self._sync_cache_gauge()
        return arr

    def flush(self) -> None:
        if not self._dirty:
            return
        batch = []
        for kind, vchunk, echunk in self._dirty:
            batch.append((
                self._chunk_key(kind, vchunk, echunk),
                self._chunks[(kind, vchunk, echunk)].tobytes(),
            ))
            batch.append((
                _PREFIX_ECHUNK_IDX
                + echunk.to_bytes(8, "big")
                + (b"m" if kind == "min" else b"x")
                + vchunk.to_bytes(8, "big"),
                b"",
            ))
        self.db.put_batch(batch)
        self._dirty.clear()

    # ------------------------------------------------------------ records

    def _rec_key(self, index: int, target: int) -> bytes:
        return (
            _PREFIX_REC
            + int(index).to_bytes(8, "big")
            + int(target).to_bytes(8, "big")
        )

    def _rec_rows(self, index: int, source: int, target: int,
                  data_root: bytes) -> "list[tuple[bytes, bytes]]":
        return [
            (self._rec_key(index, target),
             source.to_bytes(8, "big") + data_root),
            (_PREFIX_TGT_IDX + target.to_bytes(8, "big")
             + index.to_bytes(8, "big"), b""),
        ]

    def _put_record(self, index: int, source: int, target: int,
                    data_root: bytes) -> None:
        self.db.put_batch(self._rec_rows(index, source, target, data_root))

    def _record(self, index: int, target: int):
        raw = self.db.get(self._rec_key(index, target))
        if raw is None:
            return None
        raw = bytes(raw)
        return int.from_bytes(raw[:8], "big"), raw[8:40]

    def record_for(self, validator_index: int, target: int):
        """Recorded vote of `validator_index` at `target`, as
        (source_epoch, data_root) — or None when the validator has no
        recorded attestation for that target epoch. The public read used
        by the firehose and replay feeds to assemble double-vote
        evidence."""
        return self._record(int(validator_index), int(target))

    # -------------------------------------------------------- attestations

    def on_attestation(
        self, attesting_indices, source_epoch: int, target_epoch: int,
        data_root: bytes,
    ) -> "list[Slashing]":
        """Record one indexed attestation; returns any detected offenses.
        The aggregate's index set is processed as a batch: grouped by
        vchunk, one vectorized check gather and one vectorized range
        update per touched chunk. A repeated index inside one aggregate
        is order-dependent (its first occurrence can rewrite the record
        the second one reads), so those rare aggregates take the
        sequential reference path instead."""
        s, t = int(source_epoch), int(target_epoch)
        data_root = bytes(data_root)
        ids = [int(i) for i in attesting_indices]
        t0 = time.perf_counter()
        if len(set(ids)) != len(ids):
            out = self._on_attestation_seq(ids, s, t, data_root)
        else:
            out = self._on_attestation_batched(ids, s, t, data_root)
        self.flush()
        self._observe_span_update(t0, len(ids))
        self.detected.extend(out)
        return out

    def on_attestation_reference(
        self, attesting_indices, source_epoch: int, target_epoch: int,
        data_root: bytes,
    ) -> "list[Slashing]":
        """The original per-validator loop, byte-for-byte semantics.
        Kept as the oracle for the batched path's differential tests and
        the bench's batched-vs-loop diagnostic."""
        s, t = int(source_epoch), int(target_epoch)
        data_root = bytes(data_root)
        ids = [int(i) for i in attesting_indices]
        out = self._on_attestation_seq(ids, s, t, data_root)
        self.flush()
        self.detected.extend(out)
        return out

    def _on_attestation_seq(self, ids, s: int, t: int,
                            data_root: bytes) -> "list[Slashing]":
        out = []
        for i in ids:
            hit = self._check_one(i, s, t, data_root)
            if hit is not None:
                out.append(hit)
            self._put_record(i, s, t, data_root)
            self._update_spans(i, s, t)
        return out

    def _on_attestation_batched(self, ids, s: int, t: int,
                                data_root: bytes) -> "list[Slashing]":
        checks = self._check_rows(ids, s, t, data_root)
        out = [hit for hit in checks if hit is not None]
        rows = []
        for i in ids:
            rows.extend(self._rec_rows(i, s, t, data_root))
        if rows:
            self.db.put_batch(rows)
        ids_arr = np.asarray(ids, dtype=np.int64)
        vchunks = ids_arr // VALIDATORS_PER_CHUNK
        for vc in np.unique(vchunks):
            self._update_spans_rows(
                int(vc), ids_arr[vchunks == vc] % VALIDATORS_PER_CHUNK,
                s, t,
            )
        return out

    def _check_rows(self, ids, s: int, t: int, data_root: bytes):
        """Vectorized `_check_one` over an aggregate's (unique) indices:
        one gather per touched chunk, detection precedence per validator
        identical to the scalar path (double vote, surround,
        surrounded). Returns a list aligned with `ids`, None for clean
        rows."""
        n = len(ids)
        echunk_s, col_s = divmod(s, CHUNK_EPOCHS)
        ids_arr = np.asarray(ids, dtype=np.int64)
        vchunks = ids_arr // VALIDATORS_PER_CHUNK
        rows = ids_arr % VALIDATORS_PER_CHUNK
        min_vals = np.empty(n, np.uint64)
        max_vals = np.empty(n, np.uint64)
        for vc in np.unique(vchunks):
            m = vchunks == vc
            r = rows[m]
            min_vals[m] = self._chunk("min", int(vc), echunk_s)[r, col_s]
            max_vals[m] = self._chunk("max", int(vc), echunk_s)[r, col_s]
        unset = int(_UNSET_MIN)
        out = []
        for pos, i in enumerate(ids):
            existing = self._record(i, t)
            if existing is not None and existing[1] != data_root:
                out.append(Slashing("double_vote", i, {
                    "target_epoch": t,
                    "roots": [existing[1].hex(), data_root.hex()],
                }))
                continue
            min_t = int(min_vals[pos])
            if min_t != unset and min_t < t:
                rec = self._record(i, min_t)
                out.append(Slashing("surround_vote", i, {
                    "existing": [rec[0] if rec else -1, min_t],
                    "new": [s, t],
                }))
                continue
            max_t = int(max_vals[pos])
            if max_t > t:
                rec = self._record(i, max_t)
                out.append(Slashing("surrounded_vote", i, {
                    "existing": [rec[0] if rec else -1, max_t],
                    "new": [s, t],
                }))
                continue
            out.append(None)
        return out

    def _check_one(self, i: int, s: int, t: int, data_root: bytes):
        existing = self._record(i, t)
        if existing is not None and existing[1] != data_root:
            return Slashing("double_vote", i, {
                "target_epoch": t,
                "roots": [existing[1].hex(), data_root.hex()],
            })
        vchunk, row = divmod(i, VALIDATORS_PER_CHUNK)
        echunk, col = divmod(s, CHUNK_EPOCHS)
        min_t = int(self._chunk("min", vchunk, echunk)[row, col])
        if min_t != int(_UNSET_MIN) and min_t < t:
            rec = self._record(i, min_t)
            return Slashing("surround_vote", i, {
                "existing": [rec[0] if rec else -1, min_t],
                "new": [s, t],
            })
        max_t = int(self._chunk("max", vchunk, echunk)[row, col])
        if max_t > t:
            rec = self._record(i, max_t)
            return Slashing("surrounded_vote", i, {
                "existing": [rec[0] if rec else -1, max_t],
                "new": [s, t],
            })
        return None

    def _update_spans(self, i: int, s: int, t: int) -> None:
        """Amortized range update: min_targets over e ∈ [floor, s),
        max_targets over e ∈ (s, t], early-exiting on the first unchanged
        chunk (valid by monotonicity, see module docstring)."""
        vchunk, row = divmod(i, VALIDATORS_PER_CHUNK)
        tval = np.uint64(t)

        # ---- min_targets: epochs below the source
        floor = max(0, s - self.history_epochs)
        e_hi = s - 1  # inclusive
        while e_hi >= floor:
            echunk = e_hi // CHUNK_EPOCHS
            e_lo = max(floor, echunk * CHUNK_EPOCHS)
            arr = self._chunk("min", vchunk, echunk)
            sl = arr[row, e_lo - echunk * CHUNK_EPOCHS : e_hi - echunk * CHUNK_EPOCHS + 1]
            if not (sl > tval).any():
                break  # monotone: everything below is already ≤ t
            np.minimum(sl, tval, out=sl)
            self._dirty.add(("min", vchunk, echunk))
            e_hi = e_lo - 1

        # ---- max_targets: epochs above the source, bounded by the target
        # (an attestation with source past the target cannot be surrounded
        # by this one — target ≥ source always)
        e_lo = s + 1
        while e_lo <= t:
            echunk = e_lo // CHUNK_EPOCHS
            e_hi2 = min(t, echunk * CHUNK_EPOCHS + CHUNK_EPOCHS - 1)
            arr = self._chunk("max", vchunk, echunk)
            sl = arr[row, e_lo - echunk * CHUNK_EPOCHS : e_hi2 - echunk * CHUNK_EPOCHS + 1]
            if not (sl < tval).any():
                break  # monotone: everything above is already ≥ t
            np.maximum(sl, tval, out=sl)
            self._dirty.add(("max", vchunk, echunk))
            e_lo = e_hi2 + 1

    def _update_spans_rows(self, vchunk: int, rows, s: int, t: int) -> None:
        """`_update_spans` for many rows of one vchunk sharing (s, t):
        one vectorized chunk op per step of the walk, with the per-row
        early exit carried as a shrinking active set (a row leaves the
        walk at the first chunk it doesn't change, exactly where the
        scalar loop would have stopped)."""
        rows = np.asarray(rows, dtype=np.int64)
        tval = np.uint64(t)

        # ---- min_targets
        floor = max(0, s - self.history_epochs)
        active = rows
        e_hi = s - 1
        while e_hi >= floor and active.size:
            echunk = e_hi // CHUNK_EPOCHS
            e_lo = max(floor, echunk * CHUNK_EPOCHS)
            arr = self._chunk("min", vchunk, echunk)
            c0 = e_lo - echunk * CHUNK_EPOCHS
            c1 = e_hi - echunk * CHUNK_EPOCHS + 1
            full = active.size == arr.shape[0]
            sub = arr[:, c0:c1] if full else arr[active, c0:c1]
            mask = (sub > tval).any(axis=1)
            if mask.all():
                if full:
                    np.minimum(sub, tval, out=sub)  # `sub` is a view
                else:
                    arr[active, c0:c1] = np.minimum(sub, tval)
                self._dirty.add(("min", vchunk, echunk))
            elif mask.any():
                # `sub` rows follow chunk order when full, `active` order
                # otherwise — pick the matching row index either way
                active = np.nonzero(mask)[0] if full else active[mask]
                arr[active, c0:c1] = np.minimum(sub[mask], tval)
                self._dirty.add(("min", vchunk, echunk))
            else:
                break  # monotone: every active row already ≤ t below here
            e_hi = e_lo - 1

        # ---- max_targets
        active = rows
        e_lo = s + 1
        while e_lo <= t and active.size:
            echunk = e_lo // CHUNK_EPOCHS
            e_hi2 = min(t, echunk * CHUNK_EPOCHS + CHUNK_EPOCHS - 1)
            arr = self._chunk("max", vchunk, echunk)
            c0 = e_lo - echunk * CHUNK_EPOCHS
            c1 = e_hi2 - echunk * CHUNK_EPOCHS + 1
            full = active.size == arr.shape[0]
            sub = arr[:, c0:c1] if full else arr[active, c0:c1]
            mask = (sub < tval).any(axis=1)
            if mask.all():
                if full:
                    np.maximum(sub, tval, out=sub)  # `sub` is a view
                else:
                    arr[active, c0:c1] = np.maximum(sub, tval)
                self._dirty.add(("max", vchunk, echunk))
            elif mask.any():
                active = np.nonzero(mask)[0] if full else active[mask]
                arr[active, c0:c1] = np.maximum(sub[mask], tval)
                self._dirty.add(("max", vchunk, echunk))
            else:
                break  # monotone: every active row already ≥ t above here
            e_lo = e_hi2 + 1

    # ---------------------------------------------------- bulk-replay feed

    def on_attestations_bulk(self, attestations) -> "list[list[Slashing]]":
        """Ingest a replay window's attestations at once:
        `[(attesting_indices, source, target, data_root), ...]` →
        per-attestation slashing lists, semantics identical to calling
        `on_attestation` in order.

        Validators that appear once in the whole window ("solo") have
        order-independent effects (per-validator decomposability, see
        module docstring): their checks batch per aggregate against the
        pre-window chunk state and their span updates merge into one
        chunk-aligned epoch grid — a single device dispatch through
        `span_plane` when wired. Validators seen more than once keep the
        exact sequential path, interleaved at their original positions."""
        norm = []
        for indices, source, target, root in attestations:
            norm.append((
                [int(i) for i in indices], int(source), int(target),
                bytes(root),
            ))
        if not norm:
            return []
        t0 = time.perf_counter()
        counts = _Counter()
        for ids, _s, _t, _root in norm:
            counts.update(ids)
        collision = {i for i, c in counts.items() if c > 1}

        hits: "dict[tuple[int, int], Slashing]" = {}
        solo_updates: "list[tuple[int, int, int]]" = []
        record_rows: "list[tuple[bytes, bytes]]" = []
        n_indices = 0
        for a, (ids, s, t, root) in enumerate(norm):
            n_indices += len(ids)
            solo_pos = [p for p, i in enumerate(ids) if i not in collision]
            for p, i in enumerate(ids):
                if i in collision:
                    hit = self._check_one(i, s, t, root)
                    if hit is not None:
                        hits[(a, p)] = hit
                    self._put_record(i, s, t, root)
                    self._update_spans(i, s, t)
            if solo_pos:
                solo_ids = [ids[p] for p in solo_pos]
                for p, hit in zip(solo_pos,
                                  self._check_rows(solo_ids, s, t, root)):
                    if hit is not None:
                        hits[(a, p)] = hit
                for i in solo_ids:
                    record_rows.extend(self._rec_rows(i, s, t, root))
                    solo_updates.append((i, s, t))
        if solo_updates:
            self._merge_span_updates(solo_updates)
        if record_rows:
            self.db.put_batch(record_rows)
        self.flush()
        self._observe_span_update(t0, n_indices)

        out: "list[list[Slashing]]" = [[] for _ in norm]
        for a, p in sorted(hits):
            out[a].append(hits[(a, p)])
        for lst in out:
            self.detected.extend(lst)
        return out

    def _merge_span_updates(self, updates) -> None:
        """Merge span updates for distinct validators `(i, s, t)` in one
        epoch-grid pass. The grid is the SPAN_GRID_EPOCHS window whose
        top chunk holds the batch's max target; a row rides the grid
        when its whole update range fits the int32 device contract
        (epochs below the grid take the vectorized host walk — the long
        min tail early-exits almost immediately). Rows that don't fit
        (tiny history floors above the grid base, ancient chunk values,
        epochs ≥ 2^30) fall back to the shared-(s, t) chunk walk."""
        from grandine_tpu.tpu import spans as SP

        grid_chunks = SP.SPAN_GRID_EPOCHS // CHUNK_EPOCHS
        max_t = max(t for _i, _s, t in updates)
        grid_lo_chunk = max(0, max_t // CHUNK_EPOCHS - (grid_chunks - 1))
        grid_lo = grid_lo_chunk * CHUNK_EPOCHS

        grid_rows = []      # (vchunk, row, s, t, floor)
        fallback = {}       # (vchunk, s, t) -> [rows]
        for i, s, t in updates:
            vchunk, row = divmod(i, VALIDATORS_PER_CHUNK)
            floor = max(0, s - self.history_epochs)
            if s >= grid_lo and floor <= grid_lo and t < _GRID_EPOCH_LIMIT:
                grid_rows.append((vchunk, row, s, t, floor))
            else:
                fallback.setdefault((vchunk, s, t), []).append(row)

        if grid_rows:
            self._merge_grid(grid_rows, grid_lo, grid_lo_chunk, grid_chunks,
                             fallback)
        for (vchunk, s, t), rows in fallback.items():
            self._update_spans_rows(vchunk, rows, s, t)

    def _merge_grid(self, grid_rows, grid_lo: int, grid_lo_chunk: int,
                    grid_chunks: int, fallback: dict) -> None:
        from grandine_tpu.tpu import spans as SP

        echunks = range(grid_lo_chunk, grid_lo_chunk + grid_chunks)
        by_vchunk: "dict[int, list]" = {}
        for entry in grid_rows:
            by_vchunk.setdefault(entry[0], []).append(entry)

        refs = []           # (vchunk, row, floor) per stacked grid row
        mins, maxs, srcs, tgts = [], [], [], []
        limit = np.uint64(_GRID_EPOCH_LIMIT)
        for vchunk, entries in by_vchunk.items():
            rows = np.asarray([e[1] for e in entries], np.int64)
            min_blk = np.hstack([
                self._chunk("min", vchunk, ec)[rows, :] for ec in echunks
            ])
            max_blk = np.hstack([
                self._chunk("max", vchunk, ec)[rows, :] for ec in echunks
            ])
            # int32 contract: every carried value must be UNSET or small.
            # Anything else (never on a real chain) exiles the row to the
            # host walk.
            ok = (
                ((min_blk == _UNSET_MIN) | (min_blk < limit)).all(axis=1)
                & (max_blk < limit).all(axis=1)
            )
            for pos, e in enumerate(entries):
                _vc, row, s, t, floor = e
                if ok[pos]:
                    refs.append((vchunk, row, floor))
                    mins.append(np.where(min_blk[pos] == _UNSET_MIN,
                                         np.uint64(SP.INT32_UNSET),
                                         min_blk[pos]).astype(np.int32))
                    maxs.append(max_blk[pos].astype(np.int32))
                    srcs.append(s)
                    tgts.append(t)
                else:
                    fallback.setdefault((vchunk, s, t), []).append(row)
        if not refs:
            return

        in_min = np.stack(mins)
        in_max = np.stack(maxs)
        src = np.asarray(srcs, np.int32)
        tgt = np.asarray(tgts, np.int32)
        if self.span_plane is not None:
            out_min, out_max = self.span_plane.update(
                in_min, in_max, src, tgt, grid_lo
            )
        else:
            out_min, out_max = SP.grid_merge_host(
                in_min, in_max, src, tgt, grid_lo
            )

        # scatter changed segments back and run the below-grid min tail
        changed_min = out_min != in_min
        changed_max = out_max != in_max
        new_min = np.where(out_min == SP.INT32_UNSET, _UNSET_MIN,
                           out_min.astype(np.int64).astype(np.uint64))
        new_max = out_max.astype(np.int64).astype(np.uint64)
        refs_vc = np.asarray([r[0] for r in refs], np.int64)
        refs_row = np.asarray([r[1] for r in refs], np.int64)
        refs_floor = np.asarray([r[2] for r in refs], np.int64)
        for vchunk in np.unique(refs_vc):
            sel = np.nonzero(refs_vc == vchunk)[0]
            rows = refs_row[sel]
            for k, ec in enumerate(echunks):
                seg = slice(k * CHUNK_EPOCHS, (k + 1) * CHUNK_EPOCHS)
                mmask = changed_min[sel, seg].any(axis=1)
                if mmask.any():
                    arr = self._chunk("min", int(vchunk), ec)
                    arr[rows[mmask], :] = new_min[sel[mmask], seg]
                    self._dirty.add(("min", int(vchunk), ec))
                xmask = changed_max[sel, seg].any(axis=1)
                if xmask.any():
                    arr = self._chunk("max", int(vchunk), ec)
                    arr[rows[xmask], :] = new_max[sel[xmask], seg]
                    self._dirty.add(("max", int(vchunk), ec))
            below = refs_floor[sel] < grid_lo
            if grid_lo > 0 and below.any():
                bs = sel[below]
                self._walk_min_below(
                    int(vchunk), refs_row[bs],
                    tgt[bs].astype(np.uint64), refs_floor[bs],
                    grid_lo - 1,
                )

    def _walk_min_below(self, vchunk: int, rows, tvals, floors,
                        e_start: int) -> None:
        """Vectorized min-side walk below the grid: per-row target values
        and history floors, shrinking active set for the monotone early
        exit (same stopping chunk as the scalar walk for every row)."""
        active = np.arange(len(rows))
        e_hi = e_start
        while e_hi >= 0 and active.size:
            active = active[floors[active] <= e_hi]
            if not active.size:
                break
            echunk = e_hi // CHUNK_EPOCHS
            e_lo_chunk = echunk * CHUNK_EPOCHS
            c1 = e_hi - e_lo_chunk + 1
            cols = np.arange(e_lo_chunk, e_lo_chunk + c1)
            arr = self._chunk("min", vchunk, echunk)
            sub = arr[rows[active], 0:c1]
            eligible = cols[None, :] >= floors[active][:, None]
            gt = eligible & (sub > tvals[active][:, None])
            rowmask = gt.any(axis=1)
            if rowmask.any():
                upd = active[rowmask]
                submat = arr[rows[upd], 0:c1]
                el = cols[None, :] >= floors[upd][:, None]
                hit = el & (submat > tvals[upd][:, None])
                arr[np.ix_(rows[upd], np.arange(c1))] = np.where(
                    hit, tvals[upd][:, None], submat
                )
                self._dirty.add(("min", vchunk, echunk))
            active = active[rowmask]
            e_hi = e_lo_chunk - 1

    def _observe_span_update(self, t0: float, n_indices: int) -> None:
        if self.metrics is None:
            return
        self.metrics.slasher_span_update_seconds.observe(
            time.perf_counter() - t0
        )
        self.metrics.slasher_span_indices.inc(n_indices)

    # ------------------------------------------------------------- pruning

    def prune(self, finalized_epoch: int) -> int:
        """Drop span chunks and records wholly below the history window
        (the reference prunes its span DBs at finalization). Incremental:
        the `sl:e:`/`sl:t:` indexes are ascending in epoch, so the walk
        visits exactly the doomed prefix and stops — O(pruned), not
        O(database)."""
        floor = max(0, finalized_epoch - self.history_epochs)
        floor_chunk = floor // CHUNK_EPOCHS
        dropped = 0
        doomed = []
        off = len(_PREFIX_ECHUNK_IDX)
        for key, _ in self.db.iterate_prefix(_PREFIX_ECHUNK_IDX):
            echunk = int.from_bytes(key[off : off + 8], "big")
            if echunk >= floor_chunk:
                break
            kind = "min" if key[off + 8 : off + 9] == b"m" else "max"
            vchunk = int.from_bytes(key[off + 9 : off + 17], "big")
            doomed.append((key, self._chunk_key(kind, vchunk, echunk)))
        for idx_key, data_key in doomed:
            self.db.delete(data_key)
            self.db.delete(idx_key)
            dropped += 1
        doomed = []
        off = len(_PREFIX_TGT_IDX)
        for key, _ in self.db.iterate_prefix(_PREFIX_TGT_IDX):
            target = int.from_bytes(key[off : off + 8], "big")
            if target >= floor:
                break
            validator = int.from_bytes(key[off + 8 : off + 16], "big")
            doomed.append((key, self._rec_key(validator, target)))
        for idx_key, data_key in doomed:
            self.db.delete(data_key)
            self.db.delete(idx_key)
            dropped += 1
        self._chunks = OrderedDict(
            (k, v)
            for k, v in self._chunks.items()
            if k[2] >= floor_chunk or k in self._dirty
        )
        self._sync_cache_gauge()
        return dropped

    # -------------------------------------------------------------- blocks

    def on_block(self, proposer_index: int, slot: int,
                 header_root: bytes) -> "Optional[Slashing]":
        key = _PREFIX_BLOCK + int(proposer_index).to_bytes(8, "big") \
            + int(slot).to_bytes(8, "big")
        existing = self.db.get(key)
        if existing is not None and bytes(existing) != bytes(header_root):
            hit = Slashing("double_block", int(proposer_index), {
                "slot": slot,
                "roots": [bytes(existing).hex(), bytes(header_root).hex()],
            })
            self.detected.append(hit)
            return hit
        self.db.put(key, bytes(header_root))
        return None

    def drain(self) -> "list[Slashing]":
        out = self.detected
        self.detected = []
        return out


__all__ = ["Slasher", "Slashing", "CHUNK_EPOCHS", "VALIDATORS_PER_CHUNK"]
