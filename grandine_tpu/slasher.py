"""Slasher — reference: `slasher` crate (slasher/src/slasher.rs:50:
surround/double-vote detection over mdbx DBs of indexed attestations and
chunked min/max target spans, plus proposer double-block detection; emits
slashings toward the proposer pipeline).

Scale design (the reference's chunked span scheme, numpy-native):
for validator v and epoch e,
  min_targets[v][e] = min target among v's attestations with source > e
  max_targets[v][e] = max target among v's attestations with source < e
Both are MONOTONE non-decreasing in e (larger e → smaller source set for
min / larger for max), which makes range updates amortized O(1): walking
away from the new attestation's source, the update stops at the first
chunk it doesn't change.

Detection per new attestation (s, t) of validator v is O(1) chunk reads:
  min_targets[v][s] < t  →  the new vote SURROUNDS a recorded one
  max_targets[v][s] > t  →  the new vote IS SURROUNDED by a recorded one
  a recorded (v, t) with a different data root  →  double vote

Storage: (VALIDATORS_PER_CHUNK × CHUNK_EPOCHS) uint64 arrays in the K-V
store (the reference's mdbx chunk tables), an in-memory dirty-chunk cache
flushed per call, and per-(validator, target) attestation records for
evidence retrieval.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from grandine_tpu.storage.database import Database

CHUNK_EPOCHS = 16
VALIDATORS_PER_CHUNK = 256
_UNSET_MIN = np.uint64(0xFFFF_FFFF_FFFF_FFFF)

_PREFIX_MIN = b"sl:m:"    # vchunk_be8 + echunk_be8 -> uint64[VPC, CE]
_PREFIX_MAX = b"sl:x:"
_PREFIX_REC = b"sl:r:"    # validator_be8 + target_be8 -> source_be8 + root32
_PREFIX_BLOCK = b"sl:b:"  # validator_be8 + slot_be8 -> header root


class Slashing:
    """A detected offense with the evidence needed to build the on-chain
    operation."""

    __slots__ = ("kind", "validator_index", "evidence")

    def __init__(self, kind: str, validator_index: int, evidence: dict) -> None:
        self.kind = kind
        self.validator_index = validator_index
        self.evidence = evidence

    def __repr__(self) -> str:
        return f"Slashing({self.kind}, validator={self.validator_index})"


class Slasher:
    def __init__(self, database: "Optional[Database]" = None,
                 history_epochs: int = 4096) -> None:
        self.db = database or Database.in_memory()
        self.history_epochs = history_epochs
        self.detected: "list[Slashing]" = []
        #: (kind, vchunk, echunk) -> uint64[VPC, CE]; dirty set flushed
        #: back to the K-V store at the end of every mutating call
        self._chunks: "dict[tuple, np.ndarray]" = {}
        self._dirty: "set[tuple]" = set()

    # ------------------------------------------------------------- chunks

    def _chunk_key(self, kind: str, vchunk: int, echunk: int) -> bytes:
        prefix = _PREFIX_MIN if kind == "min" else _PREFIX_MAX
        return prefix + vchunk.to_bytes(8, "big") + echunk.to_bytes(8, "big")

    def _chunk(self, kind: str, vchunk: int, echunk: int) -> np.ndarray:
        key = (kind, vchunk, echunk)
        arr = self._chunks.get(key)
        if arr is None:
            raw = self.db.get(self._chunk_key(kind, vchunk, echunk))
            if raw is not None:
                arr = (
                    np.frombuffer(bytes(raw), dtype=np.uint64)
                    .reshape(VALIDATORS_PER_CHUNK, CHUNK_EPOCHS)
                    .copy()
                )
            else:
                fill = _UNSET_MIN if kind == "min" else np.uint64(0)
                arr = np.full(
                    (VALIDATORS_PER_CHUNK, CHUNK_EPOCHS), fill, np.uint64
                )
            # bound the cache: evict clean chunks beyond ~4k (64 MB)
            if len(self._chunks) > 4096:
                for k in [
                    k for k in self._chunks if k not in self._dirty
                ][:1024]:
                    del self._chunks[k]
            self._chunks[key] = arr
        return arr

    def flush(self) -> None:
        for kind, vchunk, echunk in self._dirty:
            self.db.put(
                self._chunk_key(kind, vchunk, echunk),
                self._chunks[(kind, vchunk, echunk)].tobytes(),
            )
        self._dirty.clear()

    # ------------------------------------------------------------ records

    def _rec_key(self, index: int, target: int) -> bytes:
        return (
            _PREFIX_REC
            + int(index).to_bytes(8, "big")
            + int(target).to_bytes(8, "big")
        )

    def _record(self, index: int, target: int):
        raw = self.db.get(self._rec_key(index, target))
        if raw is None:
            return None
        raw = bytes(raw)
        return int.from_bytes(raw[:8], "big"), raw[8:40]

    def record_for(self, validator_index: int, target: int):
        """Recorded vote of `validator_index` at `target`, as
        (source_epoch, data_root) — or None when the validator has no
        recorded attestation for that target epoch. The public read used
        by the firehose and replay feeds to assemble double-vote
        evidence."""
        return self._record(int(validator_index), int(target))

    # -------------------------------------------------------- attestations

    def on_attestation(
        self, attesting_indices, source_epoch: int, target_epoch: int,
        data_root: bytes,
    ) -> "list[Slashing]":
        """Record one indexed attestation; returns any detected offenses.
        Chunk reads/updates are shared across the aggregate's validators."""
        s, t = int(source_epoch), int(target_epoch)
        data_root = bytes(data_root)
        out = []
        for i in attesting_indices:
            i = int(i)
            hit = self._check_one(i, s, t, data_root)
            if hit is not None:
                out.append(hit)
            self.db.put(
                self._rec_key(i, t),
                s.to_bytes(8, "big") + data_root,
            )
            self._update_spans(i, s, t)
        self.flush()
        self.detected.extend(out)
        return out

    def _check_one(self, i: int, s: int, t: int, data_root: bytes):
        existing = self._record(i, t)
        if existing is not None and existing[1] != data_root:
            return Slashing("double_vote", i, {
                "target_epoch": t,
                "roots": [existing[1].hex(), data_root.hex()],
            })
        vchunk, row = divmod(i, VALIDATORS_PER_CHUNK)
        echunk, col = divmod(s, CHUNK_EPOCHS)
        min_t = int(self._chunk("min", vchunk, echunk)[row, col])
        if min_t != int(_UNSET_MIN) and min_t < t:
            rec = self._record(i, min_t)
            return Slashing("surround_vote", i, {
                "existing": [rec[0] if rec else -1, min_t],
                "new": [s, t],
            })
        max_t = int(self._chunk("max", vchunk, echunk)[row, col])
        if max_t > t:
            rec = self._record(i, max_t)
            return Slashing("surrounded_vote", i, {
                "existing": [rec[0] if rec else -1, max_t],
                "new": [s, t],
            })
        return None

    def _update_spans(self, i: int, s: int, t: int) -> None:
        """Amortized range update: min_targets over e ∈ [floor, s),
        max_targets over e ∈ (s, t], early-exiting on the first unchanged
        chunk (valid by monotonicity, see module docstring)."""
        vchunk, row = divmod(i, VALIDATORS_PER_CHUNK)
        tval = np.uint64(t)

        # ---- min_targets: epochs below the source
        floor = max(0, s - self.history_epochs)
        e_hi = s - 1  # inclusive
        while e_hi >= floor:
            echunk = e_hi // CHUNK_EPOCHS
            e_lo = max(floor, echunk * CHUNK_EPOCHS)
            arr = self._chunk("min", vchunk, echunk)
            sl = arr[row, e_lo - echunk * CHUNK_EPOCHS : e_hi - echunk * CHUNK_EPOCHS + 1]
            if not (sl > tval).any():
                break  # monotone: everything below is already ≤ t
            np.minimum(sl, tval, out=sl)
            self._dirty.add(("min", vchunk, echunk))
            e_hi = e_lo - 1

        # ---- max_targets: epochs above the source, bounded by the target
        # (an attestation with source past the target cannot be surrounded
        # by this one — target ≥ source always)
        e_lo = s + 1
        while e_lo <= t:
            echunk = e_lo // CHUNK_EPOCHS
            e_hi2 = min(t, echunk * CHUNK_EPOCHS + CHUNK_EPOCHS - 1)
            arr = self._chunk("max", vchunk, echunk)
            sl = arr[row, e_lo - echunk * CHUNK_EPOCHS : e_hi2 - echunk * CHUNK_EPOCHS + 1]
            if not (sl < tval).any():
                break  # monotone: everything above is already ≥ t
            np.maximum(sl, tval, out=sl)
            self._dirty.add(("max", vchunk, echunk))
            e_lo = e_hi2 + 1

    # ------------------------------------------------------------- pruning

    def prune(self, finalized_epoch: int) -> int:
        """Drop span chunks and records wholly below the history window
        (the reference prunes its span DBs at finalization)."""
        floor = max(0, finalized_epoch - self.history_epochs)
        floor_chunk = floor // CHUNK_EPOCHS
        dropped = 0
        for prefix in (_PREFIX_MIN, _PREFIX_MAX):
            for key, _ in list(self.db.iterate_prefix(prefix)):
                echunk = int.from_bytes(key[len(prefix) + 8 :], "big")
                if echunk < floor_chunk:
                    self.db.delete(key)
                    dropped += 1
        for key, _ in list(self.db.iterate_prefix(_PREFIX_REC)):
            target = int.from_bytes(key[len(_PREFIX_REC) + 8 :], "big")
            if target < floor:
                self.db.delete(key)
                dropped += 1
        self._chunks = {
            k: v
            for k, v in self._chunks.items()
            if k[2] >= floor_chunk or k in self._dirty
        }
        return dropped

    # -------------------------------------------------------------- blocks

    def on_block(self, proposer_index: int, slot: int,
                 header_root: bytes) -> "Optional[Slashing]":
        key = _PREFIX_BLOCK + int(proposer_index).to_bytes(8, "big") \
            + int(slot).to_bytes(8, "big")
        existing = self.db.get(key)
        if existing is not None and bytes(existing) != bytes(header_root):
            hit = Slashing("double_block", int(proposer_index), {
                "slot": slot,
                "roots": [bytes(existing).hex(), bytes(header_root).hex()],
            })
            self.detected.append(hit)
            return hit
        self.db.put(key, bytes(header_root))
        return None

    def drain(self) -> "list[Slashing]":
        out = self.detected
        self.detected = []
        return out


__all__ = ["Slasher", "Slashing", "CHUNK_EPOCHS", "VALIDATORS_PER_CHUNK"]
