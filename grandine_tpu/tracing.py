"""Structured tracing: monotonic-clock spans with parent context that
survives thread hops, a bounded ring buffer of completed spans, and
Chrome trace-event JSON export (load the dump in `chrome://tracing` or
Perfetto).

Design mirrors the reference client's tracing feature flag: spans are
cheap enough to leave on (two `perf_counter` calls and a deque append),
carry string attributes, and nest via an explicit parent id rather than
global state — the current span is tracked per-thread, and
`Tracer.capture()` / `Tracer.attach()` move that context across the
runtime's thread pool (see runtime/thread_pool.py, which captures at
`spawn` and attaches in the worker).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "NULL_TRACER"]

#: process-wide epoch for trace timestamps: Chrome trace-event `ts` is in
#: microseconds from an arbitrary origin; anchoring every tracer at import
#: keeps spans from different tracers on one comparable timeline.
_EPOCH = time.perf_counter()


class Span:
    """One timed operation. Use as a context manager (finishes on exit)
    or call `finish()` explicitly for hand-rolled begin/end pairs."""

    __slots__ = (
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "start",
        "end",
        "attrs",
        "thread_id",
        "thread_name",
        "_tracer",
        "_token",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: int,
        span_id: int,
        parent_id: Optional[int],
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self._tracer = tracer
        self._token: Optional[Span] = None

    # ------------------------------------------------------------ lifecycle

    @property
    def duration(self) -> float:
        """Seconds; 0.0 while the span is still open."""
        if self.end is None:
            return 0.0
        return self.end - self.start

    def set_attr(self, key: str, value: Any) -> "Span":
        self.attrs[key] = value
        return self

    def finish(self) -> None:
        if self.end is not None:  # idempotent
            return
        self.end = time.perf_counter()
        self._tracer._on_finish(self)

    def __enter__(self) -> "Span":
        self._token = self._tracer._push(self)
        return self

    def __exit__(self, *_exc) -> None:
        self._tracer._pop(self, self._token)
        self.finish()

    # -------------------------------------------------------------- export

    def to_chrome_event(self) -> Dict[str, Any]:
        """Chrome trace-event "complete" event (ph=X, µs timestamps)."""
        dur = self.duration
        ev: Dict[str, Any] = {
            "name": self.name,
            "ph": "X",
            "ts": round((self.start - _EPOCH) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": os.getpid(),
            "tid": self.thread_id,
            "args": dict(self.attrs),
        }
        ev["args"]["trace_id"] = self.trace_id
        ev["args"]["span_id"] = self.span_id
        if self.parent_id is not None:
            ev["args"]["parent_id"] = self.parent_id
        return ev


class _NullSpan:
    """Do-nothing span so instrumented code never branches on tracer
    presence: `with tracer.span(...)` works whether tracing is live."""

    __slots__ = ()
    name = "null"
    trace_id = 0
    span_id = 0
    parent_id = None
    duration = 0.0
    attrs: Dict[str, Any] = {}

    def set_attr(self, *_a, **_k) -> "_NullSpan":
        return self

    def finish(self) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_exc) -> None:
        pass

    def to_chrome_event(self) -> Dict[str, Any]:
        return {}


_NULL_SPAN = _NullSpan()


class Tracer:
    """Span factory + bounded ring buffer of completed spans.

    Thread-safe: span-id allocation and buffer appends take a lock; the
    per-thread "current span" lives in a `threading.local`, so nesting is
    tracked independently on every thread. To carry context across a
    thread hop, call `capture()` on the submitting thread and `attach()`
    (a context manager) on the worker.
    """

    def __init__(self, capacity: int = 4096, enabled: bool = True) -> None:
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._finished: deque = deque(maxlen=self.capacity)
        self._next_id = 1
        self._local = threading.local()
        self._jsonl_path: Optional[str] = None
        self._jsonl_lock = threading.Lock()

    # ----------------------------------------------------------- span API

    def _alloc_id(self) -> int:
        with self._lock:
            i = self._next_id
            self._next_id += 1
            return i

    def current(self) -> Optional[Span]:
        return getattr(self._local, "span", None)

    def span(
        self,
        name: str,
        attrs: Optional[Dict[str, Any]] = None,
        parent: Optional[Span] = None,
    ):
        """New span parented on `parent` (or the thread's current span).
        Returns a no-op span when the tracer is disabled."""
        if not self.enabled:
            return _NULL_SPAN
        if parent is None:
            parent = self.current()
        if parent is not None and not isinstance(parent, Span):
            parent = None  # a _NullSpan or foreign token: no parent
        sid = self._alloc_id()
        if parent is not None:
            return Span(self, name, parent.trace_id, sid, parent.span_id, attrs)
        return Span(self, name, sid, sid, None, attrs)

    def _push(self, span: Span):
        prev = getattr(self._local, "span", None)
        self._local.span = span
        return prev

    def _pop(self, span: Span, prev) -> None:
        if getattr(self._local, "span", None) is span:
            self._local.span = prev

    def _on_finish(self, span: Span) -> None:
        with self._lock:
            self._finished.append(span)
        path = self._jsonl_path
        if path is not None:
            line = json.dumps(span.to_chrome_event(), separators=(",", ":"))
            with self._jsonl_lock:
                try:
                    with open(path, "a") as fh:
                        fh.write(line + "\n")
                except OSError:
                    self._jsonl_path = None  # dead sink: stop trying

    # --------------------------------------------------- cross-thread hops

    def capture(self) -> Optional[Span]:
        """Current span on this thread, to hand to `attach()` elsewhere."""
        return self.current()

    def attach(self, parent: Optional[Span]):
        """Context manager installing `parent` as the current span on the
        calling (worker) thread for the duration of a task."""
        return _Attach(self, parent)

    # -------------------------------------------------------------- export

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()

    def chrome_trace(self) -> Dict[str, Any]:
        """The whole ring buffer as a Chrome trace-event JSON object."""
        spans = self.finished_spans()
        return {
            "traceEvents": [s.to_chrome_event() for s in spans],
            "displayTimeUnit": "ms",
            "otherData": {
                "clock": "perf_counter",
                "span_count": len(spans),
                "capacity": self.capacity,
            },
        }

    def set_jsonl_path(self, path: Optional[str]) -> None:
        """Mirror every finished span to `path` as one JSON line each
        (Chrome trace-event objects; `jq -s '{traceEvents:.}'` rebuilds a
        loadable trace). Truncates any existing file."""
        if path is not None:
            with open(path, "w"):
                pass
        self._jsonl_path = path


class _Attach:
    __slots__ = ("_tracer", "_parent", "_prev")

    def __init__(self, tracer: Tracer, parent: Optional[Span]) -> None:
        self._tracer = tracer
        self._parent = parent if isinstance(parent, Span) else None
        self._prev: Optional[Span] = None

    def __enter__(self) -> "_Attach":
        self._prev = self._tracer.current()
        self._tracer._local.span = self._parent
        return self

    def __exit__(self, *_exc) -> None:
        self._tracer._local.span = self._prev


#: shared disabled tracer: modules can default to this and never check
#: for None before opening spans.
NULL_TRACER = Tracer(capacity=1, enabled=False)
