"""Networking — reference: `eth2_libp2p` (the Lighthouse-derived WAN stack:
gossipsub, discv5, req/resp protocols) and the `p2p` crate (service loop
`Network::run`, gossip dispatch, `BlockSyncService`/`SyncManager` range
tracking, `back_sync`, `BlockVerificationPool`).

The WAN transport is abstracted behind `Transport` (publish/subscribe +
req/resp); `InMemoryHub` provides a process-local mesh so multi-node
behavior is testable in-repo (the reference tests only at channel
boundaries — SURVEY §4.3). Topic names and SSZ-snappy payload encoding
follow the consensus network spec, so a real libp2p transport drops in
behind the same interface.
"""

from grandine_tpu.p2p.network import (  # noqa: F401
    GossipTopics,
    InMemoryHub,
    Network,
    Transport,
)
from grandine_tpu.p2p.sync import BlockSyncService, SyncManager  # noqa: F401
