"""Subnet subscription state machines — reference:
p2p/src/attestation_subnets.rs (short-lived per-duty subscriptions +
node-id-seeded persistent subnets), p2p/src/sync_committee_subnets.rs
(per-period subscriptions until an expiry epoch), and the `SubnetService`
that folds both into the gossip layer's active topic set.

The gossip layer asks `active_attestation_subnets(slot)` /
`active_sync_subnets(epoch)` each tick; everything else is bookkeeping
driven by the Beacon API subscription routes and the validator service's
own duties (own_*_subscriptions.rs).
"""

from __future__ import annotations

import threading
from typing import Optional

from grandine_tpu.core.hashing import hash_bytes
from grandine_tpu.core.shuffling import compute_shuffled_index

#: consensus networking spec constants
SUBNETS_PER_NODE = 2
EPOCHS_PER_SUBNET_SUBSCRIPTION = 256
ATTESTATION_SUBNET_PREFIX_BITS = 6
SYNC_COMMITTEE_SUBNET_COUNT = 4
#: keep a short-lived subscription this many slots past the duty slot
#: (aggregation happens within the duty slot; one slot of slack absorbs
#: late gossip, attestation_subnets.rs keeps the same window)
SUBSCRIPTION_SLACK_SLOTS = 1


def compute_subnet_id(
    committee_index: int,
    slot: int,
    committees_at_slot: int,
    preset,
    subnet_count: int = 64,
) -> int:
    """Spec `compute_subnet_for_attestation` (subnet_count is
    ATTESTATION_SUBNET_COUNT, configurable like cfg.attestation_subnet_count)."""
    slots_since_epoch_start = slot % preset.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_at_slot * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % subnet_count


def compute_subscribed_subnets(
    node_id: int, epoch: int, subnet_count: int = 64
) -> "list[int]":
    """Spec `compute_subscribed_subnets`: the node's persistent subnets,
    rotated every EPOCHS_PER_SUBNET_SUBSCRIPTION epochs by a shuffled
    permutation of the node-id prefix."""
    node_id_prefix = node_id >> (256 - ATTESTATION_SUBNET_PREFIX_BITS)
    node_offset = node_id % EPOCHS_PER_SUBNET_SUBSCRIPTION
    period = (epoch + node_offset) // EPOCHS_PER_SUBNET_SUBSCRIPTION
    seed = hash_bytes(period.to_bytes(8, "little"))
    permutated = compute_shuffled_index(
        node_id_prefix, 1 << ATTESTATION_SUBNET_PREFIX_BITS, seed
    )
    return [
        (permutated + index) % subnet_count
        for index in range(SUBNETS_PER_NODE)
    ]


def sync_subnets_for_positions(positions, preset) -> "set[int]":
    """Committee positions -> sync committee subnet ids."""
    sub_size = preset.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    return {int(p) // sub_size for p in positions}


class SubnetService:
    """Tracks which attestation / sync-committee subnets this node must
    be joined to, from both API subscriptions and own-validator duties.
    Thread-safe: API handlers, the validator service, and the network
    tick all touch it."""

    def __init__(self, cfg, node_id: int = 0, network=None) -> None:
        self.cfg = cfg
        self.p = cfg.preset
        self.node_id = node_id
        self.network = network
        self._lock = threading.Lock()
        #: latest slot seen via on_slot (for persistent-subnet epochs)
        self._current_slot = 0
        #: subnet -> latest slot it is needed through (short-lived subs)
        self._att_until_slot: "dict[int, int]" = {}
        #: subnet -> latest epoch it is needed through (sync committee)
        self._sync_until_epoch: "dict[int, int]" = {}
        #: (validator_index, slot) -> subnet, for aggregator lookups
        self._aggregator_duties: "dict[tuple[int, int], int]" = {}

    # ------------------------------------------------------ subscriptions

    def subscribe_attestation(
        self,
        validator_index: int,
        committee_index: int,
        committees_at_slot: int,
        slot: int,
        is_aggregator: bool = False,
    ) -> int:
        """Beacon API beacon_committee_subscriptions handler + the
        validator service's own attester duties. Returns the subnet."""
        subnet = compute_subnet_id(
            committee_index,
            slot,
            committees_at_slot,
            self.p,
            self.cfg.attestation_subnet_count,
        )
        until = slot + SUBSCRIPTION_SLACK_SLOTS
        with self._lock:
            if until > self._att_until_slot.get(subnet, -1):
                self._att_until_slot[subnet] = until
            if is_aggregator:
                self._aggregator_duties[(validator_index, slot)] = subnet
        self._push_to_network()
        return subnet

    def subscribe_sync_committee(
        self,
        validator_index: int,
        sync_committee_indices,
        until_epoch: int,
    ) -> "set[int]":
        """sync_committee_subscriptions handler: positions are committee
        indices of the validator; subnets derive from positions."""
        subnets = sync_subnets_for_positions(
            sync_committee_indices, self.p
        )
        with self._lock:
            for subnet in subnets:
                if until_epoch > self._sync_until_epoch.get(subnet, -1):
                    self._sync_until_epoch[subnet] = until_epoch
        self._push_to_network()
        return subnets

    # ------------------------------------------------------------- ticks

    def on_slot(self, slot: int) -> None:
        """Expire finished short-lived subscriptions (the state-machine
        tick of attestation_subnets.rs)."""
        epoch = slot // self.p.SLOTS_PER_EPOCH
        with self._lock:
            self._current_slot = max(self._current_slot, slot)
            self._att_until_slot = {
                s: u for s, u in self._att_until_slot.items() if u >= slot
            }
            self._sync_until_epoch = {
                s: u for s, u in self._sync_until_epoch.items() if u >= epoch
            }
            self._aggregator_duties = {
                k: v
                for k, v in self._aggregator_duties.items()
                if k[1] + SUBSCRIPTION_SLACK_SLOTS >= slot
            }
        self._push_to_network(slot)

    # ------------------------------------------------------------- views

    def active_attestation_subnets(self, slot: int) -> "set[int]":
        """Short-lived + persistent subnets for `slot`."""
        epoch = slot // self.p.SLOTS_PER_EPOCH
        with self._lock:
            short = {
                s for s, u in self._att_until_slot.items() if u >= slot
            }
        return short | set(
            compute_subscribed_subnets(
                self.node_id, epoch, self.cfg.attestation_subnet_count
            )
        )

    def active_sync_subnets(self, epoch: int) -> "set[int]":
        with self._lock:
            return {
                s for s, u in self._sync_until_epoch.items() if u >= epoch
            }

    def aggregator_subnet(
        self, validator_index: int, slot: int
    ) -> "Optional[int]":
        with self._lock:
            return self._aggregator_duties.get((validator_index, slot))

    # ---------------------------------------------------------- network

    def _push_to_network(self, slot: "Optional[int]" = None) -> None:
        """Push the union of ALL live short-lived subscriptions plus the
        persistent subnets — a subscription for a FUTURE duty must never
        gate out a subnet still needed for an imminent one, so the set is
        not evaluated at any single subscription's expiry slot."""
        if self.network is None:
            return
        with self._lock:
            cur = self._current_slot if slot is None else slot
            live = set(self._att_until_slot)
        epoch = cur // self.p.SLOTS_PER_EPOCH
        self.network.set_attestation_subnets(
            live
            | set(
                compute_subscribed_subnets(
                    self.node_id, epoch, self.cfg.attestation_subnet_count
                )
            )
        )


__all__ = [
    "SUBNETS_PER_NODE",
    "EPOCHS_PER_SUBNET_SUBSCRIPTION",
    "SYNC_COMMITTEE_SUBNET_COUNT",
    "compute_subnet_id",
    "compute_subscribed_subnets",
    "sync_subnets_for_positions",
    "SubnetService",
]
