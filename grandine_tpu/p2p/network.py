"""Gossip transport + network service — reference: p2p/src/network.rs
(`Network::run` select loop :204, gossip dispatch :1411-1445, publishes
:539-560) over the eth2_libp2p behaviours.

`Transport` is the seam a libp2p backend implements; `InMemoryHub` is the
in-process mesh used by tests and the devnet. Payloads on the wire are
ssz_snappy (the real encoding), topics carry the fork digest.
"""

from __future__ import annotations

import inspect
import threading
from collections import defaultdict
from typing import Callable, Optional

from grandine_tpu.consensus import misc
from grandine_tpu.spec_tests.snappy import frame_compress, frame_decompress


class GossipTopics:
    """Topic name construction (consensus networking spec)."""

    @staticmethod
    def fork_digest(cfg, state) -> bytes:
        return misc.compute_fork_digest(
            bytes(state.fork.current_version),
            bytes(state.genesis_validators_root),
        )

    @staticmethod
    def beacon_block(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/beacon_block/ssz_snappy"

    @staticmethod
    def beacon_attestation(digest: bytes, subnet: int) -> str:
        return f"/eth2/{digest.hex()}/beacon_attestation_{subnet}/ssz_snappy"

    @staticmethod
    def aggregate_and_proof(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/beacon_aggregate_and_proof/ssz_snappy"

    @staticmethod
    def voluntary_exit(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/voluntary_exit/ssz_snappy"

    @staticmethod
    def blob_sidecar(digest: bytes, subnet: int) -> str:
        return f"/eth2/{digest.hex()}/blob_sidecar_{subnet}/ssz_snappy"

    @staticmethod
    def sync_committee(digest: bytes, subnet: int) -> str:
        return f"/eth2/{digest.hex()}/sync_committee_{subnet}/ssz_snappy"

    @staticmethod
    def sync_committee_contribution(digest: bytes) -> str:
        return (
            f"/eth2/{digest.hex()}"
            "/sync_committee_contribution_and_proof/ssz_snappy"
        )

    @staticmethod
    def proposer_slashing(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/proposer_slashing/ssz_snappy"

    @staticmethod
    def attester_slashing(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/attester_slashing/ssz_snappy"

    @staticmethod
    def bls_to_execution_change(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/bls_to_execution_change/ssz_snappy"


class Transport:
    """What a WAN backend provides: pubsub + the req/resp protocols
    (Status, BlocksByRange/Root, BlobsByRange/Root — p2p/src/network.rs
    :13-24,911-912)."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str, handler: "Callable[[str, bytes], None]") -> None:
        """Handlers taking a third positional argument additionally
        receive the sending peer's id (failure-attribution feed for the
        flight recorder); two-argument handlers keep working unchanged."""
        raise NotImplementedError

    def peers(self) -> "list[str]":
        raise NotImplementedError

    def request_blocks_by_range(
        self, peer: str, start_slot: int, count: int
    ) -> "list[bytes]":
        raise NotImplementedError

    def request_blocks_by_root(
        self, peer: str, roots: "list[bytes]"
    ) -> "list[bytes]":
        raise NotImplementedError

    def request_blobs_by_range(
        self, peer: str, start_slot: int, count: int
    ) -> "list[bytes]":
        raise NotImplementedError

    def request_blobs_by_root(
        self, peer: str, ids: "list[tuple[bytes, int]]"
    ) -> "list[bytes]":
        raise NotImplementedError

    def request_status(self, peer: str) -> dict:
        raise NotImplementedError

    def register_provider(
        self, blocks_by_range, status,
        blocks_by_root=None, blobs_by_range=None, blobs_by_root=None,
    ) -> None:
        """Install the local node's req/resp serving callbacks."""
        raise NotImplementedError


def _handler_accepts_sender(handler) -> bool:
    """Arity probe done ONCE at subscribe time: a handler whose bound
    signature takes a third positional parameter (topic, payload, sender)
    gets the sending peer id on every publish; legacy two-argument
    handlers never see it. Unintrospectable callables (C builtins, some
    mocks) fall back to the legacy shape."""
    try:
        params = list(inspect.signature(handler).parameters.values())
    except (TypeError, ValueError):
        return False
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    positional = [
        p for p in params
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
    ]
    return len(positional) >= 3


class InMemoryHub:
    """Process-local gossip mesh + req/resp: every joined transport sees
    every publish (except its own); range/status requests are served by
    peer-registered providers."""

    def __init__(self) -> None:
        self._subs: "dict[str, list[tuple[str, Callable]]]" = defaultdict(list)
        self._providers: "dict[str, dict]" = {}
        self._lock = threading.Lock()

    def join(self, peer_id: str) -> "Transport":
        return _HubTransport(self, peer_id)

    def register_provider(
        self, peer_id: str,
        blocks_by_range: "Callable[[int, int], list[bytes]]",
        status: "Callable[[], dict]",
        blocks_by_root=None, blobs_by_range=None, blobs_by_root=None,
    ) -> None:
        with self._lock:
            self._providers[peer_id] = {
                "blocks_by_range": blocks_by_range,
                "status": status,
                "blocks_by_root": blocks_by_root,
                "blobs_by_range": blobs_by_range,
                "blobs_by_root": blobs_by_root,
            }

    # -- hub internals ------------------------------------------------------

    def _publish(self, sender: str, topic: str, payload: bytes) -> None:
        with self._lock:
            handlers = list(self._subs.get(topic, ()))
        for peer_id, handler, wants_sender in handlers:
            if peer_id != sender:
                if wants_sender:
                    handler(topic, payload, sender)
                else:
                    handler(topic, payload)

    def _subscribe(self, peer_id: str, topic: str, handler) -> None:
        wants_sender = _handler_accepts_sender(handler)
        with self._lock:
            self._subs[topic].append((peer_id, handler, wants_sender))

    def _peers(self, excluding: str) -> "list[str]":
        with self._lock:
            return [p for p in self._providers if p != excluding]

    def _request(self, peer: str, what: str, *args):
        with self._lock:
            provider = self._providers.get(peer)
        if provider is None:
            raise ConnectionError(f"unknown peer {peer}")
        fn = provider.get(what)
        if fn is None:
            raise ConnectionError(f"peer {peer} does not serve {what}")
        return fn(*args)


class _HubTransport(Transport):
    def __init__(self, hub: InMemoryHub, peer_id: str) -> None:
        self.hub = hub
        self.peer_id = peer_id

    def publish(self, topic, payload):
        self.hub._publish(self.peer_id, topic, payload)

    def subscribe(self, topic, handler):
        self.hub._subscribe(self.peer_id, topic, handler)

    def peers(self):
        return self.hub._peers(self.peer_id)

    def request_blocks_by_range(self, peer, start_slot, count):
        return self.hub._request(peer, "blocks_by_range", start_slot, count)

    def request_blocks_by_root(self, peer, roots):
        return self.hub._request(peer, "blocks_by_root", roots)

    def request_blobs_by_range(self, peer, start_slot, count):
        return self.hub._request(peer, "blobs_by_range", start_slot, count)

    def request_blobs_by_root(self, peer, ids):
        return self.hub._request(peer, "blobs_by_root", ids)

    def request_status(self, peer):
        return self.hub._request(peer, "status")

    def register_provider(self, blocks_by_range, status, **extra):
        self.hub.register_provider(
            self.peer_id, blocks_by_range, status, **extra
        )


class Network:
    """The service loop glue (network.rs): gossip in → controller /
    attestation firehose; own objects → gossip out; serves BlocksByRange
    and Status to peers from the store + storage."""

    def __init__(
        self,
        transport: Transport,
        controller,
        cfg,
        attestation_verifier=None,
        storage=None,
        sync_pool=None,
        operation_pool=None,
        metrics=None,
        verify_scheduler=None,
        admission=None,
    ) -> None:
        self.transport = transport
        self.controller = controller
        self.cfg = cfg
        self.attestation_verifier = attestation_verifier
        self.storage = storage
        self.sync_pool = sync_pool
        self.operation_pool = operation_pool
        #: central verify scheduler (runtime/verify_scheduler.py): when
        #: wired, gossip handlers submit signature checks to its lanes
        #: and apply effects from the ticket callback; when None the
        #: handlers verify eagerly inline (the historical synchronous
        #: path — tests and minimal deployments)
        self.verify_scheduler = verify_scheduler
        #: per-origin fair-share admission control
        #: (runtime/isolation.AdmissionController): when wired, gossip
        #: verify submissions from an over-quota origin are shed at the
        #: door — a gossipsub "ignore", never a "reject" — before they
        #: can queue against honest traffic; None admits everything
        self.admission = admission
        #: shared Metrics struct (labeled per-topic gossip counters +
        #: per-protocol req/resp counters); defaults to the controller's
        self.metrics = (
            metrics if metrics is not None
            else getattr(controller, "metrics", None)
        )
        snap = controller.snapshot()
        self.digest = GossipTopics.fork_digest(cfg, snap.head_state)
        self.stats = defaultdict(int)
        #: None = all subnets (no SubnetService wired, the historical
        #: behavior); otherwise the active set maintained by SubnetService
        #: (attestation_subnets.rs) — gossip on other subnets is dropped
        self.active_attestation_subnets: "Optional[set[int]]" = None
        #: pubkey → committee positions for the CURRENT sync-committee
        #: period, built once per period instead of re-scanning the
        #: 512-entry committee per gossip message; invalidated on the
        #: period key AND the validator-set-change hook
        self._sync_positions: "Optional[tuple[int, dict]]" = None
        hooks = getattr(controller, "on_validator_set_change", None)
        if hooks is not None:
            hooks.append(lambda old, new: self._invalidate_sync_positions())

        transport.subscribe(
            GossipTopics.beacon_block(self.digest), self._on_gossip_block
        )
        # the GLOBAL aggregate topic is never subnet-gated — it is the
        # always-on fork-choice vote feed that makes per-subnet gating
        # safe (network.rs subscribes beacon_aggregate_and_proof
        # unconditionally)
        transport.subscribe(
            GossipTopics.aggregate_and_proof(self.digest),
            self._on_gossip_aggregate,
        )
        p = cfg.preset
        for subnet in range(min(cfg.attestation_subnet_count, 64)):
            transport.subscribe(
                GossipTopics.beacon_attestation(self.digest, subnet),
                self._on_gossip_attestation,
            )
        # deneb blob-sidecar subnets (p2p/src/network.rs:104,221-222)
        for subnet in range(cfg.blob_sidecar_subnet_count):
            transport.subscribe(
                GossipTopics.blob_sidecar(self.digest, subnet),
                self._on_gossip_blob_sidecar,
            )
        # sync-committee message/contribution + operation topics
        # (p2p/src/network.rs:42-50,233,273)
        for subnet in range(cfg.sync_committee_subnet_count):
            transport.subscribe(
                GossipTopics.sync_committee(self.digest, subnet),
                self._on_gossip_sync_committee_message,
            )
        transport.subscribe(
            GossipTopics.sync_committee_contribution(self.digest),
            self._on_gossip_sync_contribution,
        )
        transport.subscribe(
            GossipTopics.proposer_slashing(self.digest),
            self._on_gossip_proposer_slashing,
        )
        transport.subscribe(
            GossipTopics.attester_slashing(self.digest),
            self._on_gossip_attester_slashing,
        )
        transport.subscribe(
            GossipTopics.bls_to_execution_change(self.digest),
            self._on_gossip_bls_change,
        )
        transport.subscribe(
            GossipTopics.voluntary_exit(self.digest),
            self._on_gossip_voluntary_exit,
        )
        try:
            transport.register_provider(
                self._serve_blocks_by_range, self._serve_status,
                blocks_by_root=self._serve_blocks_by_root,
                blobs_by_range=self._serve_blobs_by_range,
                blobs_by_root=self._serve_blobs_by_root,
            )
        except NotImplementedError:
            pass

    # ------------------------------------------------------------ inbound

    @staticmethod
    def _topic_kind(topic: str) -> str:
        """`/eth2/<digest>/beacon_attestation_5/ssz_snappy` →
        `beacon_attestation` — the subnet number is stripped so label
        cardinality stays at the topic-kind count, not 64× it."""
        parts = topic.split("/")
        name = parts[3] if len(parts) > 3 else topic
        base, _, suffix = name.rpartition("_")
        return base if suffix.isdigit() and base else name

    def _count_gossip(self, topic: str, result: str) -> None:
        """Per-topic accept/ignore/reject accounting (the gossipsub
        MessageAcceptance triple): accept = handed to a service, ignore =
        dropped without prejudice (off-subnet / no service wired), reject
        = invalid (decode or validation failure)."""
        if self.metrics is not None:
            self.metrics.gossip_messages.labels(
                self._topic_kind(topic), result
            ).inc()

    def _count_rpc(self, protocol: str) -> None:
        if self.metrics is not None:
            self.metrics.rpc_requests.labels(protocol).inc()

    # --------------------------------------------- signature dispatching

    def _eager_verify_items(self, items) -> bool:
        """WHITELISTED eager fallback (tools/check_no_inline_gossip_verify
        audits that gossip handlers hold no other verification calls):
        SingleVerifier-equivalent per-item host checks, used when no
        verify scheduler is wired so handler semantics stay synchronous."""
        from grandine_tpu.runtime.verify_scheduler import host_check_item

        return all(host_check_item(it) for it in items)

    @staticmethod
    def _origin_of(sender: "Optional[str]") -> "Optional[str]":
        """Gossip sender → failure-attribution origin string. The
        `peer:` prefix namespaces the id so the flight recorder's top-K
        table can mix peer origins with future validator origins; the
        string NEVER becomes a Prometheus label (unbounded cardinality —
        tools/lint metrics_cardinality enforces this)."""
        return f"peer:{sender}" if sender else None

    def _dispatch_verify(
        self, lane: str, items, topic: str, reject_key: str, on_accept,
        origin: "Optional[str]" = None,
    ) -> None:
        """Route one handler's deferred signature checks: submit to the
        scheduler lane (effects run from the ticket callback) or fall
        back to the eager inline path. A job shed under overload counts
        as gossipsub "ignore" — dropped without prejudice — never as a
        validation reject."""

        def deliver(ok: bool, dropped: bool = False) -> None:
            if dropped:
                self.stats["verify_shed"] += 1
                sched = self.verify_scheduler
                if sched is not None and getattr(
                    sched, "device_degraded", lambda: False
                )():
                    # sheds while the device breaker is quarantining the
                    # backend: overload-under-degradation, not plain
                    # overload — the operator's cue that host-path
                    # throughput, not gossip volume, is the bottleneck
                    self.stats["verify_shed_degraded"] += 1
                self._count_gossip(topic, "ignore")
                return
            if not ok:
                self.stats[reject_key] += 1
                self._count_gossip(topic, "reject")
                return
            self._count_gossip(topic, "accept")
            on_accept()

        if (
            self.admission is not None
            and not self.admission.admit(origin, len(items), lane=lane)
        ):
            # over fair share: shed at the door, before the job can
            # queue against honest traffic (the controller counts
            # verify_admission_rejected_total by lane)
            self.stats["verify_admission_rejected"] += 1
            deliver(False, dropped=True)
            return
        sched = self.verify_scheduler
        if sched is not None:
            sched.submit(
                lane, items,
                callback=lambda t: deliver(t.ok, t.dropped),
                origin=origin,
            )
            return
        deliver(self._eager_verify_items(items))

    def _invalidate_sync_positions(self) -> None:
        self._sync_positions = None

    def _sync_committee_for_slot(self, state, slot: int):
        """The sync committee that signs at `slot`: the head state
        carries the CURRENT committee and (near a rotation boundary)
        the NEXT one — a message timestamped one period ahead of the
        state must resolve against next_sync_committee, not current.
        Returns (committee, period) — committee is None when the slot's
        period is outside the two the state knows."""
        from grandine_tpu.consensus import misc

        p = self.cfg.preset
        state_period = misc.sync_committee_period(int(state.slot), p)
        period = misc.sync_committee_period(int(slot), p)
        if period == state_period:
            return state.current_sync_committee, period
        if period == state_period + 1:
            return state.next_sync_committee, period
        return None, period

    def _sync_committee_positions(self, state, slot: int, pubkey: bytes):
        """Committee position(s) of `pubkey` in the sync committee of
        `slot`'s PERIOD (current vs next, resolved against the head
        state) — one table build per period (the period key catches
        rotation; the validator-set-change hook catches deposits/
        finalization) instead of an O(committee) scan per message."""
        committee, period = self._sync_committee_for_slot(state, slot)
        if committee is None:
            return ()
        cache = self._sync_positions
        if cache is None:
            cache = {}
            self._sync_positions = cache
        table = cache.get(period)
        if table is None:
            table = {}
            for pos, pk_bytes in enumerate(committee.pubkeys):
                key = bytes(pk_bytes)
                table[key] = table.get(key, ()) + (pos,)
            cache[period] = table
            # only the state's own and the next period are resolvable —
            # drop rotated-out tables instead of accreting one per period
            for stale in [k for k in cache if k not in (period, period + 1,
                                                        period - 1)]:
                del cache[stale]
        return table.get(bytes(pubkey), ())

    def _on_gossip_block(self, topic: str, payload: bytes) -> None:
        from grandine_tpu.types.combined import decode_signed_block

        self.stats["blocks_in"] += 1
        try:
            block = decode_signed_block(frame_decompress(payload), self.cfg)
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        self._count_gossip(topic, "accept")
        self.controller.on_gossip_block(block)

    def set_attestation_subnets(self, subnets: "set[int]") -> None:
        """SubnetService push: which beacon_attestation_{n} topics this
        node is currently joined to (transports without unsubscribe keep
        the topic; the gate below drops off-subnet traffic)."""
        self.active_attestation_subnets = set(subnets)

    @staticmethod
    def _subnet_of_topic(topic: str) -> "Optional[int]":
        marker = "/beacon_attestation_"
        if marker not in topic:
            return None
        try:
            return int(topic.split(marker, 1)[1].split("/", 1)[0])
        except ValueError:
            return None

    def _on_gossip_attestation(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        from grandine_tpu.types.combined import decode_attestation

        subnet = self._subnet_of_topic(topic)
        if (
            self.active_attestation_subnets is not None
            and subnet is not None
            and subnet not in self.active_attestation_subnets
        ):
            self.stats["attestations_off_subnet"] += 1
            self._count_gossip(topic, "ignore")
            return
        self.stats["attestations_in"] += 1
        if self.attestation_verifier is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            slot = self.controller.snapshot().slot
            att = decode_attestation(frame_decompress(payload), self.cfg, slot)
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        self._count_gossip(topic, "accept")
        self.attestation_verifier.submit(att, origin=self._origin_of(sender))

    def _on_gossip_aggregate(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        from grandine_tpu.types.combined import decode_signed_aggregate

        self.stats["aggregates_in"] += 1
        if self.attestation_verifier is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            slot = self.controller.snapshot().slot
            signed = decode_signed_aggregate(
                frame_decompress(payload), self.cfg, slot
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        self._count_gossip(topic, "accept")
        self.attestation_verifier.submit(
            signed.message.aggregate, origin=self._origin_of(sender)
        )

    def _deneb_ns(self):
        from grandine_tpu.types.containers import spec_types

        return spec_types(self.cfg.preset).deneb

    def _on_gossip_blob_sidecar(self, topic: str, payload: bytes) -> None:
        self.stats["blob_sidecars_in"] += 1
        try:
            sidecar = self._deneb_ns().BlobSidecar.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        self._count_gossip(topic, "accept")
        self.controller.on_gossip_blob_sidecar(sidecar)

    def _on_gossip_sync_committee_message(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["sync_messages_in"] += 1
        if self.sync_pool is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            msg = self._deneb_ns().SyncCommitteeMessage.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        # validator_index → committee position(s) via the head state's
        # current sync committee (a validator can hold several positions)
        state = self.controller.snapshot().head_state
        vidx = int(msg.validator_index)
        if vidx >= len(state.validators):
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        pubkey = bytes(state.validators[vidx].pubkey)
        # gossip validation: the signature must verify against the
        # claimed validator's key for the message's slot/root — a forged
        # signature inserted into the pool would poison the produced
        # sync aggregate and invalidate this node's own proposals
        # (p2p gossip rules; sync_committee_agg_pool tasks.rs)
        from grandine_tpu.consensus import accessors, misc, signing
        from grandine_tpu.runtime.verify_scheduler import VerifyItem

        try:
            root = signing.sync_committee_message_signing_root(
                state, bytes(msg.beacon_block_root),
                misc.compute_epoch_at_slot(int(msg.slot), self.cfg.preset),
                self.cfg,
            )
            cols = accessors.registry_columns(state)
        except Exception:
            self.stats["sync_messages_rejected"] += 1
            self._count_gossip(topic, "reject")
            return
        slot = int(msg.slot)
        positions = self._sync_committee_positions(state, slot, pubkey)
        block_root = bytes(msg.beacon_block_root)
        signature = bytes(msg.signature)

        def insert() -> None:
            self.sync_pool.insert_message_at_positions(
                slot, block_root, positions, signature
            )

        # the index+columns form lets the scheduler's device path gather
        # the pubkey from the registry instead of uploading it
        self._dispatch_verify(
            "sync_message",
            [VerifyItem(root, signature, member_indices=(vidx,),
                        pubkey_columns=cols.pubkeys)],
            topic, "sync_messages_rejected", insert,
            origin=self._origin_of(sender),
        )

    def _on_gossip_sync_contribution(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["sync_contributions_in"] += 1
        if self.sync_pool is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            signed = self._deneb_ns().SignedContributionAndProof.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        contribution = signed.message.contribution
        # full gossip validation before the pool: the aggregator's
        # selection proof (proves the right to aggregate this slot/
        # subcommittee), the outer SignedContributionAndProof signature,
        # and the contribution's aggregate signature against the set
        # subcommittee members — any one forged could poison the pool's
        # aggregates or let a non-aggregator flood the topic
        from grandine_tpu.consensus import accessors, misc, signing
        from grandine_tpu.crypto import bls as A
        from grandine_tpu.runtime.verify_scheduler import VerifyItem

        state = self.controller.snapshot().head_state
        p = self.cfg.preset
        try:
            sub = int(contribution.subcommittee_index)
            sub_size = p.SYNC_COMMITTEE_SIZE // self.cfg.sync_committee_subnet_count
            committee, _period = self._sync_committee_for_slot(
                state, int(contribution.slot)
            )
            if committee is None:
                raise ValueError("slot outside known sync periods")
            members = committee.pubkeys[
                sub * sub_size : (sub + 1) * sub_size
            ]
            bits = list(contribution.aggregation_bits)
            pks = [
                A.PublicKey.from_bytes(bytes(pk))
                for bit, pk in zip(bits, members)
                if bit
            ]
            if not pks:
                raise ValueError("empty contribution")
            agg_idx = int(signed.message.aggregator_index)
            if agg_idx >= len(state.validators):
                raise ValueError("aggregator index out of range")
            agg_pubkey = bytes(state.validators[agg_idx].pubkey)
            if not any(bytes(pk) == agg_pubkey for pk in members):
                raise ValueError("aggregator not in declared subcommittee")
            selection_proof = bytes(signed.message.selection_proof)
            if not misc.is_sync_committee_aggregator(
                selection_proof, p, self.cfg.sync_committee_subnet_count
            ):
                raise ValueError("selection proof does not elect aggregator")
            ns = self._deneb_ns()
            selection_root = signing.sync_selection_proof_signing_root(
                state,
                ns.SyncAggregatorSelectionData(
                    slot=contribution.slot, subcommittee_index=sub
                ),
                self.cfg,
            )
            outer_root = signing.contribution_and_proof_signing_root(
                state, signed.message, self.cfg
            )
            root = signing.sync_committee_message_signing_root(
                state, bytes(contribution.beacon_block_root),
                misc.compute_epoch_at_slot(int(contribution.slot), p),
                self.cfg,
            )
            cols = accessors.registry_columns(state)
        except Exception:
            self.stats["sync_contributions_rejected"] += 1
            self._count_gossip(topic, "reject")
            return
        # one ticket, three signatures: selection proof + outer proof
        # ride the registry's indexed path (aggregator index known);
        # the contribution aggregate carries its member keys
        self._dispatch_verify(
            "sync_contribution",
            [
                VerifyItem(selection_root, selection_proof,
                           member_indices=(agg_idx,),
                           pubkey_columns=cols.pubkeys),
                VerifyItem(outer_root, bytes(signed.signature),
                           member_indices=(agg_idx,),
                           pubkey_columns=cols.pubkeys),
                VerifyItem(root, bytes(contribution.signature),
                           public_keys=pks),
            ],
            topic, "sync_contributions_rejected",
            lambda: self.sync_pool.insert_contribution(contribution),
            origin=self._origin_of(sender),
        )

    def _on_gossip_proposer_slashing(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["proposer_slashings_in"] += 1
        if self.operation_pool is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            slashing = self._deneb_ns().ProposerSlashing.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        # full validation BEFORE insert, mirroring the attester-slashing
        # handler: process_proposer_slashing preconditions + BOTH header
        # signatures. Without this any peer could stuff the pool with
        # junk that invalidates our own block proposals at pack time.
        from grandine_tpu.consensus import (
            accessors, misc, predicates, signing,
        )
        from grandine_tpu.runtime.verify_scheduler import VerifyItem

        h1 = slashing.signed_header_1.message
        h2 = slashing.signed_header_2.message
        state = self.controller.snapshot().head_state
        try:
            if int(h1.slot) != int(h2.slot):
                raise ValueError("headers are for different slots")
            if int(h1.proposer_index) != int(h2.proposer_index):
                raise ValueError("headers are for different proposers")
            if h1.hash_tree_root() == h2.hash_tree_root():
                raise ValueError("headers are identical")
            idx = int(h1.proposer_index)
            if idx >= len(state.validators):
                raise ValueError("proposer index out of range")
            epoch = misc.compute_epoch_at_slot(
                int(state.slot), self.cfg.preset
            )
            if not predicates.is_slashable_validator(
                state.validators[idx], epoch
            ):
                raise ValueError("proposer is not slashable")
            cols = accessors.registry_columns(state)
            items = [
                VerifyItem(
                    signing.header_signing_root(
                        state, signed.message, self.cfg
                    ),
                    bytes(signed.signature),
                    member_indices=(idx,),
                    pubkey_columns=cols.pubkeys,
                )
                for signed in (slashing.signed_header_1,
                               slashing.signed_header_2)
            ]
        except Exception:
            self.stats["proposer_slashings_rejected"] += 1
            self._count_gossip(topic, "reject")
            return
        self._dispatch_verify(
            "slashing", items, topic, "proposer_slashings_rejected",
            lambda: self.operation_pool.insert_proposer_slashing(slashing),
            origin=self._origin_of(sender),
        )

    def _on_gossip_attester_slashing(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["attester_slashings_in"] += 1
        try:
            slashing = self._deneb_ns().AttesterSlashing.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        # full validation BEFORE any effect: slashable data + BOTH indexed
        # attestation signatures. An unvalidated slashing would let any
        # peer zero arbitrary validators' fork-choice weight and poison
        # this node's own block proposals (spec p2p gossip validation;
        # process_attester_slashing preconditions). The structural checks
        # stay inline; the signatures are COLLECTED (MultiVerifier defers
        # them as triples) and routed through the slashing lane.
        from grandine_tpu.consensus import predicates
        from grandine_tpu.consensus.verifier import MultiVerifier
        from grandine_tpu.runtime.verify_scheduler import VerifyItem

        att1, att2 = slashing.attestation_1, slashing.attestation_2
        state = self.controller.snapshot().head_state
        try:
            if not predicates.is_slashable_attestation_data(
                att1.data, att2.data
            ):
                raise ValueError("attestations are not slashable")
            collector = MultiVerifier()
            for indexed in (att1, att2):
                predicates.validate_indexed_attestation(
                    indexed, state, collector, self.cfg
                )
            items = [
                VerifyItem(t.message, t.signature,
                           public_keys=(t.public_key,))
                for t in collector.triples
            ]
        except Exception:
            self.stats["attester_slashings_rejected"] += 1
            self._count_gossip(topic, "reject")
            return

        def apply() -> None:
            if self.operation_pool is not None:
                self.operation_pool.insert_attester_slashing(slashing)
            # fork choice marks the intersection equivocating
            a = set(int(i) for i in att1.attesting_indices)
            b = set(int(i) for i in att2.attesting_indices)
            both = sorted(a & b)
            if both:
                self.controller.on_attester_slashing(both)

        self._dispatch_verify(
            "slashing", items, topic, "attester_slashings_rejected", apply,
            origin=self._origin_of(sender),
        )

    def _on_gossip_bls_change(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["bls_changes_in"] += 1
        if self.operation_pool is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            signed = self._deneb_ns().SignedBLSToExecutionChange.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        # verify the change signature (under the genesis-fork-version
        # domain, against the claimed from_bls_pubkey) before it can
        # reach the pool. The withdrawal-credential hash binding stays in
        # OperationPool.pack, where the packing state is authoritative.
        from grandine_tpu.consensus import signing
        from grandine_tpu.consensus.verifier import MultiVerifier
        from grandine_tpu.runtime.verify_scheduler import VerifyItem

        state = self.controller.snapshot().head_state
        try:
            if int(signed.message.validator_index) >= len(state.validators):
                raise ValueError("validator index out of range")
            collector = MultiVerifier()
            signing.extend_with_bls_to_execution_change(
                collector, state, signed, self.cfg
            )
            items = [
                VerifyItem(t.message, t.signature,
                           public_keys=(t.public_key,))
                for t in collector.triples
            ]
        except Exception:
            self.stats["bls_changes_rejected"] += 1
            self._count_gossip(topic, "reject")
            return
        self._dispatch_verify(
            "bls_change", items, topic, "bls_changes_rejected",
            lambda: self.operation_pool.insert_bls_to_execution_change(
                signed
            ),
            origin=self._origin_of(sender),
        )

    def _on_gossip_voluntary_exit(
        self, topic: str, payload: bytes, sender: "Optional[str]" = None
    ) -> None:
        self.stats["voluntary_exits_in"] += 1
        if self.operation_pool is None:
            self._count_gossip(topic, "ignore")
            return
        try:
            signed = self._deneb_ns().SignedVoluntaryExit.deserialize(
                frame_decompress(payload)
            )
        except Exception:
            self.stats["decode_failures"] += 1
            self._count_gossip(topic, "reject")
            return
        # verify the exit signature (EIP-7044-aware domain) against the
        # exiting validator's key before the pool can pack it
        from grandine_tpu.consensus import signing
        from grandine_tpu.consensus.verifier import MultiVerifier
        from grandine_tpu.runtime.verify_scheduler import VerifyItem
        from grandine_tpu.types.combined import state_phase_of

        state = self.controller.snapshot().head_state
        try:
            if int(signed.message.validator_index) >= len(state.validators):
                raise ValueError("validator index out of range")
            collector = MultiVerifier()
            signing.extend_with_voluntary_exit(
                collector, state, signed, self.cfg,
                state_phase_of(state, self.cfg),
            )
            items = [
                VerifyItem(t.message, t.signature,
                           public_keys=(t.public_key,))
                for t in collector.triples
            ]
        except Exception:
            self.stats["voluntary_exits_rejected"] += 1
            self._count_gossip(topic, "reject")
            return
        self._dispatch_verify(
            "exit", items, topic, "voluntary_exits_rejected",
            lambda: self.operation_pool.insert_voluntary_exit(signed),
            origin=self._origin_of(sender),
        )

    # ----------------------------------------------------------- outbound

    def publish_aggregate(self, signed_aggregate_and_proof) -> None:
        self.stats["aggregates_out"] += 1
        self.transport.publish(
            GossipTopics.aggregate_and_proof(self.digest),
            frame_compress(signed_aggregate_and_proof.serialize()),
        )

    def publish_block(self, signed_block) -> None:
        self.stats["blocks_out"] += 1
        self.transport.publish(
            GossipTopics.beacon_block(self.digest),
            frame_compress(signed_block.serialize()),
        )

    def publish_attestation(self, attestation, subnet: int = 0) -> None:
        self.stats["attestations_out"] += 1
        self.transport.publish(
            GossipTopics.beacon_attestation(self.digest, subnet),
            frame_compress(attestation.serialize()),
        )

    def publish_blob_sidecar(self, sidecar) -> None:
        """Subnet = index % BLOB_SIDECAR_SUBNET_COUNT (spec
        compute_subnet_for_blob_sidecar)."""
        self.stats["blob_sidecars_out"] += 1
        subnet = int(sidecar.index) % self.cfg.blob_sidecar_subnet_count
        self.transport.publish(
            GossipTopics.blob_sidecar(self.digest, subnet),
            frame_compress(sidecar.serialize()),
        )

    def publish_sync_committee_message(self, msg, subnet: int = 0) -> None:
        self.stats["sync_messages_out"] += 1
        self.transport.publish(
            GossipTopics.sync_committee(self.digest, subnet),
            frame_compress(msg.serialize()),
        )

    def publish_sync_contribution(self, signed_contribution) -> None:
        self.stats["sync_contributions_out"] += 1
        self.transport.publish(
            GossipTopics.sync_committee_contribution(self.digest),
            frame_compress(signed_contribution.serialize()),
        )

    def publish_proposer_slashing(self, slashing) -> None:
        self.stats["proposer_slashings_out"] += 1
        self.transport.publish(
            GossipTopics.proposer_slashing(self.digest),
            frame_compress(slashing.serialize()),
        )

    def publish_attester_slashing(self, slashing) -> None:
        self.stats["attester_slashings_out"] += 1
        self.transport.publish(
            GossipTopics.attester_slashing(self.digest),
            frame_compress(slashing.serialize()),
        )

    def publish_bls_change(self, signed_change) -> None:
        self.stats["bls_changes_out"] += 1
        self.transport.publish(
            GossipTopics.bls_to_execution_change(self.digest),
            frame_compress(signed_change.serialize()),
        )

    def publish_voluntary_exit(self, signed_exit) -> None:
        self.stats["voluntary_exits_out"] += 1
        self.transport.publish(
            GossipTopics.voluntary_exit(self.digest),
            frame_compress(signed_exit.serialize()),
        )

    # ------------------------------------------------------------ serving

    def _serve_blocks_by_range(self, start_slot: int, count: int) -> "list[bytes]":
        self._count_rpc("beacon_blocks_by_range")
        out = []
        store = self.controller.store
        by_slot = {}
        for node in store.blocks.values():
            if hasattr(node.signed_block, "serialize"):
                by_slot[node.slot] = node.signed_block
        for slot in range(start_slot, start_slot + count):
            block = by_slot.get(slot)
            if block is None and self.storage is not None:
                root = self.storage.finalized_root_by_slot(slot)
                if root is not None:
                    block = self.storage.finalized_block_by_root(root)
            if block is not None:
                out.append(block.serialize())
        return out

    def _serve_blocks_by_root(self, roots: "list[bytes]") -> "list[bytes]":
        """BeaconBlocksByRoot (p2p/src/network.rs:911-912): resolve a
        delayed block's unknown parent without waiting for range sync."""
        self._count_rpc("beacon_blocks_by_root")
        out = []
        store = self.controller.store
        for root in roots:
            root = bytes(root)
            node = store.blocks.get(root)
            block = node.signed_block if node is not None else None
            if (
                block is None or not hasattr(block, "serialize")
            ) and self.storage is not None:
                block = self.storage.finalized_block_by_root(root)
            if block is not None and hasattr(block, "serialize"):
                out.append(block.serialize())
        return out

    def _serve_blobs_by_range(self, start_slot: int, count: int) -> "list[bytes]":
        self._count_rpc("blob_sidecars_by_range")
        out = []
        store = self.controller.store
        for node in sorted(store.blocks.values(), key=lambda n: n.slot):
            if start_slot <= node.slot < start_slot + count:
                for sc in self.controller.blob_sidecars_for(node.root):
                    out.append(sc.serialize())
        return out

    def _serve_blobs_by_root(self, ids: "list") -> "list[bytes]":
        """ids: [(block_root, index), ...] (spec BlobIdentifier)."""
        self._count_rpc("blob_sidecars_by_root")
        out = []
        for root, index in ids:
            for sc in self.controller.blob_sidecars_for(bytes(root)):
                if int(sc.index) == int(index):
                    out.append(sc.serialize())
        return out

    def _serve_status(self) -> dict:
        self._count_rpc("status")
        snap = self.controller.snapshot()
        return {
            "head_slot": int(snap.head_state.slot),
            "head_root": snap.head_root.hex(),
            "finalized_epoch": int(snap.finalized_checkpoint.epoch),
            "fork_digest": self.digest.hex(),
        }


__all__ = ["GossipTopics", "Transport", "InMemoryHub", "Network"]
