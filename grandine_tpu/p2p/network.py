"""Gossip transport + network service — reference: p2p/src/network.rs
(`Network::run` select loop :204, gossip dispatch :1411-1445, publishes
:539-560) over the eth2_libp2p behaviours.

`Transport` is the seam a libp2p backend implements; `InMemoryHub` is the
in-process mesh used by tests and the devnet. Payloads on the wire are
ssz_snappy (the real encoding), topics carry the fork digest.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Optional

from grandine_tpu.consensus import misc
from grandine_tpu.spec_tests.snappy import frame_compress, frame_decompress


class GossipTopics:
    """Topic name construction (consensus networking spec)."""

    @staticmethod
    def fork_digest(cfg, state) -> bytes:
        return misc.compute_fork_digest(
            bytes(state.fork.current_version),
            bytes(state.genesis_validators_root),
        )

    @staticmethod
    def beacon_block(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/beacon_block/ssz_snappy"

    @staticmethod
    def beacon_attestation(digest: bytes, subnet: int) -> str:
        return f"/eth2/{digest.hex()}/beacon_attestation_{subnet}/ssz_snappy"

    @staticmethod
    def aggregate_and_proof(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/beacon_aggregate_and_proof/ssz_snappy"

    @staticmethod
    def voluntary_exit(digest: bytes) -> str:
        return f"/eth2/{digest.hex()}/voluntary_exit/ssz_snappy"


class Transport:
    """What a WAN backend provides: pubsub + the BlocksByRange req/resp."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str, handler: "Callable[[str, bytes], None]") -> None:
        raise NotImplementedError

    def peers(self) -> "list[str]":
        raise NotImplementedError

    def request_blocks_by_range(
        self, peer: str, start_slot: int, count: int
    ) -> "list[bytes]":
        raise NotImplementedError

    def request_status(self, peer: str) -> dict:
        raise NotImplementedError

    def register_provider(self, blocks_by_range, status) -> None:
        """Install the local node's req/resp serving callbacks."""
        raise NotImplementedError


class InMemoryHub:
    """Process-local gossip mesh + req/resp: every joined transport sees
    every publish (except its own); range/status requests are served by
    peer-registered providers."""

    def __init__(self) -> None:
        self._subs: "dict[str, list[tuple[str, Callable]]]" = defaultdict(list)
        self._providers: "dict[str, dict]" = {}
        self._lock = threading.Lock()

    def join(self, peer_id: str) -> "Transport":
        return _HubTransport(self, peer_id)

    def register_provider(
        self, peer_id: str,
        blocks_by_range: "Callable[[int, int], list[bytes]]",
        status: "Callable[[], dict]",
    ) -> None:
        with self._lock:
            self._providers[peer_id] = {
                "blocks_by_range": blocks_by_range,
                "status": status,
            }

    # -- hub internals ------------------------------------------------------

    def _publish(self, sender: str, topic: str, payload: bytes) -> None:
        with self._lock:
            handlers = list(self._subs.get(topic, ()))
        for peer_id, handler in handlers:
            if peer_id != sender:
                handler(topic, payload)

    def _subscribe(self, peer_id: str, topic: str, handler) -> None:
        with self._lock:
            self._subs[topic].append((peer_id, handler))

    def _peers(self, excluding: str) -> "list[str]":
        with self._lock:
            return [p for p in self._providers if p != excluding]

    def _request(self, peer: str, what: str, *args):
        with self._lock:
            provider = self._providers.get(peer)
        if provider is None:
            raise ConnectionError(f"unknown peer {peer}")
        return provider[what](*args)


class _HubTransport(Transport):
    def __init__(self, hub: InMemoryHub, peer_id: str) -> None:
        self.hub = hub
        self.peer_id = peer_id

    def publish(self, topic, payload):
        self.hub._publish(self.peer_id, topic, payload)

    def subscribe(self, topic, handler):
        self.hub._subscribe(self.peer_id, topic, handler)

    def peers(self):
        return self.hub._peers(self.peer_id)

    def request_blocks_by_range(self, peer, start_slot, count):
        return self.hub._request(peer, "blocks_by_range", start_slot, count)

    def request_status(self, peer):
        return self.hub._request(peer, "status")

    def register_provider(self, blocks_by_range, status):
        self.hub.register_provider(self.peer_id, blocks_by_range, status)


class Network:
    """The service loop glue (network.rs): gossip in → controller /
    attestation firehose; own objects → gossip out; serves BlocksByRange
    and Status to peers from the store + storage."""

    def __init__(
        self,
        transport: Transport,
        controller,
        cfg,
        attestation_verifier=None,
        storage=None,
    ) -> None:
        self.transport = transport
        self.controller = controller
        self.cfg = cfg
        self.attestation_verifier = attestation_verifier
        self.storage = storage
        snap = controller.snapshot()
        self.digest = GossipTopics.fork_digest(cfg, snap.head_state)
        self.stats = defaultdict(int)
        #: None = all subnets (no SubnetService wired, the historical
        #: behavior); otherwise the active set maintained by SubnetService
        #: (attestation_subnets.rs) — gossip on other subnets is dropped
        self.active_attestation_subnets: "Optional[set[int]]" = None

        transport.subscribe(
            GossipTopics.beacon_block(self.digest), self._on_gossip_block
        )
        # the GLOBAL aggregate topic is never subnet-gated — it is the
        # always-on fork-choice vote feed that makes per-subnet gating
        # safe (network.rs subscribes beacon_aggregate_and_proof
        # unconditionally)
        transport.subscribe(
            GossipTopics.aggregate_and_proof(self.digest),
            self._on_gossip_aggregate,
        )
        p = cfg.preset
        for subnet in range(min(cfg.attestation_subnet_count, 64)):
            transport.subscribe(
                GossipTopics.beacon_attestation(self.digest, subnet),
                self._on_gossip_attestation,
            )
        try:
            transport.register_provider(
                self._serve_blocks_by_range, self._serve_status
            )
        except NotImplementedError:
            pass

    # ------------------------------------------------------------ inbound

    def _on_gossip_block(self, topic: str, payload: bytes) -> None:
        from grandine_tpu.types.combined import decode_signed_block

        self.stats["blocks_in"] += 1
        try:
            block = decode_signed_block(frame_decompress(payload), self.cfg)
        except Exception:
            self.stats["decode_failures"] += 1
            return
        self.controller.on_gossip_block(block)

    def set_attestation_subnets(self, subnets: "set[int]") -> None:
        """SubnetService push: which beacon_attestation_{n} topics this
        node is currently joined to (transports without unsubscribe keep
        the topic; the gate below drops off-subnet traffic)."""
        self.active_attestation_subnets = set(subnets)

    @staticmethod
    def _subnet_of_topic(topic: str) -> "Optional[int]":
        marker = "/beacon_attestation_"
        if marker not in topic:
            return None
        try:
            return int(topic.split(marker, 1)[1].split("/", 1)[0])
        except ValueError:
            return None

    def _on_gossip_attestation(self, topic: str, payload: bytes) -> None:
        from grandine_tpu.types.combined import decode_attestation

        subnet = self._subnet_of_topic(topic)
        if (
            self.active_attestation_subnets is not None
            and subnet is not None
            and subnet not in self.active_attestation_subnets
        ):
            self.stats["attestations_off_subnet"] += 1
            return
        self.stats["attestations_in"] += 1
        if self.attestation_verifier is None:
            return
        try:
            slot = self.controller.snapshot().slot
            att = decode_attestation(frame_decompress(payload), self.cfg, slot)
        except Exception:
            self.stats["decode_failures"] += 1
            return
        self.attestation_verifier.submit(att)

    def _on_gossip_aggregate(self, topic: str, payload: bytes) -> None:
        from grandine_tpu.types.combined import decode_signed_aggregate

        self.stats["aggregates_in"] += 1
        if self.attestation_verifier is None:
            return
        try:
            slot = self.controller.snapshot().slot
            signed = decode_signed_aggregate(
                frame_decompress(payload), self.cfg, slot
            )
        except Exception:
            self.stats["decode_failures"] += 1
            return
        self.attestation_verifier.submit(signed.message.aggregate)

    # ----------------------------------------------------------- outbound

    def publish_aggregate(self, signed_aggregate_and_proof) -> None:
        self.stats["aggregates_out"] += 1
        self.transport.publish(
            GossipTopics.aggregate_and_proof(self.digest),
            frame_compress(signed_aggregate_and_proof.serialize()),
        )

    def publish_block(self, signed_block) -> None:
        self.stats["blocks_out"] += 1
        self.transport.publish(
            GossipTopics.beacon_block(self.digest),
            frame_compress(signed_block.serialize()),
        )

    def publish_attestation(self, attestation, subnet: int = 0) -> None:
        self.stats["attestations_out"] += 1
        self.transport.publish(
            GossipTopics.beacon_attestation(self.digest, subnet),
            frame_compress(attestation.serialize()),
        )

    # ------------------------------------------------------------ serving

    def _serve_blocks_by_range(self, start_slot: int, count: int) -> "list[bytes]":
        out = []
        store = self.controller.store
        by_slot = {}
        for node in store.blocks.values():
            if hasattr(node.signed_block, "serialize"):
                by_slot[node.slot] = node.signed_block
        for slot in range(start_slot, start_slot + count):
            block = by_slot.get(slot)
            if block is None and self.storage is not None:
                root = self.storage.finalized_root_by_slot(slot)
                if root is not None:
                    block = self.storage.finalized_block_by_root(root)
            if block is not None:
                out.append(block.serialize())
        return out

    def _serve_status(self) -> dict:
        snap = self.controller.snapshot()
        return {
            "head_slot": int(snap.head_state.slot),
            "head_root": snap.head_root.hex(),
            "finalized_epoch": int(snap.finalized_checkpoint.epoch),
            "fork_digest": self.digest.hex(),
        }


__all__ = ["GossipTopics", "Transport", "InMemoryHub", "Network"]
