"""Block sync — reference: p2p/src/block_sync_service.rs + sync_manager.rs
(range/root request tracking), back_sync.rs (reverse fill to genesis with
batch verification), block_verification_pool.rs:76-129 (two-epoch block
batches verified against one head state).
"""

from __future__ import annotations

import logging
from typing import Optional

from grandine_tpu.consensus.verifier import MultiVerifier, NullVerifier
from grandine_tpu.types.combined import decode_signed_block

logger = logging.getLogger("grandine.sync")


class SyncManager:
    """Tracks peer statuses and picks sync targets
    (sync_manager.rs / range_and_root_requests.rs)."""

    def __init__(self, transport) -> None:
        self.transport = transport
        self.peer_status: "dict[str, dict]" = {}

    def refresh(self) -> None:
        for peer in self.transport.peers():
            try:
                self.peer_status[peer] = self.transport.request_status(peer)
            except ConnectionError:
                self.peer_status.pop(peer, None)

    def best_peer(self) -> "Optional[str]":
        if not self.peer_status:
            return None
        return max(
            self.peer_status, key=lambda p: self.peer_status[p]["head_slot"]
        )

    def target_slot(self) -> int:
        return max(
            (s["head_slot"] for s in self.peer_status.values()), default=0
        )


class BlockSyncService:
    """Forward range sync: while the head lags the best peer, request
    slot ranges and feed them through the controller's normal validation
    (block_sync_service shape; the controller's delayed-maps handle
    out-of-order arrival)."""

    def __init__(self, transport, controller, cfg,
                 batch_size: "Optional[int]" = None,
                 bulk_verify: bool = False,
                 replay_pipeline=None) -> None:
        self.transport = transport
        self.controller = controller
        self.cfg = cfg
        self.sync_manager = SyncManager(transport)
        # two epochs per round, like the reference's verification pool
        self.batch_size = batch_size or 2 * cfg.preset.SLOTS_PER_EPOCH
        #: bulk mode: verify a fetched range as ONE cross-block batch
        #: through the replay pipeline, then import trusted — any
        #: pipeline failure degrades to the per-block path, which stays
        #: the arbiter of validity
        self.bulk_verify = bulk_verify
        self._pipeline = replay_pipeline
        self.stats = {"requested": 0, "applied_batches": 0,
                      "root_requests": 0, "blob_requests": 0,
                      "bulk_blocks": 0, "bulk_fallbacks": 0}
        # resolve delayed-by-parent blocks via BlocksByRoot instead of
        # waiting for the next range round (p2p/src/network.rs:911-912)
        if hasattr(controller, "on_unknown_parent"):
            controller.on_unknown_parent.append(self._on_unknown_parent)

    def _on_unknown_parent(self, parent_root: bytes) -> None:
        """Mutator-thread hook: fetch the missing parent off-thread."""
        def task() -> None:
            self.sync_manager.refresh()
            peer = self.sync_manager.best_peer()
            if peer is None:
                return
            try:
                raw = self.transport.request_blocks_by_root(
                    peer, [parent_root]
                )
            except Exception:
                return  # range sync remains the fallback
            self.stats["root_requests"] += 1
            for data in raw:
                try:
                    block = decode_signed_block(data, self.cfg)
                except Exception:
                    continue
                self.controller.on_requested_block(block)

        from grandine_tpu.runtime.thread_pool import Priority

        self.controller.pool.spawn(task, Priority.LOW)

    def _fetch_blobs(self, peer: str, blocks) -> None:
        """Range-synced deneb blocks need their sidecars before the blob
        gate lets them import (BlobsByRange; p2p/src/network.rs:15)."""
        need = [
            b for b in blocks
            if getattr(b.message.body, "blob_kzg_commitments", None)
        ]
        if not need:
            return
        lo = min(int(b.message.slot) for b in need)
        hi = max(int(b.message.slot) for b in need)
        try:
            raw = self.transport.request_blobs_by_range(peer, lo, hi - lo + 1)
        except Exception:
            return
        self.stats["blob_requests"] += len(raw)
        from grandine_tpu.types.containers import spec_types

        ns = spec_types(self.cfg.preset).deneb
        for data in raw:
            try:
                sidecar = ns.BlobSidecar.deserialize(data)
            except Exception:
                continue
            self.controller.on_gossip_blob_sidecar(sidecar)

    def sync_once(self) -> bool:
        """One round: returns True when more work remains."""
        self.sync_manager.refresh()
        peer = self.sync_manager.best_peer()
        if peer is None:
            return False
        snap = self.controller.snapshot()
        head_slot = int(snap.head_state.slot)
        target = self.sync_manager.target_slot()
        if head_slot >= target:
            return False
        # walk windows upward past empty stretches (a >= batch_size gap of
        # empty slots must not stall the sync or fake completion)
        start = head_slot + 1
        blocks = []
        while start <= target:
            raw_blocks = self.transport.request_blocks_by_range(
                peer, start, self.batch_size
            )
            self.stats["requested"] += len(raw_blocks)
            blocks = [decode_signed_block(raw, self.cfg) for raw in raw_blocks]
            if blocks:
                break
            start += self.batch_size
        if blocks:
            # advance the local clock only to slots we actually RECEIVED
            # blocks for — never to a peer's unverified head_slot claim
            # (a malicious Status could fast-forward our clock arbitrarily)
            from grandine_tpu.fork_choice.store import Tick, TickKind

            max_received = max(int(b.message.slot) for b in blocks)
            self.controller.on_tick(Tick(max_received, TickKind.AGGREGATE))
            self._fetch_blobs(peer, blocks)
        if not (self.bulk_verify and self._bulk_import(snap, blocks)):
            for block in blocks:
                self.controller.on_requested_block(block)
        self.controller.wait()
        self.stats["applied_batches"] += 1
        head = int(self.controller.snapshot().head_state.slot)
        return bool(blocks) and head < target

    def _bulk_import(self, snap, blocks) -> bool:
        """Verify a fetched range as ONE cross-block pipeline batch against
        the head state, then import trusted. Returns False (per-block
        fallback) when the range is not a contiguous chain off the head,
        or when the pipeline rejects anything — the per-block path stays
        the arbiter of validity and will name the bad block."""
        if not blocks:
            return False
        ordered = sorted(blocks, key=lambda b: int(b.message.slot))
        parent = bytes(snap.head_root)
        for b in ordered:
            if bytes(b.message.parent_root) != parent:
                self.stats["bulk_fallbacks"] += 1
                return False
            parent = bytes(b.message.hash_tree_root())
        if self._pipeline is None:
            from grandine_tpu.runtime.replay import BulkReplayPipeline

            self._pipeline = BulkReplayPipeline(self.cfg)
        try:
            self._pipeline.replay(snap.head_state, ordered)
        except Exception as e:
            logger.warning("bulk range verification failed (%s); "
                           "falling back to per-block import", e)
            self.stats["bulk_fallbacks"] += 1
            return False
        for b in ordered:
            self.controller.on_verified_block(b)
        self.stats["bulk_blocks"] += len(ordered)
        return True

    def sync_to_head(self, max_rounds: int = 1000) -> None:
        for _ in range(max_rounds):
            if not self.sync_once():
                return
        raise TimeoutError("sync did not converge")


def back_sync(storage, transport, cfg, anchor_slot: int,
              peer: "Optional[str]" = None, batch_size: int = 64,
              verify: bool = True, use_device: bool = False,
              window_size: "Optional[int]" = None,
              slasher=None) -> dict:
    """Reverse-fill history below a checkpoint anchor down to genesis
    (back_sync.rs): request ranges below `anchor_slot`, check hash-chain
    linkage child->parent, persist to the finalized schema. Returns a
    stats dict: ``stored`` blocks persisted, ``off_chain`` blocks dropped
    for not being on the anchored chain, ``reverified`` blocks whose
    signatures were re-checked.

    With verify=True the linkage to the trusted anchor root guards
    integrity during the fill; once the fill reaches a stored genesis
    state the whole history is additionally replayed through the bulk
    pipeline for FULL signature re-verification (closing the reference's
    `TrustBackSyncBlocks` escape hatch). Checkpoint-sync nodes whose
    first anchor IS the checkpoint have no pre-anchor state to replay
    from; they keep linkage-only verification (logged once)."""
    from grandine_tpu.storage.storage import (
        PREFIX_BLOCK,
        PREFIX_SLOT_INDEX,
        _slot_key,
    )

    stats = {"stored": 0, "off_chain": 0, "reverified": 0}
    if peer is None:
        peers = transport.peers()
        if not peers:
            return stats
        peer = peers[0]

    # expected root of the next (lower) block comes from the anchor chain
    anchor_root = storage.finalized_root_by_slot(anchor_slot)
    expected_parent = None
    if anchor_root is not None:
        anchor_block = storage.finalized_block_by_root(anchor_root)
        if anchor_block is not None:
            expected_parent = bytes(anchor_block.message.parent_root)
    if verify and expected_parent is None:
        # without the anchor's parent root there is nothing to chain the
        # fetched history to — refusing beats storing unverified blocks
        # as finalized
        raise LookupError(
            f"no anchor block stored at slot {anchor_slot}; cannot verify "
            "back-synced history"
        )

    slot_hi = anchor_slot - 1
    while slot_hi >= 0:
        start = max(0, slot_hi - batch_size + 1)
        raws = transport.request_blocks_by_range(peer, start, slot_hi - start + 1)
        blocks = [decode_signed_block(r, cfg) for r in raws]
        blocks.sort(key=lambda b: -int(b.message.slot))  # high -> low
        items = []
        off_chain = 0
        for block in blocks:
            root = block.message.hash_tree_root()
            if verify and expected_parent is not None and root != expected_parent:
                off_chain += 1
                continue  # not on the anchored chain
            items.append((PREFIX_BLOCK + root, block.serialize()))
            items.append(
                (_slot_key(PREFIX_SLOT_INDEX, int(block.message.slot)), root)
            )
            expected_parent = bytes(block.message.parent_root)
            stats["stored"] += 1
        if off_chain:
            stats["off_chain"] += off_chain
            logger.warning(
                "back_sync: dropped %d off-anchor-chain block(s) in "
                "slots [%d, %d] from peer %s", off_chain, start, slot_hi,
                peer,
            )
        storage.db.put_batch(items)
        # an empty window just moves the cursor down (long empty stretches
        # are normal); the loop ends when the window reaches genesis
        slot_hi = start - 1
        if start == 0:
            break

    if verify and stats["stored"]:
        stats["reverified"] = _reverify_back_synced(
            storage, cfg, anchor_slot, use_device=use_device,
            window_size=window_size, slasher=slasher,
        )
    return stats


def _reverify_back_synced(storage, cfg, anchor_slot: int, *,
                          use_device: bool = False,
                          window_size: "Optional[int]" = None,
                          slasher=None) -> int:
    """Full signature re-verification of the back-synced range through
    the bulk replay pipeline, anchored at the stored genesis state.
    Raises ReplayInvalidBlock on a bad signature; returns the number of
    blocks re-verified (0 when no pre-anchor state exists to replay
    from — the checkpoint-sync case)."""
    genesis = storage.load_genesis_state()
    if genesis is None or int(genesis.slot) >= anchor_slot:
        logger.warning(
            "back_sync: no pre-anchor state available; back-synced "
            "history below slot %d keeps linkage-only verification",
            anchor_slot,
        )
        return 0
    blocks = []
    for slot in range(int(genesis.slot) + 1, anchor_slot):
        root = storage.finalized_root_by_slot(slot)
        if root is None:
            continue  # empty slot
        block = storage.finalized_block_by_root(root)
        if block is not None:
            blocks.append(block)
    if not blocks:
        return 0
    from grandine_tpu.runtime.replay import (
        DEFAULT_WINDOW_BLOCKS,
        BulkReplayPipeline,
    )

    pipeline = BulkReplayPipeline(
        cfg, use_device=use_device,
        window_size=window_size or DEFAULT_WINDOW_BLOCKS,
        slasher=slasher,
    )
    pipeline.replay(genesis, blocks)
    logger.info("back_sync: re-verified %d block(s) of back-synced "
                "history (%d signature sets)", len(blocks),
                pipeline.stats["sigsets"])
    return len(blocks)


def verify_block_batch(anchor_state, blocks, cfg, use_device: bool = False,
                       bulk: bool = True,
                       window_size: "Optional[int]" = None,
                       slasher=None):
    """Batch verification against one base state
    (block_verification_pool.rs:76-129), returning the post states and
    raising on the first invalid block.

    bulk=True (default) routes through the BulkReplayPipeline: ONE
    cross-block batch per window instead of one dispatch per block.
    bulk=False keeps the legacy shape — a fresh verifier and one RLC
    batch PER BLOCK — as the per-block baseline (`bench.py --replay`
    measures the two against each other)."""
    if bulk:
        from grandine_tpu.runtime.replay import (
            DEFAULT_WINDOW_BLOCKS,
            BulkReplayPipeline,
        )

        pipeline = BulkReplayPipeline(
            cfg, use_device=use_device,
            window_size=window_size or DEFAULT_WINDOW_BLOCKS,
            slasher=slasher,
        )
        return pipeline.replay(anchor_state, blocks)
    from grandine_tpu.consensus.verifier import TpuVerifier
    from grandine_tpu.transition.combined import custom_state_transition

    state = anchor_state
    posts = []
    for block in blocks:
        verifier = TpuVerifier() if use_device else MultiVerifier()
        state = custom_state_transition(state, block, cfg, verifier)
        posts.append(state)
    return posts


__all__ = [
    "SyncManager",
    "BlockSyncService",
    "back_sync",
    "verify_block_batch",
]
