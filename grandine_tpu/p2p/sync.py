"""Block sync — reference: p2p/src/block_sync_service.rs + sync_manager.rs
(range/root request tracking), back_sync.rs (reverse fill to genesis with
batch verification), block_verification_pool.rs:76-129 (two-epoch block
batches verified against one head state).
"""

from __future__ import annotations

from typing import Optional

from grandine_tpu.consensus.verifier import MultiVerifier, NullVerifier
from grandine_tpu.types.combined import decode_signed_block


class SyncManager:
    """Tracks peer statuses and picks sync targets
    (sync_manager.rs / range_and_root_requests.rs)."""

    def __init__(self, transport) -> None:
        self.transport = transport
        self.peer_status: "dict[str, dict]" = {}

    def refresh(self) -> None:
        for peer in self.transport.peers():
            try:
                self.peer_status[peer] = self.transport.request_status(peer)
            except ConnectionError:
                self.peer_status.pop(peer, None)

    def best_peer(self) -> "Optional[str]":
        if not self.peer_status:
            return None
        return max(
            self.peer_status, key=lambda p: self.peer_status[p]["head_slot"]
        )

    def target_slot(self) -> int:
        return max(
            (s["head_slot"] for s in self.peer_status.values()), default=0
        )


class BlockSyncService:
    """Forward range sync: while the head lags the best peer, request
    slot ranges and feed them through the controller's normal validation
    (block_sync_service shape; the controller's delayed-maps handle
    out-of-order arrival)."""

    def __init__(self, transport, controller, cfg,
                 batch_size: "Optional[int]" = None) -> None:
        self.transport = transport
        self.controller = controller
        self.cfg = cfg
        self.sync_manager = SyncManager(transport)
        # two epochs per round, like the reference's verification pool
        self.batch_size = batch_size or 2 * cfg.preset.SLOTS_PER_EPOCH
        self.stats = {"requested": 0, "applied_batches": 0,
                      "root_requests": 0, "blob_requests": 0}
        # resolve delayed-by-parent blocks via BlocksByRoot instead of
        # waiting for the next range round (p2p/src/network.rs:911-912)
        if hasattr(controller, "on_unknown_parent"):
            controller.on_unknown_parent.append(self._on_unknown_parent)

    def _on_unknown_parent(self, parent_root: bytes) -> None:
        """Mutator-thread hook: fetch the missing parent off-thread."""
        def task() -> None:
            self.sync_manager.refresh()
            peer = self.sync_manager.best_peer()
            if peer is None:
                return
            try:
                raw = self.transport.request_blocks_by_root(
                    peer, [parent_root]
                )
            except Exception:
                return  # range sync remains the fallback
            self.stats["root_requests"] += 1
            for data in raw:
                try:
                    block = decode_signed_block(data, self.cfg)
                except Exception:
                    continue
                self.controller.on_requested_block(block)

        from grandine_tpu.runtime.thread_pool import Priority

        self.controller.pool.spawn(task, Priority.LOW)

    def _fetch_blobs(self, peer: str, blocks) -> None:
        """Range-synced deneb blocks need their sidecars before the blob
        gate lets them import (BlobsByRange; p2p/src/network.rs:15)."""
        need = [
            b for b in blocks
            if getattr(b.message.body, "blob_kzg_commitments", None)
        ]
        if not need:
            return
        lo = min(int(b.message.slot) for b in need)
        hi = max(int(b.message.slot) for b in need)
        try:
            raw = self.transport.request_blobs_by_range(peer, lo, hi - lo + 1)
        except Exception:
            return
        self.stats["blob_requests"] += len(raw)
        from grandine_tpu.types.containers import spec_types

        ns = spec_types(self.cfg.preset).deneb
        for data in raw:
            try:
                sidecar = ns.BlobSidecar.deserialize(data)
            except Exception:
                continue
            self.controller.on_gossip_blob_sidecar(sidecar)

    def sync_once(self) -> bool:
        """One round: returns True when more work remains."""
        self.sync_manager.refresh()
        peer = self.sync_manager.best_peer()
        if peer is None:
            return False
        snap = self.controller.snapshot()
        head_slot = int(snap.head_state.slot)
        target = self.sync_manager.target_slot()
        if head_slot >= target:
            return False
        # walk windows upward past empty stretches (a >= batch_size gap of
        # empty slots must not stall the sync or fake completion)
        start = head_slot + 1
        blocks = []
        while start <= target:
            raw_blocks = self.transport.request_blocks_by_range(
                peer, start, self.batch_size
            )
            self.stats["requested"] += len(raw_blocks)
            blocks = [decode_signed_block(raw, self.cfg) for raw in raw_blocks]
            if blocks:
                break
            start += self.batch_size
        if blocks:
            # advance the local clock only to slots we actually RECEIVED
            # blocks for — never to a peer's unverified head_slot claim
            # (a malicious Status could fast-forward our clock arbitrarily)
            from grandine_tpu.fork_choice.store import Tick, TickKind

            max_received = max(int(b.message.slot) for b in blocks)
            self.controller.on_tick(Tick(max_received, TickKind.AGGREGATE))
            self._fetch_blobs(peer, blocks)
        for block in blocks:
            self.controller.on_requested_block(block)
        self.controller.wait()
        self.stats["applied_batches"] += 1
        head = int(self.controller.snapshot().head_state.slot)
        return bool(blocks) and head < target

    def sync_to_head(self, max_rounds: int = 1000) -> None:
        for _ in range(max_rounds):
            if not self.sync_once():
                return
        raise TimeoutError("sync did not converge")


def back_sync(storage, transport, cfg, anchor_slot: int,
              peer: "Optional[str]" = None, batch_size: int = 64,
              verify: bool = True) -> int:
    """Reverse-fill history below a checkpoint anchor down to genesis
    (back_sync.rs): request ranges below `anchor_slot`, check hash-chain
    linkage child->parent, persist to the finalized schema. Returns the
    number of blocks stored.

    With verify=True the linkage to the trusted anchor root guards
    integrity (the reference trusts back-synced signature batches behind
    `TrustBackSyncBlocks`; full signature re-verification would need the
    historical states)."""
    from grandine_tpu.storage.storage import (
        PREFIX_BLOCK,
        PREFIX_SLOT_INDEX,
        _slot_key,
    )

    if peer is None:
        peers = transport.peers()
        if not peers:
            return 0
        peer = peers[0]

    stored = 0
    # expected root of the next (lower) block comes from the anchor chain
    anchor_root = storage.finalized_root_by_slot(anchor_slot)
    expected_parent = None
    if anchor_root is not None:
        anchor_block = storage.finalized_block_by_root(anchor_root)
        if anchor_block is not None:
            expected_parent = bytes(anchor_block.message.parent_root)
    if verify and expected_parent is None:
        # without the anchor's parent root there is nothing to chain the
        # fetched history to — refusing beats storing unverified blocks
        # as finalized
        raise LookupError(
            f"no anchor block stored at slot {anchor_slot}; cannot verify "
            "back-synced history"
        )

    slot_hi = anchor_slot - 1
    while slot_hi >= 0:
        start = max(0, slot_hi - batch_size + 1)
        raws = transport.request_blocks_by_range(peer, start, slot_hi - start + 1)
        blocks = [decode_signed_block(r, cfg) for r in raws]
        blocks.sort(key=lambda b: -int(b.message.slot))  # high -> low
        items = []
        for block in blocks:
            root = block.message.hash_tree_root()
            if verify and expected_parent is not None and root != expected_parent:
                continue  # not on the anchored chain
            items.append((PREFIX_BLOCK + root, block.serialize()))
            items.append(
                (_slot_key(PREFIX_SLOT_INDEX, int(block.message.slot)), root)
            )
            expected_parent = bytes(block.message.parent_root)
            stored += 1
        storage.db.put_batch(items)
        # an empty window just moves the cursor down (long empty stretches
        # are normal); the loop ends when the window reaches genesis
        slot_hi = start - 1
        if start == 0:
            break
    return stored


def verify_block_batch(anchor_state, blocks, cfg, use_device: bool = False):
    """Two-epoch batch verification against one base state
    (block_verification_pool.rs:76-129): replay each block with a fresh
    MultiVerifier (one RLC batch per block), returning the post states.
    Raises on the first invalid block."""
    from grandine_tpu.consensus.verifier import TpuVerifier
    from grandine_tpu.transition.combined import custom_state_transition

    state = anchor_state
    posts = []
    for block in blocks:
        verifier = TpuVerifier() if use_device else MultiVerifier()
        state = custom_state_transition(state, block, cfg, verifier)
        posts.append(state)
    return posts


__all__ = [
    "SyncManager",
    "BlockSyncService",
    "back_sync",
    "verify_block_batch",
]
