"""Socket-real transport: gossip + req/resp over TCP with length-prefixed
ssz_snappy framing — the second `Transport` implementation (the first,
InMemoryHub, stays for unit tests).

Reference shape: p2p/src/network.rs over eth2_libp2p (gossipsub + req/resp
protocols `/eth2/beacon_chain/req/{status,beacon_blocks_by_range}/…`,
ssz_snappy payloads, ENR fork-digest gating). This implementation keeps the
consensus-networking SEMANTICS — topic strings with fork digest, ssz_snappy
gossip payloads, Status/BlocksByRange verbs, digest-gated handshake,
seen-cache flood relay — over plain TCP framing instead of libp2p's
noise/yamux stack (vendoring libp2p is out of scope; the `Transport` seam
is exactly where a full libp2p backend would drop in).

Wire format (all integers big-endian):
  frame   := kind:u8 len:u32 body[len]
  kinds   : 1 HELLO   body = JSON {peer_id, fork_digest}
            2 GOSSIP  body = tlen:u16 topic[tlen] payload  (payload ssz_snappy)
            3 REQ     body = id:u32 mlen:u16 method[mlen] params-JSON
            4 RESP    body = id:u32 status:u8 chunks       (chunk := len:u32 ssz)
Req/resp methods mirror the consensus spec protocol ids:
  /eth2/beacon_chain/req/status/1         params {} → one JSON chunk
  /eth2/beacon_chain/req/beacon_blocks_by_range/2
                                          params {start_slot, count} → ssz chunks
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
import threading
import time
from collections import OrderedDict, defaultdict, deque
from typing import Callable, Optional

from grandine_tpu.p2p.network import Transport

KIND_HELLO = 1
KIND_GOSSIP = 2
KIND_REQ = 3
KIND_RESP = 4

METHOD_STATUS = "/eth2/beacon_chain/req/status/1"
METHOD_BLOCKS_BY_RANGE = "/eth2/beacon_chain/req/beacon_blocks_by_range/2"
METHOD_BLOCKS_BY_ROOT = "/eth2/beacon_chain/req/beacon_blocks_by_root/2"
METHOD_BLOBS_BY_RANGE = "/eth2/beacon_chain/req/blob_sidecars_by_range/1"
METHOD_BLOBS_BY_ROOT = "/eth2/beacon_chain/req/blob_sidecars_by_root/1"

_MAX_FRAME = 1 << 26  # 64 MiB: a full minimal-preset state fits with margin


#: Per-peer outbound buffer bound. A reader that stalls past this much
#: queued data is DROPPED instead of blocking the sender — one slow peer
#: must never stall the flood relay (VERDICT r4 weak #8).
_MAX_WRITE_BUFFER = 16 << 20


class _Conn:
    """One peer connection: reader thread + writer thread over a BOUNDED
    per-peer queue (backpressure by disconnect, not by blocking)."""

    def __init__(self, sock: socket.socket, transport: "TcpTransport") -> None:
        self.sock = sock
        self.transport = transport
        self.peer_id: "Optional[str]" = None
        self.alive = True
        self._wq: "deque[bytes]" = deque()
        self._wbytes = 0
        self._wcond = threading.Condition()
        self.thread = threading.Thread(target=self._read_loop, daemon=True)
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()

    # -- framing ----------------------------------------------------------

    def send(self, kind: int, body: bytes) -> None:
        frame = struct.pack(">BI", kind, len(body)) + body
        with self._wcond:
            if not self.alive:
                return
            # a single frame may legitimately exceed the buffer bound
            # (req/resp responses up to _MAX_FRAME — e.g. a full-blob
            # BlobsByRange window); the bound trips only when data is
            # already QUEUED, i.e. the reader is demonstrably slow
            if self._wq and self._wbytes + len(frame) > _MAX_WRITE_BUFFER:
                self.transport.stats["slow_peer_drops"] += 1
                drop = True
            else:
                self._wq.append(frame)
                self._wbytes += len(frame)
                self._wcond.notify()
                drop = False
        if drop:
            self.close()

    def _write_loop(self) -> None:
        while True:
            with self._wcond:
                while self.alive and not self._wq:
                    self._wcond.wait(0.5)
                if not self.alive:
                    return
                frame = self._wq.popleft()
                self._wbytes -= len(frame)
            try:
                self.sock.sendall(frame)
            except OSError:
                self.close()
                return

    def _recv_exact(self, n: int) -> "Optional[bytes]":
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self.sock.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return bytes(buf)

    def _read_loop(self) -> None:
        while self.alive:
            head = self._recv_exact(5)
            if head is None:
                break
            kind, length = struct.unpack(">BI", head)
            if length > _MAX_FRAME:
                break  # protocol violation: drop the peer
            body = self._recv_exact(length)
            if body is None:
                break
            try:
                self.transport._on_frame(self, kind, body)
            except Exception:
                self.transport.stats["handler_errors"] += 1
        self.close()

    def close(self) -> None:
        if not self.alive:
            return
        self.alive = False
        with self._wcond:
            self._wq.clear()
            self._wbytes = 0
            self._wcond.notify_all()  # release the writer thread
        try:
            self.sock.close()
        except OSError:
            pass
        self.transport._drop(self)


class TcpTransport(Transport):
    """TCP mesh node. `listen_port=0` picks a free port (see `.port`)."""

    def __init__(
        self,
        peer_id: str,
        fork_digest: bytes,
        listen_port: int = 0,
        request_timeout: float = 10.0,
    ) -> None:
        self.peer_id = peer_id
        self.fork_digest = fork_digest
        self.request_timeout = request_timeout
        self.stats = defaultdict(int)
        self._subs: "dict[str, list[Callable]]" = defaultdict(list)
        self._conns: "dict[str, _Conn]" = {}
        self._pending: "dict[int, tuple[threading.Event, list]]" = {}
        self._req_id = 0
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._lock = threading.Lock()
        self._blocks_by_range = None
        self._status = None
        self._blocks_by_root = None
        self._blobs_by_range = None
        self._blobs_by_root = None

        self._server = socket.create_server(("127.0.0.1", listen_port))
        self.port = self._server.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    # -- lifecycle ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _addr = self._server.accept()
            except OSError:
                return
            self._start_conn(sock)

    def _start_conn(self, sock: socket.socket) -> "_Conn":
        conn = _Conn(sock, self)
        conn.send(KIND_HELLO, json.dumps({
            "peer_id": self.peer_id,
            "fork_digest": self.fork_digest.hex(),
        }).encode())
        conn.thread.start()
        return conn

    def connect(self, host: str, port: int, wait: float = 30.0) -> str:
        """Dial a peer; returns its peer_id after the HELLO handshake.
        Connection-refused/timeout are retried until `wait` expires — the
        peer process may still be starting up (imports alone take
        seconds), and a follower races the proposer's bind in the
        two-process devnet. Permanent errors (DNS failure, unroutable
        address) raise immediately, and `wait` bounds dial + handshake
        TOGETHER."""
        deadline = time.time() + wait
        while True:
            try:
                sock = socket.create_connection(
                    (host, port), timeout=max(1.0, deadline - time.time())
                )
                break
            except (ConnectionRefusedError, socket.timeout, TimeoutError):
                if time.time() >= deadline:
                    raise
                time.sleep(0.25)
        sock.settimeout(None)
        conn = self._start_conn(sock)
        while conn.peer_id is None and conn.alive and time.time() < deadline:
            time.sleep(0.01)
        if conn.peer_id is None:
            conn.close()
            raise ConnectionError(f"handshake with {host}:{port} failed")
        return conn.peer_id

    def close(self) -> None:
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c.close()

    def _drop(self, conn: "_Conn") -> None:
        with self._lock:
            if conn.peer_id and self._conns.get(conn.peer_id) is conn:
                del self._conns[conn.peer_id]

    # -- frame dispatch ----------------------------------------------------

    def _on_frame(self, conn: "_Conn", kind: int, body: bytes) -> None:
        if kind == KIND_HELLO:
            hello = json.loads(body)
            if hello.get("fork_digest") != self.fork_digest.hex():
                self.stats["digest_rejects"] += 1
                conn.close()  # wrong fork: the ENR fork-id gate equivalent
                return
            conn.peer_id = hello["peer_id"]
            with self._lock:
                self._conns[conn.peer_id] = conn
        elif kind == KIND_GOSSIP:
            (tlen,) = struct.unpack(">H", body[:2])
            topic = body[2 : 2 + tlen].decode()
            payload = body[2 + tlen :]
            self._deliver(topic, payload, exclude=conn)
        elif kind == KIND_REQ:
            req_id, = struct.unpack(">I", body[:4])
            (mlen,) = struct.unpack(">H", body[4:6])
            method = body[6 : 6 + mlen].decode()
            params = json.loads(body[6 + mlen :] or b"{}")
            self._serve(conn, req_id, method, params)
        elif kind == KIND_RESP:
            req_id, = struct.unpack(">I", body[:4])
            ok = body[4]
            chunks, pos = [], 5
            while pos < len(body):
                (clen,) = struct.unpack(">I", body[pos : pos + 4])
                chunks.append(body[pos + 4 : pos + 4 + clen])
                pos += 4 + clen
            with self._lock:
                pending = self._pending.pop(req_id, None)
            if pending is not None:
                event, out = pending
                out.append((ok, chunks))
                event.set()
        else:
            self.stats["unknown_frames"] += 1

    # -- gossip ------------------------------------------------------------

    def _deliver(
        self, topic: str, payload: bytes, exclude=None, local: bool = True
    ) -> None:
        """Seen-cache dedup, local handler delivery (inbound only — a
        publisher does not hear its own gossip, matching InMemoryHub), and
        flood relay to every other peer (gossipsub-lite: full fanout, the
        seen cache breaks cycles)."""
        digest = hashlib.sha256(topic.encode() + b"\x00" + payload).digest()
        with self._lock:
            if digest in self._seen:
                return
            self._seen[digest] = None
            while len(self._seen) > 4096:
                self._seen.popitem(last=False)
            handlers = list(self._subs.get(topic, ())) if local else []
            conns = [c for c in self._conns.values() if c is not exclude]
        for handler in handlers:
            try:
                handler(topic, payload)
            except Exception:
                self.stats["handler_errors"] += 1
        body = struct.pack(">H", len(topic)) + topic.encode() + payload
        for c in conns:
            c.send(KIND_GOSSIP, body)

    def publish(self, topic: str, payload: bytes) -> None:
        self.stats["published"] += 1
        self._deliver(topic, payload, local=False)

    def subscribe(self, topic: str, handler) -> None:
        with self._lock:
            self._subs[topic].append(handler)

    def peers(self) -> "list[str]":
        with self._lock:
            return list(self._conns)

    # -- req/resp ----------------------------------------------------------

    def register_provider(
        self, blocks_by_range, status,
        blocks_by_root=None, blobs_by_range=None, blobs_by_root=None,
    ) -> None:
        self._blocks_by_range = blocks_by_range
        self._status = status
        self._blocks_by_root = blocks_by_root
        self._blobs_by_range = blobs_by_range
        self._blobs_by_root = blobs_by_root

    def _serve(self, conn: "_Conn", req_id: int, method: str, params: dict):
        try:
            if method == METHOD_STATUS:
                if self._status is None:
                    raise RuntimeError("no status provider")
                chunks = [json.dumps(self._status()).encode()]
            elif method == METHOD_BLOCKS_BY_RANGE:
                if self._blocks_by_range is None:
                    raise RuntimeError("no blocks provider")
                chunks = self._blocks_by_range(
                    int(params["start_slot"]), int(params["count"])
                )
            elif method == METHOD_BLOCKS_BY_ROOT:
                if self._blocks_by_root is None:
                    raise RuntimeError("no blocks-by-root provider")
                chunks = self._blocks_by_root(
                    [bytes.fromhex(r) for r in params["roots"]]
                )
            elif method == METHOD_BLOBS_BY_RANGE:
                if self._blobs_by_range is None:
                    raise RuntimeError("no blobs provider")
                chunks = self._blobs_by_range(
                    int(params["start_slot"]), int(params["count"])
                )
            elif method == METHOD_BLOBS_BY_ROOT:
                if self._blobs_by_root is None:
                    raise RuntimeError("no blobs-by-root provider")
                chunks = self._blobs_by_root(
                    [(bytes.fromhex(r), int(i)) for r, i in params["ids"]]
                )
            else:
                raise RuntimeError(f"unknown method {method}")
            ok = 1
        except Exception as e:
            self.stats["serve_errors"] += 1
            chunks, ok = [str(e).encode()], 0
        body = struct.pack(">IB", req_id, ok) + b"".join(
            struct.pack(">I", len(c)) + c for c in chunks
        )
        conn.send(KIND_RESP, body)

    def _request(self, peer: str, method: str, params: dict) -> "list[bytes]":
        with self._lock:
            conn = self._conns.get(peer)
            self._req_id += 1
            req_id = self._req_id
            event, out = threading.Event(), []
            self._pending[req_id] = (event, out)
        if conn is None:
            with self._lock:
                self._pending.pop(req_id, None)
            raise ConnectionError(f"unknown peer {peer}")
        body = (
            struct.pack(">IH", req_id, len(method))
            + method.encode()
            + json.dumps(params).encode()
        )
        conn.send(KIND_REQ, body)
        if not event.wait(self.request_timeout):
            with self._lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"{method} to {peer} timed out")
        ok, chunks = out[0]
        if not ok:
            detail = chunks[0].decode(errors="replace") if chunks else "?"
            raise ConnectionError(f"{method} failed: {detail}")
        return chunks

    def request_status(self, peer: str) -> dict:
        chunks = self._request(peer, METHOD_STATUS, {})
        if not chunks:  # peer protocol violation, not a local crash
            raise ConnectionError("empty status response")
        try:
            return json.loads(chunks[0])
        except ValueError as e:
            raise ConnectionError("malformed status response") from e

    def request_blocks_by_range(self, peer, start_slot, count) -> "list[bytes]":
        return self._request(
            peer, METHOD_BLOCKS_BY_RANGE,
            {"start_slot": int(start_slot), "count": int(count)},
        )

    def request_blocks_by_root(self, peer, roots) -> "list[bytes]":
        return self._request(
            peer, METHOD_BLOCKS_BY_ROOT,
            {"roots": [bytes(r).hex() for r in roots]},
        )

    def request_blobs_by_range(self, peer, start_slot, count) -> "list[bytes]":
        return self._request(
            peer, METHOD_BLOBS_BY_RANGE,
            {"start_slot": int(start_slot), "count": int(count)},
        )

    def request_blobs_by_root(self, peer, ids) -> "list[bytes]":
        return self._request(
            peer, METHOD_BLOBS_BY_ROOT,
            {"ids": [[bytes(r).hex(), int(i)] for r, i in ids]},
        )


__all__ = [
    "TcpTransport",
    "METHOD_STATUS",
    "METHOD_BLOCKS_BY_RANGE",
    "METHOD_BLOCKS_BY_ROOT",
    "METHOD_BLOBS_BY_RANGE",
    "METHOD_BLOBS_BY_ROOT",
]
