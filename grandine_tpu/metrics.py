"""Metrics registry — reference: prometheus_metrics crate (the one
`Metrics` struct of ~100 histograms/gauges/counters shared via
Option<Arc<Metrics>> through every constructor, prometheus_metrics/src/
metrics.rs:14-120) plus the `metrics` crate's scrape server.

Dependency-free: counters/gauges/histograms with Prometheus text
exposition. The scrape endpoint is served by the HTTP API layer.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence


class Counter:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help_: str = "") -> None:
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> str:
        with self._lock:
            value = self._value
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} counter\n"
            f"{self.name} {value}\n"
        )


class Gauge(Counter):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def expose(self) -> str:
        with self._lock:
            value = self._value
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} gauge\n"
            f"{self.name} {value}\n"
        )


_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class Histogram:
    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str, help_: str = "",
                 buckets: "Sequence[float]" = _DEFAULT_BUCKETS) -> None:
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def time(self) -> "_Timer":
        return _Timer(self)

    def expose(self) -> str:
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        cumulative = 0
        for bound, bucket in zip(self.buckets, counts):
            cumulative += bucket
            out.append(f'{self.name}_bucket{{le="{bound}"}} {cumulative}')
        cumulative += counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cumulative}')
        out.append(f"{self.name}_sum {total}")
        out.append(f"{self.name}_count {count}")
        return "\n".join(out) + "\n"


class _Timer:
    def __init__(self, hist) -> None:
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *_):
        self._hist.observe(time.perf_counter() - self._t0)


# --- labeled families -------------------------------------------------------
#
# The reference client leans on prometheus's labeled vectors
# (IntCounterVec / HistogramVec) for anything with a dimension — gossip
# topic, req/resp protocol, kernel variant. Children are cached per
# label-value tuple so the hot path is one dict lookup, and exposition
# emits one HELP/TYPE header per family with `{label="value"}` samples.


def _escape_label_value(value: str) -> str:
    """Prometheus text-format escaping for label values."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labelnames, values) -> str:
    return ",".join(
        f'{n}="{_escape_label_value(v)}"'
        for n, v in zip(labelnames, values)
    )


class _LabeledFamily:
    """Shared child-caching machinery for labeled counters/gauges/
    histograms. `labels(*values)` returns (creating on first use) the
    child for that label-value tuple; children are never evicted, so
    label cardinality must stay bounded by construction (topic names,
    protocol ids, kernel names — not peer ids).

    `defaults` maps TRAILING label names to fill-in values so a family
    can grow a dimension without breaking existing call sites: after
    widening `verify_stage_seconds` from ("stage",) to ("stage", "lane")
    with defaults={"lane": "attestation"}, `labels("execute")` keeps
    resolving to the pre-existing attestation series."""

    def __init__(self, name: str, help_: str,
                 labelnames: "Sequence[str]",
                 defaults: "Optional[dict]" = None) -> None:
        if not labelnames:
            raise ValueError(f"{name}: labeled family needs >= 1 label")
        self.name = name
        self.help = help_
        self.labelnames = tuple(str(n) for n in labelnames)
        self.defaults = {str(k): str(v) for k, v in (defaults or {}).items()}
        for k in self.defaults:
            if k not in self.labelnames:
                raise ValueError(f"{name}: default for unknown label {k!r}")
        self._children: dict = {}
        self._lock = threading.Lock()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kwargs):
        if kwargs:
            if values:
                raise ValueError("pass label values positionally or by name")
            try:
                values = tuple(
                    kwargs[n] if n in kwargs else self.defaults[n]
                    for n in self.labelnames
                )
            except KeyError as e:
                raise ValueError(f"{self.name}: missing label {e}") from e
        values = tuple(str(v) for v in values)
        if len(values) < len(self.labelnames):
            tail = self.labelnames[len(values):]
            if all(n in self.defaults for n in tail):
                values = values + tuple(self.defaults[n] for n in tail)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values, got {len(values)}"
            )
        # single-lock lookup (no bare double-checked read): an uncontended
        # Lock acquire is cheap enough for the inc() hot path, and every
        # thread then agrees on one child per label tuple
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return child

    def children(self) -> dict:
        with self._lock:
            return dict(self._children)

    def _sorted_children(self):
        with self._lock:
            return sorted(self._children.items())


class LabeledCounter(_LabeledFamily):
    _TYPE = "counter"

    class Child:
        __slots__ = ("_value", "_lock")

        def __init__(self) -> None:
            self._value = 0.0
            self._lock = threading.Lock()

        def inc(self, amount: float = 1.0) -> None:
            with self._lock:
                self._value += amount

        @property
        def value(self) -> float:
            with self._lock:
                return self._value

    def _make_child(self):
        return self.Child()

    def inc(self, *values, amount: float = 1.0) -> None:
        self.labels(*values).inc(amount)

    def value(self, *values) -> float:
        return self.labels(*values).value

    def expose(self) -> str:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self._TYPE}",
        ]
        for values, child in self._sorted_children():
            ls = _label_str(self.labelnames, values)
            out.append(f"{self.name}{{{ls}}} {child.value}")
        return "\n".join(out) + "\n"


class LabeledGauge(LabeledCounter):
    _TYPE = "gauge"

    class Child(LabeledCounter.Child):
        __slots__ = ()

        def set(self, value: float) -> None:
            with self._lock:
                self._value = float(value)

        def dec(self, amount: float = 1.0) -> None:
            self.inc(-amount)

    def set(self, *values, value: float) -> None:
        self.labels(*values).set(value)


class LabeledHistogram(_LabeledFamily):
    def __init__(self, name: str, help_: str,
                 labelnames: "Sequence[str]",
                 buckets: "Sequence[float]" = _DEFAULT_BUCKETS,
                 defaults: "Optional[dict]" = None) -> None:
        super().__init__(name, help_, labelnames, defaults=defaults)
        self.buckets = tuple(buckets)

    class Child:
        __slots__ = ("buckets", "_counts", "_sum", "_count", "_lock")

        def __init__(self, buckets) -> None:
            self.buckets = buckets
            self._counts = [0] * (len(buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._lock = threading.Lock()

        def observe(self, value: float) -> None:
            with self._lock:
                self._sum += value
                self._count += 1
                for i, bound in enumerate(self.buckets):
                    if value <= bound:
                        self._counts[i] += 1
                        return
                self._counts[-1] += 1

        def time(self) -> "_Timer":
            return _Timer(self)

        @property
        def count(self) -> int:
            with self._lock:
                return self._count

        @property
        def sum(self) -> float:
            with self._lock:
                return self._sum

        def snapshot(self) -> "tuple[list, float, int]":
            """(bucket counts, sum, count) read consistently under the
            child's lock — the scrape path's view."""
            with self._lock:
                return list(self._counts), self._sum, self._count

    def _make_child(self):
        return self.Child(self.buckets)

    def observe(self, *values, value: float) -> None:
        self.labels(*values).observe(value)

    def time(self, *values) -> "_Timer":
        return self.labels(*values).time()

    def expose(self) -> str:
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        for values, child in self._sorted_children():
            base = _label_str(self.labelnames, values)
            counts, total, count = child.snapshot()
            cumulative = 0
            for bound, bucket in zip(self.buckets, counts):
                cumulative += bucket
                out.append(
                    f'{self.name}_bucket{{{base},le="{bound}"}} {cumulative}'
                )
            cumulative += counts[-1]
            out.append(f'{self.name}_bucket{{{base},le="+Inf"}} {cumulative}')
            out.append(f"{self.name}_sum{{{base}}} {total}")
            out.append(f"{self.name}_count{{{base}}} {count}")
        return "\n".join(out) + "\n"


class Metrics:
    """The shared metrics struct: the framework's counterpart of
    prometheus_metrics::Metrics, passed as Optional through constructors."""

    def __init__(self) -> None:
        # fork choice / mutator (metrics.rs:49-53,106)
        self.fc_blocks_applied = Counter(
            "fc_blocks_applied_total", "blocks applied to the store")
        self.fc_attestations_applied = Counter(
            "fc_attestations_applied_total", "attestations applied")
        self.fc_block_task_times = Histogram(
            "fc_block_task_seconds", "block validation task duration")
        self.fc_head_changes = Counter(
            "fc_head_changes_total", "head switches")
        # attestation verifier (metrics.rs:58-60)
        self.att_batches = Counter(
            "attestation_verifier_batches_total", "verified gossip batches")
        self.att_batch_times = Histogram(
            "attestation_verifier_batch_seconds", "batch verify duration")
        self.att_fallbacks = Counter(
            "attestation_verifier_fallbacks_total",
            "batches degraded to singular verification")
        # device plane
        self.device_batch_sigs = Counter(
            "device_batch_signatures_total",
            "signatures shipped to the accelerator")
        self.block_processing_times = Histogram(
            "block_processing_seconds", "state-transition duration")
        self.head_slot = Gauge("head_slot", "current head slot")
        self.finalized_epoch = Gauge("finalized_epoch", "finalized epoch")
        # system stats (the reference's metrics SERVICE collects these
        # via sysinfo; here straight from /proc, dependency-free)
        self.process_resident_memory_bytes = Gauge(
            "process_resident_memory_bytes", "resident set size")
        self.process_cpu_seconds_total = Gauge(
            "process_cpu_seconds_total", "user+system CPU time")
        self.process_open_fds = Gauge(
            "process_open_fds", "open file descriptors")
        self.process_start_time_seconds = Gauge(
            "process_start_time_seconds", "process start, unix time")
        self.data_dir_bytes = Gauge(
            "grandine_data_dir_bytes", "on-disk size of the data dir")
        # gossip boundary (labeled per topic kind: the reference's
        # gossipsub acceptance vectors)
        self.gossip_messages = LabeledCounter(
            "gossip_messages_total",
            "gossip messages by topic kind and validation result",
            ("topic", "result"),
        )
        # req/resp boundary: requests served per protocol
        self.rpc_requests = LabeledCounter(
            "rpc_requests_total",
            "req/resp requests served, by protocol",
            ("protocol",),
        )
        # device plane, per kernel variant
        self.device_kernel_calls = LabeledCounter(
            "device_kernel_calls_total",
            "accelerator kernel dispatches, by kernel variant",
            ("kernel",),
        )
        self.device_kernel_sigs = LabeledCounter(
            "device_kernel_signatures_total",
            "signatures processed per kernel variant",
            ("kernel",),
        )
        # host→device transfer accounting, per kernel variant: the basis
        # of the no-per-batch-pubkey-upload guard
        # (tools/check_no_per_batch_upload.py) — registry uploads land
        # under kernel="pubkey_registry", per-batch uploads under the
        # dispatching kernel's name
        self.device_upload_bytes = LabeledCounter(
            "device_upload_bytes_total",
            "host to device bytes uploaded, by kernel variant",
            ("kernel",),
        )
        # device-resident pubkey registry (tpu/registry.py)
        self.pubkey_registry_size = Gauge(
            "pubkey_registry_size",
            "validator pubkeys resident on the accelerator")
        self.pubkey_registry_events = LabeledCounter(
            "pubkey_registry_events_total",
            "registry lifecycle events "
            "(hit/miss/append/refresh/invalidate)",
            ("event",),
        )
        # bounded host-side device-point caches (hash-to-curve, …)
        self.device_cache_size = LabeledGauge(
            "device_cache_size",
            "entries held in bounded device-point caches, by cache",
            ("cache",),
        )
        self.device_cache_events = LabeledCounter(
            "device_cache_events_total",
            "cache lookups and evictions, by cache and event "
            "(hit/miss/evict)",
            ("cache", "event"),
        )
        # two-deep verify dispatch queue occupancy (0..2): batches
        # dispatched to the device whose readback has not completed
        self.verify_pipeline_depth = Gauge(
            "verify_pipeline_depth",
            "device verify batches in flight (dispatched, not settled)")
        # verify-plane stage attribution: host_prep / upload_bytes /
        # compile / execute / readback / fallback, split by lane since
        # the verify scheduler shares the device plane across object
        # kinds. lane defaults to "attestation" so pre-lane dashboards
        # and call sites keep resolving to the same series. Finer low
        # end than the defaults: host prep for a 64-att batch is
        # ~100 µs.
        self.verify_stage_seconds = LabeledHistogram(
            "verify_stage_seconds",
            "batch-verify latency, by pipeline stage and lane",
            ("stage", "lane"),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            ),
            defaults={"lane": "attestation"},
        )
        # verify scheduler (runtime/verify_scheduler.py): per-lane
        # queue occupancy, flushed batches by outcome, enqueue→flush
        # wait, and overload sheds (low lanes drop oldest-first rather
        # than stall block import)
        self.verify_lane_depth = LabeledGauge(
            "verify_lane_depth",
            "verify-scheduler jobs queued, by lane",
            ("lane",),
        )
        self.verify_lane_batches = LabeledCounter(
            "verify_lane_batches_total",
            "verify-scheduler batches flushed, by lane and result "
            "(ok/invalid/degraded)",
            ("lane", "result"),
        )
        self.verify_lane_wait_seconds = LabeledHistogram(
            "verify_lane_wait_seconds",
            "enqueue-to-flush wait of verify-scheduler jobs, by lane",
            ("lane",),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            ),
        )
        self.verify_lane_dropped = LabeledCounter(
            "verify_lane_dropped_total",
            "verify-scheduler jobs shed under overload, by lane",
            ("lane",),
        )
        # device signing plane (runtime/sign_plane.py): per-lane queue
        # occupancy, flushed batches by outcome (device = released
        # through the gate / degraded = gate or device fault re-signed
        # on the host anchor / host = breaker-open host signing),
        # enqueue→release wait, release-gate latency, and slashing-
        # interlock refusals. Labels are CLOSED sets — lane names and
        # refusal reasons are fixed enums, never per-key values.
        self.sign_lane_depth = LabeledGauge(
            "sign_lane_depth",
            "signing-plane requests queued, by lane",
            ("lane",),
        )
        self.sign_lane_batches = LabeledCounter(
            "sign_lane_batches_total",
            "signing-plane batches released, by lane and result "
            "(device/degraded/host)",
            ("lane", "result"),
        )
        self.sign_lane_wait_seconds = LabeledHistogram(
            "sign_lane_wait_seconds",
            "enqueue-to-release wait of signing-plane requests, by lane",
            ("lane",),
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            ),
        )
        # sheds/drops reuse verify_lane_dropped_total (the ONE drop
        # family — drop-counter-reuse lint): sign lanes carry their own
        # label values in it
        self.sign_release_gate_seconds = Histogram(
            "sign_release_gate_seconds",
            "release-gate batch-verify latency per signing batch",
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
            ),
        )
        self.sign_refused = LabeledCounter(
            "sign_refused_total",
            "signing requests refused by the slashing interlock before "
            "reaching a kernel, by reason "
            "(block_regression/attestation_regression)",
            ("reason",),
        )
        self.sign_pipeline_depth = Gauge(
            "sign_pipeline_depth",
            "signing batches in flight (dispatched, not released)",
        )
        # device health supervisor (runtime/health.py): breaker state
        # machine, canary re-promotion probes, settle watchdog, bounded
        # transient retries, and daemon-loop crash containment
        self.verify_breaker_state = LabeledGauge(
            "verify_breaker_state",
            "device circuit-breaker state (0=closed 1=open 2=half_open), "
            "by backend",
            ("backend",),
        )
        self.verify_breaker_transitions = LabeledCounter(
            "verify_breaker_transitions_total",
            "device circuit-breaker state transitions, by backend and "
            "entered state",
            ("backend", "state"),
        )
        self.verify_breaker_faults = LabeledCounter(
            "verify_breaker_faults_total",
            "faults filed with the device circuit breaker, by backend "
            "and kind (dispatch/settle/watchdog/verdict)",
            ("backend", "kind"),
        )
        self.verify_canary_probes = LabeledCounter(
            "verify_canary_probes_total",
            "HALF_OPEN canary probe batches, by backend and result "
            "(pass/fail)",
            ("backend", "result"),
        )
        self.verify_watchdog_fired = LabeledCounter(
            "verify_watchdog_fired_total",
            "device settles abandoned by the watchdog deadline, by lane",
            ("lane",),
        )
        self.verify_retry = LabeledCounter(
            "verify_retry_total",
            "bounded transient re-dispatches of a faulted device batch, "
            "by lane",
            ("lane",),
        )
        self.el_retries = Counter(
            "el_retry_total",
            "execution-engine call retries (capped exponential backoff "
            "with jitter)",
        )
        self.daemon_loop_failures = LabeledCounter(
            "daemon_loop_failures_total",
            "contained crashes of long-running daemon loops, by thread",
            ("thread",),
        )
        self.verify_recompiles = Counter(
            "verify_recompiles_total",
            "novel kernel shape signatures dispatched AFTER warmup "
            "declared completion — each one is an XLA compile stalling "
            "a live batch; steady state must hold at zero "
            "(tools/shapes manifest)",
        )
        # flight recorder (runtime/flight.py): per-lane SLO misses with
        # a CLOSED cause enum (flight.SLO_CAUSES — the lint rule
        # rejects values outside it), bucket-fill/padding-waste per
        # kernel (multi-chip capacity planning), and the duty-cycle /
        # occupancy gauges measuring the two-deep overlap. Origins are
        # NEVER labels here — they live only in the bounded flight
        # top-K table.
        self.verify_slo_miss = LabeledCounter(
            "verify_slo_miss_total",
            "verify batches that blew their lane's deadline budget, by "
            "lane and dominant cause (queue_wait/device/bisection/"
            "breaker_open/expired/brownout)",
            ("lane", "cause"),
        )
        self.verify_bucket_fill = LabeledHistogram(
            "verify_bucket_fill_ratio",
            "items over the pow-2 device bucket actually dispatched, "
            "by kernel",
            ("kernel",),
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )
        self.verify_padding_waste = LabeledCounter(
            "verify_padding_waste_total",
            "padded-out device batch slots (bucket minus items), by "
            "kernel",
            ("kernel",),
        )
        # adversarial isolation plane (runtime/isolation.py): on-device
        # fault localization passes, the quarantine lane, and per-origin
        # admission control. Origin identities are NEVER labels — the
        # `kernel` and `lane` labels here are closed sets; attribution
        # lives in the flight recorder's bounded top-K origin table.
        self.verify_isolation_passes = LabeledCounter(
            "verify_isolation_passes_total",
            "fault-localization passes run against a failed verify "
            "batch, by kernel (rlc_partition/g2_subgroup device passes, "
            "host for degraded host sweeps)",
            ("kernel",),
        )
        self.verify_quarantine_lane_depth = Gauge(
            "verify_quarantine_lane_depth",
            "verify jobs queued in the quarantine lane (suspect-origin "
            "traffic isolated from honest batches)",
        )
        self.verify_quarantine_batches = Counter(
            "verify_quarantine_batches_total",
            "verify batches flushed from the quarantine lane",
        )
        self.verify_admission_rejected = LabeledCounter(
            "verify_admission_rejected_total",
            "verify submissions rejected by per-origin fair-share "
            "admission control, by lane",
            ("lane",),
        )
        # brownout overload-control plane (runtime/brownout.py): the
        # current ladder level, every transition by endpoint pair (the
        # from/to labels are the CLOSED brownout.LEVELS enum — lint-
        # enforced like SLO causes), and deadline-budget expiries by
        # lane (the shed-before-dispatch path)
        self.verify_brownout_level = Gauge(
            "verify_brownout_level",
            "current brownout ladder level as an index into "
            "brownout.LEVELS (0=normal .. 4=critical)",
        )
        self.verify_brownout_transitions = LabeledCounter(
            "verify_brownout_transitions_total",
            "brownout ladder transitions, by from/to level (closed "
            "enum: normal/b1/b2/b3/critical)",
            ("from", "to"),
        )
        self.verify_expired = LabeledCounter(
            "verify_expired_total",
            "tickets shed because their absolute deadline passed "
            "before dispatch (the budget-expiry path), by lane",
            ("lane",),
        )
        self.verify_device_duty_cycle = Gauge(
            "verify_device_duty_cycle",
            "fraction of wall time with at least one verify batch on "
            "the device",
        )
        self.verify_pipeline_occupancy = Gauge(
            "verify_pipeline_occupancy",
            "time-weighted mean verify batches in flight (the two-deep "
            "overlap's real depth)",
        )
        # device-time profiling plane (runtime/profiler.py): dispatch→
        # settle deltas reconciled from committed flight records, live
        # device bytes by array family, and capture-session churn.
        # Labels are the CLOSED kernel/scheme/family sets — never
        # session ids (lint: metrics-cardinality)
        self.verify_device_seconds = LabeledCounter(
            "verify_device_seconds_total",
            "estimated device seconds attributed per kernel and scheme "
            "(flight-record dispatch-to-settle deltas)",
            ("kernel", "scheme"),
        )
        self.verify_device_hbm_bytes = LabeledGauge(
            "verify_device_hbm_bytes",
            "live device bytes by array family (jax.live_arrays "
            "snapshot, taken at session close or on demand)",
            ("family",),
        )
        self.verify_profile_sessions = Counter(
            "verify_profile_sessions_total",
            "profiler capture sessions started",
        )
        # bulk replay pipeline (runtime/replay.py): whole-window wall
        # time (transition+collect through settle), cross-block
        # signature sets and blocks verified, and how many windows are
        # in flight (dispatched, not settled — 0..pipeline_depth)
        self.replay_window_seconds = Histogram(
            "replay_window_seconds",
            "bulk replay window wall time, transition through settle",
            buckets=(
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                25.0, 60.0,
            ),
        )
        self.replay_sigsets = Counter(
            "replay_sigsets_total",
            "signature sets verified by the bulk replay pipeline",
        )
        self.replay_blocks = Counter(
            "replay_blocks_total",
            "blocks whose window batch settled valid in the bulk "
            "replay pipeline",
        )
        self.replay_pipeline_depth = Gauge(
            "replay_pipeline_depth",
            "replay windows in flight (dispatched, not settled)",
        )
        # slasher span plane (slasher.py): bounded LRU chunk-cache
        # traffic, batched span-update latency, and attesting indices
        # folded into the span store — the keep-up numerator the
        # --mainnet soak gates against the derived attestation arrival
        # rate. The event label is a closed set.
        self.slasher_chunk_cache_events = LabeledCounter(
            "slasher_chunk_cache_events_total",
            "slasher span-chunk cache lookups and evictions, by event "
            "(hit/miss/evict)",
            ("event",),
        )
        self.slasher_chunk_cache_size = Gauge(
            "slasher_chunk_cache_size",
            "span chunks held in the slasher's bounded LRU cache",
        )
        self.slasher_span_update_seconds = Histogram(
            "slasher_span_update_seconds",
            "batched slasher span-update duration, per aggregate or "
            "bulk window",
            buckets=(
                0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
            ),
        )
        self.slasher_span_indices = Counter(
            "slasher_span_indices_total",
            "attesting indices folded into the slasher span store",
        )
        # pubkey registry memory accounting (tpu/registry.py): the
        # mainnet-capacity audit's observables — allocated vs occupied
        # rows, host-mirror footprint, and device bytes total/per shard
        self.pubkey_registry_capacity = Gauge(
            "pubkey_registry_capacity",
            "allocated pubkey-registry rows (pow-2 device capacity)",
        )
        self.pubkey_registry_host_bytes = Gauge(
            "pubkey_registry_host_bytes",
            "host-mirror bytes held by the pubkey registry",
        )
        self.pubkey_registry_device_bytes = Gauge(
            "pubkey_registry_device_bytes",
            "device bytes held by the pubkey registry across all shards",
        )
        self.pubkey_registry_shard_bytes = Gauge(
            "pubkey_registry_shard_bytes",
            "device bytes per mesh shard in the pubkey registry",
        )

    def collect_system_stats(self, data_dir: "str | None" = None) -> None:
        """Refresh the /proc-sourced gauges (metrics/src/service.rs
        system-stats collection). Called from the /metrics handler so
        every scrape sees fresh values; all reads are best-effort."""
        import os

        try:
            with open("/proc/self/statm") as f:
                rss_pages = int(f.read().split()[1])
            self.process_resident_memory_bytes.set(
                rss_pages * os.sysconf("SC_PAGE_SIZE")
            )
        except (OSError, ValueError, IndexError):
            pass
        try:
            tck = os.sysconf("SC_CLK_TCK")
            with open("/proc/self/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            utime, stime = int(parts[11]), int(parts[12])
            self.process_cpu_seconds_total.set((utime + stime) / tck)
            with open("/proc/uptime") as f:
                uptime = float(f.read().split()[0])
            starttime = int(parts[19]) / tck
            self.process_start_time_seconds.set(
                time.time() - uptime + starttime
            )
        except (OSError, ValueError, IndexError):
            pass
        try:
            self.process_open_fds.set(len(os.listdir("/proc/self/fd")))
        except OSError:
            pass
        if data_dir:
            # the recursive walk is O(files); refresh at most once a
            # minute so Prometheus scrape latency stays flat as the DB
            # grows
            now = time.monotonic()
            if now - getattr(self, "_data_dir_scanned", 0.0) >= 60.0:
                self._data_dir_scanned = now
                try:
                    total = 0
                    for root, _dirs, files in os.walk(data_dir):
                        for name in files:
                            try:
                                total += os.path.getsize(
                                    os.path.join(root, name)
                                )
                            except OSError:
                                pass
                    self.data_dir_bytes.set(total)
                except OSError:
                    pass

    def all(self):
        return [
            v for v in vars(self).values()
            if isinstance(v, (Counter, Gauge, Histogram, _LabeledFamily))
        ]

    def expose(self) -> str:
        """Prometheus text exposition of every registered metric."""
        return "".join(m.expose() for m in self.all())


class RemoteMetricsService:
    """Periodic push of client stats to a beaconcha.in-style endpoint —
    reference metrics/src/service.rs (METRICS_UPDATE_INTERVAL = 60 s) +
    beaconchain.rs (the MetricsContent JSON shape: a list of
    {version, timestamp, process, ...} entries).

    `post` is an injected callable (url, json_body) → status for tests;
    the default uses urllib. Runs on a daemon thread; failures are
    counted, never raised (losing a stats push must not hurt the node)."""

    INTERVAL_S = 60.0

    def __init__(self, url: str, metrics: "Metrics", controller=None,
                 data_dir: "str | None" = None, post=None) -> None:
        self.url = url
        self.metrics = metrics
        self.controller = controller
        self.data_dir = data_dir
        self.post = post or self._default_post
        self.stats = {"pushes": 0, "failures": 0}
        #: guards `stats` (push thread + direct push_once callers) and
        #: the start()/stop() thread handle
        self._lock = threading.Lock()
        #: stop signal as an Event: set() from any thread, is_set()/wait()
        #: from the push loop — no bare-bool publication
        self._stop = threading.Event()
        self._thread = None

    @staticmethod
    def _default_post(url: str, body: dict) -> int:
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            url,
            data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status

    def snapshot_body(self) -> list:
        """The beaconcha.in client-stats payload (beaconchain.rs
        MetricsContent: one 'beaconnode' entry + one 'system' entry)."""
        self.metrics.collect_system_stats(self.data_dir)

        def g(m):
            v = m.value
            return v() if callable(v) else v
        beaconnode: dict = {
            "version": 1,
            "timestamp": int(time.time() * 1000),
            "process": "beaconnode",
            "cpu_process_seconds_total": g(
                self.metrics.process_cpu_seconds_total
            ),
            "memory_process_bytes": g(
                self.metrics.process_resident_memory_bytes
            ),
        }
        if self.controller is not None:
            snap = self.controller.snapshot()
            beaconnode["sync_beacon_head_slot"] = int(snap.head_state.slot)
            beaconnode["sync_eth2_synced"] = bool(
                snap.slot - int(snap.head_state.slot) <= 1
            )
        system = {
            "version": 1,
            "timestamp": beaconnode["timestamp"],
            "process": "system",
            "disk_beaconchain_bytes_total": g(self.metrics.data_dir_bytes),
            "memory_node_bytes_total": g(
                self.metrics.process_resident_memory_bytes
            ),
        }
        return [beaconnode, system]

    def push_once(self) -> bool:
        try:
            status = self.post(self.url, self.snapshot_body())
            ok = 200 <= int(status) < 300
        except Exception:
            ok = False
        with self._lock:
            self.stats["pushes" if ok else "failures"] += 1
        return ok

    def start(self) -> None:
        import threading

        def loop() -> None:
            # thread ownership: the single "metrics-push" daemon owns
            # this loop; it shares `stats` with direct push_once()
            # callers under _lock and watches the _stop Event
            while not self._stop.is_set():
                # push_once contains its own network errors, but snapshot
                # assembly reads live controller/metrics state — contain
                # every iteration so one bad snapshot can't kill the
                # push thread for the life of the process
                try:
                    self.push_once()
                    self._stop.wait(self.INTERVAL_S)
                except Exception:
                    with self._lock:
                        self.stats["failures"] += 1
                    self._stop.wait(1.0)

        with self._lock:
            if self._thread is not None:
                return  # already running: keep the singleton push loop
            self._thread = threading.Thread(
                target=loop, name="metrics-push", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()


__all__ = [
    "Counter", "Gauge", "Histogram",
    "LabeledCounter", "LabeledGauge", "LabeledHistogram",
    "Metrics", "RemoteMetricsService",
]
