"""State mutation seam: `StateDraft` + spec mutators.

Reference parity: helper_functions/src/mutators.rs (increase/decrease
balance, initiate_validator_exit, slash_validator) operating on
`&mut BeaconState`. Here states are immutable SSZ containers, so a block's
worth of mutations accumulates in a `StateDraft` — balances as one numpy
working array, registry edits as sparse per-index replacements — and
`commit()` produces the next immutable state. This keeps per-op cost O(1)
instead of O(registry) (a naive `SszList.set` would copy the 50k-entry
balance array for every reward) while preserving per-validator cached
hash-tree-roots for untouched validators.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.consensus import accessors, misc
from grandine_tpu.types.primitives import (
    FAR_FUTURE_EPOCH,
    PROPOSER_WEIGHT,
    WEIGHT_DENOMINATOR,
    Phase,
)


class StateDraft:
    """Mutable working copy of a BeaconState for one processing unit
    (a block, or a batch of slot updates). Reads fall through to the base
    state unless overridden; `commit()` builds the successor state."""

    __slots__ = (
        "base",
        "cfg",
        "p",
        "scratch",
        "_fields",
        "_balances",
        "_validators",
        "_exit_epoch_col",
    )

    def __init__(self, state, cfg) -> None:
        object.__setattr__(self, "base", state)
        object.__setattr__(self, "cfg", cfg)
        object.__setattr__(self, "p", cfg.preset)
        object.__setattr__(self, "scratch", {})  # never committed
        object.__setattr__(self, "_fields", {})
        object.__setattr__(self, "_balances", None)
        object.__setattr__(self, "_validators", None)
        object.__setattr__(self, "_exit_epoch_col", None)

    def __setattr__(self, *_):
        raise AttributeError("use set()/mutators; StateDraft fields are managed")

    # -- reads --------------------------------------------------------------

    def __getattr__(self, name):
        fields = object.__getattribute__(self, "_fields")
        if name in fields:
            return fields[name]
        return getattr(object.__getattribute__(self, "base"), name)

    @property
    def balances_array(self) -> np.ndarray:
        """Mutable uint64 working copy of state.balances."""
        if self._balances is None:
            base = object.__getattribute__(self, "base")
            arr = np.array(base.balances.array, dtype=np.uint64, copy=True)
            object.__setattr__(self, "_balances", arr)
        return self._balances

    @property
    def validators_list(self) -> list:
        """Mutable list of Validator containers (unchanged entries keep
        their cached hash-tree-roots)."""
        if self._validators is None:
            base = object.__getattribute__(self, "base")
            object.__setattr__(self, "_validators", list(base.validators))
        return self._validators

    def validator(self, index: int):
        if self._validators is not None:
            return self._validators[index]
        return object.__getattribute__(self, "base").validators[index]

    def num_validators(self) -> int:
        if self._validators is not None:
            return len(self._validators)
        return len(object.__getattribute__(self, "base").validators)

    def exit_epoch_column(self) -> np.ndarray:
        """uint64 working column of exit epochs (for churn scans)."""
        if self._exit_epoch_col is None:
            base = object.__getattribute__(self, "base")
            col = np.array(
                accessors.registry_columns(base).exit_epoch,
                dtype=np.uint64,
                copy=True,
            )
            if self._validators is not None:
                for i in range(len(col), len(self._validators)):
                    col = np.append(col, np.uint64(FAR_FUTURE_EPOCH))
            object.__setattr__(self, "_exit_epoch_col", col)
        return self._exit_epoch_col

    def array_field(self, name: str) -> np.ndarray:
        """Mutable numpy working copy of a packed-basic list field (e.g.
        participation columns, inactivity scores), committed like any other
        overridden field."""
        val = self._fields.get(name)
        if isinstance(val, np.ndarray):
            return val
        base_val = getattr(self, name)
        arr = np.array(base_val.array, copy=True)
        self._fields[name] = arr
        return arr

    # -- writes -------------------------------------------------------------

    def set(self, name: str, value) -> None:
        self._fields[name] = value

    def set_validator(self, index: int, validator) -> None:
        self.validators_list[index] = validator
        if self._exit_epoch_col is not None:
            self._exit_epoch_col[index] = np.uint64(int(validator.exit_epoch))

    def append_validator(self, validator, balance: int) -> None:
        self.validators_list.append(validator)
        arr = self.balances_array
        object.__setattr__(
            self, "_balances", np.append(arr, np.uint64(balance))
        )
        if self._exit_epoch_col is not None:
            object.__setattr__(
                self,
                "_exit_epoch_col",
                np.append(self._exit_epoch_col, np.uint64(int(validator.exit_epoch))),
            )

    # -- commit -------------------------------------------------------------

    def commit(self):
        base = object.__getattribute__(self, "base")
        changes = dict(self._fields)
        if self._validators is not None:
            changes["validators"] = self._validators
        if self._balances is not None:
            changes["balances"] = self._balances
        return base.replace(**changes) if changes else base


# --- balance mutators -------------------------------------------------------


def increase_balance(draft: StateDraft, index: int, delta: int) -> None:
    arr = draft.balances_array
    arr[index] = np.uint64(int(arr[index]) + int(delta))


def decrease_balance(draft: StateDraft, index: int, delta: int) -> None:
    """Saturating at zero (spec decrease_balance)."""
    arr = draft.balances_array
    cur = int(arr[index])
    arr[index] = np.uint64(max(0, cur - int(delta)))


# --- validator lifecycle ----------------------------------------------------


def initiate_validator_exit(draft: StateDraft, index: int) -> None:
    """Spec `initiate_validator_exit`: assign the exit-queue epoch bounded
    by the churn limit. Churn scans are vectorized over the draft's
    exit-epoch column."""
    v = draft.validator(index)
    if int(v.exit_epoch) != FAR_FUTURE_EPOCH:
        return
    p = draft.p
    cfg = draft.cfg
    base = object.__getattribute__(draft, "base")
    current_epoch = accessors.get_current_epoch(base, p)

    col = draft.exit_epoch_column()
    exiting = col[col != np.uint64(FAR_FUTURE_EPOCH)]
    floor = misc.compute_activation_exit_epoch(current_epoch, p)
    exit_queue_epoch = max(int(exiting.max()), floor) if len(exiting) else floor
    churn = int((col == np.uint64(exit_queue_epoch)).sum())
    active_count = len(accessors.get_active_validator_indices(base, current_epoch))
    if churn >= misc.get_validator_churn_limit(active_count, cfg):
        exit_queue_epoch += 1

    draft.set_validator(
        index,
        v.replace(
            exit_epoch=exit_queue_epoch,
            withdrawable_epoch=exit_queue_epoch
            + cfg.min_validator_withdrawability_delay,
        ),
    )


def slashing_penalty_quotient(p, phase: Phase) -> int:
    if phase >= Phase.BELLATRIX:
        return p.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    if phase >= Phase.ALTAIR:
        return p.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    return p.MIN_SLASHING_PENALTY_QUOTIENT


def proportional_slashing_multiplier(p, phase: Phase) -> int:
    if phase >= Phase.BELLATRIX:
        return p.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX
    if phase >= Phase.ALTAIR:
        return p.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    return p.PROPORTIONAL_SLASHING_MULTIPLIER


def slash_validator(
    draft: StateDraft,
    slashed_index: int,
    phase: Phase,
    whistleblower_index: "int | None" = None,
) -> None:
    """Spec `slash_validator` with per-fork penalty quotients and the
    altair proposer-weight reward split."""
    p = draft.p
    base = object.__getattribute__(draft, "base")
    epoch = accessors.get_current_epoch(base, p)
    initiate_validator_exit(draft, slashed_index)
    v = draft.validator(slashed_index)
    draft.set_validator(
        slashed_index,
        v.replace(
            slashed=True,
            withdrawable_epoch=max(
                int(v.withdrawable_epoch), epoch + p.EPOCHS_PER_SLASHINGS_VECTOR
            ),
        ),
    )
    eb = int(v.effective_balance)
    slot_index = epoch % p.EPOCHS_PER_SLASHINGS_VECTOR
    slashings = draft.slashings
    draft.set(
        "slashings", slashings.set(slot_index, int(slashings[slot_index]) + eb)
    )
    decrease_balance(draft, slashed_index, eb // slashing_penalty_quotient(p, phase))

    proposer_index = accessors.get_beacon_proposer_index(base, p)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = eb // p.WHISTLEBLOWER_REWARD_QUOTIENT
    if phase >= Phase.ALTAIR:
        proposer_reward = (
            whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
        )
    else:
        proposer_reward = whistleblower_reward // p.PROPOSER_REWARD_QUOTIENT
    increase_balance(draft, proposer_index, proposer_reward)
    increase_balance(draft, whistleblower_index, whistleblower_reward - proposer_reward)


__all__ = [
    "StateDraft",
    "increase_balance",
    "decrease_balance",
    "initiate_validator_exit",
    "slash_validator",
    "slashing_penalty_quotient",
    "proportional_slashing_multiplier",
]
