"""Spec `misc`/time/domain math — reference: helper_functions/src/misc.rs
(`compute_signing_root` misc.rs:122, `compute_domain`, epoch/slot/committee
arithmetic) over the framework's own SSZ containers.

All functions are pure; anything that needs registry-wide data takes numpy
arrays so callers (accessors.EpochCache) stay vectorized.
"""

from __future__ import annotations

import hashlib

import numpy as np

from grandine_tpu.core.shuffling import shuffled_indices
from grandine_tpu.ssz import Bytes4, Bytes32, Container, uint64
from grandine_tpu.ssz.base import ContainerMeta
from grandine_tpu.types.preset import Preset
from grandine_tpu.types.primitives import (
    DOMAIN_BEACON_PROPOSER,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
)


def _container(name: str, fields: dict) -> ContainerMeta:
    return ContainerMeta(name, (Container,), {"__annotations__": dict(fields)})


# Preset-independent signing containers. Structurally identical to the
# per-preset `spec_types(...)` versions (same field layout ⇒ same roots);
# defined locally so domain math has no preset dependency.
ForkData = _container(
    "ForkData", dict(current_version=Bytes4, genesis_validators_root=Bytes32)
)
SigningData = _container("SigningData", dict(object_root=Bytes32, domain=Bytes32))


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def uint_to_bytes(n: int, size: int = 8) -> bytes:
    return int(n).to_bytes(size, "little")


def bytes_to_uint64(data: bytes) -> int:
    return int.from_bytes(data[:8], "little")


def integer_squareroot(n: int) -> int:
    # math.isqrt is exact for arbitrary ints (spec integer_squareroot)
    import math

    return math.isqrt(int(n))


def xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


# --- time ------------------------------------------------------------------


def compute_epoch_at_slot(slot: int, p: Preset) -> int:
    return slot // p.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(epoch: int, p: Preset) -> int:
    return epoch * p.SLOTS_PER_EPOCH


def compute_activation_exit_epoch(epoch: int, p: Preset) -> int:
    return epoch + 1 + p.MAX_SEED_LOOKAHEAD


def sync_committee_period(slot: int, p: Preset) -> int:
    """Which sync-committee rotation a slot belongs to (altair
    `compute_sync_committee_period` over compute_epoch_at_slot)."""
    return slot // p.SLOTS_PER_EPOCH // p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD


def is_sync_committee_aggregator(
    signature: bytes, p: Preset, subnet_count: int
) -> bool:
    """Altair `is_sync_committee_aggregator`: the selection proof elects
    its signer when sha256(proof)[:8] mod the per-subcommittee modulo is
    zero (validator/sync_committee.md)."""
    from grandine_tpu.types.primitives import (
        TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )

    modulo = max(
        1,
        p.SYNC_COMMITTEE_SIZE
        // subnet_count
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    return bytes_to_uint64(sha256(bytes(signature))[:8]) % modulo == 0


# --- committees ------------------------------------------------------------


def committee_count_per_slot(active_count: int, p: Preset) -> int:
    return max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            active_count // p.SLOTS_PER_EPOCH // p.TARGET_COMMITTEE_SIZE,
        ),
    )


def compute_committee_partition(
    active_indices: np.ndarray, seed: bytes, p: Preset
) -> "list[np.ndarray]":
    """All committees of one epoch in order: the whole-list shuffle applied
    once, then sliced into SLOTS_PER_EPOCH × committees_per_slot pieces (the
    spec's `compute_committee` for every (slot, index) pair).

    Committee k (k = (slot % SLOTS_PER_EPOCH) * count + index) is
    `active[sigma[n*k//total : n*(k+1)//total]]`.
    """
    n = len(active_indices)
    sigma = shuffled_indices(seed, n, p.SHUFFLE_ROUND_COUNT)
    shuffled = np.asarray(active_indices)[sigma]
    count = committee_count_per_slot(n, p) * p.SLOTS_PER_EPOCH
    return [
        shuffled[n * k // count : n * (k + 1) // count] for k in range(count)
    ]


def compute_proposer_index(
    effective_balances: np.ndarray,
    active_indices: np.ndarray,
    seed: bytes,
    p: Preset,
) -> int:
    """Spec `compute_proposer_index` (effective-balance-weighted rejection
    sampling). `effective_balances` is the whole-registry column in Gwei.

    Uses the single-index shuffle per candidate: the proposer seed is
    per-slot, so a whole-list shuffle could never be reused — a handful of
    90-hash walks beats an O(n) shuffle every slot."""
    from grandine_tpu.core.shuffling import compute_shuffled_index

    total = len(active_indices)
    if total == 0:
        raise ValueError("empty active validator set")
    max_eb = p.MAX_EFFECTIVE_BALANCE
    i = 0
    while True:
        pos = compute_shuffled_index(i % total, total, seed, p.SHUFFLE_ROUND_COUNT)
        candidate = int(active_indices[pos])
        random_byte = sha256(seed + uint_to_bytes(i // 32))[i % 32]
        if int(effective_balances[candidate]) * 0xFF >= max_eb * random_byte:
            return candidate
        i += 1


# --- forks / domains / signing roots ---------------------------------------


def compute_fork_data_root(
    current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return ForkData(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    ).hash_tree_root()


def compute_fork_digest(
    current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes = b"\x00" * 4,
    genesis_validators_root: bytes = b"\x00" * 32,
) -> bytes:
    root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + root[:28]


def get_domain(state, domain_type: bytes, epoch: "int | None", p: Preset) -> bytes:
    """Spec `get_domain` over a BeaconState: picks previous/current fork
    version by epoch (helper_functions/src/accessors.rs get_domain)."""
    if epoch is None:
        epoch = compute_epoch_at_slot(int(state.slot), p)
    fork = state.fork
    version = (
        bytes(fork.previous_version)
        if epoch < int(fork.epoch)
        else bytes(fork.current_version)
    )
    return compute_domain(domain_type, version, bytes(state.genesis_validators_root))


def compute_signing_root(obj, domain: bytes) -> bytes:
    """Spec `compute_signing_root` (helper_functions/src/misc.rs:122).
    `obj` is a Container (its hash_tree_root is taken) or a 32-byte root."""
    root = obj if isinstance(obj, bytes) else obj.hash_tree_root()
    return SigningData(object_root=root, domain=domain).hash_tree_root()


# --- seeds -----------------------------------------------------------------


def get_randao_mix(state, epoch: int, p: Preset) -> bytes:
    return bytes(state.randao_mixes[epoch % p.EPOCHS_PER_HISTORICAL_VECTOR])


def get_seed(state, epoch: int, domain_type: bytes, p: Preset) -> bytes:
    mix = get_randao_mix(
        state, epoch + p.EPOCHS_PER_HISTORICAL_VECTOR - p.MIN_SEED_LOOKAHEAD - 1, p
    )
    return sha256(domain_type + uint_to_bytes(epoch) + mix)


def proposer_seed(state, slot: int, p: Preset) -> bytes:
    epoch = compute_epoch_at_slot(slot, p)
    return sha256(
        get_seed(state, epoch, DOMAIN_BEACON_PROPOSER, p) + uint_to_bytes(slot)
    )


# --- misc registry math ----------------------------------------------------


def get_validator_churn_limit(active_count: int, cfg) -> int:
    return max(
        cfg.min_per_epoch_churn_limit, active_count // cfg.churn_limit_quotient
    )


def get_validator_activation_churn_limit(active_count: int, cfg) -> int:
    """Deneb caps the activation churn (EIP-7514)."""
    return min(
        cfg.max_per_epoch_activation_churn_limit,
        get_validator_churn_limit(active_count, cfg),
    )


__all__ = [
    "ForkData",
    "SigningData",
    "sha256",
    "uint_to_bytes",
    "bytes_to_uint64",
    "integer_squareroot",
    "xor",
    "compute_epoch_at_slot",
    "compute_start_slot_at_epoch",
    "compute_activation_exit_epoch",
    "sync_committee_period",
    "is_sync_committee_aggregator",
    "committee_count_per_slot",
    "compute_committee_partition",
    "compute_proposer_index",
    "compute_fork_data_root",
    "compute_fork_digest",
    "compute_domain",
    "get_domain",
    "compute_signing_root",
    "get_randao_mix",
    "get_seed",
    "proposer_seed",
    "get_validator_churn_limit",
    "get_validator_activation_churn_limit",
]
