"""The batch-verification seam — reference: helper_functions/src/verifier.rs
(`Verifier` trait :16-69; `NullVerifier` :121, `SingleVerifier` :171,
`MultiVerifier` :250 with one `multi_verify` in `finish()` :302-323).

This is the ONE place that knows which BLS backend runs a batch:

  NullVerifier   — trust everything (own blocks, spec replay of pre-checked
                   data)
  SingleVerifier — eager per-signature verification on the anchor (fails
                   fast; used to isolate bad items after a batch failure)
  MultiVerifier  — accumulate `Triple`s, one anchor RLC batch in finish()
  TpuVerifier    — accumulate `Triple`s, ship ONE padded batch to
                   `TpuBlsBackend.multi_verify` (the accelerator plane)
  CollectingVerifier — defer everything into an external sink spanning
                   MANY blocks (the bulk replay pipeline's window mode)

Transition/fork-choice code takes a `Verifier` argument and never sees the
backend choice, exactly like the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto import constants


class SignatureInvalid(Exception):
    """A signature (or batch of signatures) failed verification."""


class Triple:
    """One deferred signature check: 32-byte signing root (the BLS message),
    96-byte compressed signature, and the (possibly aggregated) public key
    point (verifier.rs `Triple`)."""

    __slots__ = ("message", "signature", "public_key")

    def __init__(self, message: bytes, signature: bytes, public_key: "A.PublicKey"):
        self.message = bytes(message)
        self.signature = bytes(signature)
        self.public_key = public_key

    def __repr__(self) -> str:
        return f"Triple(msg={self.message.hex()[:16]}…)"


class Verifier:
    """Interface. `verify_singular`/`verify_aggregate` enqueue or eagerly
    check one signature; `extend` takes prebuilt triples; `finish` settles
    whatever was deferred, raising SignatureInvalid on failure."""

    def verify_singular(
        self, message: bytes, signature: bytes, public_key: "A.PublicKey"
    ) -> None:
        raise NotImplementedError

    def verify_aggregate(
        self,
        message: bytes,
        signature: bytes,
        public_keys: "Sequence[A.PublicKey]",
    ) -> None:
        """fast_aggregate_verify shape: many signers, one message. The key
        aggregation happens here (host G1 adds); an aggregate that sums to
        the identity is rejected at verification time (infinity pubkey)."""
        if not public_keys:
            raise SignatureInvalid("aggregate with no public keys")
        self.verify_singular(message, signature, A.PublicKey.aggregate(public_keys))

    def verify_aggregate_indexed(
        self,
        message: bytes,
        signature: bytes,
        member_indices: "Sequence[int]",
        pubkey_columns,
    ) -> None:
        """fast_aggregate_verify with the signer set named by registry row
        indices into the state's compressed pubkey columns — the geometry
        device backends need to gather keys from the resident registry
        (tpu/registry.py) without the host decompressing them. Default:
        decompress and delegate, so host verifiers keep their exact
        semantics."""
        if not member_indices:
            raise SignatureInvalid("aggregate with no public keys")
        from grandine_tpu.consensus import keys

        try:
            pks = [
                keys.decompress_pubkey(
                    bytes(pubkey_columns[int(i)]), trusted=True
                )
                for i in member_indices
            ]
        except Exception as e:
            raise SignatureInvalid(f"invalid registry pubkey: {e}") from e
        self.verify_aggregate(message, signature, pks)

    def extend(self, triples: "Sequence[Triple]") -> None:
        for t in triples:
            self.verify_singular(t.message, t.signature, t.public_key)

    def finish(self) -> None:
        pass

    def finish_async(self):
        """Dispatch whatever finish() would settle, returning a zero-arg
        callable that completes it (raising SignatureInvalid on failure).
        Backends with true async dispatch (TPU) overlap the device batch
        with host work between the two calls — the verify-∥-process split."""
        self.finish()
        return lambda: None

    # has_option_to_defer in the reference: lets callers skip building
    # triples when verification is a no-op (NullVerifier).
    def is_null(self) -> bool:
        return False


class NullVerifier(Verifier):
    """Trust every signature (verifier.rs:121 — used for own blocks and
    trusted replays)."""

    def verify_singular(self, message, signature, public_key) -> None:
        pass

    def verify_aggregate(self, message, signature, public_keys) -> None:
        pass

    def verify_aggregate_indexed(
        self, message, signature, member_indices, pubkey_columns
    ) -> None:
        pass

    def extend(self, triples) -> None:
        pass

    def is_null(self) -> bool:
        return True


class SingleVerifier(Verifier):
    """Eager per-signature verification (verifier.rs:171). Decompresses and
    checks immediately — the fallback that isolates a bad signature after a
    batch rejection."""

    def verify_singular(self, message, signature, public_key) -> None:
        try:
            sig = A.Signature.from_bytes(signature)
        except A.BlsError as e:
            raise SignatureInvalid(f"malformed signature: {e}") from e
        if not sig.verify(bytes(message), public_key):
            raise SignatureInvalid(f"invalid signature over {bytes(message).hex()}")


class CollectingVerifier(Verifier):
    """Defer every check into an external cross-block sink; finish() is a
    no-op. The bulk replay pipeline (runtime/replay.py) runs
    `custom_state_transition` over a WINDOW of blocks with one of these,
    so the signatures of all blocks in the window accumulate into shared
    device batches instead of one dispatch per block.

    `sink` is duck-typed: `sink.add(message, signature, public_keys=...)`
    or `sink.add(message, signature, member_indices=..., pubkey_columns=
    ...)`. Structural rejections that the host verifiers raise at collect
    time (empty aggregates) still raise here — they are properties of the
    block, not of any signature batch."""

    def __init__(self, sink) -> None:
        self.sink = sink

    def verify_singular(self, message, signature, public_key) -> None:
        self.sink.add(message, signature, public_keys=(public_key,))

    def verify_aggregate(self, message, signature, public_keys) -> None:
        if not public_keys:
            raise SignatureInvalid("aggregate with no public keys")
        self.sink.add(message, signature, public_keys=tuple(public_keys))

    def verify_aggregate_indexed(
        self, message, signature, member_indices, pubkey_columns
    ) -> None:
        if not member_indices:
            raise SignatureInvalid("aggregate with no public keys")
        self.sink.add(
            message,
            signature,
            member_indices=tuple(int(i) for i in member_indices),
            pubkey_columns=pubkey_columns,
        )

    def extend(self, triples) -> None:
        for t in triples:
            self.sink.add(t.message, t.signature, public_keys=(t.public_key,))


class MultiVerifier(Verifier):
    """Accumulate triples; one anchor RLC `multi_verify` in finish()
    (verifier.rs:250,302-323)."""

    def __init__(self) -> None:
        self.triples: "list[Triple]" = []

    def verify_singular(self, message, signature, public_key) -> None:
        self.triples.append(Triple(message, signature, public_key))

    def extend(self, triples) -> None:
        self.triples.extend(triples)

    def _decompress(self):
        messages = []
        signatures = []
        keys = []
        for t in self.triples:
            try:
                signatures.append(A.Signature.from_bytes(t.signature))
            except A.BlsError as e:
                raise SignatureInvalid(f"malformed signature: {e}") from e
            messages.append(t.message)
            keys.append(t.public_key)
        return messages, signatures, keys

    def finish(self) -> None:
        if not self.triples:
            return
        messages, signatures, keys = self._decompress()
        if not A.multi_verify(messages, signatures, keys):
            raise SignatureInvalid(f"batch of {len(messages)} failed multi_verify")
        self.triples = []


class TpuVerifier(MultiVerifier):
    """MultiVerifier whose finish() ships the batch to the device backend —
    the TPU instantiation of the seam (SURVEY.md §2.2: a TpuVerifier in
    finish() requires zero changes to transition/fork-choice code)."""

    def __init__(self, backend=None) -> None:
        super().__init__()
        if backend is None:
            from grandine_tpu.tpu.bls import TpuBlsBackend

            backend = TpuBlsBackend()
        self.backend = backend

    def finish(self) -> None:
        self.finish_async()()

    def finish_async(self):
        """Dispatch the device batch now; the returned callable forces the
        result (XLA async-execution overlap for the verify-∥-process split)."""
        if not self.triples:
            return lambda: None
        messages, signatures, keys = self._decompress()
        n = len(messages)
        self.triples = []
        pending = self.backend.multi_verify_async(messages, signatures, keys)

        def settle() -> None:
            if not pending():
                raise SignatureInvalid(f"batch of {n} failed device multi_verify")

        return settle


__all__ = [
    "SignatureInvalid",
    "Triple",
    "Verifier",
    "NullVerifier",
    "CollectingVerifier",
    "SingleVerifier",
    "MultiVerifier",
    "TpuVerifier",
]
