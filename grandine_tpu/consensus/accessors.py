"""Spec accessors with registry-columnar caching — reference:
helper_functions/src/accessors.rs (committees, proposer index, cached
shuffled indices, total balances) and types/src/cache.rs (intra-state
caches).

TPU-first design: the validator registry is viewed as numpy *columns*
(effective balance, activation/exit epochs, slashed) so every registry-wide
computation — active sets, churn, epoch deltas — is a vectorized array op,
not a per-validator loop. The expensive artifacts (whole-list shuffles,
committee partitions) are memoized in bounded module-level caches keyed
*structurally* (shuffle seed + digest of the active set), so they are shared
across the many states of one epoch — the same economy the reference gets
from types/src/cache.rs, without tying cache lifetime to one state object.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Sequence

import numpy as np

from grandine_tpu.consensus import misc
from grandine_tpu.consensus.misc import (
    committee_count_per_slot,
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
)
from grandine_tpu.types.preset import Preset
from grandine_tpu.types.primitives import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_SYNC_COMMITTEE,
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
)


# One coarse lock for all accessor caches: they are hit concurrently from
# the controller's parallel validation tasks; get+move_to_end / put+evict
# are not atomic on their own.
_CACHE_LOCK = threading.Lock()


def _lru_put(cache: OrderedDict, key, value, cap: int) -> None:
    cache[key] = value
    cache.move_to_end(key)
    while len(cache) > cap:
        cache.popitem(last=False)


# --------------------------------------------------------- registry columns


class RegistryColumns:
    """Columnar numpy view of `state.validators` (one array per field)."""

    __slots__ = (
        "pubkeys",
        "withdrawal_credentials",
        "effective_balance",
        "slashed",
        "activation_eligibility_epoch",
        "activation_epoch",
        "exit_epoch",
        "withdrawable_epoch",
    )

    def __init__(self, validators) -> None:
        vs = list(validators)
        n = len(vs)
        self.pubkeys = tuple(bytes(v.pubkey) for v in vs)
        self.withdrawal_credentials = tuple(
            bytes(v.withdrawal_credentials) for v in vs
        )
        self.effective_balance = np.fromiter(
            (int(v.effective_balance) for v in vs), np.uint64, n
        )
        self.slashed = np.fromiter((bool(v.slashed) for v in vs), bool, n)
        self.activation_eligibility_epoch = np.fromiter(
            (int(v.activation_eligibility_epoch) for v in vs), np.uint64, n
        )
        self.activation_epoch = np.fromiter(
            (int(v.activation_epoch) for v in vs), np.uint64, n
        )
        self.exit_epoch = np.fromiter(
            (int(v.exit_epoch) for v in vs), np.uint64, n
        )
        self.withdrawable_epoch = np.fromiter(
            (int(v.withdrawable_epoch) for v in vs), np.uint64, n
        )

    def __len__(self) -> int:
        return len(self.pubkeys)

    def active_indices(self, epoch: int) -> np.ndarray:
        e = np.uint64(epoch)
        return np.nonzero(
            (self.activation_epoch <= e) & (e < self.exit_epoch)
        )[0].astype(np.int64)


_COLUMNS_CACHE: OrderedDict = OrderedDict()  # id(items) -> (items, columns)


def registry_columns(state) -> RegistryColumns:
    """Columns for `state.validators`, cached by registry identity (states
    sharing an unmodified registry — the common case within an epoch —
    share one columnar view)."""
    items = state.validators.items
    key = id(items)
    with _CACHE_LOCK:
        hit = _COLUMNS_CACHE.get(key)
        if hit is not None and hit[0] is items:
            _COLUMNS_CACHE.move_to_end(key)
            return hit[1]
    cols = RegistryColumns(state.validators)
    with _CACHE_LOCK:
        _lru_put(_COLUMNS_CACHE, key, (items, cols), cap=8)
    return cols


def _active_digest(active: np.ndarray) -> bytes:
    return hashlib.blake2b(active.tobytes(), digest_size=16).digest()


# ------------------------------------------------------------ shuffle caches

# (seed, active-digest) -> shuffled active indices / committee partition.
# Structurally keyed: reusable across every state that shares the seed and
# active set (all states of an epoch, across forks with a common mix).
_SHUFFLE_CACHE: OrderedDict = OrderedDict()
_PARTITION_CACHE: OrderedDict = OrderedDict()


def shuffled_active_indices(
    seed: bytes, active: np.ndarray, p: Preset
) -> np.ndarray:
    key = (seed, _active_digest(active))
    with _CACHE_LOCK:
        hit = _SHUFFLE_CACHE.get(key)
        if hit is not None:
            _SHUFFLE_CACHE.move_to_end(key)
            return hit
    from grandine_tpu.core.shuffling import shuffled_indices

    sigma = shuffled_indices(seed, len(active), p.SHUFFLE_ROUND_COUNT)
    hit = np.asarray(active)[sigma]
    with _CACHE_LOCK:
        _lru_put(_SHUFFLE_CACHE, key, hit, cap=16)
    return hit


def committee_partition(
    seed: bytes, active: np.ndarray, p: Preset
) -> "list[np.ndarray]":
    """All committees of the epoch with shuffle seed `seed`, flat-indexed
    k = (slot % SLOTS_PER_EPOCH) * committees_per_slot + committee_index."""
    key = (seed, _active_digest(active))
    with _CACHE_LOCK:
        hit = _PARTITION_CACHE.get(key)
        if hit is not None:
            _PARTITION_CACHE.move_to_end(key)
            return hit
    shuffled = shuffled_active_indices(seed, active, p)
    n = len(shuffled)
    count = committee_count_per_slot(n, p) * p.SLOTS_PER_EPOCH
    hit = [
        shuffled[n * k // count : n * (k + 1) // count] for k in range(count)
    ]
    with _CACHE_LOCK:
        _lru_put(_PARTITION_CACHE, key, hit, cap=16)
    return hit


# ------------------------------------------------------------ time & roots


def get_current_epoch(state, p: Preset) -> int:
    return compute_epoch_at_slot(int(state.slot), p)


def get_previous_epoch(state, p: Preset) -> int:
    cur = get_current_epoch(state, p)
    return GENESIS_EPOCH if cur == GENESIS_EPOCH else cur - 1


def get_block_root_at_slot(state, slot: int, p: Preset) -> bytes:
    if not slot < int(state.slot) <= slot + p.SLOTS_PER_HISTORICAL_ROOT:
        raise ValueError(f"slot {slot} outside historical root window")
    return bytes(state.block_roots[slot % p.SLOTS_PER_HISTORICAL_ROOT])


def get_block_root(state, epoch: int, p: Preset) -> bytes:
    return get_block_root_at_slot(state, compute_start_slot_at_epoch(epoch, p), p)


# ------------------------------------------------------------- active sets


def get_active_validator_indices(state, epoch: int) -> np.ndarray:
    return registry_columns(state).active_indices(epoch)


def get_total_balance(state, indices, p: Preset) -> int:
    cols = registry_columns(state)
    idx = np.asarray(list(indices), dtype=np.int64)
    total = int(cols.effective_balance[idx].sum()) if len(idx) else 0
    return max(p.EFFECTIVE_BALANCE_INCREMENT, total)


def get_total_active_balance(state, p: Preset) -> int:
    cols = registry_columns(state)
    active = cols.active_indices(get_current_epoch(state, p))
    total = int(cols.effective_balance[active].sum()) if len(active) else 0
    return max(p.EFFECTIVE_BALANCE_INCREMENT, total)


# -------------------------------------------------------------- committees


def get_committee_count_per_slot(state, epoch: int, p: Preset) -> int:
    return committee_count_per_slot(
        len(get_active_validator_indices(state, epoch)), p
    )


def _attester_partition(state, epoch: int, p: Preset) -> "list[np.ndarray]":
    seed = misc.get_seed(state, epoch, DOMAIN_BEACON_ATTESTER, p)
    active = get_active_validator_indices(state, epoch)
    if len(active) == 0:
        raise ValueError(f"no active validators at epoch {epoch}")
    return committee_partition(seed, active, p)


def get_beacon_committee(state, slot: int, index: int, p: Preset) -> np.ndarray:
    epoch = compute_epoch_at_slot(slot, p)
    partition = _attester_partition(state, epoch, p)
    per_slot = len(partition) // p.SLOTS_PER_EPOCH
    if index >= per_slot:
        raise ValueError(f"committee index {index} >= {per_slot}")
    return partition[(slot % p.SLOTS_PER_EPOCH) * per_slot + index]


def get_beacon_proposer_index(state, p: Preset) -> int:
    slot = int(state.slot)
    epoch = compute_epoch_at_slot(slot, p)
    seed = misc.proposer_seed(state, slot, p)
    cols = registry_columns(state)
    active = cols.active_indices(epoch)
    return misc.compute_proposer_index(cols.effective_balance, active, seed, p)


# ------------------------------------------------------------ attestations


def get_attesting_indices(state, data, aggregation_bits, p: Preset) -> np.ndarray:
    committee = get_beacon_committee(state, int(data.slot), int(data.index), p)
    bits = np.asarray(aggregation_bits.array, dtype=bool)
    if len(bits) != len(committee):
        raise ValueError(
            f"aggregation bits {len(bits)} != committee size {len(committee)}"
        )
    return committee[bits]


def get_indexed_attestation(state, attestation, types_ns, p: Preset):
    """Spec `get_indexed_attestation` → an IndexedAttestation container from
    `types_ns` (the fork namespace of `spec_types`)."""
    indices = get_attesting_indices(
        state, attestation.data, attestation.aggregation_bits, p
    )
    return types_ns.IndexedAttestation(
        attesting_indices=sorted(int(i) for i in indices),
        data=attestation.data,
        signature=bytes(attestation.signature),
    )


# ----------------------------------------------------------- altair rewards


def get_base_reward_per_increment(state, p: Preset) -> int:
    return (
        p.EFFECTIVE_BALANCE_INCREMENT
        * p.BASE_REWARD_FACTOR
        // misc.integer_squareroot(get_total_active_balance(state, p))
    )


def get_base_reward(state, index: int, p: Preset) -> int:
    """Altair per-validator base reward (increments × per-increment)."""
    cols = registry_columns(state)
    increments = int(cols.effective_balance[index]) // p.EFFECTIVE_BALANCE_INCREMENT
    return increments * get_base_reward_per_increment(state, p)


def has_flag(flags: int, flag_index: int) -> bool:
    return bool((int(flags) >> flag_index) & 1)


def add_flag(flags: int, flag_index: int) -> int:
    return int(flags) | (1 << flag_index)


def get_unslashed_participating_mask(
    state, flag_index: int, epoch: int, p: Preset
) -> np.ndarray:
    """Boolean registry mask of unslashed validators active at `epoch` with
    `flag_index` set in that epoch's participation column (vectorized twin
    of spec `get_unslashed_participating_indices`)."""
    cur = get_current_epoch(state, p)
    if epoch not in (cur, get_previous_epoch(state, p)):
        raise ValueError("participation is only tracked for current/previous")
    col = (
        state.current_epoch_participation
        if epoch == cur
        else state.previous_epoch_participation
    )
    flags = np.asarray(col.array, dtype=np.uint8)
    cols = registry_columns(state)
    active = np.zeros(len(cols), dtype=bool)
    active[cols.active_indices(epoch)] = True
    flag_bit = (flags >> flag_index) & 1
    return active & (flag_bit == 1) & ~cols.slashed


def get_attestation_participation_flag_indices(
    state, data, inclusion_delay: int, cfg, phase
) -> "list[int]":
    """Altair+ `get_attestation_participation_flag_indices`. Raises on a
    non-matching source (structural invalidity)."""
    from grandine_tpu.types.primitives import Phase

    p = cfg.preset
    cur = get_current_epoch(state, p)
    if int(data.target.epoch) == cur:
        justified = state.current_justified_checkpoint
    else:
        justified = state.previous_justified_checkpoint
    matching_source = data.source == justified
    if not matching_source:
        raise ValueError("attestation source does not match justified checkpoint")
    matching_target = (
        bytes(data.target.root) == get_block_root(state, int(data.target.epoch), p)
    )
    matching_head = matching_target and (
        bytes(data.beacon_block_root)
        == get_block_root_at_slot(state, int(data.slot), p)
    )
    flags = []
    if inclusion_delay <= misc.integer_squareroot(p.SLOTS_PER_EPOCH):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if matching_target and (
        phase >= Phase.DENEB or inclusion_delay <= p.SLOTS_PER_EPOCH
    ):
        # EIP-7045 (deneb) drops the target inclusion-delay cap
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if matching_head and inclusion_delay == p.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


# ----------------------------------------------------------- sync committee


def get_next_sync_committee_indices(state, cfg) -> "list[int]":
    """Altair `get_next_sync_committee_indices`: effective-balance-weighted
    rejection sampling, SYNC_COMMITTEE_SIZE picks (with replacement)."""
    p = cfg.preset
    epoch = get_current_epoch(state, p) + 1
    cols = registry_columns(state)
    active = cols.active_indices(epoch)
    n = len(active)
    if n == 0:
        raise ValueError("no active validators for sync committee")
    seed = misc.get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE, p)
    shuffled = shuffled_active_indices(seed, active, p)
    max_eb = p.MAX_EFFECTIVE_BALANCE
    out: "list[int]" = []
    i = 0
    hash_cache: dict = {}
    while len(out) < p.SYNC_COMMITTEE_SIZE:
        candidate = int(shuffled[i % n])
        block = i // 32
        rand = hash_cache.get(block)
        if rand is None:
            rand = misc.sha256(seed + misc.uint_to_bytes(block))
            hash_cache[block] = rand
        if int(cols.effective_balance[candidate]) * 0xFF >= max_eb * rand[i % 32]:
            out.append(candidate)
        i += 1
    return out


def get_next_sync_committee(state, types_ns, cfg):
    """Build the altair `SyncCommittee` container (pubkeys + aggregate)."""
    from grandine_tpu.consensus.keys import aggregate_pubkey_bytes

    indices = get_next_sync_committee_indices(state, cfg)
    cols = registry_columns(state)
    pubkeys = [cols.pubkeys[i] for i in indices]
    return types_ns.SyncCommittee(
        pubkeys=pubkeys,
        aggregate_pubkey=aggregate_pubkey_bytes(pubkeys),
    )


__all__ = [
    "RegistryColumns",
    "registry_columns",
    "shuffled_active_indices",
    "committee_partition",
    "get_current_epoch",
    "get_previous_epoch",
    "get_block_root_at_slot",
    "get_block_root",
    "get_active_validator_indices",
    "get_total_balance",
    "get_total_active_balance",
    "get_committee_count_per_slot",
    "get_beacon_committee",
    "get_beacon_proposer_index",
    "get_attesting_indices",
    "get_indexed_attestation",
    "get_base_reward_per_increment",
    "get_base_reward",
    "has_flag",
    "add_flag",
    "get_unslashed_participating_mask",
    "get_attestation_participation_flag_indices",
    "get_next_sync_committee_indices",
    "get_next_sync_committee",
]
