"""Spec helper functions — the equivalent of the reference's
`helper_functions` crate (accessors, predicates, misc, mutators, signing
domains, and the Verifier batch-verification seam).

Layer 3 of SURVEY.md §1: sits on types (layer 1) and crypto (layer 2);
consumed by transition functions, fork choice, pools, and the validator.
"""

from grandine_tpu.consensus import (  # noqa: F401
    accessors,
    misc,
    mutators,
    predicates,
    signing,
    verifier,
)
