"""Signing-root machinery per signed container — reference:
helper_functions/src/signing.rs:59-405 (`SignForSingleFork` /
`SignForAllForks` impls for every signed object kind).

Each `*_signing_root` computes the spec domain + signing root for one signed
container kind; each `extend_with_*` resolves the signer's public key(s)
from the state and defers the check into a Verifier. The fork-version
plumbing (which fork version signs which object, including the EIP-7044
capella-pinned voluntary exits and the genesis-pinned BLS-to-execution
changes) lives here and nowhere else.
"""

from __future__ import annotations

from grandine_tpu.consensus import accessors, keys, misc
from grandine_tpu.consensus.verifier import SignatureInvalid, Verifier
from grandine_tpu.ssz import uint64
from grandine_tpu.types.primitives import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
    Phase,
)


def _pubkey(state, index: int):
    cols = accessors.registry_columns(state)
    try:
        # registry keys passed KeyValidate at deposit: trusted decompress
        return keys.decompress_pubkey(cols.pubkeys[index], trusted=True)
    except Exception as e:
        raise SignatureInvalid(f"invalid registry pubkey at {index}: {e}") from e


# --- blocks ----------------------------------------------------------------


def block_signing_root(state, block, cfg) -> bytes:
    p = cfg.preset
    epoch = misc.compute_epoch_at_slot(int(block.slot), p)
    domain = misc.get_domain(state, DOMAIN_BEACON_PROPOSER, epoch, p)
    return misc.compute_signing_root(block, domain)


def extend_with_block_signature(v: Verifier, state, signed_block, cfg) -> None:
    block = signed_block.message
    root = block_signing_root(state, block, cfg)
    v.verify_singular(
        root, bytes(signed_block.signature), _pubkey(state, int(block.proposer_index))
    )


def header_signing_root(state, header, cfg) -> bytes:
    """SignedBeaconBlockHeader (proposer slashings)."""
    p = cfg.preset
    epoch = misc.compute_epoch_at_slot(int(header.slot), p)
    domain = misc.get_domain(state, DOMAIN_BEACON_PROPOSER, epoch, p)
    return misc.compute_signing_root(header, domain)


# --- randao ----------------------------------------------------------------


def randao_signing_root(state, epoch: int, cfg) -> bytes:
    domain = misc.get_domain(state, DOMAIN_RANDAO, epoch, cfg.preset)
    return misc.compute_signing_root(uint64.hash_tree_root(epoch), domain)


def extend_with_randao_reveal(v: Verifier, state, block, cfg) -> None:
    epoch = misc.compute_epoch_at_slot(int(block.slot), cfg.preset)
    root = randao_signing_root(state, epoch, cfg)
    v.verify_singular(
        root,
        bytes(block.body.randao_reveal),
        _pubkey(state, int(block.proposer_index)),
    )


# --- attestations ----------------------------------------------------------


def attestation_signing_root(state, data, cfg) -> bytes:
    domain = misc.get_domain(
        state, DOMAIN_BEACON_ATTESTER, int(data.target.epoch), cfg.preset
    )
    return misc.compute_signing_root(data, domain)


def extend_with_indexed_attestation(v: Verifier, state, indexed, cfg) -> None:
    """fast_aggregate_verify shape, handed to the verifier in INDEXED form
    (registry rows + the state's compressed pubkey columns) so device
    verifiers can gather the keys from the resident registry; host
    verifiers decompress-and-delegate in the base class, preserving the
    old aggregate-the-keys semantics (verifier.rs Triple aggregation
    :367-405)."""
    if v.is_null():
        return
    root = attestation_signing_root(state, indexed.data, cfg)
    cols = accessors.registry_columns(state)
    v.verify_aggregate_indexed(
        root,
        bytes(indexed.signature),
        [int(i) for i in indexed.attesting_indices],
        cols.pubkeys,
    )


# --- voluntary exits -------------------------------------------------------


def voluntary_exit_signing_root(state, exit_msg, cfg, phase: Phase) -> bytes:
    if phase >= Phase.DENEB:
        # EIP-7044: exits are always signed with the capella fork version
        domain = misc.compute_domain(
            DOMAIN_VOLUNTARY_EXIT,
            cfg.capella_fork_version,
            bytes(state.genesis_validators_root),
        )
    else:
        domain = misc.get_domain(
            state, DOMAIN_VOLUNTARY_EXIT, int(exit_msg.epoch), cfg.preset
        )
    return misc.compute_signing_root(exit_msg, domain)


def extend_with_voluntary_exit(v: Verifier, state, signed_exit, cfg, phase) -> None:
    msg = signed_exit.message
    root = voluntary_exit_signing_root(state, msg, cfg, phase)
    v.verify_singular(
        root, bytes(signed_exit.signature), _pubkey(state, int(msg.validator_index))
    )


# --- deposits --------------------------------------------------------------


def deposit_signing_root(deposit_data, cfg) -> bytes:
    """Deposit signatures are fork-agnostic: genesis fork version, ZERO
    genesis_validators_root (valid before genesis exists)."""
    from grandine_tpu.types.containers import spec_types

    T = spec_types(cfg.preset)
    message = T.phase0.DepositMessage(
        pubkey=bytes(deposit_data.pubkey),
        withdrawal_credentials=bytes(deposit_data.withdrawal_credentials),
        amount=int(deposit_data.amount),
    )
    domain = misc.compute_domain(DOMAIN_DEPOSIT, cfg.genesis_fork_version)
    return misc.compute_signing_root(message, domain)


# --- sync committee --------------------------------------------------------


def sync_aggregate_signing_root(state, cfg) -> bytes:
    """The sync aggregate in a block at slot S signs the block root at
    slot S-1 under DOMAIN_SYNC_COMMITTEE of epoch(S-1)."""
    p = cfg.preset
    prev_slot = max(int(state.slot), 1) - 1
    epoch = misc.compute_epoch_at_slot(prev_slot, p)
    domain = misc.get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, p)
    root = accessors.get_block_root_at_slot(state, prev_slot, p)
    return misc.compute_signing_root(root, domain)


def extend_with_sync_aggregate(v: Verifier, state, sync_aggregate, cfg) -> None:
    """Participating current-sync-committee keys sign the previous block
    root. An empty participation set with the infinity signature is valid
    (altair `eth_fast_aggregate_verify` G2_POINT_AT_INFINITY special case)."""
    from grandine_tpu.crypto import bls as A

    bits = sync_aggregate.sync_committee_bits
    sig = bytes(sync_aggregate.sync_committee_signature)
    participants = [
        keys.decompress_pubkey(
            bytes(state.current_sync_committee.pubkeys[i]), trusted=True
        )
        for i in bits.nonzero_indices()
    ]
    if not participants:
        if sig == A.Signature.empty().to_bytes():
            return
        raise SignatureInvalid("empty sync aggregate with non-infinity signature")
    if v.is_null():
        return
    root = sync_aggregate_signing_root(state, cfg)
    v.verify_aggregate(root, sig, participants)


def sync_committee_message_signing_root(state, block_root: bytes, epoch, cfg) -> bytes:
    domain = misc.get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch, cfg.preset)
    return misc.compute_signing_root(block_root, domain)


# --- BLS to execution change ----------------------------------------------


def bls_to_execution_change_signing_root(state, change, cfg) -> bytes:
    """Pinned to the GENESIS fork version for all time (capella spec)."""
    domain = misc.compute_domain(
        DOMAIN_BLS_TO_EXECUTION_CHANGE,
        cfg.genesis_fork_version,
        bytes(state.genesis_validators_root),
    )
    return misc.compute_signing_root(change, domain)


def extend_with_bls_to_execution_change(v: Verifier, state, signed_change, cfg) -> None:
    from grandine_tpu.crypto import bls as A

    change = signed_change.message
    root = bls_to_execution_change_signing_root(state, change, cfg)
    try:
        pk = keys.decompress_pubkey(bytes(change.from_bls_pubkey))
    except A.BlsError as e:
        raise SignatureInvalid(f"invalid from_bls_pubkey: {e}") from e
    v.verify_singular(root, bytes(signed_change.signature), pk)


# --- aggregator duties (validator plane) -----------------------------------


def selection_proof_signing_root(state, slot: int, cfg) -> bytes:
    domain = misc.get_domain(
        state,
        DOMAIN_SELECTION_PROOF,
        misc.compute_epoch_at_slot(slot, cfg.preset),
        cfg.preset,
    )
    return misc.compute_signing_root(uint64.hash_tree_root(slot), domain)


def aggregate_and_proof_signing_root(state, aggregate_and_proof, cfg) -> bytes:
    p = cfg.preset
    epoch = misc.compute_epoch_at_slot(
        int(aggregate_and_proof.aggregate.data.slot), p
    )
    domain = misc.get_domain(state, DOMAIN_AGGREGATE_AND_PROOF, epoch, p)
    return misc.compute_signing_root(aggregate_and_proof, domain)


def contribution_and_proof_signing_root(state, contribution_and_proof, cfg) -> bytes:
    p = cfg.preset
    epoch = misc.compute_epoch_at_slot(
        int(contribution_and_proof.contribution.slot), p
    )
    domain = misc.get_domain(state, DOMAIN_CONTRIBUTION_AND_PROOF, epoch, p)
    return misc.compute_signing_root(contribution_and_proof, domain)


def sync_selection_proof_signing_root(state, selection_data, cfg) -> bytes:
    p = cfg.preset
    epoch = misc.compute_epoch_at_slot(int(selection_data.slot), p)
    domain = misc.get_domain(
        state, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch, p
    )
    return misc.compute_signing_root(selection_data, domain)


__all__ = [
    "block_signing_root",
    "extend_with_block_signature",
    "header_signing_root",
    "randao_signing_root",
    "extend_with_randao_reveal",
    "attestation_signing_root",
    "extend_with_indexed_attestation",
    "voluntary_exit_signing_root",
    "extend_with_voluntary_exit",
    "deposit_signing_root",
    "sync_aggregate_signing_root",
    "extend_with_sync_aggregate",
    "sync_committee_message_signing_root",
    "bls_to_execution_change_signing_root",
    "extend_with_bls_to_execution_change",
    "selection_proof_signing_root",
    "aggregate_and_proof_signing_root",
    "contribution_and_proof_signing_root",
    "sync_selection_proof_signing_root",
]
