"""Process-wide public-key decompression cache.

Reference parity: bls/src/cached_public_key.rs (lazy decompress) +
validator_key_cache (persistent decompressed-key reuse). Decompressing a
48-byte G1 key costs a field sqrt + subgroup check; a 50k-validator registry
re-verifies the same keys constantly, so the cache is global and unbounded
(50k entries ≈ a few MB of Fq ints — the reference holds the same data in
`CachedPublicKey` fields inside the state).
"""

from __future__ import annotations

from typing import Iterable, Optional

from grandine_tpu.crypto import bls as A

_CACHE: dict = {}


def decompress_pubkey(pubkey_bytes: bytes) -> "A.PublicKey":
    """Decompressed, subgroup-checked, non-identity public key.
    Raises BlsError on invalid encodings (never cached)."""
    key = bytes(pubkey_bytes)
    hit = _CACHE.get(key)
    if hit is None:
        hit = A.PublicKey.from_bytes(key)
        _CACHE[key] = hit
    return hit


def try_decompress_pubkey(pubkey_bytes: bytes) -> "Optional[A.PublicKey]":
    try:
        return decompress_pubkey(pubkey_bytes)
    except A.BlsError:
        return None


def aggregate_pubkeys(pubkeys: "Iterable[bytes]") -> "A.PublicKey":
    """eth_aggregate_pubkeys: aggregate of decompressed keys (all must be
    valid; empty input is an error per the spec)."""
    keys = [decompress_pubkey(pk) for pk in pubkeys]
    if not keys:
        raise A.BlsError("eth_aggregate_pubkeys of empty list")
    return A.PublicKey.aggregate(keys)


def aggregate_pubkey_bytes(pubkeys: "Iterable[bytes]") -> bytes:
    return aggregate_pubkeys(pubkeys).to_bytes()


__all__ = [
    "decompress_pubkey",
    "try_decompress_pubkey",
    "aggregate_pubkeys",
    "aggregate_pubkey_bytes",
]
