"""Process-wide public-key decompression cache.

Reference parity: bls/src/cached_public_key.rs (lazy decompress) +
validator_key_cache (persistent decompressed-key reuse). Decompressing a
48-byte G1 key costs a field sqrt + subgroup check; a 50k-validator registry
re-verifies the same keys constantly, so the cache is global and unbounded
(50k entries ≈ a few MB of Fq ints — the reference holds the same data in
`CachedPublicKey` fields inside the state).
"""

from __future__ import annotations

from typing import Iterable, Optional

from grandine_tpu.crypto import bls as A

#: key bytes -> (PublicKey, subgroup_checked) — a trusted decompression
#: may later be upgraded by an untrusted caller
_CACHE: "dict[bytes, tuple]" = {}


def decompress_pubkey(
    pubkey_bytes: bytes, trusted: bool = False
) -> "A.PublicKey":
    """Decompressed, non-identity public key; subgroup-checked unless
    `trusted`. Raises BlsError on invalid encodings (never cached).

    trusted=True is for keys sourced from the VALIDATOR REGISTRY: they
    passed KeyValidate at deposit time, and re-running the subgroup
    scalar-mul per decompression (~30 ms host) made cold-cache block
    replay O(committee·30ms). This mirrors the reference's
    CachedPublicKey (bls/src/cached_public_key.rs), which also
    decompresses registry keys without re-validating."""
    key = bytes(pubkey_bytes)
    hit = _CACHE.get(key)
    if hit is not None:
        pk, checked = hit
        if checked or trusted:
            return pk
    point = A.g1_from_bytes(key, subgroup_check=not trusted)
    if point.is_infinity():
        raise A.BlsError("identity public key is invalid")
    pk = A.PublicKey(point)
    _CACHE[key] = (pk, not trusted)
    return pk


def decompress_pubkeys(
    pubkey_bytes_seq: "Iterable[bytes]", trusted: bool = False
) -> "list[A.PublicKey]":
    """Batch decompression through the process-wide cache — the bulk
    entry point for registry builds (tpu/registry.py uploads the whole
    validator set) and committee resolution. Raises BlsError on the
    first invalid encoding."""
    return [decompress_pubkey(b, trusted=trusted) for b in pubkey_bytes_seq]


def try_decompress_pubkey(pubkey_bytes: bytes) -> "Optional[A.PublicKey]":
    try:
        return decompress_pubkey(pubkey_bytes)
    except A.BlsError:
        return None


def aggregate_pubkeys(pubkeys: "Iterable[bytes]") -> "A.PublicKey":
    """eth_aggregate_pubkeys: aggregate of decompressed keys (all must be
    valid; empty input is an error per the spec)."""
    keys = [decompress_pubkey(pk) for pk in pubkeys]
    if not keys:
        raise A.BlsError("eth_aggregate_pubkeys of empty list")
    return A.PublicKey.aggregate(keys)


def aggregate_pubkey_bytes(pubkeys: "Iterable[bytes]") -> bytes:
    return aggregate_pubkeys(pubkeys).to_bytes()


__all__ = [
    "decompress_pubkey",
    "decompress_pubkeys",
    "try_decompress_pubkey",
    "aggregate_pubkeys",
    "aggregate_pubkey_bytes",
]
