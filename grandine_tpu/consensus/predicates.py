"""Spec predicates — reference: helper_functions/src/predicates.rs
(is_active_validator, slashability, indexed-attestation validity,
merkle-branch validation).

Registry-wide variants take numpy columns (accessors.RegistryColumns) so
epoch processing stays vectorized.
"""

from __future__ import annotations

import numpy as np

from grandine_tpu.ssz.merkle import verify_merkle_proof
from grandine_tpu.types.primitives import FAR_FUTURE_EPOCH


# --- single-validator predicates (container-typed) -------------------------


def is_active_validator(validator, epoch: int) -> bool:
    return int(validator.activation_epoch) <= epoch < int(validator.exit_epoch)


def is_eligible_for_activation_queue(validator, p) -> bool:
    return (
        int(validator.activation_eligibility_epoch) == FAR_FUTURE_EPOCH
        and int(validator.effective_balance) == p.MAX_EFFECTIVE_BALANCE
    )


def is_eligible_for_activation(validator, finalized_epoch: int) -> bool:
    return (
        int(validator.activation_eligibility_epoch) <= finalized_epoch
        and int(validator.activation_epoch) == FAR_FUTURE_EPOCH
    )


def is_slashable_validator(validator, epoch: int) -> bool:
    return not bool(validator.slashed) and (
        int(validator.activation_epoch) <= epoch < int(validator.withdrawable_epoch)
    )


# --- vectorized column variants --------------------------------------------


def active_mask(
    activation_epoch: np.ndarray, exit_epoch: np.ndarray, epoch: int
) -> np.ndarray:
    """Boolean mask of validators active at `epoch` over whole-registry
    columns (uint64)."""
    e = np.uint64(epoch)
    return (activation_epoch <= e) & (e < exit_epoch)


# --- attestation predicates ------------------------------------------------


def is_slashable_attestation_data(data_1, data_2) -> bool:
    """Double vote or surround vote (spec `is_slashable_attestation_data`)."""
    double = (
        data_1 != data_2
        and int(data_1.target.epoch) == int(data_2.target.epoch)
    )
    surround = (
        int(data_1.source.epoch) < int(data_2.source.epoch)
        and int(data_2.target.epoch) < int(data_1.target.epoch)
    )
    return double or surround


def validate_indexed_attestation(indexed, state, verifier, cfg) -> None:
    """Spec `is_valid_indexed_attestation`, split in the reference's style:
    structural checks raise; the signature is *deferred* into `verifier`
    (helper_functions Verifier seam) so batch callers pay one pairing.

    Raises ValueError on structural invalidity.
    """
    from grandine_tpu.consensus import signing

    indices = list(indexed.attesting_indices)
    if not indices:
        raise ValueError("indexed attestation has no attesting indices")
    if indices != sorted(set(indices)):
        raise ValueError("attesting indices not sorted/unique")
    n_validators = len(state.validators)
    if indices[-1] >= n_validators:
        raise ValueError("attesting index out of range")
    signing.extend_with_indexed_attestation(verifier, state, indexed, cfg)


def is_valid_merkle_branch(
    leaf: bytes, branch, depth: int, index: int, root: bytes
) -> bool:
    return verify_merkle_proof(leaf, list(branch), depth, index, root)


__all__ = [
    "is_active_validator",
    "is_eligible_for_activation_queue",
    "is_eligible_for_activation",
    "is_slashable_validator",
    "active_mask",
    "is_slashable_attestation_data",
    "validate_indexed_attestation",
    "is_valid_merkle_branch",
]
