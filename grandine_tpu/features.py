"""Runtime feature toggles — reference: `features` crate (enum of flags in
a static AtomicBool array, features/src/lib.rs:24,40-71; settable from the
CLI `--features` flag and PATCHable at runtime).
"""

from __future__ import annotations

import enum
import threading


class Feature(enum.Enum):
    # the subset of the reference's 28 flags meaningful to this framework
    TRUST_OWN_BLOCK_SIGNATURES = "TrustOwnBlockSignatures"
    TRUST_BACK_SYNC_BLOCKS = "TrustBackSyncBlocks"
    INHIBIT_APPLICATION_RESTART = "InhibitApplicationRestart"
    LOG_BLOCK_PROCESSING_TIME = "LogBlockProcessingTime"
    PROPOSE_WITHOUT_AGGREGATES = "ProposeWithoutAggregates"
    DISABLE_DEVICE_BACKEND = "DisableDeviceBackend"
    DISABLE_PROPOSER_BOOST = "DisableProposerBoost"
    ALWAYS_PREPROCESS_NEXT_SLOT = "AlwaysPreprocessNextSlot"
    # revert block packing to the pure greedy packer (the default is the
    # max-clique + branch-and-bound packer, pools/packer.py; reference
    # attestation_packer.rs ships ILP-on-by-default with greedy fallback)
    GREEDY_ATTESTATION_PACKING = "GreedyAttestationPacking"


_STATE: "dict[Feature, bool]" = {f: False for f in Feature}
_LOCK = threading.Lock()


def is_enabled(feature: Feature) -> bool:
    return _STATE[feature]


def enable(feature: Feature) -> None:
    with _LOCK:
        _STATE[feature] = True


def disable(feature: Feature) -> None:
    with _LOCK:
        _STATE[feature] = False


def enable_by_name(name: str) -> Feature:
    for f in Feature:
        if f.value == name or f.name == name:
            enable(f)
            return f
    raise ValueError(f"unknown feature {name!r}")


def all_features() -> "dict[str, bool]":
    return {f.value: _STATE[f] for f in Feature}


def reset() -> None:
    with _LOCK:
        for f in Feature:
            _STATE[f] = False


__all__ = [
    "Feature",
    "is_enabled",
    "enable",
    "disable",
    "enable_by_name",
    "all_features",
    "reset",
]
