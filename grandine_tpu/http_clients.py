"""Concrete HTTP clients behind the four injected I/O seams.

Round-3 review finding: every external boundary (engine API, Web3Signer,
checkpoint-sync, builder relay) was an injected Python callable with no
client behind it. This module supplies the real wire implementations on
stdlib `http.client` only:

  - `EngineApiClient` — execution-engine JSON-RPC over HTTP with JWT
    (HS256) auth, implementing the `ExecutionEngine` interface
    (reference: eth1_api/src/auth.rs JWT claims + eth1_api/src/
    eth1_execution_engine.rs newPayload/forkchoiceUpdated round-trips).
  - `Web3SignerClient` — remote-signer REST client, pluggable as the
    `web3signer` callable of validator/signer.py (reference:
    signer/src/web3signer/mod.rs).
  - `checkpoint_fetcher` — Beacon-API checkpoint-sync state download for
    `StateLoadStrategy.REMOTE` (reference:
    fork_choice_control/src/checkpoint_sync.rs:1-120).
  - `BuilderRelayClient` — builder-specs getHeader/submitBlindedBlock
    relay transport for builder_api.BuilderApi (reference:
    builder_api/src/api.rs).

All clients: bounded timeouts, explicit error mapping (`HttpClientError`
carries the HTTP status / JSON-RPC error), fresh connection per request
(the callers are low-rate control-plane paths; connection reuse is not
worth the staleness handling).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import socket
import time
import urllib.parse
from http import client as http_client
from typing import Optional

from grandine_tpu.execution.engine import ExecutionEngine, PayloadStatus


class HttpClientError(Exception):
    """Transport/protocol failure at an HTTP seam: carries `status` (HTTP
    code, or None for socket-level failures) and `info` (server detail)."""

    def __init__(self, message: str, status: "Optional[int]" = None,
                 info: object = None) -> None:
        super().__init__(message)
        self.status = status
        self.info = info


def _b64url(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def jwt_hs256(secret: bytes, claims: "Optional[dict]" = None) -> str:
    """Compact JWS over HS256 — the engine-API auth token. Claims default
    to a fresh `iat` (the engine enforces ±60 s drift; reference
    eth1_api/src/auth.rs)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(
        json.dumps(claims if claims is not None else {"iat": int(time.time())}).encode()
    )
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret, signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def _request(
    url: str,
    method: str,
    path: str,
    body: "Optional[bytes]" = None,
    headers: "Optional[dict]" = None,
    timeout: float = 8.0,
) -> "tuple[int, bytes]":
    """One HTTP round-trip; maps socket errors to HttpClientError."""
    parsed = urllib.parse.urlsplit(url)
    if parsed.scheme not in ("http", ""):
        raise HttpClientError(f"unsupported scheme {parsed.scheme!r} (http only)")
    host = parsed.hostname or "127.0.0.1"
    port = parsed.port or 80
    full_path = (parsed.path.rstrip("/") + path) or "/"
    conn = http_client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, full_path, body=body, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, data
    except (socket.timeout, TimeoutError) as e:
        raise HttpClientError(f"timeout talking to {host}:{port}{full_path}") from e
    except OSError as e:
        raise HttpClientError(f"connection to {host}:{port} failed: {e}") from e
    finally:
        conn.close()


# --------------------------------------------------------------------------
# Engine API (execution layer) JSON-RPC client
# --------------------------------------------------------------------------

_QUANTITY_FIELDS = {
    "block_number", "gas_limit", "gas_used", "timestamp",
    "base_fee_per_gas", "blob_gas_used", "excess_blob_gas",
    "index", "validator_index", "amount",
}
_CAMEL = {
    "parent_hash": "parentHash", "fee_recipient": "feeRecipient",
    "state_root": "stateRoot", "receipts_root": "receiptsRoot",
    "logs_bloom": "logsBloom", "prev_randao": "prevRandao",
    "block_number": "blockNumber", "gas_limit": "gasLimit",
    "gas_used": "gasUsed", "timestamp": "timestamp",
    "extra_data": "extraData", "base_fee_per_gas": "baseFeePerGas",
    "block_hash": "blockHash", "transactions": "transactions",
    "withdrawals": "withdrawals", "blob_gas_used": "blobGasUsed",
    "excess_blob_gas": "excessBlobGas", "index": "index",
    "validator_index": "validatorIndex", "address": "address",
    "amount": "amount",
}
_SNAKE = {v: k for k, v in _CAMEL.items()}


def payload_to_json(payload) -> dict:
    """SSZ ExecutionPayload container → engine-API JSON (camelCase, hex
    QUANTITY/DATA encodings per the execution-apis spec)."""
    out: dict = {}
    for name, _typ in type(payload).FIELDS:
        value = getattr(payload, name)
        camel = _CAMEL.get(name, name)
        if name == "transactions":
            out[camel] = ["0x" + bytes(tx).hex() for tx in value]
        elif name == "withdrawals":
            out[camel] = [payload_to_json(w) for w in value]
        elif name in _QUANTITY_FIELDS:
            out[camel] = hex(int(value))
        else:
            out[camel] = "0x" + bytes(value).hex()
    return out


def json_to_payload(cls, obj: dict):
    """Engine-API JSON → SSZ ExecutionPayload container of type `cls`."""
    kw = {}
    for name, ftyp in cls.FIELDS:
        camel = _CAMEL.get(name, name)
        if camel not in obj:
            raise HttpClientError(f"payload JSON missing {camel}")
        v = obj[camel]
        if name == "transactions":
            kw[name] = [bytes.fromhex(t[2:]) for t in v]
        elif name == "withdrawals":
            kw[name] = [json_to_payload(ftyp.elem, w) for w in v]
        elif name in _QUANTITY_FIELDS:
            kw[name] = int(v, 16)
        else:
            kw[name] = bytes.fromhex(v[2:])
    return cls(**kw)


class EngineApiClient(ExecutionEngine):
    """Engine-API JSON-RPC with per-request JWT (HS256) auth.

    Method versions are selected from the payload's own fields
    (withdrawals → V2, blob gas → V3), matching the reference's
    fork-dispatched `Eth1ExecutionEngine`."""

    def __init__(self, url: str, jwt_secret: bytes, timeout: float = 8.0) -> None:
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self.last_payload_id: "Optional[str]" = None
        self._id = 0

    def with_retries(self, metrics=None, **kwargs) -> "ExecutionEngine":
        """This client behind capped-exponential-backoff retries for
        transient failures (socket errors, EL 5xx) — the node wiring's
        default; see execution/engine.py RetryingExecutionEngine."""
        from grandine_tpu.execution.engine import RetryingExecutionEngine

        return RetryingExecutionEngine(self, metrics=metrics, **kwargs)

    # -- JSON-RPC plumbing ------------------------------------------------

    def call(self, method: str, params: list) -> object:
        self._id += 1
        req = {"jsonrpc": "2.0", "id": self._id, "method": method,
               "params": params}
        body = json.dumps(req).encode()
        headers = {
            "Content-Type": "application/json",
            "Authorization": f"Bearer {jwt_hs256(self.jwt_secret)}",
        }
        status, data = _request(
            self.url, "POST", "", body, headers, self.timeout
        )
        if status != 200:
            raise HttpClientError(
                f"engine API HTTP {status}", status=status, info=data[:200]
            )
        try:
            resp = json.loads(data)
        except ValueError as e:
            raise HttpClientError("engine API returned invalid JSON") from e
        if resp.get("id") != self._id:
            raise HttpClientError("engine API response id mismatch")
        if "error" in resp:
            err = resp["error"]
            raise HttpClientError(
                f"engine API error {err.get('code')}: {err.get('message')}",
                info=err,
            )
        return resp.get("result")

    # -- ExecutionEngine interface ----------------------------------------

    @staticmethod
    def _status(result: dict) -> PayloadStatus:
        try:
            return PayloadStatus(result["status"])
        except (KeyError, TypeError, ValueError) as e:
            raise HttpClientError(
                f"malformed payloadStatus: {result!r}"
            ) from e

    def notify_new_payload(
        self, payload, versioned_hashes: "Optional[list]" = None,
        parent_beacon_block_root: "Optional[bytes]" = None,
    ) -> PayloadStatus:
        obj = payload_to_json(payload)
        if "blobGasUsed" in obj:
            params: list = [
                obj,
                ["0x" + bytes(h).hex() for h in (versioned_hashes or [])],
                "0x" + bytes(parent_beacon_block_root or b"\x00" * 32).hex(),
            ]
            method = "engine_newPayloadV3"
        elif "withdrawals" in obj:
            params, method = [obj], "engine_newPayloadV2"
        else:
            params, method = [obj], "engine_newPayloadV1"
        return self._status(self.call(method, params))

    def notify_forkchoice_updated(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> PayloadStatus:
        state = {
            "headBlockHash": "0x" + bytes(head_block_hash).hex(),
            "safeBlockHash": "0x" + bytes(safe_block_hash).hex(),
            "finalizedBlockHash": "0x" + bytes(finalized_block_hash).hex(),
        }
        version = "V1"
        attrs = None
        if payload_attributes is not None:
            attrs = dict(payload_attributes)
            if "withdrawals" in attrs:
                version = "V2"
            if "parentBeaconBlockRoot" in attrs:
                version = "V3"
        result = self.call(f"engine_forkchoiceUpdated{version}", [state, attrs])
        if not isinstance(result, dict):
            raise HttpClientError(
                f"malformed forkchoiceUpdated result: {result!r}"
            )
        if result.get("payloadId"):
            self.last_payload_id = result["payloadId"]
        return self._status(result.get("payloadStatus", {}))

    def get_payload(self, payload_id: str, version: int = 2) -> dict:
        """engine_getPayloadVn → raw JSON result (executionPayload + fees);
        convert with json_to_payload against the fork's container type."""
        return self.call(f"engine_getPayloadV{version}", [payload_id])


# --------------------------------------------------------------------------
# Web3Signer REST client
# --------------------------------------------------------------------------


class Web3SignerClient:
    """Remote-signer client; instances are pluggable as the `web3signer`
    callable of validator/signer.py ((pubkey_hex, root_hex) → sig_hex)."""

    def __init__(self, url: str, timeout: float = 8.0) -> None:
        self.url = url
        self.timeout = timeout

    def __call__(self, pubkey_hex: str, signing_root_hex: str) -> str:
        body = json.dumps({"signing_root": "0x" + signing_root_hex}).encode()
        status, data = _request(
            self.url, "POST", f"/api/v1/eth2/sign/0x{pubkey_hex}",
            body, {"Content-Type": "application/json"}, self.timeout,
        )
        if status != 200:
            raise HttpClientError(
                f"web3signer HTTP {status}", status=status, info=data[:200]
            )
        text = data.decode().strip()
        if text.startswith("{"):
            try:
                text = json.loads(text)["signature"]
            except (ValueError, KeyError) as e:
                raise HttpClientError("web3signer malformed response") from e
        return text[2:] if text.startswith("0x") else text

    def list_keys(self) -> "list[str]":
        status, data = _request(
            self.url, "GET", "/api/v1/eth2/publicKeys", None, {}, self.timeout
        )
        if status != 200:
            raise HttpClientError(
                f"web3signer HTTP {status}", status=status, info=data[:200]
            )
        keys = json.loads(data)
        return [k[2:] if k.startswith("0x") else k for k in keys]


# --------------------------------------------------------------------------
# Checkpoint sync + builder relay
# --------------------------------------------------------------------------


def checkpoint_fetcher(url: str, timeout: float = 30.0):
    """Beacon-API checkpoint-sync fetcher for storage.Storage.load
    (StateLoadStrategy.REMOTE): kind 'finalized_state' → SSZ bytes of
    /eth/v2/debug/beacon/states/finalized."""

    paths = {
        "finalized_state": "/eth/v2/debug/beacon/states/finalized",
        "genesis_state": "/eth/v2/debug/beacon/states/genesis",
    }

    def fetch(kind: str) -> bytes:
        path = paths.get(kind)
        if path is None:
            raise HttpClientError(f"unknown checkpoint object {kind!r}")
        status, data = _request(
            url, "GET", path, None,
            {"Accept": "application/octet-stream"}, timeout,
        )
        if status != 200:
            raise HttpClientError(
                f"checkpoint sync HTTP {status} for {kind}",
                status=status, info=data[:200],
            )
        if not data:
            raise HttpClientError(f"checkpoint sync returned empty {kind}")
        return data

    return fetch


class BuilderRelayClient:
    """builder-specs transport; instances are pluggable as the `relay`
    callable of builder_api.BuilderApi ((op, params) → dict)."""

    def __init__(self, url: str, timeout: float = 8.0) -> None:
        self.url = url
        self.timeout = timeout

    def __call__(self, op: str, params: dict) -> dict:
        if op == "get_header":
            path = (
                f"/eth/v1/builder/header/{params['slot']}"
                f"/0x{params['parent_hash']}/0x{params['pubkey']}"
            )
            status, data = _request(self.url, "GET", path, None, {}, self.timeout)
        elif op == "submit_blinded_block":
            status, data = _request(
                self.url, "POST", "/eth/v1/builder/blinded_blocks",
                bytes.fromhex(params["ssz"]),
                {"Content-Type": "application/octet-stream"}, self.timeout,
            )
        else:
            raise HttpClientError(f"unknown builder op {op!r}")
        if status != 200:
            raise HttpClientError(
                f"builder relay HTTP {status} for {op}",
                status=status, info=data[:200],
            )
        try:
            obj = json.loads(data)
        except ValueError as e:
            raise HttpClientError("builder relay returned invalid JSON") from e
        return obj.get("data", obj)


__all__ = [
    "HttpClientError",
    "jwt_hs256",
    "payload_to_json",
    "json_to_payload",
    "EngineApiClient",
    "Web3SignerClient",
    "checkpoint_fetcher",
    "BuilderRelayClient",
]
