"""Runtime tests: thread pool/WaitGroup, mutator-actor controller with
delayed-block retry, the attestation-verifier firehose (batching +
bad-signature fallback), and the in-process node ticking through epochs.

Reference test parity: fork_choice_control's TestController harness
(specialized.rs:43-47, helpers.rs:34-80 — WaitGroup drain, channel-boundary
assertions) and attestation_verifier batching semantics.
"""

import threading
import time

import numpy as np
import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.runtime import (
    AttestationVerifier,
    Controller,
    InProcessNode,
    Priority,
    SlotClock,
    ThreadPool,
    WaitGroup,
)
from grandine_tpu.runtime.thread_pool import PoolPoisoned
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()


@pytest.fixture()
def genesis():
    return interop_genesis_state(32, CFG)


# ------------------------------------------------------------- thread pool


def test_pool_runs_and_drains():
    results = []
    with ThreadPool(n_threads=4) as pool:
        for i in range(20):
            pool.spawn(lambda i=i: results.append(i), Priority.LOW)
        pool.wait_group.wait(10)
    assert sorted(results) == list(range(20))


def test_pool_priority_order():
    order = []
    lock = threading.Lock()
    with ThreadPool(n_threads=1) as pool:
        gate = threading.Event()
        pool.spawn(gate.wait)  # block the single worker
        for i in range(3):
            pool.spawn(lambda i=i: order.append(("low", i)), Priority.LOW)
        for i in range(3):
            pool.spawn(lambda i=i: order.append(("high", i)), Priority.HIGH)
        gate.set()
        pool.wait_group.wait(10)
    assert order[:3] == [("high", 0), ("high", 1), ("high", 2)]


def test_wait_group_poisons_on_panic():
    with ThreadPool(n_threads=2) as pool:
        pool.spawn(lambda: 1 / 0)
        with pytest.raises(PoolPoisoned):
            pool.wait_group.wait(10)


# -------------------------------------------------------------- slot clock


def test_slot_clock_math():
    clock = SlotClock(genesis_time=1000, seconds_per_slot=12)
    assert clock.current_slot(1000) == 0
    assert clock.current_slot(1000 + 25) == 2
    t = clock.tick_at(1000 + 12 + 5)
    assert (t.slot, t.kind) == (1, TickKind.ATTEST)
    nxt = clock.next_tick(1000 + 12 + 11.9)
    assert (nxt.slot, nxt.kind) == (2, TickKind.PROPOSE)
    assert clock.time_of(Tick(2, TickKind.PROPOSE)) == 1000 + 24


# -------------------------------------------------------------- controller


def test_controller_applies_blocks_and_updates_head(genesis):
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        state = genesis
        roots = []
        for slot in (1, 2):
            blk, state = produce_block(
                state, slot, CFG, full_sync_participation=False
            )
            ctrl.on_tick(Tick(slot, TickKind.PROPOSE))
            ctrl.on_gossip_block(blk)
            ctrl.wait()
            roots.append(blk.message.hash_tree_root())
        snap = ctrl.snapshot()
        assert snap.head_root == roots[-1]
        assert snap.block_count == 3
        assert not ctrl.rejected()
    finally:
        ctrl.stop()


def test_controller_delays_until_parent_arrives(genesis):
    """Child delivered before parent: delayed, then retried and applied."""
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        b1, s1 = produce_block(genesis, 1, CFG, full_sync_participation=False)
        b2, s2 = produce_block(s1, 2, CFG, full_sync_participation=False)
        ctrl.on_tick(Tick(2, TickKind.PROPOSE))
        ctrl.on_gossip_block(b2)  # parent unknown -> delayed
        ctrl.wait()
        assert ctrl.snapshot().block_count == 1
        ctrl.on_gossip_block(b1)  # parent arrives -> child retried
        ctrl.wait()
        snap = ctrl.snapshot()
        assert snap.block_count == 3
        assert snap.head_root == b2.message.hash_tree_root()
    finally:
        ctrl.stop()


def test_controller_rejects_invalid_block(genesis):
    from grandine_tpu.consensus.verifier import MultiVerifier

    ctrl = Controller(genesis, CFG, verifier_factory=MultiVerifier)
    try:
        blk, _ = produce_block(genesis, 1, CFG, full_sync_participation=False)
        bad = blk.replace(signature=b"\x80" + b"\x22" * 95)
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_gossip_block(bad)
        ctrl.wait()
        assert ctrl.snapshot().block_count == 1
        assert len(ctrl.rejected()) == 1
    finally:
        ctrl.stop()


def test_controller_concurrent_forks(genesis):
    """Two sibling blocks validated concurrently on the pool; both land."""
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        ba, _ = produce_block(
            genesis, 1, CFG, full_sync_participation=False, graffiti=b"a"
        )
        bb, _ = produce_block(
            genesis, 1, CFG, full_sync_participation=False, graffiti=b"b"
        )
        ctrl.on_tick(Tick(1, TickKind.ATTEST))
        ctrl.on_gossip_block(ba)
        ctrl.on_gossip_block(bb)
        ctrl.wait()
        assert ctrl.snapshot().block_count == 3
    finally:
        ctrl.stop()


# ---------------------------------------------------------------- firehose


def test_firehose_verifies_and_feeds_fork_choice(genesis):
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    verifier = AttestationVerifier(ctrl, use_device=False, deadline_s=0.01)
    try:
        blk, post = produce_block(genesis, 1, CFG, full_sync_participation=False)
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_own_block(blk)
        ctrl.wait()
        atts = produce_attestations(post, CFG, slot=1)
        verifier.submit_many(atts)
        verifier.flush()
        ctrl.wait()
        assert verifier.stats["accepted"] == len(atts)
        assert verifier.stats["rejected"] == 0
        # votes are delayed until slot 2, then counted
        assert not ctrl.store.latest_message_root
        ctrl.on_tick(Tick(2, TickKind.PROPOSE))
        ctrl.wait()
        assert len(ctrl.store.latest_message_root) > 0
    finally:
        verifier.stop()
        ctrl.stop()


def test_firehose_fallback_isolates_bad_signature(genesis):
    """A batch with one corrupted signature: batch check fails, singular
    fallback accepts the good ones and drops the bad one."""
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    verifier = AttestationVerifier(ctrl, use_device=False, deadline_s=0.01)
    try:
        blk, post = produce_block(genesis, 1, CFG, full_sync_participation=False)
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_own_block(blk)
        ctrl.wait()
        atts = produce_attestations(post, CFG, slot=1)
        good = atts[0]
        # drop a signer from the bits without re-signing: structurally fine,
        # signature no longer matches the claimed participant set
        bad = good.replace(aggregation_bits=good.aggregation_bits.set(1, False))
        verifier.submit_many([bad, good])
        verifier.flush()
        ctrl.wait()
        assert verifier.stats["fallbacks"] >= 1
        assert verifier.stats["accepted"] == 1
        assert verifier.stats["rejected"] == 1
    finally:
        verifier.stop()
        ctrl.stop()


# ------------------------------------------------------------------- node


def test_in_process_node_runs_epochs(genesis):
    """The minimal runtime skeleton: clock ticks drive propose/attest
    through the controller + firehose for 2+ epochs; head advances and
    justification kicks in."""
    node = InProcessNode(genesis, CFG)
    try:
        node.run_until(17)  # two minimal epochs + 1
        snap = node.head()
        assert snap.slot == 17
        assert int(snap.head_state.slot) == 17
        assert len(node.produced_blocks) == 17
        assert int(snap.justified_checkpoint.epoch) >= 0
        # LMD messages accumulated from the firehose
        assert len(node.controller.store.latest_message_root) > 0
        assert node.attestation_verifier.stats["rejected"] == 0
    finally:
        node.stop()


def test_kernel_warmup_manifest():
    """The startup warmer runs manifest entries without error — driven
    through the cheapest kernel kind only (subgroup): tracing the
    aggregate/multi_verify kernels here costs ~2 min of the tier-1
    budget and their backend entry points are already differentially
    covered by the dedicated kernel suites."""
    from grandine_tpu.runtime import warmup

    entries = [("subgroup", 4), ("subgroup", 8)]
    msgs = []
    done = warmup.warm_all(entries, progress=msgs.append)
    assert done == len(entries)
    assert all("FAILED" not in m for m in msgs)
    assert len(warmup.manifest()) >= 10


def test_remote_metrics_push():
    """RemoteMetricsService pushes the beaconcha.in client-stats shape
    (one beaconnode + one system entry) and counts failures without
    raising (metrics/src/service.rs + beaconchain.rs)."""
    from grandine_tpu.metrics import Metrics, RemoteMetricsService

    got = []

    def fake_post(url, body):
        got.append((url, body))
        return 200

    svc = RemoteMetricsService(
        "http://push.example/stats", Metrics(), post=fake_post
    )
    assert svc.push_once()
    url, body = got[0]
    assert url == "http://push.example/stats"
    procs = {e["process"] for e in body}
    assert procs == {"beaconnode", "system"}
    assert all("timestamp" in e and e["version"] == 1 for e in body)
    assert svc.stats == {"pushes": 1, "failures": 0}

    svc.post = lambda u, b: (_ for _ in ()).throw(OSError("down"))
    assert not svc.push_once()
    assert svc.stats["failures"] == 1
