import pytest
from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.pools import OperationPool
from grandine_tpu.runtime import Controller, InProcessNode
from grandine_tpu.runtime.attestation_verifier import AttestationVerifier
from grandine_tpu.slasher import Slasher
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()

def test_firehose_emits_attester_slashing_op():
    genesis = interop_genesis_state(16, CFG)
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    pool = OperationPool(CFG)
    verifier = AttestationVerifier(
        ctrl, use_device=False, slasher=Slasher(), operation_pool=pool
    )
    try:
        blk, state = produce_block(genesis, 1, CFG, full_sync_participation=False)
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_own_block(blk); ctrl.wait()
        ctrl.on_tick(Tick(2, TickKind.ATTEST)); ctrl.wait()
        atts = produce_attestations(state, CFG, slot=1)
        verifier.submit_many(atts); verifier.flush(); ctrl.wait()
        assert verifier.stats["accepted"] == len(atts)
        assert pool.contents()["attester_slashings"] == []
        # same validators DOUBLE-VOTE: same target, different beacon root
        import numpy as np
        from grandine_tpu.consensus import signing as S
        from grandine_tpu.types.containers import spec_types
        ns = spec_types(CFG.preset).deneb
        from grandine_tpu.transition.genesis import interop_secret_key
        from grandine_tpu.consensus import accessors
        att = atts[0]
        data2 = att.data.replace(beacon_block_root=bytes(blk.message.parent_root))
        committee = accessors.get_beacon_committee(state, 1, int(att.data.index), CFG.preset)
        root2 = S.attestation_signing_root(state, data2, CFG)
        from grandine_tpu.crypto import bls as A
        sigs = [interop_secret_key(int(v)).sign(root2) for v in committee]
        att2 = ns.Attestation(
            aggregation_bits=np.ones(len(committee), dtype=bool),
            data=data2,
            signature=A.Signature.aggregate(sigs).to_bytes(),
        )
        verifier.submit(att2); verifier.flush(); ctrl.wait()
        slashings = pool.contents()["attester_slashings"]
        assert len(slashings) >= 1, verifier.stats
        s = slashings[0]
        assert sorted(map(int, s.attestation_1.attesting_indices))
        assert verifier.stats.get("slashings_emitted", 0) >= 1
        print("ok")
    finally:
        verifier.stop(); ctrl.stop()


def test_surround_slashing_op_passes_spec_predicate():
    """A surround_vote hit must produce an AttesterSlashing whose
    attestation_1 SURROUNDS attestation_2 (spec argument order) so the
    pack-time predicate keeps it. Driven at the slasher-feed level (the
    fork-choice validity of the votes is covered by the e2e test above)."""
    import numpy as np

    from grandine_tpu.consensus import predicates
    from grandine_tpu.fork_choice.store import ValidAttestation
    from grandine_tpu.types.containers import spec_types

    ns = spec_types(CFG.preset).deneb
    genesis = interop_genesis_state(16, CFG)
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    pool = OperationPool(CFG)
    verifier = AttestationVerifier(
        ctrl, use_device=False, slasher=Slasher(), operation_pool=pool
    )
    try:
        def att(source_epoch, target_epoch, tag):
            return ns.Attestation(
                aggregation_bits=np.ones(4, dtype=bool),
                data=ns.AttestationData(
                    slot=target_epoch * CFG.preset.SLOTS_PER_EPOCH,
                    index=0,
                    beacon_block_root=bytes([tag]) * 32,
                    source=ns.Checkpoint(
                        epoch=source_epoch, root=bytes([tag]) * 32
                    ),
                    target=ns.Checkpoint(
                        epoch=target_epoch, root=bytes([tag]) * 32
                    ),
                ),
                signature=b"\x00" * 96,
            )

        def valid_for(a):
            return ValidAttestation(
                [3, 4], int(a.data.target.epoch),
                bytes(a.data.beacon_block_root), 0,
            )

        inner = att(1, 2, 0x11)
        outer = att(0, 3, 0x22)  # surrounds (1, 2)
        verifier._feed_slasher([(inner, valid_for(inner))])
        assert pool.contents()["attester_slashings"] == []
        verifier._feed_slasher([(outer, valid_for(outer))])
        slashings = pool.contents()["attester_slashings"]
        assert len(slashings) == 1, verifier.stats
        s = slashings[0]
        # attestation_1 is the SURROUNDING (outer) one
        assert int(s.attestation_1.data.source.epoch) == 0
        assert int(s.attestation_1.data.target.epoch) == 3
        assert predicates.is_slashable_attestation_data(
            s.attestation_1.data, s.attestation_2.data
        )
        # and the reverse case: a new vote SURROUNDED by an existing one
        # (fresh verifier+slasher: phase-1 history would legitimately
        # add more offenses)
        pool2 = OperationPool(CFG)
        verifier2 = AttestationVerifier(
            ctrl, use_device=False, slasher=Slasher(), operation_pool=pool2
        )
        try:
            wide = att(0, 6, 0x33)
            narrow = att(1, 5, 0x44)
            verifier2._feed_slasher([(wide, valid_for(wide))])
            verifier2._feed_slasher([(narrow, valid_for(narrow))])
        finally:
            verifier2.stop()
        slashings = pool2.contents()["attester_slashings"]
        assert len(slashings) == 1
        s = slashings[0]
        assert int(s.attestation_1.data.source.epoch) == 0
        assert int(s.attestation_1.data.target.epoch) == 6
        assert predicates.is_slashable_attestation_data(
            s.attestation_1.data, s.attestation_2.data
        )
    finally:
        verifier.stop()
        ctrl.stop()
