"""Differential tests: device limb/field arithmetic vs the pure-Python anchor.

Runs on CPU (tests/conftest.py forces JAX_PLATFORMS=cpu with 8 virtual
devices). Every op is compared against grandine_tpu/crypto/fields.py on
random and worst-case inputs, including realistic op-chains that exercise
the relaxed signed-digit representation's bound discipline.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.kernel

from grandine_tpu.crypto.constants import P
from grandine_tpu.crypto.fields import Fq, Fq2, Fq6, Fq12
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L

rng = random.Random(0xD1F)


def rand_ints(n):
    return [rng.randrange(P) for _ in range(n - 1)] + [P - 1]


def rq2():
    return Fq2.from_ints(rng.randrange(P), rng.randrange(P))


def rq6():
    return Fq6(rq2(), rq2(), rq2())


def rq12():
    return Fq12(rq6(), rq6())


def fp_of(ints):
    """ints → limb-list element with a leading batch axis."""
    return L.split(jnp.asarray(np.stack([L.to_mont(x) for x in ints])))


def test_limb_roundtrip_and_basic_ops():
    xs, ys = rand_ints(4), rand_ints(4)
    A, B = fp_of(xs), fp_of(ys)
    mm = L.merge_np(jax.jit(L.montmul)(A, B))
    for i in range(4):
        assert L.from_mont(mm[i]) == xs[i] * ys[i] % P
        assert L.from_mont(L.merge_np(L.add_mod(A, B))[i]) == (xs[i] + ys[i]) % P
        assert L.from_mont(L.merge_np(L.sub_mod(A, B))[i]) == (xs[i] - ys[i]) % P
        assert L.from_mont(L.merge_np(L.neg_mod(A))[i]) == (-xs[i]) % P


def test_limb_inverse():
    xs = rand_ints(3)
    inv = L.merge_np(jax.jit(L.inv_mod)(fp_of(xs)))
    for i, x in enumerate(xs):
        assert L.from_mont(inv[i]) == pow(x, P - 2, P)


def test_realistic_op_chain_stays_exact():
    # alternating adds and a reducing multiplication — the op pattern of the
    # curve/pairing formulas (at most a few adds between montmuls)
    x0, x1 = rng.randrange(P), rng.randrange(P)

    def chain(acc, b):
        for _ in range(20):
            acc = L.montmul(L.add_mod(L.add_mod(acc, acc), b), acc)
        return acc

    acc = jax.jit(chain)(fp_of([x0]), fp_of([x1]))
    ref = x0
    for _ in range(20):
        ref = (2 * ref + x1) * ref % P
    assert L.from_mont(L.merge_np(acc)[0]) == ref


def test_montmul_on_negative_representations():
    xs = rand_ints(3)
    A = fp_of(xs)
    neg = L.neg_mod(A)  # digits represent -x (signed)
    sq = L.merge_np(jax.jit(L.montmul)(neg, neg))
    for i, x in enumerate(xs):
        assert L.from_mont(sq[i]) == x * x % P


def test_value_predicates():
    a = fp_of([rng.randrange(1, P)])
    assert bool(L.is_zero_val(L.sub_mod(a, a))[0])
    assert bool(L.is_zero_val(L.neg_mod(L.sub_mod(a, a)))[0])
    assert not bool(L.is_zero_val(a)[0])
    one = L.split(jnp.asarray(L.ONE_MONT)[None])
    assert bool(L.is_one_mont(one)[0])
    assert not bool(L.is_one_mont(a)[0])


def fq2_in(a):
    return F.fp2_split(jnp.asarray(F.fq2_to_dev(a)))


def fq2_out(d):
    return F.dev_to_fq2(F.fp2_merge_np(d))


def test_fp2_ops():
    a, b = rq2(), rq2()
    A, B = fq2_in(a), fq2_in(b)
    assert fq2_out(jax.jit(F.fp2_mul)(A, B)) == a * b
    assert fq2_out(jax.jit(F.fp2_sq)(A)) == a.square()
    assert fq2_out(jax.jit(F.fp2_inv)(A)) == a.inv()
    assert fq2_out(F.fp2_mul_by_xi(A)) == a.mul_by_xi()
    assert fq2_out(F.fp2_conj(A)) == a.conjugate()
    k = Fq(rng.randrange(P))
    kl = L.split(jnp.asarray(L.to_mont(k.n)))
    assert fq2_out(jax.jit(F.fp2_scale)(A, kl)) == a.scale(k)


def test_fp6_ops():
    a, b = rq6(), rq6()
    A = F.fp6_split(jnp.asarray(F.fq6_to_dev(a)))
    B = F.fp6_split(jnp.asarray(F.fq6_to_dev(b)))

    def out(d):
        return F.dev_to_fq6(F.fp6_merge_np(d))

    assert out(jax.jit(F.fp6_mul)(A, B)) == a * b
    assert out(jax.jit(F.fp6_inv)(A)) == a.inv()
    assert out(jax.jit(F.fp6_frobenius)(A)) == a.frobenius()
    assert out(F.fp6_mul_by_v(A)) == a.mul_by_v()


def test_fp12_ops():
    a, b = rq12(), rq12()
    A = F.fp12_split(jnp.asarray(F.fq12_to_dev(a)))
    B = F.fp12_split(jnp.asarray(F.fq12_to_dev(b)))

    def out(d):
        return F.dev_to_fq12(F.fp12_merge_np(d))

    assert out(jax.jit(F.fp12_mul)(A, B)) == a * b
    assert out(jax.jit(F.fp12_inv)(A)) == a.inv()
    assert out(jax.jit(F.fp12_frobenius)(A)) == a.frobenius()
    assert (
        out(jax.jit(lambda x: F.fp12_frobenius_n(x, 2))(A))
        == a.frobenius().frobenius()
    )
    assert out(F.fp12_conj(A)) == a.conjugate()
    one = F.fp12_split(jnp.asarray(F.fq12_to_dev(Fq12.one())))
    assert bool(F.fp12_is_one(one))
    assert not bool(F.fp12_is_one(A))
