"""Differential tests: device limb/field arithmetic vs the pure-Python anchor.

Runs on CPU (tests/conftest.py forces JAX_PLATFORMS=cpu with 8 virtual
devices). Every op is compared against grandine_tpu/crypto/fields.py on
random and worst-case inputs, including realistic op-chains that exercise
the relaxed signed-digit representation's bound discipline.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grandine_tpu.crypto.constants import P
from grandine_tpu.crypto.fields import Fq, Fq2, Fq6, Fq12
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L

rng = random.Random(0xD1F)


def rand_ints(n):
    return [rng.randrange(P) for _ in range(n - 1)] + [P - 1]


def rq2():
    return Fq2.from_ints(rng.randrange(P), rng.randrange(P))


def rq6():
    return Fq6(rq2(), rq2(), rq2())


def rq12():
    return Fq12(rq6(), rq6())


def test_limb_roundtrip_and_basic_ops():
    xs, ys = rand_ints(4), rand_ints(4)
    A = jnp.asarray(np.stack([L.to_mont(x) for x in xs]))
    B = jnp.asarray(np.stack([L.to_mont(y) for y in ys]))
    mm = jax.jit(L.montmul)(A, B)
    for i in range(4):
        assert L.from_mont(np.asarray(mm)[i]) == xs[i] * ys[i] % P
        assert L.from_mont(np.asarray(L.add_mod(A, B))[i]) == (xs[i] + ys[i]) % P
        assert L.from_mont(np.asarray(L.sub_mod(A, B))[i]) == (xs[i] - ys[i]) % P
        assert L.from_mont(np.asarray(L.neg_mod(A))[i]) == (-xs[i]) % P


def test_limb_inverse():
    xs = rand_ints(3)
    A = jnp.asarray(np.stack([L.to_mont(x) for x in xs]))
    inv = jax.jit(L.inv_mod)(A)
    for i, x in enumerate(xs):
        assert L.from_mont(np.asarray(inv)[i]) == pow(x, P - 2, P)


def test_realistic_op_chain_stays_exact():
    # alternating adds and a reducing multiplication — the op pattern of the
    # curve/pairing formulas (at most a few adds between montmuls)
    x0, x1 = rng.randrange(P), rng.randrange(P)
    acc = jnp.asarray(L.to_mont(x0))
    b = jnp.asarray(L.to_mont(x1))
    ref = x0
    for _ in range(20):
        acc = L.montmul(L.add_mod(L.add_mod(acc, acc), b), acc)
        ref = (2 * ref + x1) * ref % P
    assert L.from_mont(np.asarray(acc)) == ref


def test_montmul_on_negative_representations():
    xs = rand_ints(3)
    A = jnp.asarray(np.stack([L.to_mont(x) for x in xs]))
    neg = L.neg_mod(A)  # digits represent -x (signed)
    sq = jax.jit(L.montmul)(neg, neg)
    for i, x in enumerate(xs):
        assert L.from_mont(np.asarray(sq)[i]) == x * x % P


def test_value_predicates():
    a = jnp.asarray(L.to_mont(rng.randrange(1, P)))
    assert bool(L.is_zero_val(L.sub_mod(a, a)))
    assert bool(L.is_zero_val(L.neg_mod(L.sub_mod(a, a))))
    assert not bool(L.is_zero_val(a))
    assert bool(L.is_one_mont(jnp.asarray(L.ONE_MONT)))
    assert not bool(L.is_one_mont(a))


def test_fp2_ops():
    a, b = rq2(), rq2()
    A, B = jnp.asarray(F.fq2_to_dev(a)), jnp.asarray(F.fq2_to_dev(b))
    assert F.dev_to_fq2(jax.jit(F.fp2_mul)(A, B)) == a * b
    assert F.dev_to_fq2(jax.jit(F.fp2_sq)(A)) == a.square()
    assert F.dev_to_fq2(jax.jit(F.fp2_inv)(A)) == a.inv()
    assert F.dev_to_fq2(F.fp2_mul_by_xi(A)) == a.mul_by_xi()
    assert F.dev_to_fq2(F.fp2_conj(A)) == a.conjugate()
    k = Fq(rng.randrange(P))
    assert F.dev_to_fq2(jax.jit(F.fp2_scale)(A, jnp.asarray(L.to_mont(k.n)))) == a.scale(k)


def test_fp6_ops():
    a, b = rq6(), rq6()
    A, B = jnp.asarray(F.fq6_to_dev(a)), jnp.asarray(F.fq6_to_dev(b))
    assert F.dev_to_fq6(jax.jit(F.fp6_mul)(A, B)) == a * b
    assert F.dev_to_fq6(jax.jit(F.fp6_inv)(A)) == a.inv()
    assert F.dev_to_fq6(jax.jit(F.fp6_frobenius)(A)) == a.frobenius()
    assert F.dev_to_fq6(F.fp6_mul_by_v(A)) == a.mul_by_v()


def test_fp12_ops():
    a, b = rq12(), rq12()
    A, B = jnp.asarray(F.fq12_to_dev(a)), jnp.asarray(F.fq12_to_dev(b))
    assert F.dev_to_fq12(jax.jit(F.fp12_mul)(A, B)) == a * b
    assert F.dev_to_fq12(jax.jit(F.fp12_inv)(A)) == a.inv()
    assert F.dev_to_fq12(jax.jit(F.fp12_frobenius)(A)) == a.frobenius()
    assert (
        F.dev_to_fq12(jax.jit(lambda x: F.fp12_frobenius_n(x, 2))(A))
        == a.frobenius().frobenius()
    )
    assert F.dev_to_fq12(F.fp12_conj(A)) == a.conjugate()
    assert bool(F.fp12_is_one(jnp.asarray(F.fq12_to_dev(Fq12.one()))))
    assert not bool(F.fp12_is_one(A))
