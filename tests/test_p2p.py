"""Multi-node networking tests over the in-process gossip hub: gossip
block propagation between two nodes, a late joiner catching up by range
sync, back-sync of pre-checkpoint history, and slasher detection.

The reference cannot test multi-node behavior in-repo (SURVEY §4.3: "gossip
logic is tested at the unit level and via channel-boundary assertions");
the Transport seam makes it possible here.
"""

import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.p2p import BlockSyncService, InMemoryHub, Network
from grandine_tpu.p2p.sync import back_sync, verify_block_batch
from grandine_tpu.runtime import AttestationVerifier, Controller
from grandine_tpu.slasher import Slasher
from grandine_tpu.storage import Database, Storage
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()


def make_node(genesis, hub, name, with_storage=False):
    storage = Storage(Database.in_memory(), CFG) if with_storage else None
    ctrl = Controller(
        genesis, CFG, verifier_factory=NullVerifier, storage=storage
    )
    transport = hub.join(name)
    verifier = AttestationVerifier(ctrl, use_device=False, deadline_s=0.01)
    net = Network(transport, ctrl, CFG, attestation_verifier=verifier,
                  storage=storage)
    return ctrl, net, verifier, storage


def test_gossip_block_propagation():
    genesis = interop_genesis_state(16, CFG)
    hub = InMemoryHub()
    ctrl_a, net_a, ver_a, _ = make_node(genesis, hub, "alice")
    ctrl_b, net_b, ver_b, _ = make_node(genesis, hub, "bob")
    try:
        state = genesis
        for slot in (1, 2, 3):
            blk, state = produce_block(
                state, slot, CFG, full_sync_participation=False
            )
            for c in (ctrl_a, ctrl_b):
                c.on_tick(Tick(slot, TickKind.PROPOSE))
            ctrl_a.on_own_block(blk)
            ctrl_a.wait()
            net_a.publish_block(blk)  # gossip to bob
            ctrl_b.wait()
        assert ctrl_b.snapshot().head_root == ctrl_a.snapshot().head_root
        assert net_b.stats["blocks_in"] == 3
        assert net_a.stats["blocks_in"] == 0  # no echo to self
    finally:
        ver_a.stop(); ver_b.stop()
        ctrl_a.stop(); ctrl_b.stop()


def test_gossip_attestations_feed_firehose():
    genesis = interop_genesis_state(16, CFG)
    hub = InMemoryHub()
    ctrl_a, net_a, ver_a, _ = make_node(genesis, hub, "alice")
    ctrl_b, net_b, ver_b, _ = make_node(genesis, hub, "bob")
    try:
        blk, post = produce_block(genesis, 1, CFG, full_sync_participation=False)
        for c in (ctrl_a, ctrl_b):
            c.on_tick(Tick(1, TickKind.PROPOSE))
            c.on_own_block(blk)
            c.wait()
        for att in produce_attestations(post, CFG, slot=1):
            net_a.publish_attestation(att)
        ver_b.flush()
        ctrl_b.wait()
        assert ver_b.stats["accepted"] >= 1
        # votes mature at the next slot
        ctrl_b.on_tick(Tick(2, TickKind.PROPOSE))
        ctrl_b.wait()
        assert len(ctrl_b.store.latest_message_root) > 0
    finally:
        ver_a.stop(); ver_b.stop()
        ctrl_a.stop(); ctrl_b.stop()


def test_late_joiner_range_syncs():
    genesis = interop_genesis_state(16, CFG)
    hub = InMemoryHub()
    ctrl_a, net_a, ver_a, _ = make_node(genesis, hub, "alice")
    state = genesis
    try:
        for slot in range(1, 11):
            blk, state = produce_block(
                state, slot, CFG, full_sync_participation=False
            )
            ctrl_a.on_tick(Tick(slot, TickKind.PROPOSE))
            ctrl_a.on_own_block(blk)
            ctrl_a.wait()

        # carol joins at slot 10 with nothing but genesis
        ctrl_c, net_c, ver_c, _ = make_node(genesis, hub, "carol")
        try:
            service = BlockSyncService(net_c.transport, ctrl_c, CFG)
            service.sync_to_head()
            assert (
                ctrl_c.snapshot().head_root == ctrl_a.snapshot().head_root
            )
            assert int(ctrl_c.snapshot().head_state.slot) == 10
            assert service.stats["requested"] >= 10
        finally:
            ver_c.stop(); ctrl_c.stop()

        # erin syncs the same range in bulk mode: the fetched window is
        # verified as ONE cross-block pipeline batch, then imported
        # trusted (no per-block verifier)
        ctrl_e, net_e, ver_e, _ = make_node(genesis, hub, "erin")
        try:
            service = BlockSyncService(
                net_e.transport, ctrl_e, CFG, bulk_verify=True
            )
            service.sync_to_head()
            assert (
                ctrl_e.snapshot().head_root == ctrl_a.snapshot().head_root
            )
            assert service.stats["bulk_blocks"] == 10
            assert service.stats["bulk_fallbacks"] == 0
        finally:
            ver_e.stop(); ctrl_e.stop()
    finally:
        ver_a.stop(); ctrl_a.stop()


def test_back_sync_fills_history():
    genesis = interop_genesis_state(16, CFG)
    hub = InMemoryHub()
    ctrl_a, net_a, ver_a, _ = make_node(genesis, hub, "alice")
    state = genesis
    blocks = {}
    try:
        for slot in range(1, 9):
            blk, state = produce_block(
                state, slot, CFG, full_sync_participation=False
            )
            blocks[slot] = blk
            ctrl_a.on_tick(Tick(slot, TickKind.PROPOSE))
            ctrl_a.on_own_block(blk)
            ctrl_a.wait()

        # a checkpoint-synced node: storage holds only the slot-8 anchor
        storage = Storage(Database.in_memory(), CFG)
        from grandine_tpu.storage.storage import PREFIX_BLOCK, PREFIX_SLOT_INDEX, _slot_key

        anchor = blocks[8]
        root = anchor.message.hash_tree_root()
        storage.db.put(PREFIX_BLOCK + root, anchor.serialize())
        storage.db.put(_slot_key(PREFIX_SLOT_INDEX, 8), root)

        transport = hub.join("dave")
        stats = back_sync(storage, transport, CFG, anchor_slot=8)
        assert stats["stored"] == 7  # slots 1..7
        assert stats["off_chain"] == 0
        # checkpoint-sync shape: no pre-anchor state to replay from, so
        # the fill keeps linkage-only verification
        assert stats["reverified"] == 0
        for slot in range(1, 8):
            r = storage.finalized_root_by_slot(slot)
            assert r == blocks[slot].message.hash_tree_root()
    finally:
        ver_a.stop(); ctrl_a.stop()


def test_verify_block_batch():
    genesis = interop_genesis_state(16, CFG)
    state = genesis
    chain = []
    for slot in (1, 2, 3):
        blk, state = produce_block(state, slot, CFG, full_sync_participation=False)
        chain.append(blk)
    posts = verify_block_batch(genesis, chain, CFG)
    assert len(posts) == 3
    assert posts[-1].hash_tree_root() == state.hash_tree_root()
    from grandine_tpu.consensus.verifier import SignatureInvalid

    bad = chain[1].replace(signature=b"\x80" + b"\x01" * 95)
    with pytest.raises(Exception):
        verify_block_batch(genesis, [chain[0], bad], CFG)


# ------------------------------------------------------------------ slasher


def test_slasher_detects_offenses():
    sl = Slasher()
    # double vote: same target, different data roots
    assert sl.on_attestation([1, 2], 0, 5, b"\xaa" * 32) == []
    hits = sl.on_attestation([2], 0, 5, b"\xbb" * 32)
    assert len(hits) == 1 and hits[0].kind == "double_vote"
    # surround: recorded (2,3); new (1,4) surrounds it
    sl.on_attestation([7], 2, 3, b"\xcc" * 32)
    hits = sl.on_attestation([7], 1, 4, b"\xdd" * 32)
    assert len(hits) == 1 and hits[0].kind == "surround_vote"
    # surrounded: recorded (1,4) now; new (2,3)... already recorded, use fresh
    sl.on_attestation([9], 1, 6, b"\xee" * 32)
    hits = sl.on_attestation([9], 2, 5, b"\xff" * 32)
    assert len(hits) == 1 and hits[0].kind == "surrounded_vote"
    # double block
    assert sl.on_block(3, 10, b"\x01" * 32) is None
    hit = sl.on_block(3, 10, b"\x02" * 32)
    assert hit is not None and hit.kind == "double_block"
    assert len(sl.drain()) == 4
    assert sl.drain() == []
