"""RFC 9380 known-answer conformance vectors (external anchoring).

Embeds the published Appendix K.1 (expand_message_xmd, SHA-256) and
Appendix J.10.1 (BLS12381G2_XMD:SHA-256_SSWU_RO_) test vectors and checks
the anchor implementation reproduces them bit-exactly. This is the external
correctness anchor for the whole G2 hash pipeline — expand_message_xmd,
hash_to_field, simplified-SWU, the 3-isogeny, and h_eff cofactor clearing
all have to be right for even one of these to match.

Structural self-checks below additionally make any transcription error in
the embedded isogeny/h_eff constants detectable without the vectors.

Reference equivalent: blst's hash-to-G2 backing `SecretKey::sign`
(bls/src/secret_key.rs:82-86); spec suite binding in
helper_functions/src/spec_tests.rs.
"""

import pytest

from grandine_tpu.crypto import constants
from grandine_tpu.crypto.curves import B2, Point
from grandine_tpu.crypto.fields import Fq2
from grandine_tpu.crypto.hash_to_curve import (
    _iso3_map,
    _map_to_curve_sswu_g2,
    expand_message_xmd,
    hash_to_g2,
)

# --- Appendix K.1: expand_message_xmd(SHA-256) ----------------------------

XMD_DST = b"QUUX-V01-CS02-with-expander-SHA256-128"

# (msg, len_in_bytes, uniform_bytes hex)
XMD_VECTORS = [
    (b"", 0x20,
     "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"),
    (b"abc", 0x20,
     "d8ccab23b5985ccea865c6c97b6e5b8350e794e603b4b97902f53a8a0d605615"),
    (b"abcdef0123456789", 0x20,
     "eff31487c770a893cfb36f912fbfcbff40d5661771ca4b2cb4eafe524333f5c1"),
    (b"q128_" + b"q" * 128, 0x20,
     "b23a1d2b4d97b2ef7785562a7e8bac7eed54ed6e97e29aa51bfe3f12ddad1ff9"),
    (b"a512_" + b"a" * 512, 0x20,
     "4623227bcc01293b8c130bf771da8c298dede7383243dc0993d2d94823958c4c"),
    (b"", 0x80,
     "af84c27ccfd45d41914fdff5df25293e221afc53d8ad2ac06d5e3e29485dadbe"
     "e0d121587713a3e0dd4d5e69e93eb7cd4f5df4cd103e188cf60cb02edc3edf18"
     "eda8576c412b18ffb658e3dd6ec849469b979d444cf7b26911a08e63cf31f9dc"
     "c541708d3491184472c2c29bb749d4286b004ceb5ee6b9a7fa5b646c993f0ced"),
    (b"abc", 0x80,
     "abba86a6129e366fc877aab32fc4ffc70120d8996c88aee2fe4b32d6c7b6437a"
     "647e6c3163d40b76a73cf6a5674ef1d890f95b664ee0afa5359a5c4e07985635"
     "bbecbac65d747d3d2da7ec2b8221b17b0ca9dc8a1ac1c07ea6a1e60583e2cb00"
     "058e77b7b72a298425cd1b941ad4ec65e8afc50303a22c0f99b0509b4c895f40"),
]


@pytest.mark.parametrize("msg,n,expected", XMD_VECTORS, ids=lambda v: str(v)[:16])
def test_expand_message_xmd_k1(msg, n, expected):
    assert expand_message_xmd(msg, XMD_DST, n).hex() == expected


# --- Appendix J.10.1: BLS12381G2_XMD:SHA-256_SSWU_RO_ ---------------------

G2_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

# (msg, P.x c0, P.x c1, P.y c0, P.y c1)
G2_RO_VECTORS = [
    (b"",
     0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
     0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D,
     0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
     0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6),
    (b"abc",
     0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
     0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8,
     0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
     0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16),
    (b"abcdef0123456789",
     0x121982811D2491FDE9BA7ED31EF9CA474F0E1501297F68C298E9F4C0028ADD35AEA8BB83D53C08CFC007C1E005723CD0,
     0x190D119345B94FBD15497BCBA94ECF7DB2CBFD1E1FE7DA034D26CBBA169FB3968288B3FAFB265F9EBD380512A71C3F2C,
     0x05571A0F8D3C08D094576981F4A3B8EDA0A8E771FCDCC8ECCEAF1356A6ACF17574518ACB506E435B639353C2E14827C8,
     0x0BB5E7572275C567462D91807DE765611490205A941A5A6AF3B1691BFE596C31225D3AABDF15FAFF860CB4EF17C7C3BE),
    (b"q128_" + b"q" * 128,
     0x19A84DD7248A1066F737CC34502EE5555BD3C19F2ECDB3C7D9E24DC65D4E25E50D83F0F77105E955D78F4762D33C17DA,
     0x0934ABA516A52D8AE479939A91998299C76D39CC0C035CD18813BEC433F587E2D7A4FEF038260EEF0CEF4D02AAE3EB91,
     0x14F81CD421617428BC3B9FE25AFBB751D934A00493524BC4E065635B0555084DD54679DF1536101B2C979C0152D09192,
     0x09BCCCFA036B4847C9950780733633F13619994394C23FF0B32FA6B795844F4A0673E20282D07BC69641CEE04F5E5662),
    (b"a512_" + b"a" * 512,
     0x01A6BA2F9A11FA5598B2D8ACE0FBE0A0EACB65DECEB476FBBCB64FD24557C2F4B18ECFC5663E54AE16A84F5AB7F62534,
     0x11FCA2FF525572795A801EED17EB12785887C7B63FB77A42BE46CE4A34131D71F7A73E95FEE3F812AEA3DE78B4D01569,
     0x0B6798718C8AED24BC19CB27F866F1C9EFFCDBF92397AD6448B5C9DB90D2B9DA6CBABF48ADC1ADF59A1A28344E79D57E,
     0x03A47F8E6D1763BA0CAD63D6114C0ACCBEF65707825A511B251A660A9B3994249AE4E63FAC38B23DA0C398689EE2AB52),
]


@pytest.mark.parametrize(
    "msg,x0,x1,y0,y1", G2_RO_VECTORS, ids=lambda v: str(v)[:16]
)
def test_hash_to_g2_j10_1(msg, x0, x1, y0, y1):
    aff = hash_to_g2(msg, G2_DST).to_affine()
    assert aff is not None
    x, y = aff
    assert (x.c0.n, x.c1.n, y.c0.n, y.c1.n) == (x0, x1, y0, y1)


# --- structural self-checks on the embedded constants ---------------------


def test_sswu_lands_on_iso_curve_and_iso_lands_on_e():
    """Any transcription error in A'/B'/Z or the isogeny tables breaks this."""
    a = Fq2.from_ints(*constants.SSWU_A_G2)
    b = Fq2.from_ints(*constants.SSWU_B_G2)
    for i in range(8):
        u = Fq2.from_ints(0xDEAD0000 + i, 0xBEEF0000 + 31 * i)
        xp, yp = _map_to_curve_sswu_g2(u)
        assert yp.square() == xp.square() * xp + a * xp + b
        x, y = _iso3_map(xp, yp)
        assert y.square() == x.square() * x + B2


def test_h_eff_output_is_r_torsion():
    """h_eff·P must land in G2 for arbitrary curve points P ∈ E'(Fp2)...

    ...here exercised through the full map (whose pre-clearing point is a
    practically-random E point). A wrong h_eff leaves an r-coprime factor
    alive with overwhelming probability.
    """
    p = hash_to_g2(b"h_eff structural check", G2_DST)
    assert not p.is_infinity()
    assert p.mul(constants.R).is_infinity()


def test_sswu_exceptional_case_tv2_zero():
    """u = 0 drives Z²u⁴+Zu² = 0 — the inv0 branch of SSWU."""
    xp, yp = _map_to_curve_sswu_g2(Fq2.zero())
    a = Fq2.from_ints(*constants.SSWU_A_G2)
    b = Fq2.from_ints(*constants.SSWU_B_G2)
    assert yp.square() == xp.square() * xp + a * xp + b
    x, y = _iso3_map(xp, yp)
    assert y.square() == x.square() * x + B2
