"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh: multi-chip sharding tests run
here, and unit tests stay off the (single) real TPU chip, which the driver
uses for bench runs. The environment's sitecustomize registers the `axon`
TPU platform programmatically, overriding the JAX_PLATFORMS env var — so the
override must also be programmatic (jax.config), before any backend use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (same dir as bench.py/__graft_entry__ —
# CPU and TPU entries coexist under different keys, and the driver's dryrun
# hits what the tests compiled).
import sys as _sys  # noqa: E402

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402

bench._enable_compilation_cache()

import pytest  # noqa: E402

_last_kernel_module = [None]


@pytest.fixture(autouse=True)
def _drop_jit_memory_between_kernel_modules(request):
    """Release compiled-executable memory when the suite crosses from one
    kernel-tier module to the next. A full single-process run
    (`pytest tests/ -x -q`, the driver's invocation) accumulates every
    heavy pairing/MSM executable on the 8-device mesh and can abort in
    XLA's allocator; dropping caches at module boundaries bounds the
    high-water mark. Warm recompiles come from the persistent on-disk
    cache, so the cost is seconds, not minutes."""
    if request.node.get_closest_marker("kernel") is not None:
        module = request.node.module.__name__
        if _last_kernel_module[0] not in (None, module):
            jax.clear_caches()
        _last_kernel_module[0] = module
    yield
