"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh (the multi-chip sharding tests
run here; the driver separately dry-runs the real multi-chip path via
__graft_entry__.dryrun_multichip). Must run before the first jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
