"""Spec-type structure tests: per-fork field layouts, roundtrips, preset
parameterization, config fork schedule."""

import pytest

from grandine_tpu.ssz import Bits
from grandine_tpu.types import (
    Config, MAINNET, MINIMAL, Phase, spec_types)


T = spec_types(MAINNET)
TM = spec_types(MINIMAL)


def field_names(cls):
    return [f for f, _ in cls.FIELDS]


def test_state_field_counts_per_fork():
    # spec-known container widths (consensus-specs deneb/beacon-chain.md)
    assert len(T.phase0.BeaconState.FIELDS) == 21
    assert len(T.altair.BeaconState.FIELDS) == 24
    assert len(T.bellatrix.BeaconState.FIELDS) == 25
    assert len(T.capella.BeaconState.FIELDS) == 28
    assert len(T.deneb.BeaconState.FIELDS) == 28


def test_body_field_counts_per_fork():
    assert len(T.phase0.BeaconBlockBody.FIELDS) == 8
    assert len(T.altair.BeaconBlockBody.FIELDS) == 9
    assert len(T.bellatrix.BeaconBlockBody.FIELDS) == 10
    assert len(T.capella.BeaconBlockBody.FIELDS) == 11
    assert len(T.deneb.BeaconBlockBody.FIELDS) == 12
    assert field_names(T.deneb.BeaconBlockBody)[-2:] == [
        "bls_to_execution_changes", "blob_kzg_commitments"]


def test_altair_replaces_pending_attestations():
    p0 = field_names(T.phase0.BeaconState)
    al = field_names(T.altair.BeaconState)
    i = p0.index("previous_epoch_attestations")
    assert al[i] == "previous_epoch_participation"
    assert al[i + 1] == "current_epoch_participation"
    assert "previous_epoch_attestations" not in al


def test_execution_payload_evolution():
    be = field_names(T.bellatrix.ExecutionPayload)
    ca = field_names(T.capella.ExecutionPayload)
    de = field_names(T.deneb.ExecutionPayload)
    assert be[-1] == "transactions"
    assert ca[-2:] == ["transactions", "withdrawals"]
    assert de[-2:] == ["blob_gas_used", "excess_blob_gas"]
    # headers mirror with roots
    assert field_names(T.deneb.ExecutionPayloadHeader)[-4:] == [
        "transactions_root", "withdrawals_root", "blob_gas_used",
        "excess_blob_gas"]


def test_preset_parameterization():
    att_m = T.phase0.Attestation
    att_n = TM.phase0.Attestation
    assert att_m is not att_n
    assert att_m.FIELDS[0][1].limit == 2048
    assert att_n.FIELDS[0][1].limit == 2048  # MVPC same in minimal
    assert TM.altair.SyncAggregate.FIELDS[0][1].length == 32
    assert T.altair.SyncAggregate.FIELDS[0][1].length == 512
    assert spec_types(MAINNET) is T  # cached


def test_block_roundtrip_each_fork():
    for fork in ("phase0", "altair", "bellatrix", "capella", "deneb"):
        ns = getattr(T, fork)
        blk = ns.SignedBeaconBlock.default()
        assert ns.SignedBeaconBlock.deserialize(blk.serialize()) == blk
        root = blk.message.hash_tree_root()
        assert len(root) == 32


def test_nontrivial_block_roundtrip():
    ns = T.deneb
    att = ns.Attestation(
        aggregation_bits=Bits([1, 0, 1]),
        data=ns.AttestationData(
            slot=9, index=1, beacon_block_root=b"\x01" * 32,
            source=ns.Checkpoint(epoch=1, root=b"\x02" * 32),
            target=ns.Checkpoint(epoch=2, root=b"\x03" * 32)),
        signature=b"\x05" * 96)
    body = ns.BeaconBlockBody(
        randao_reveal=b"\x06" * 96,
        attestations=[att],
        execution_payload=ns.ExecutionPayload(
            transactions=[b"\xaa\xbb", b""],
            withdrawals=[ns.Withdrawal(index=3, validator_index=7,
                                       address=b"\x01" * 20, amount=12)],
            blob_gas_used=5),
        blob_kzg_commitments=[b"\x09" * 48])
    blk = ns.SignedBeaconBlock(
        message=ns.BeaconBlock(slot=9, proposer_index=4, body=body),
        signature=b"\x0a" * 96)
    back = ns.SignedBeaconBlock.deserialize(blk.serialize())
    assert back == blk
    assert back.message.body.attestations[0].data.target.epoch == 2
    assert list(back.message.body.execution_payload.transactions) == [
        b"\xaa\xbb", b""]


def test_blinded_blocks():
    ns = T.deneb
    bb = ns.SignedBlindedBeaconBlock.default()
    assert "execution_payload_header" in field_names(ns.BlindedBeaconBlockBody)
    assert ns.SignedBlindedBeaconBlock.deserialize(bb.serialize()) == bb


def test_config_fork_schedule():
    cfg = Config.mainnet()
    assert cfg.phase_at_epoch(0) == Phase.PHASE0
    assert cfg.phase_at_epoch(74239) == Phase.PHASE0
    assert cfg.phase_at_epoch(74240) == Phase.ALTAIR
    assert cfg.phase_at_epoch(269568) == Phase.DENEB
    assert cfg.fork_version(Phase.CAPELLA) == bytes.fromhex("03000000")
    assert cfg.phase_at_slot(74240 * 32) == Phase.ALTAIR
    mini = Config.minimal()
    assert mini.phase_at_epoch(0) == Phase.DENEB
    assert mini.preset is MINIMAL


def test_config_from_dict():
    cfg = Config.from_dict({
        "CONFIG_NAME": "custom",
        "PRESET_BASE": "minimal",
        "ALTAIR_FORK_EPOCH": "5",
        "ALTAIR_FORK_VERSION": "0x01000099",
        "UNKNOWN_KEY": "ignored",
    })
    assert cfg.config_name == "custom"
    assert cfg.altair_fork_epoch == 5
    assert cfg.altair_fork_version == bytes.fromhex("01000099")
    assert cfg.phase_at_epoch(4) == Phase.PHASE0


def test_state_roundtrip_with_validators():
    import numpy as np
    ns = T.deneb
    vals = [ns.Validator(pubkey=bytes([i]) * 48,
                         effective_balance=32 * 10**9,
                         exit_epoch=2**64 - 1) for i in range(5)]
    st = ns.BeaconState(
        slot=17,
        validators=vals,
        balances=np.full(5, 32 * 10**9, np.uint64),
        justification_bits=Bits([1, 0, 1, 0]),
    )
    back = ns.BeaconState.deserialize(st.serialize())
    assert back == st
    assert back.validators[3].pubkey == bytes([3]) * 48
    assert back.balances[4] == 32 * 10**9
    assert back.hash_tree_root() == st.hash_tree_root()
