"""Differential tests: GLV/ψ² dual-scalar ladders vs anchor scalar mul."""

import pytest

pytestmark = pytest.mark.kernel

import random

import jax
import jax.numpy as jnp
import numpy as np

from grandine_tpu.crypto.constants import P, R
from grandine_tpu.crypto.curves import (
    G1, G2, LAMBDA, decompose_glv, endo_constants, g1_infinity,
)
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L

rng = random.Random(0x61F)


def _g1_endo(n):
    bx, by = endo_constants()["g1"]
    return (
        L.const_fp([int(d) for d in L.to_mont(bx)], (n,)),
        L.const_fp([int(d) for d in L.to_mont(by)], (n,)),
    )


def _g2_endo(n):
    wx, wy = endo_constants()["g2"]
    zx = L.zeros_fp((n,))
    return (
        (L.const_fp([int(d) for d in L.to_mont(wx)], (n,)), zx),
        (L.const_fp([int(d) for d in L.to_mont(wy)], (n,)), zx),
    )


def test_glv_scalar_mul_both_groups():
    n = 4
    ks = [rng.randrange(1, R) for _ in range(n)]
    r0s = [rng.randrange(1, 1 << 32) for _ in range(n)]
    r1s = [rng.randrange(0, 1 << 32) for _ in range(n)]
    scalars = [(a + b * LAMBDA) % R for a, b in zip(r0s, r1s)]
    bits_lo = jnp.asarray(C.scalars_to_bits_msb(r0s, 32)).T
    bits_hi = jnp.asarray(C.scalars_to_bits_msb(r1s, 32)).T
    infl = jnp.zeros((n,), bool)

    pts1 = [G1.mul(k) for k in ks]
    devs = [C.g1_point_to_dev(p) for p in pts1]
    X = L.split(jnp.asarray(np.stack([d[0] for d in devs])))
    Y = L.split(jnp.asarray(np.stack([d[1] for d in devs])))
    fn = jax.jit(
        lambda qx, qy, qi, b0, b1: C.scalar_mul_glv(
            qx, qy, qi, b0, b1, _g1_endo(n), C.FP_OPS
        )
    )
    sm = fn(X, Y, infl, bits_lo, bits_hi)
    for i in range(n):
        got = C.dev_to_g1_point(
            L.merge_np(sm[0])[i], L.merge_np(sm[1])[i], L.merge_np(sm[2])[i]
        )
        assert got == pts1[i].mul(scalars[i])

    pts2 = [G2.mul(k) for k in ks]
    devs2 = [C.g2_point_to_dev(p) for p in pts2]
    X2 = F.fp2_split(jnp.asarray(np.stack([d[0] for d in devs2])))
    Y2 = F.fp2_split(jnp.asarray(np.stack([d[1] for d in devs2])))
    fn2 = jax.jit(
        lambda qx, qy, qi, b0, b1: C.scalar_mul_glv(
            qx, qy, qi, b0, b1, _g2_endo(n), C.FP2_OPS
        )
    )
    sm2 = fn2(X2, Y2, infl, bits_lo, bits_hi)
    for i in range(n):
        got = C.dev_to_g2_point(
            F.fp2_merge_np(sm2[0])[i],
            F.fp2_merge_np(sm2[1])[i],
            F.fp2_merge_np(sm2[2])[i],
        )
        assert got == pts2[i].mul(scalars[i])


def test_glv_signed_decomposition_g2():
    """The batch-sign path: full-width scalars via decompose_glv with signs."""
    n = 4
    ks = [rng.randrange(1, R) for _ in range(n)]
    decs = [decompose_glv(k) for k in ks]
    bits_lo = jnp.asarray(C.scalars_to_bits_msb([d[0] for d in decs], 128)).T
    bits_hi = jnp.asarray(C.scalars_to_bits_msb([d[2] for d in decs], 128)).T
    neg_lo = jnp.asarray(np.array([d[1] < 0 for d in decs]))
    neg_hi = jnp.asarray(np.array([d[3] < 0 for d in decs]))
    base_ks = [rng.randrange(1, R) for _ in range(n)]
    pts = [G2.mul(k) for k in base_ks]
    devs = [C.g2_point_to_dev(p) for p in pts]
    X = F.fp2_split(jnp.asarray(np.stack([d[0] for d in devs])))
    Y = F.fp2_split(jnp.asarray(np.stack([d[1] for d in devs])))
    infl = jnp.zeros((n,), bool)
    fn = jax.jit(
        lambda qx, qy, qi, b0, b1, n0, n1: C.scalar_mul_glv(
            qx, qy, qi, b0, b1, _g2_endo(n), C.FP2_OPS, neg_lo=n0, neg_hi=n1
        )
    )
    sm = fn(X, Y, infl, bits_lo, bits_hi, neg_lo, neg_hi)
    for i in range(n):
        got = C.dev_to_g2_point(
            F.fp2_merge_np(sm[0])[i],
            F.fp2_merge_np(sm[1])[i],
            F.fp2_merge_np(sm[2])[i],
        )
        assert got == pts[i].mul(ks[i])


def test_glv_jacobian_and_infinity():
    n = 4
    base = [G1.mul(rng.randrange(1, R)) for _ in range(2)]
    pts = [base[0], base[1], g1_infinity(), base[0]]
    r0s = [3, 1, 7, 0]
    r1s = [0, 5, 2, 4]
    scalars = [(a + b * LAMBDA) % R for a, b in zip(r0s, r1s)]
    devs = [C.g1_point_to_dev(p) for p in pts]
    one = np.asarray(L.to_mont(1))
    X = L.split(jnp.asarray(np.stack([d[0] for d in devs])))
    Y = L.split(jnp.asarray(np.stack([d[1] for d in devs])))
    Z = L.split(jnp.asarray(np.stack(
        [np.zeros(L.NLIMBS, np.int32) if d[2] else one for d in devs]
    )))
    infl = jnp.asarray(np.array([False, False, True, False]))
    bits_lo = jnp.asarray(C.scalars_to_bits_msb(r0s, 32)).T
    bits_hi = jnp.asarray(C.scalars_to_bits_msb(r1s, 32)).T
    fn = jax.jit(
        lambda q, qi, b0, b1: C.scalar_mul_jac_glv(
            q, qi, b0, b1, _g1_endo(4), C.FP_OPS
        )
    )
    sm = fn((X, Y, Z), infl, bits_lo, bits_hi)
    for i in range(4):
        got = C.dev_to_g1_point(
            L.merge_np(sm[0])[i], L.merge_np(sm[1])[i], L.merge_np(sm[2])[i]
        )
        if pts[i].is_infinity():
            assert got.is_infinity()
        else:
            assert got == pts[i].mul(scalars[i])
