"""Bulk replay pipeline tests: differential equivalence against the
per-block verifier path, bisection localization of a forged block,
back-sync full re-verification, and the bench smoke invocation.

The differential test is the load-bearing one: windowed cross-block
batch verification must produce byte-identical post-states and verdicts
to the legacy one-verifier-per-block path on the same chain.
"""

import contextlib
import io
import json
import os

import pytest

from grandine_tpu.p2p.sync import verify_block_batch
from grandine_tpu.runtime.replay import BulkReplayPipeline, ReplayInvalidBlock
from grandine_tpu.slasher import Slasher
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()


@pytest.fixture(scope="module")
def chain():
    """4 signature-dense blocks (proposer + randao + attestation
    aggregates) on the minimal preset."""
    genesis = interop_genesis_state(16, CFG)
    state, blocks, atts = genesis, [], []
    for slot in range(1, 5):
        blk, state = produce_block(
            state, slot, CFG, attestations=atts,
            full_sync_participation=False,
        )
        blocks.append(blk)
        atts = produce_attestations(state, CFG, slot=slot)
    return genesis, blocks


def test_bulk_replay_differential(chain):
    genesis, blocks = chain
    ref = verify_block_batch(genesis, blocks, CFG, bulk=False)
    pipe = BulkReplayPipeline(CFG, window_size=2, slasher=Slasher())
    posts = pipe.replay(genesis, blocks)
    assert len(posts) == len(ref)
    for bulk_post, ref_post in zip(posts, ref):
        assert bulk_post.hash_tree_root() == ref_post.hash_tree_root()
    assert pipe.stats["windows"] == 2  # 2+2
    assert pipe.stats["blocks"] == 4
    # cross-block batching actually happened: more signature sets than
    # blocks (block sig + randao at minimum), fed from shared windows
    assert pipe.stats["sigsets"] >= 2 * len(blocks)
    # every replayed attestation reached the slasher
    assert pipe.stats["slasher_attestations"] > 0
    assert pipe.stats["slasher_hits"] == 0


def test_forged_block_localized(chain):
    """A valid-point-wrong-message signature on block k fails the window
    batch; split-in-half re-dispatch must name exactly block k and hand
    back the verified posts of every block before it."""
    genesis, blocks = chain
    k = 2
    forged = blocks[k].replace(signature=bytes(blocks[0].signature))
    seq = blocks[:k] + [forged] + blocks[k + 1 :]
    pipe = BulkReplayPipeline(CFG, window_size=len(seq))
    with pytest.raises(ReplayInvalidBlock) as excinfo:
        pipe.replay(genesis, seq)
    err = excinfo.value
    assert err.index == k
    assert err.slot == int(blocks[k].message.slot)
    assert len(err.verified_posts) == k
    assert pipe.stats["localizations"] == 1


def test_verify_block_batch_routes_through_pipeline(chain):
    genesis, blocks = chain
    posts = verify_block_batch(genesis, blocks[:2], CFG, window_size=2)
    assert len(posts) == 2
    with pytest.raises(ReplayInvalidBlock):
        bad = blocks[1].replace(signature=bytes(blocks[0].signature))
        verify_block_batch(genesis, [blocks[0], bad], CFG)


def test_back_sync_reverifies_through_pipeline():
    """A back-synced node with a stored genesis state re-verifies every
    signature of the filled history through the pipeline."""
    from grandine_tpu.consensus.verifier import NullVerifier
    from grandine_tpu.fork_choice.store import Tick, TickKind
    from grandine_tpu.p2p import InMemoryHub
    from grandine_tpu.p2p.sync import back_sync
    from grandine_tpu.runtime import AttestationVerifier, Controller
    from grandine_tpu.storage import Database, Storage
    from grandine_tpu.storage.storage import (
        PREFIX_BLOCK,
        PREFIX_SLOT_INDEX,
        _slot_key,
    )

    genesis = interop_genesis_state(16, CFG)
    hub = InMemoryHub()
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    transport_a = hub.join("alice")
    ver = AttestationVerifier(ctrl, use_device=False, deadline_s=0.01)
    from grandine_tpu.p2p import Network

    net = Network(transport_a, ctrl, CFG, attestation_verifier=ver)
    state, blocks = genesis, {}
    try:
        for slot in range(1, 4):
            blk, state = produce_block(
                state, slot, CFG, full_sync_participation=False
            )
            blocks[slot] = blk
            ctrl.on_tick(Tick(slot, TickKind.PROPOSE))
            ctrl.on_own_block(blk)
            ctrl.wait()

        storage = Storage(Database.in_memory(), CFG)
        storage.persist_anchor(genesis)  # pre-anchor state available
        anchor = blocks[3]
        root = anchor.message.hash_tree_root()
        storage.db.put(PREFIX_BLOCK + root, anchor.serialize())
        storage.db.put(_slot_key(PREFIX_SLOT_INDEX, 3), root)

        transport_b = hub.join("dave")
        stats = back_sync(storage, transport_b, CFG, anchor_slot=3)
        assert stats["stored"] == 2
        assert stats["off_chain"] == 0
        assert stats["reverified"] == 2  # full signature re-verification
    finally:
        ver.stop()
        ctrl.stop()
    assert net is not None


def test_bench_replay_smoke(monkeypatch):
    """`bench.py --replay` emits one parseable replay_bulk_vs_perblock
    JSON line (host mode, tiny chain — the cheap smoke the CI gate
    parses)."""
    import bench

    for key, val in {
        "BENCH_REPLAY_BLOCKS": "2",
        "BENCH_REPLAY_VALIDATORS": "16",
        "BENCH_REPLAY_DEVICE": "0",
        "BENCH_REPLAY_REPS": "1",
        "BENCH_SKIP_LINT": "1",
        "BENCH_SKIP_RANGES": "1",  # preflight gate has its own tests
    }.items():
        monkeypatch.setenv(key, val)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.bench_replay()
    lines = [ln for ln in buf.getvalue().splitlines() if ln.startswith("{")]
    assert lines, "no JSON line emitted"
    report = json.loads(lines[-1])
    assert report["metric"] == "replay_bulk_vs_perblock"
    assert report["sigsets"] > 0
    assert report["value"] > 0
    assert report["per_block"] > 0
    assert report["blocks"] == 2
    assert os.environ["BENCH_SKIP_LINT"] == "1"
