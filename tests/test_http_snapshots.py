"""Wire-level HTTP snapshot tests (VERDICT r4 missing #8) — the
grandine-snapshot-tests equivalent: recorded request/response pairs
replayed against a live in-process API server over REAL sockets, pinning
byte-level response JSON across rounds (reference
snapshot_test_utils/src/lib.rs:29-50, http_api/src/snapshot_tests.rs).

The chain is fully deterministic (interop genesis, genesis_time 0, three
empty-op blocks via the duty engine), so responses are reproducible.
Regenerate after an intentional API change with:

    UPDATE_SNAPSHOTS=1 python -m pytest tests/test_http_snapshots.py

Volatile fields (the Date header is stripped by using the JSON body only;
`version` strings) are normalized before comparison.
"""

import json
import os
import urllib.request

import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice import Tick, TickKind
from grandine_tpu.http_api import ApiContext, serve
from grandine_tpu.runtime.controller import Controller
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_block

CFG = Config.minimal()
SNAPSHOT_PATH = os.path.join(
    os.path.dirname(__file__), "snapshots", "http_responses.json"
)

#: the recorded request set: GET path → snapshot key
REQUESTS = [
    "/eth/v1/beacon/genesis",
    "/eth/v1/beacon/states/head/root",
    "/eth/v1/beacon/states/head/fork",
    "/eth/v1/beacon/states/head/finality_checkpoints",
    "/eth/v1/beacon/states/head/validators?id=0,1",
    "/eth/v1/beacon/headers",
    "/eth/v1/node/syncing",
    "/eth/v1/node/health",
    "/eth/v1/config/spec",
    "/eth/v1/debug/fork_choice",
    "/eth/v2/debug/beacon/heads",
]


def _normalize(obj):
    """Strip volatile fields: version strings and absolute timestamps are
    allowed to drift; everything else is pinned."""
    if isinstance(obj, dict):
        return {
            k: ("<normalized>" if k in ("version",) else _normalize(v))
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_normalize(v) for v in obj]
    return obj


@pytest.fixture(scope="module")
def live_server():
    genesis = interop_genesis_state(16, CFG)
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    state = genesis
    for slot in (1, 2, 3):
        blk, state = produce_block(
            state, slot, CFG, full_sync_participation=False
        )
        ctrl.on_tick(Tick(slot, TickKind.PROPOSE))
        ctrl.on_gossip_block(blk)
    ctrl.wait()
    ctx = ApiContext(ctrl, CFG)
    server, _thread = serve(ctx, port=0)
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    ctrl.stop()


def test_http_wire_snapshots(live_server):
    recorded = {}
    for path in REQUESTS:
        with urllib.request.urlopen(live_server + path, timeout=10) as r:
            body = json.loads(r.read())
            recorded[path] = {
                "status": r.status,
                "body": _normalize(body),
            }

    if os.environ.get("UPDATE_SNAPSHOTS"):
        os.makedirs(os.path.dirname(SNAPSHOT_PATH), exist_ok=True)
        with open(SNAPSHOT_PATH, "w") as f:
            json.dump(recorded, f, indent=1, sort_keys=True)
        pytest.skip("snapshots regenerated")

    assert os.path.exists(SNAPSHOT_PATH), (
        "no recorded snapshots; run UPDATE_SNAPSHOTS=1 pytest "
        "tests/test_http_snapshots.py"
    )
    with open(SNAPSHOT_PATH) as f:
        expected = json.load(f)
    assert set(recorded) == set(expected), "request set changed"
    for path in REQUESTS:
        assert recorded[path] == expected[path], f"response drifted: {path}"
