"""CLI, feature toggles, and keymanager/keystore tests."""

import json
import os

import pytest

from grandine_tpu import features
from grandine_tpu.cli import build_parser, load_config, main
from grandine_tpu.crypto import bls as A
from grandine_tpu.validator.keymanager import (
    KeyManager,
    decrypt_keystore,
    encrypt_keystore,
)
from grandine_tpu.validator.signer import Signer


@pytest.fixture(autouse=True)
def reset_features():
    features.reset()
    yield
    features.reset()


# -------------------------------------------------------------------- CLI


def test_cli_info(capsys):
    assert main(["--network", "minimal", "info"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["preset"] == "minimal"
    assert out["slots_per_epoch"] == 8


def test_cli_features_flag():
    main(["--features", "TrustOwnBlockSignatures", "info"])
    assert features.is_enabled(features.Feature.TRUST_OWN_BLOCK_SIGNATURES)
    with pytest.raises(ValueError):
        features.enable_by_name("NoSuchFeature")


def test_cli_config_yaml(tmp_path):
    yml = tmp_path / "custom.yaml"
    yml.write_text(
        "PRESET_BASE: minimal\n"
        "CONFIG_NAME: customnet\n"
        "SECONDS_PER_SLOT: 3\n"
        "GENESIS_FORK_VERSION: '0x00000009'\n"
    )
    parser = build_parser()
    args = parser.parse_args(["--config-file", str(yml), "info"])
    cfg = load_config(args)
    assert cfg.config_name == "customnet"
    assert cfg.seconds_per_slot == 3
    assert cfg.genesis_fork_version == bytes.fromhex("00000009")


def test_cli_run_devnet(tmp_path, capsys):
    """`run` drives a real in-process node for a few slots with storage."""
    rc = main([
        "--network", "minimal", "--data-dir", str(tmp_path / "node"),
        "run", "--validators", "16", "--slots", "3", "--no-restart",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "slot 3" in out
    assert os.path.exists(tmp_path / "node" / "chain.sqlite")


def test_cli_interchange_roundtrip(tmp_path):
    data_dir = str(tmp_path / "d")
    os.makedirs(data_dir)
    from grandine_tpu.storage import Database
    from grandine_tpu.validator.slashing_protection import SlashingProtection

    db = Database.persistent(
        os.path.join(data_dir, "slashing_protection.sqlite"))
    sp = SlashingProtection(db)
    sp.check_and_insert_block(b"\xee" * 48, 7)
    db.close()

    out_path = str(tmp_path / "interchange.json")
    assert main(["--data-dir", data_dir, "export-interchange", out_path]) == 0
    blob = json.load(open(out_path))
    assert blob["data"][0]["signed_blocks"][0]["slot"] == "7"

    data_dir2 = str(tmp_path / "d2")
    os.makedirs(data_dir2)
    assert main(["--data-dir", data_dir2, "import-interchange", out_path]) == 0


# ------------------------------------------------------------- keystores


def test_keystore_roundtrip_pbkdf2():
    sk = A.SecretKey.keygen(b"\x11" * 32)
    ks = encrypt_keystore(sk, "hunter2 but longer")
    assert ks["version"] == 4
    assert ks["pubkey"] == sk.public_key().to_bytes().hex()
    back = decrypt_keystore(ks, "hunter2 but longer")
    assert back.to_bytes() == sk.to_bytes()
    with pytest.raises(ValueError, match="checksum"):
        decrypt_keystore(ks, "wrong password")


def test_keymanager_surface():
    signer = Signer()
    km = KeyManager(signer)
    sk = A.SecretKey.keygen(b"\x22" * 32)
    ks = encrypt_keystore(sk, "pw")
    results = km.import_keystores([ks], ["pw"])
    assert results[0]["status"] == "imported"
    assert len(km.list_keystores()) == 1
    pk = sk.public_key().to_bytes()
    km.set_fee_recipient(pk, b"\xaa" * 20)
    km.set_graffiti(pk, b"hello")
    assert km.proposer_config(pk)["fee_recipient"] == b"\xaa" * 20
    assert km.delete_keystores([pk])[0]["status"] == "deleted"
    assert km.delete_keystores([pk])[0]["status"] == "not_found"
    # wrong password -> error row, nothing imported
    bad = km.import_keystores([ks], ["nope"])
    assert bad[0]["status"] == "error"


def test_signer_batch_sign_host():
    signer = Signer()
    sks = [A.SecretKey.keygen(bytes([i]) * 32) for i in range(1, 4)]
    pks = [signer.add_key(sk) for sk in sks]
    roots = [bytes([i]) * 32 for i in range(3)]
    sigs = signer.sign_triples(list(zip(pks, roots)))
    for sk, root, sig in zip(sks, roots, sigs):
        assert A.Signature.from_bytes(sig).verify(root, sk.public_key())


def test_signer_remote_web3signer_path():
    """Remote keys route through the injected Web3Signer client and mix
    with local/device keys in sign_triples order."""
    remote_sk = A.SecretKey.keygen(b"\x66" * 32)
    remote_pk = remote_sk.public_key().to_bytes()

    calls = []

    def fake_web3signer(pubkey_hex, root_hex):
        calls.append(pubkey_hex)
        assert pubkey_hex == remote_pk.hex()
        return remote_sk.sign(bytes.fromhex(root_hex)).to_bytes().hex()

    signer = Signer(web3signer=fake_web3signer)
    local_sk = A.SecretKey.keygen(b"\x67" * 32)
    local_pk = signer.add_key(local_sk)
    signer.add_remote_key(remote_pk)
    assert signer.has_key(remote_pk) and len(signer) == 2

    roots = [b"\x01" * 32, b"\x02" * 32]
    sigs = signer.sign_triples([(local_pk, roots[0]), (remote_pk, roots[1])])
    assert A.Signature.from_bytes(sigs[0]).verify(roots[0], local_sk.public_key())
    assert A.Signature.from_bytes(sigs[1]).verify(roots[1], remote_sk.public_key())
    assert calls == [remote_pk.hex()]
    # no client configured -> registration refused
    with pytest.raises(ValueError):
        Signer().add_remote_key(remote_pk)


def test_builder_api_flow():
    from grandine_tpu.builder_api import BuilderApi, BuilderApiError, BuilderConfig

    def relay(method, params):
        if method == "get_header":
            return {"header": {"parent_hash": params["parent_hash"]},
                    "value": 123}
        if method == "submit_blinded_block":
            return {"execution_payload": {"ok": True}}
        raise AssertionError(method)

    api = BuilderApi(relay, BuilderConfig(max_skipped_slots=2))
    bid = api.get_execution_payload_header(5, b"\xab" * 32, b"\xcd" * 48)
    assert bid["value"] == 123
    with pytest.raises(BuilderApiError):
        bad_relay = lambda m, p: {"header": {"parent_hash": "00" * 32}}
        BuilderApi(bad_relay).get_execution_payload_header(
            5, b"\xab" * 32, b"\xcd" * 48
        )

    class FakeBlock:
        def serialize(self):
            return b"\x00" * 8

    payload = api.submit_blinded_block(FakeBlock())
    assert payload["execution_payload"] == {"ok": True}
    assert api.stats == {"headers": 1, "submissions": 1, "circuit_breaks": 0}
