"""Differential tests for the device Pippenger MSM (tpu/msm.py).

Every case checks msm_bucket_scan against the anchor crypto plane
(grandine_tpu/crypto/curves.py): Σᵢ (r0ᵢ + r1ᵢ·λ)·Pᵢ per group, with
adversarial shapes — duplicate points, infinity points, zero scalar
halves, empty groups — plus the MSM-backed verify kernels end to end.
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.kernel
import jax

from grandine_tpu.crypto.bls import SecretKey
from grandine_tpu.crypto.constants import R
from grandine_tpu.crypto.curves import (
    G1,
    G2,
    LAMBDA,
    g1_infinity,
    g2_infinity,
)
from grandine_tpu.tpu import bls as B
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import msm as M


def _host_msm(points, r_lo, r_hi, groups, n_groups, infinity):
    acc = [infinity() for _ in range(n_groups)]
    for p, lo, hi, g in zip(points, r_lo, r_hi, groups):
        k = (int(lo) + int(hi) * LAMBDA) % R
        acc[g] = acc[g] + p.mul(k)
    return acc


def _run_g1(points, r_lo, r_hi, groups, n_groups, w):
    inf_mask = np.array([p.is_infinity() for p in points])
    plan = M.plan_msm(
        r_lo, r_hi, inf_mask, groups, n_groups, window_bits=w, lanes=64
    )
    x, y, inf = C.g1_points_to_dev(points)
    import jax.numpy as jnp
    from grandine_tpu.tpu import limbs as L

    def kern(x, y, inf, *arrs):
        px, py = L.split(jnp.asarray(x)), L.split(jnp.asarray(y))
        epx, epy, elive = M.expand_glv_points(
            px, py, jnp.asarray(inf), B._g1_endo(len(points)), C.FP_OPS
        )
        out = M.msm_bucket_scan(
            epx, epy, elive, *arrs,
            windows=plan.windows, window_bits=plan.window_bits,
            n_groups=n_groups, ops=C.FP_OPS,
        )
        return tuple(L.merge(e) for e in out)

    X, Y, Z = jax.jit(kern)(x, y, inf, *plan.arrays)
    return [
        C.dev_to_g1_point(np.asarray(X)[i], np.asarray(Y)[i], np.asarray(Z)[i])
        for i in range(n_groups)
    ]


def _run_g2(points, r_lo, r_hi, groups, n_groups, w):
    inf_mask = np.array([p.is_infinity() for p in points])
    plan = M.plan_msm(
        r_lo, r_hi, inf_mask, groups, n_groups, window_bits=w, lanes=64
    )
    x, y, inf = C.g2_points_to_dev(points)
    import jax.numpy as jnp
    from grandine_tpu.tpu import field as F

    def kern(x, y, inf, *arrs):
        px, py = F.fp2_split(jnp.asarray(x)), F.fp2_split(jnp.asarray(y))
        epx, epy, elive = M.expand_glv_points(
            px, py, jnp.asarray(inf), B._g2_endo(len(points)), C.FP2_OPS
        )
        out = M.msm_bucket_scan(
            epx, epy, elive, *arrs,
            windows=plan.windows, window_bits=plan.window_bits,
            n_groups=n_groups, ops=C.FP2_OPS,
        )
        return tuple(F.fp2_merge(e) for e in out)

    X, Y, Z = jax.jit(kern)(x, y, inf, *plan.arrays)
    return [
        C.dev_to_g2_point(np.asarray(X)[i], np.asarray(Y)[i], np.asarray(Z)[i])
        for i in range(n_groups)
    ]


@pytest.mark.parametrize("w", [4, 8])
def test_msm_g1_single_group(w):
    rng = random.Random(7)
    n = 23
    points = [G1.mul(rng.randrange(1, 1 << 64)) for _ in range(n)]
    points[3] = points[5]  # duplicates share a bucket sometimes
    points[9] = g1_infinity()
    r_lo = [rng.randrange(0, 1 << 32) for _ in range(n)]
    r_hi = [rng.randrange(0, 1 << 32) for _ in range(n)]
    r_lo[4] = 0
    r_hi[4] = 0  # whole scalar zero
    r_lo[6] = 0
    got = _run_g1(points, r_lo, r_hi, [0] * n, 1, w)
    want = _host_msm(points, r_lo, r_hi, [0] * n, 1, g1_infinity)
    assert got[0] == want[0]


@pytest.mark.parametrize("w", [4, 6])
def test_msm_g1_grouped(w):
    rng = random.Random(11)
    n, n_groups = 37, 5
    points = [G1.mul(rng.randrange(1, 1 << 64)) for _ in range(n)]
    groups = [rng.randrange(0, n_groups - 1) for _ in range(n)]  # group 4 empty
    r_lo = [rng.randrange(0, 1 << 32) for _ in range(n)]
    r_hi = [rng.randrange(0, 1 << 32) for _ in range(n)]
    got = _run_g1(points, r_lo, r_hi, groups, n_groups, w)
    want = _host_msm(points, r_lo, r_hi, groups, n_groups, g1_infinity)
    assert got == want
    assert got[4].is_infinity()


def test_msm_g2_single_group():
    rng = random.Random(13)
    n = 17
    points = [G2.mul(rng.randrange(1, 1 << 64)) for _ in range(n)]
    points[2] = g2_infinity()
    points[8] = points[11]
    r_lo = [rng.randrange(0, 1 << 32) for _ in range(n)]
    r_hi = [rng.randrange(0, 1 << 32) for _ in range(n)]
    got = _run_g2(points, r_lo, r_hi, [0] * n, 1, 8)
    want = _host_msm(points, r_lo, r_hi, [0] * n, 1, g2_infinity)
    assert got[0] == want[0]


@pytest.mark.slow
def test_grouped_msm_kernel_matches_ladder_kernel():
    """End-to-end: the MSM-backed grouped verify kernel accepts a valid
    batch and rejects a corrupted one, agreeing with the ladder kernel.

    Slow tier: the full grouped-verify compile dominates. The grouped
    MSM scan itself keeps fast differential coverage above
    (test_msm_g1_grouped / test_msm_g2_single_group), and the grouped
    verify path stays covered by test_tpu_bls_grouped."""
    rng = random.Random(17)
    m, k = 4, 8
    n = m * k
    msgs = [b"msm-msg-%d" % j for j in range(m)]
    sks = [SecretKey(rng.randrange(1, 1 << 200)) for _ in range(n)]
    from grandine_tpu.crypto import bls as A
    from grandine_tpu.crypto.hash_to_curve import hash_to_g2
    from grandine_tpu.crypto import constants as CONST

    sigs, pks = [], []
    for i, sk in enumerate(sks):
        pks.append(sk.public_key())
        sigs.append(
            A.Signature(hash_to_g2(msgs[i % m], CONST.DST_SIGNATURE).mul(sk.scalar))
        )

    g1x, g1y, g1inf = C.g1_points_to_dev([pk.point for pk in pks])
    g2x, g2y, g2inf = C.g2_points_to_dev([s.point for s in sigs])
    mx, my, minf = C.g2_points_to_dev(
        [hash_to_g2(msg, CONST.DST_SIGNATURE) for msg in msgs]
    )

    def pack(order):
        def grp(a):
            return np.ascontiguousarray(
                a[order].reshape((m, k) + a.shape[1:])
            )
        return grp

    order = np.argsort(np.arange(n) % m, kind="stable")
    grp = pack(order)
    args_pts = (
        grp(g1x), grp(g1y), grp(g1inf),
        grp(g2x), grp(g2y), grp(g2inf),
        mx, my, minf,
    )

    r_lo = np.array([rng.randrange(1, 1 << 32) for _ in range(n)], np.uint64)
    r_hi = np.array([rng.randrange(0, 1 << 32) for _ in range(n)], np.uint64)
    # flat k-major point f ↔ grouped slot (f % m, f // m): group = f % m
    groups = np.arange(n) % m
    flat_inf = np.zeros(n, bool)
    g1_plan = M.plan_msm(
        r_lo, r_hi, flat_inf, groups, m, window_bits=4, lanes=64
    )
    g2_plan = M.plan_msm(r_lo, r_hi, flat_inf, None, 1, window_bits=6, lanes=64)

    import functools

    fn = jax.jit(
        functools.partial(
            B.grouped_multi_verify_msm_kernel,
            g1_windows=g1_plan.windows, g1_wbits=g1_plan.window_bits,
            g2_windows=g2_plan.windows, g2_wbits=g2_plan.window_bits,
        )
    )
    ok = fn(*args_pts, *g1_plan.arrays, *g2_plan.arrays)
    assert bool(ok)

    # corrupt one signature → must reject
    bad = list(sigs)
    bad[5] = A.Signature(bad[5].point.mul(3))
    b2x, b2y, b2inf = C.g2_points_to_dev([s.point for s in bad])
    args_bad = (
        grp(g1x), grp(g1y), grp(g1inf),
        grp(b2x), grp(b2y), grp(b2inf),
        mx, my, minf,
    )
    assert not bool(fn(*args_bad, *g1_plan.arrays, *g2_plan.arrays))
