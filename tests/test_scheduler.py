"""Unified verify scheduler tests: lane scheduling semantics (deadline
flush, priority, shed/backpressure), bisection isolation of poisoned
batches, graceful degradation off a faulted device backend, the
Verifier-seam adapter, and a differential check that the scheduled
gossip path makes the SAME accept/reject decisions as the eager inline
path — including forged sync-committee messages.

Host BLS verification on the pure-python anchor costs ~0.7 s/pairing, so
scheduling-semantics tests stub `host_check_item` (the crypto leaf) and
only the isolation/differential/robustness tests spend real signatures —
a handful each. All scheduler instances here run `use_device=False` or
an injected fake backend: no kernel compiles at test time.
"""

import threading
import time

import numpy as np
import pytest

from grandine_tpu.consensus import signing
from grandine_tpu.consensus.verifier import NullVerifier, SignatureInvalid
from grandine_tpu.fork_choice import Tick, TickKind
from grandine_tpu.metrics import Metrics
from grandine_tpu.p2p.network import InMemoryHub, Network
from grandine_tpu.pools.sync_committee_pool import SyncCommitteeAggPool
from grandine_tpu.runtime import verify_scheduler as vs
from grandine_tpu.runtime.controller import Controller
from grandine_tpu.runtime.thread_pool import Priority
from grandine_tpu.runtime.verify_scheduler import (
    LaneConfig,
    VerifyItem,
    VerifyScheduler,
)
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.validator.duties import _interop_keys, produce_block

CFG = Config.minimal()
P = CFG.preset
NS = spec_types(P).deneb


@pytest.fixture(scope="module")
def genesis():
    return interop_genesis_state(16, CFG)


def _stub_item(tag: bytes) -> VerifyItem:
    """Key material is never touched when host_check_item is stubbed."""
    return VerifyItem(
        tag.ljust(32, b"\x00"), tag.ljust(96, b"\x00"), public_keys=("stub",)
    )


# ------------------------------------------------------- lane semantics


def test_deadline_flush_fires_without_further_submissions(monkeypatch):
    """A lone job flushes at max_wait — no follow-up submission, no
    max_batch trigger — and not (much) before the deadline."""
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    lanes = (LaneConfig("low", Priority.LOW, 1000, 0.05, 100, shed=True),)
    s = VerifyScheduler(lanes=lanes, use_device=False, metrics=Metrics())
    try:
        t0 = time.monotonic()
        ticket = s.submit("low", [_stub_item(b"a")])
        assert ticket.result(5.0) is True
        elapsed = time.monotonic() - t0
        assert 0.04 <= elapsed < 2.0
        assert s.stats["low"]["batches"] == 1
        assert s.stats["low"]["accepted"] == 1
    finally:
        s.stop()


def test_max_batch_flushes_before_deadline(monkeypatch):
    """Reaching max_batch items flushes immediately even when max_wait
    is far away (whichever-first policy)."""
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    lanes = (LaneConfig("low", Priority.LOW, 4, 60.0, 100, shed=True),)
    s = VerifyScheduler(lanes=lanes, use_device=False)
    try:
        tickets = [s.submit("low", [_stub_item(bytes([i]))]) for i in range(4)]
        for t in tickets:
            assert t.result(5.0) is True
        assert s.stats["low"]["max_batch_items"] == 4
    finally:
        s.stop()


def test_high_lane_picked_over_saturated_low_lane(monkeypatch):
    """Deterministic priority check: with both lanes overdue, _pick_lane
    selects the HIGH lane regardless of which is more overdue."""
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    lanes = (
        LaneConfig("high", Priority.HIGH, 64, 0.01, 100, shed=False),
        LaneConfig("low", Priority.LOW, 64, 0.001, 100, shed=True),
    )
    s = VerifyScheduler(lanes=lanes, use_device=False)
    try:
        # the condition's lock is re-entrant: holding it parks the
        # dispatcher so the queue state is ours to stage
        with s._cond:
            t_low = s.submit("low", [_stub_item(b"l")])
            t_high = s.submit("high", [_stub_item(b"h")])
            t_low.enqueued_at -= 10.0  # low is MORE overdue than high
            t_high.enqueued_at -= 1.0
            assert s._pick_lane(time.monotonic()) == "high"
        s.flush(10.0)
        assert t_high.ok and t_low.ok
    finally:
        s.stop()


def test_high_lane_never_starved_by_low_backlog(monkeypatch):
    """End-to-end: a HIGH job submitted behind a deep LOW backlog
    settles while most of the backlog is still queued."""
    monkeypatch.setattr(
        vs, "host_check_item", lambda it: time.sleep(0.02) or True
    )
    lanes = (
        LaneConfig("high", Priority.HIGH, 4, 0.001, 100, shed=False),
        LaneConfig("low", Priority.LOW, 4, 0.0, 1000, shed=True),
    )
    s = VerifyScheduler(lanes=lanes, use_device=False)
    try:
        low = [s.submit("low", [_stub_item(bytes([i]))]) for i in range(40)]
        t_high = s.submit("high", [_stub_item(b"hi")])
        assert t_high.result(10.0) is True
        assert sum(1 for t in low if not t.done()) > 0
        s.flush(30.0)
    finally:
        s.stop()


def test_low_lane_sheds_oldest_first_and_counts_drops(monkeypatch):
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    # never due (huge max_batch + max_wait): the queue only fills
    lanes = (LaneConfig("low", Priority.LOW, 10_000, 60.0, 4, shed=True),)
    m = Metrics()
    s = VerifyScheduler(lanes=lanes, use_device=False, metrics=m)
    tickets = [s.submit("low", [_stub_item(bytes([i]))]) for i in range(6)]
    try:
        # the two OLDEST jobs were shed; shed resolves False+dropped so
        # gossip accounting counts an "ignore", not a "reject"
        for t in tickets[:2]:
            assert t.done() and t.dropped and t.ok is False
        assert not any(t.done() for t in tickets[2:])
        assert s.stats["low"]["shed"] == 2
        assert m.verify_lane_dropped.value("low") == 2.0
    finally:
        s.stop()
    # stop() drains by DROPPING: the survivors resolve immediately with
    # dropped=True (an "ignore", never a "reject") — no result() caller
    # hangs to its full timeout during shutdown
    for t in tickets[2:]:
        assert t.done() and t.dropped and t.ok is False


def test_high_lane_backpressures_instead_of_shedding(monkeypatch):
    """A full HIGH lane blocks the submitter (bounded producer); it
    never drops — `shed` stays zero even at capacity."""
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    lanes = (LaneConfig("high", Priority.HIGH, 10_000, 60.0, 1, shed=False),)
    s = VerifyScheduler(lanes=lanes, use_device=False)
    first = s.submit("high", [_stub_item(b"a")])
    blocked: list = []
    th = threading.Thread(
        target=lambda: blocked.append(s.submit("high", [_stub_item(b"b")]))
    )
    th.start()
    time.sleep(0.3)
    assert th.is_alive()  # backpressured, not shed
    assert s.stats["high"]["shed"] == 0
    s.stop()
    th.join(5.0)
    assert not th.is_alive()
    assert first.done() and first.dropped  # drained (dropped) at stop
    # the blocked submission surfaces as an explicit drop, never silence
    assert blocked[0].done() and blocked[0].dropped


# ------------------------------------------- fake device backend (tests)


class _FakeAsyncBackend:
    """Async-seam double for the device backend: verdicts come from a
    truth table keyed by message bytes; records verify-batch sizes so
    tests can assert the bisection pattern; injects dispatch-time or
    settle-time faults."""

    def __init__(self, truth=None, fail_dispatch=False, fail_settle=False):
        self.truth = dict(truth or {})
        self.batches: "list[int]" = []
        self.fail_dispatch = fail_dispatch
        self.fail_settle = fail_settle

    def g2_subgroup_check_batch_async(self, points):
        if self.fail_dispatch:
            raise RuntimeError("injected dispatch fault")
        out = np.ones(len(points), dtype=bool)

        def settle():
            if self.fail_settle:
                raise RuntimeError("injected settle fault")
            return out

        return settle

    def fast_aggregate_verify_batch_async(self, messages, signatures, keys):
        if self.fail_dispatch:
            raise RuntimeError("injected dispatch fault")
        self.batches.append(len(messages))
        ok = all(self.truth.get(bytes(m), False) for m in messages)

        def settle():
            if self.fail_settle:
                raise RuntimeError("injected settle fault")
            return ok

        return settle


# -------------------------------------------------- bisection isolation


def test_bisection_admits_good_items_of_poisoned_batch():
    """One forged signature in a coalesced batch: the batch verdict
    fails, bisection descends ONLY into the failing half, and the good
    items' tickets still resolve True (real signatures; real host
    verification at the leaves)."""
    key = _interop_keys(0)
    msgs = [bytes([i]) * 32 for i in range(4)]
    sigs = [key.sign(m).to_bytes() for m in msgs[:3]]
    # forged: a REAL G2 point (decompresses fine) over the wrong message
    sigs.append(sigs[0])
    items = [
        VerifyItem(m, s, public_keys=(key.public_key(),))
        for m, s in zip(msgs, sigs)
    ]
    backend = _FakeAsyncBackend(truth={m: True for m in msgs[:3]})
    m = Metrics()
    lanes = (LaneConfig("sync_message", Priority.LOW, 128, 0.05, 100, True),)
    s = VerifyScheduler(
        backend=backend, lanes=lanes, use_device=True, metrics=m
    )
    try:
        tickets = [s.submit("sync_message", [it]) for it in items]
        verdicts = [t.result(60.0) for t in tickets]
        assert verdicts == [True, True, True, False]
        # one coalesced batch of 4; the good half passes whole, only the
        # bad half descends (its two singletons re-check)
        assert backend.batches == [4, 2, 2, 1, 1]
        assert s.stats["sync_message"]["accepted"] == 3
        assert s.stats["sync_message"]["rejected"] == 1
        assert m.verify_lane_batches.value("sync_message", "invalid") == 1.0
    finally:
        s.stop()


# --------------------------------------------------- fault degradation


def test_settle_fault_degrades_to_host_and_blocks_still_import(genesis):
    """A device backend that faults at readback: every lane degrades to
    the eager host path and the node KEEPS importing blocks through the
    scheduler's block lane."""
    backend = _FakeAsyncBackend(fail_settle=True)
    m = Metrics()
    s = VerifyScheduler(backend=backend, use_device=True, metrics=m)
    ctrl = Controller(
        genesis, CFG, verifier_factory=s.verifier_factory("block")
    )
    try:
        signed, _post = produce_block(
            genesis, 1, CFG, full_sync_participation=False
        )
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_gossip_block(signed)
        ctrl.wait()
        assert signed.message.hash_tree_root() in ctrl.store.blocks
        assert s.stats["block"]["device_faults"] >= 1
        assert m.verify_lane_batches.value("block", "degraded") >= 1.0
        # a LOW lane degrades the same way (valid item still accepted)
        key = _interop_keys(0)
        msg = b"\x07" * 32
        item = VerifyItem(
            msg, key.sign(msg).to_bytes(), public_keys=(key.public_key(),)
        )
        t = s.submit("sync_message", [item])
        assert t.result(30.0) is True
        assert s.stats["sync_message"]["device_faults"] >= 1
    finally:
        ctrl.stop()
        s.stop()


def test_dispatch_fault_degrades_to_host(monkeypatch):
    """A fault at dispatch time (before any settle exists) is caught in
    _flush: counted, the batch host-checks, nothing drops."""
    monkeypatch.setattr(vs, "host_check_item", lambda it: True)
    key = _interop_keys(1)
    msg = b"\x09" * 32
    item = VerifyItem(
        msg, key.sign(msg).to_bytes(), public_keys=(key.public_key(),)
    )
    backend = _FakeAsyncBackend(fail_dispatch=True)
    m = Metrics()
    lanes = (LaneConfig("exit", Priority.LOW, 16, 0.01, 100, shed=True),)
    s = VerifyScheduler(
        backend=backend, lanes=lanes, use_device=True, metrics=m
    )
    try:
        t = s.submit("exit", [item])
        assert t.result(10.0) is True
        assert s.stats["exit"]["device_faults"] == 1
        assert m.verify_lane_batches.value("exit", "degraded") == 1.0
    finally:
        s.stop()


# ------------------------------------------------------- Verifier seam


def test_deferred_verifier_raises_on_invalid_batch(monkeypatch):
    monkeypatch.setattr(vs, "host_check_item", lambda it: False)
    lanes = (LaneConfig("block", Priority.HIGH, 64, 0.002, 100, False),)
    s = VerifyScheduler(lanes=lanes, use_device=False)
    try:
        v = s.deferred("block", timeout=10.0)
        v.verify_singular(b"\x00" * 32, b"\x00" * 96, "k")
        with pytest.raises(SignatureInvalid):
            v.finish()
        assert s.stats["block"]["rejected"] == 1
    finally:
        s.stop()


# ----------------------------------------- gossip boundary differential


def test_scheduled_gossip_matches_eager_on_every_object_kind(genesis):
    """Differential acceptance test: one receiver verifies through the
    scheduler, one through the eager inline path. A valid + forged
    specimen of EVERY signed gossip object kind — sync-committee
    message, contribution, proposer slashing, attester slashing,
    BLS-to-execution change, voluntary exit — must produce IDENTICAL
    accept/reject stats and pool contents on both."""
    from grandine_tpu.consensus import accessors
    from grandine_tpu.consensus.verifier import MultiVerifier
    from grandine_tpu.crypto import bls as A
    from grandine_tpu.pools.operation_pool import OperationPool
    from grandine_tpu.types.combined import state_phase_of

    hub = InMemoryHub()
    ctrl_a = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_e = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_s = Controller(genesis, CFG, verifier_factory=NullVerifier)
    sched = VerifyScheduler(use_device=False, metrics=Metrics())
    try:
        net_a = Network(hub.join("a"), ctrl_a, CFG)
        pool_e, pool_s = SyncCommitteeAggPool(CFG), SyncCommitteeAggPool(CFG)
        op_e, op_s = OperationPool(CFG), OperationPool(CFG)
        net_e = Network(
            hub.join("e"), ctrl_e, CFG, sync_pool=pool_e,
            operation_pool=op_e,
        )
        net_s = Network(
            hub.join("s"), ctrl_s, CFG, sync_pool=pool_s,
            operation_pool=op_s, verify_scheduler=sched,
        )
        head_root = ctrl_a.snapshot().head_root
        bad_sig = b"\xc0" + b"\x00" * 95

        # --- sync-committee message ---------------------------------
        key = _interop_keys(0)
        root = signing.sync_committee_message_signing_root(
            genesis, head_root, 0, CFG
        )
        msg = NS.SyncCommitteeMessage(
            slot=1, beacon_block_root=head_root, validator_index=0,
            signature=key.sign(root).to_bytes(),
        )
        net_a.publish_sync_committee_message(msg)
        net_a.publish_sync_committee_message(msg.replace(signature=bad_sig))

        # --- contribution -------------------------------------------
        sub_size = P.SYNC_COMMITTEE_SIZE // CFG.sync_committee_subnet_count
        members = [
            bytes(pk)
            for pk in genesis.current_sync_committee.pubkeys[:sub_size]
        ]
        # sign as whichever validator holds the first subcommittee slot so
        # the test never depends on how the committee shuffle landed
        val_pubkeys = [bytes(v.pubkey) for v in genesis.validators]
        mkey = _interop_keys(val_pubkeys.index(members[0]))
        bits = [False] * sub_size
        bits[0] = True
        contribution = NS.SyncCommitteeContribution(
            slot=1, beacon_block_root=head_root, subcommittee_index=0,
            aggregation_bits=bits, signature=mkey.sign(root).to_bytes(),
        )
        # the aggregator must be a subcommittee member with a REAL
        # selection proof and outer signature (both now verified)
        agg_idx = val_pubkeys.index(members[0])
        proof = NS.ContributionAndProof(
            aggregator_index=agg_idx, contribution=contribution,
            selection_proof=mkey.sign(
                signing.sync_selection_proof_signing_root(
                    genesis,
                    NS.SyncAggregatorSelectionData(
                        slot=1, subcommittee_index=0
                    ),
                    CFG,
                )
            ).to_bytes(),
        )
        signed_contrib = NS.SignedContributionAndProof(
            message=proof,
            signature=mkey.sign(
                signing.contribution_and_proof_signing_root(
                    genesis, proof, CFG
                )
            ).to_bytes(),
        )
        net_a.publish_sync_contribution(signed_contrib)
        net_a.publish_sync_contribution(
            signed_contrib.replace(
                message=signed_contrib.message.replace(
                    contribution=contribution.replace(signature=bad_sig)
                )
            )
        )

        # --- proposer slashing --------------------------------------
        pkey = _interop_keys(1)

        def signed_header(body_root):
            header = NS.BeaconBlockHeader(
                slot=0, proposer_index=1, parent_root=b"\x00" * 32,
                state_root=b"\x00" * 32, body_root=body_root,
            )
            return NS.SignedBeaconBlockHeader(
                message=header,
                signature=pkey.sign(
                    signing.header_signing_root(genesis, header, CFG)
                ).to_bytes(),
            )

        pslashing = NS.ProposerSlashing(
            signed_header_1=signed_header(b"\x01" * 32),
            signed_header_2=signed_header(b"\x02" * 32),
        )
        net_a.publish_proposer_slashing(pslashing)
        net_a.publish_proposer_slashing(
            pslashing.replace(
                signed_header_2=pslashing.signed_header_2.replace(
                    signature=bad_sig
                )
            )
        )

        # --- attester slashing (real double vote) -------------------
        committee = accessors.get_beacon_committee(genesis, 0, 0, P)
        offenders = sorted(int(i) for i in committee)[:2]

        def indexed(data):
            sroot = signing.attestation_signing_root(genesis, data, CFG)
            sig = A.Signature.aggregate(
                [_interop_keys(i).sign(sroot) for i in offenders]
            )
            return NS.IndexedAttestation(
                attesting_indices=offenders, data=data,
                signature=sig.to_bytes(),
            )

        data1 = NS.AttestationData(
            slot=0, index=0, beacon_block_root=b"\x01" * 32,
            source=genesis.current_justified_checkpoint,
            target=NS.Checkpoint(epoch=0, root=b"\x01" * 32),
        )
        data2 = data1.replace(
            beacon_block_root=b"\x02" * 32,
            target=NS.Checkpoint(epoch=0, root=b"\x02" * 32),
        )
        aslashing = NS.AttesterSlashing(
            attestation_1=indexed(data1), attestation_2=indexed(data2)
        )
        net_a.publish_attester_slashing(aslashing)
        net_a.publish_attester_slashing(
            aslashing.replace(
                attestation_1=aslashing.attestation_1.replace(
                    signature=bad_sig
                )
            )
        )

        # --- BLS-to-execution change --------------------------------
        ckey = _interop_keys(3)
        change_msg = NS.BLSToExecutionChange(
            validator_index=3,
            from_bls_pubkey=ckey.public_key().to_bytes(),
            to_execution_address=b"\x02" * 20,
        )
        croot = signing.bls_to_execution_change_signing_root(
            genesis, change_msg, CFG
        )
        change = NS.SignedBLSToExecutionChange(
            message=change_msg, signature=ckey.sign(croot).to_bytes(),
        )
        net_a.publish_bls_change(change)
        net_a.publish_bls_change(change.replace(signature=bad_sig))

        # --- voluntary exit -----------------------------------------
        ekey = _interop_keys(5)
        unsigned_exit = NS.SignedVoluntaryExit(
            message=NS.VoluntaryExit(epoch=0, validator_index=5),
            signature=b"\x00" * 96,
        )
        collector = MultiVerifier()
        signing.extend_with_voluntary_exit(
            collector, genesis, unsigned_exit, CFG,
            state_phase_of(genesis, CFG),
        )
        exit_root = collector.triples[0].message
        signed_exit = unsigned_exit.replace(
            signature=ekey.sign(exit_root).to_bytes()
        )
        net_a.publish_voluntary_exit(signed_exit)
        net_a.publish_voluntary_exit(signed_exit.replace(signature=bad_sig))

        # --- settle both planes, compare decisions ------------------
        sched.flush(120.0)
        ctrl_e.wait()
        ctrl_s.wait()
        expected = {
            "sync_messages_in": 2, "sync_messages_rejected": 1,
            "sync_contributions_in": 2, "sync_contributions_rejected": 1,
            "proposer_slashings_in": 2, "proposer_slashings_rejected": 1,
            "attester_slashings_in": 2, "attester_slashings_rejected": 1,
            "bls_changes_in": 2, "bls_changes_rejected": 1,
            "voluntary_exits_in": 2, "voluntary_exits_rejected": 1,
        }
        for k, want in expected.items():
            got_e = net_e.stats.get(k, 0)
            got_s = net_s.stats.get(k, 0)
            assert got_s == got_e == want, (k, got_e, got_s, want)
        # pool contents match: the one valid specimen of each kind
        for op_pool in (op_e, op_s):
            contents = op_pool.contents()
            assert len(contents["proposer_slashings"]) == 1
            assert len(contents["attester_slashings"]) == 1
            assert len(contents["bls_to_execution_changes"]) == 1
            assert len(contents["voluntary_exits"]) == 1
        assert set(offenders) <= ctrl_e.store.equivocating
        assert ctrl_s.store.equivocating == ctrl_e.store.equivocating
        agg_e = pool_e.best_aggregate(1, head_root, NS)
        agg_s = pool_s.best_aggregate(1, head_root, NS)
        assert bytes(agg_s.sync_committee_signature) == bytes(
            agg_e.sync_committee_signature
        )
        assert list(agg_s.sync_committee_bits.array) == list(
            agg_e.sync_committee_bits.array
        )
        # the scheduled plane really carried every lane. The whole test
        # gossips through ONE peer, so the first invalid specimen
        # quarantines it and LATER sheddable-lane traffic may reroute
        # into the quarantine lane (a race against batch settling) —
        # count rerouted submissions with their source lanes.
        assert sched.stats["sync_message"]["submitted"] >= 1
        assert sched.stats["sync_contribution"]["submitted"] >= 1
        reroutable = ("slashing", "bls_change", "exit")
        direct = sum(sched.stats[ln]["submitted"] for ln in reroutable)
        q = sched.stats["quarantine"]
        # 2 proposer + 2 attester slashings, 2 bls changes, 2 exits
        assert direct + q["submitted"] == 8
        lanes = ("sync_message", "sync_contribution") + reroutable
        total_rejected = (
            sum(sched.stats[ln]["rejected"] for ln in lanes) + q["rejected"]
        )
        assert total_rejected == 6  # one invalid specimen per kind
    finally:
        sched.stop()
        ctrl_a.stop()
        ctrl_e.stop()
        ctrl_s.stop()


def test_sync_positions_cache_and_invalidation(genesis):
    """Satellite: the pubkey→positions table builds once per
    sync-committee period and the validator-set-change hook drops it."""
    hub = InMemoryHub()
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        net = Network(hub.join("x"), ctrl, CFG)
        pk = bytes(genesis.validators[0].pubkey)
        expected = tuple(
            i for i, p in enumerate(genesis.current_sync_committee.pubkeys)
            if bytes(p) == pk
        )
        assert expected  # 16 interop validators fill a 32-slot committee
        pos1 = net._sync_committee_positions(genesis, 1, pk)
        table = net._sync_positions[0]
        assert pos1 == expected
        # second lookup reuses the period's table (no rebuild)
        assert net._sync_committee_positions(genesis, 1, pk) == expected
        assert net._sync_positions[0] is table
        # unknown key resolves to no positions, not a KeyError
        assert net._sync_committee_positions(genesis, 1, b"\x01" * 48) == ()
        # a slot one period AHEAD resolves against next_sync_committee
        p = CFG.preset
        ahead = p.SLOTS_PER_EPOCH * p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        next_expected = tuple(
            i for i, pkb in enumerate(genesis.next_sync_committee.pubkeys)
            if bytes(pkb) == pk
        )
        assert net._sync_committee_positions(genesis, ahead, pk) == (
            next_expected
        )
        # two periods ahead is outside what the head state knows
        assert net._sync_committee_positions(genesis, 2 * ahead, pk) == ()
        # the controller hook (wired in Network.__init__) invalidates
        for cb in ctrl.on_validator_set_change:
            cb(None, None)
        assert net._sync_positions is None
    finally:
        ctrl.stop()


# --------------------------------------------------- blob-header lane


def test_blob_sidecar_header_rides_scheduler(genesis):
    """Controller._check_sidecar_header routes through the blob_header
    lane when a scheduler is wired; the block still imports."""
    from grandine_tpu.kzg.sidecar import make_blob_sidecars

    zero_blob = b"\x00" * (P.FIELD_ELEMENTS_PER_BLOB * 32)
    inf_g1 = b"\xc0" + b"\x00" * 47  # zero blob: commitment == infinity
    sched = VerifyScheduler(use_device=False, metrics=Metrics())
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl.verify_scheduler = sched
    try:
        signed, _post = produce_block(
            genesis, 1, CFG, full_sync_participation=False,
            blob_kzg_commitments=[inf_g1],
        )
        sidecars = make_blob_sidecars(
            NS, P, signed, [zero_blob], proofs=[inf_g1]
        )
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        for sc in sidecars:
            ctrl.on_gossip_blob_sidecar(sc)
        ctrl.on_gossip_block(signed)
        ctrl.wait()
        assert signed.message.hash_tree_root() in ctrl.store.blocks
        assert sched.stats["blob_header"]["batches"] >= 1
        assert sched.stats["blob_header"]["accepted"] >= 1
    finally:
        ctrl.stop()
        sched.stop()


# ------------------------------------------------- metrics + CI guard


def test_verify_stage_seconds_lane_label_defaults():
    """Widening verify_stage_seconds to (stage, lane) must not break the
    pre-existing single-label call sites: they resolve to the
    attestation series."""
    m = Metrics()
    m.verify_stage_seconds.labels("execute").observe(0.001)
    m.verify_stage_seconds.observe("execute", value=0.002)
    m.verify_stage_seconds.labels("execute", "sync_message").observe(0.003)
    children = m.verify_stage_seconds.children()
    assert ("execute", "attestation") in children
    assert ("execute", "sync_message") in children
    assert all(len(k) == 2 for k in children)
    assert m.verify_stage_seconds.labels(stage="fallback") is (
        m.verify_stage_seconds.labels("fallback", "attestation")
    )


# The inline-gossip-verify guard now runs as part of the grandine-lint
# suite: tests/test_lint.py::test_lint_clean_on_repo covers it (with the
# rest of the rules) through `python -m tools.lint`.
