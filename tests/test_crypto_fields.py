"""Field tower tests: axioms on random samples, Frobenius vs plain pow,
square roots, and the derived Frobenius coefficients."""

import random

import pytest

from grandine_tpu.crypto.constants import P
from grandine_tpu.crypto.fields import Fq, Fq2, Fq6, Fq12, XI

rng = random.Random(0xB15)


def rand_fq() -> Fq:
    return Fq(rng.randrange(P))


def rand_fq2() -> Fq2:
    return Fq2(rand_fq(), rand_fq())


def rand_fq6() -> Fq6:
    return Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12() -> Fq12:
    return Fq12(rand_fq6(), rand_fq6())


@pytest.mark.parametrize("rand", [rand_fq, rand_fq2, rand_fq6, rand_fq12])
def test_ring_axioms(rand):
    for _ in range(5):
        a, b, c = rand(), rand(), rand()
        assert (a + b) * c == a * c + b * c
        assert a * (b * c) == (a * b) * c
        assert a * b == b * a
        assert a - a == a + (-a)


@pytest.mark.parametrize("rand", [rand_fq, rand_fq2, rand_fq6, rand_fq12])
def test_inverse(rand):
    one = rand().__class__.one() if hasattr(rand(), "__class__") else None
    for _ in range(5):
        a = rand()
        if getattr(a, "is_zero", lambda: False)():
            continue
        assert a * a.inv() == type(a).one()


def test_fq2_nonresidue():
    # u² = -1
    u = Fq2.from_ints(0, 1)
    assert u * u == Fq2.from_ints(P - 1, 0)


def test_fq6_v_cubed_is_xi():
    v = Fq6(Fq2.zero(), Fq2.one(), Fq2.zero())
    v3 = v * v * v
    assert v3 == Fq6(XI, Fq2.zero(), Fq2.zero())


def test_fq12_w_squared_is_v():
    w = Fq12(Fq6.zero(), Fq6.one())
    v = Fq12(Fq6(Fq2.zero(), Fq2.one(), Fq2.zero()), Fq6.zero())
    assert w * w == v


@pytest.mark.parametrize(
    "rand,power_fn",
    [
        (rand_fq2, lambda a: a.pow(P)),
        (rand_fq12, lambda a: a.pow(P)),
    ],
)
def test_frobenius_matches_pow(rand, power_fn):
    a = rand()
    assert a.frobenius() == power_fn(a)


def test_fq12_frobenius_order():
    a = rand_fq12()
    assert a.frobenius_n(12) == a


def test_fq12_conjugate_is_frob6():
    a = rand_fq12()
    assert a.conjugate() == a.frobenius_n(6)


def test_fq_sqrt():
    for _ in range(10):
        a = rand_fq()
        sq = a.square()
        s = sq.sqrt()
        assert s is not None and s.square() == sq


def test_fq2_sqrt():
    for _ in range(10):
        a = rand_fq2()
        sq = a.square()
        s = sq.sqrt()
        assert s is not None and s.square() == sq


def test_fq2_nonsquare_has_no_sqrt():
    found_nonsquare = False
    for _ in range(20):
        a = rand_fq2()
        if not a.is_square():
            assert a.sqrt() is None
            found_nonsquare = True
    assert found_nonsquare
