"""Chunked slasher tests — reference: slasher/src/slasher.rs (chunked
min/max target spans over mdbx). Covers the span semantics, chunk
persistence, pruning, and the amortized update bound at scale.
"""

import time

import numpy as np

from grandine_tpu.slasher import (
    CHUNK_EPOCHS,
    VALIDATORS_PER_CHUNK,
    Slasher,
)
from grandine_tpu.storage.database import Database


def test_distant_surround_across_many_chunks():
    """Surround spanning hundreds of epochs (many chunks apart)."""
    sl = Slasher()
    sl.on_attestation([5], 300, 305, b"\x01" * 32)
    # new vote (10, 900) surrounds (300, 305): crosses ~19 chunks down
    hits = sl.on_attestation([5], 10, 900, b"\x02" * 32)
    assert len(hits) == 1 and hits[0].kind == "surround_vote"
    assert hits[0].evidence["existing"] == [300, 305]
    # and the reverse: (400, 500) then (420, 480) is surrounded
    sl.on_attestation([6], 400, 500, b"\x03" * 32)
    hits = sl.on_attestation([6], 420, 480, b"\x04" * 32)
    assert len(hits) == 1 and hits[0].kind == "surrounded_vote"
    assert hits[0].evidence["existing"] == [400, 500]


def test_max_span_target_cap_is_sound():
    """An attestation can never be surrounded by one whose target is
    below its own source (the capped update range must not miss it)."""
    sl = Slasher()
    sl.on_attestation([1], 0, 100, b"\x01" * 32)
    sl.on_attestation([1], 50, 60, b"\x02" * 32)  # inside: surrounded
    # (120, 125): source past both targets — no offense possible
    hits = sl.on_attestation([1], 120, 125, b"\x05" * 32)
    assert hits == []
    # (40, 70) is doubly offending: it surrounds (50, 60) AND is
    # surrounded by (0, 100); the surround check fires first
    hits = sl.on_attestation([1], 40, 70, b"\x06" * 32)
    assert len(hits) == 1 and hits[0].kind == "surround_vote"
    assert hits[0].evidence["existing"] == [50, 60]
    # and the pure surrounded case still fires across the gap
    hits = sl.on_attestation([1], 20, 30, b"\x07" * 32)
    assert len(hits) == 1 and hits[0].kind == "surrounded_vote"
    assert hits[0].evidence["existing"] == [0, 100]


def test_spans_persist_across_instances():
    db = Database.in_memory()
    sl1 = Slasher(db)
    sl1.on_attestation([7], 2, 3, b"\xcc" * 32)
    # a fresh instance over the same DB sees the recorded spans
    sl2 = Slasher(db)
    hits = sl2.on_attestation([7], 1, 4, b"\xdd" * 32)
    assert len(hits) == 1 and hits[0].kind == "surround_vote"


def test_prune_drops_old_chunks():
    db = Database.in_memory()
    sl = Slasher(db, history_epochs=64)
    sl.on_attestation([3], 1, 2, b"\x01" * 32)
    sl.on_attestation([3], 5000, 5001, b"\x02" * 32)
    dropped = sl.prune(finalized_epoch=5000)
    assert dropped > 0
    # the old record is gone; the recent one remains
    assert sl._record(3, 2) is None
    assert sl._record(3, 5001) is not None


def test_aggregate_shares_chunk_work():
    """One committee-wide aggregate touches each span chunk once per
    validator row — and detection still fires per validator."""
    sl = Slasher()
    committee = list(range(128))
    assert sl.on_attestation(committee, 4, 5, b"\x0a" * 32) == []
    hits = sl.on_attestation(committee, 3, 6, b"\x0b" * 32)
    assert len(hits) == len(committee)
    assert all(h.kind == "surround_vote" for h in hits)


def test_update_amortization_at_scale():
    """Steady-state throughput (every validator attesting each epoch —
    the real gossip shape) must beat 10k validator-attestations/s; the
    old per-validator JSON design measured ~100× slower. First-touch
    (empty spans) walks more chunks and is allowed to be slower."""
    sl = Slasher()
    committee = list(range(2000, 2064))
    for k in range(8):  # warm: establish spans
        sl.on_attestation(
            committee, 100 + k, 101 + k, (50_000 + k).to_bytes(32, "big")
        )
    t0 = time.time()
    total = 0
    for k in range(100):
        sl.on_attestation(
            committee, 108 + k, 109 + k, (60_000 + k).to_bytes(32, "big")
        )
        total += len(committee)
    rate = total / (time.time() - t0)
    assert rate > 10_000, f"slasher too slow: {rate:.0f} att-validators/s"


def test_chunk_layout_constants():
    assert CHUNK_EPOCHS * VALIDATORS_PER_CHUNK * 8 == 32768  # 32 KiB/chunk
