"""Tests for operation pools, slashing protection, liveness tracker, and
the metrics registry."""

import numpy as np
import pytest

from grandine_tpu.crypto import bls as A
from grandine_tpu.metrics import Metrics
from grandine_tpu.pools import AttestationAggPool, OperationPool, SyncCommitteeAggPool
from grandine_tpu.runtime.liveness import LivenessTracker
from grandine_tpu.storage import Database
from grandine_tpu.transition.genesis import interop_genesis_state, interop_secret_key
from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.validator.slashing_protection import (
    SlashingProtection,
    SlashingProtectionError,
)

CFG = Config.minimal()
P = CFG.preset
NS = spec_types(P).deneb


def _attestation(slot=8, index=0, bits=None, committee=4, sk_index=0,
                 target_root=b"\x11" * 32):
    data = NS.AttestationData(
        slot=slot,
        index=index,
        beacon_block_root=b"\x22" * 32,
        source=NS.Checkpoint(epoch=0, root=b"\x00" * 32),
        target=NS.Checkpoint(epoch=1, root=target_root),
    )
    if bits is None:
        bits = np.zeros(committee, dtype=bool)
        bits[sk_index] = True
    sig = interop_secret_key(sk_index).sign(data.hash_tree_root())
    return NS.Attestation(
        aggregation_bits=bits, data=data, signature=sig.to_bytes()
    )


# ---------------------------------------------------------------- att pool


def test_attestation_pool_aggregates_on_insert():
    pool = AttestationAggPool(CFG)
    a0 = _attestation(sk_index=0)
    a1 = _attestation(sk_index=1)
    pool.insert(a0)
    pool.insert(a1)
    best = pool.best_aggregate(8, 0, a0.data.hash_tree_root())
    assert best is not None
    assert best.aggregation_bits.count() == 2  # merged disjoint singles
    # the merged aggregate signature is the aggregate of both
    expected = A.Signature.aggregate([
        A.Signature.from_bytes(bytes(a0.signature)),
        A.Signature.from_bytes(bytes(a1.signature)),
    ])
    assert bytes(best.signature) == expected.to_bytes()


def test_attestation_pool_drops_dominated():
    pool = AttestationAggPool(CFG)
    wide = _attestation(bits=np.array([True, True, True, False]))
    narrow = _attestation(bits=np.array([True, False, False, False]))
    pool.insert(wide)
    pool.insert(narrow)  # subset of wide: dominated
    key_entries = pool._by_key[(8, 0, wide.data.hash_tree_root())]
    assert all(
        not (e.bits.covers(narrow.aggregation_bits)
             and e.bits.count() == 1)
        for e in key_entries
    )
    best = pool.best_aggregate(8, 0, wide.data.hash_tree_root())
    assert best.aggregation_bits.count() >= 3


def test_attestation_pool_prune():
    pool = AttestationAggPool(CFG)
    pool.insert(_attestation(slot=4))
    pool.insert(_attestation(slot=9))
    pool.prune_before(8)
    assert pool.best_aggregate(4, 0, _attestation(slot=4).data.hash_tree_root()) is None
    assert len(pool) >= 1


# --------------------------------------------------------------- sync pool


def test_sync_pool_merges_messages():
    pool = SyncCommitteeAggPool(CFG)
    root = b"\x33" * 32
    for pos in (0, 1, 9):
        sig = interop_secret_key(pos).sign(b"m" * 32)
        pool.insert_message(5, root, pos, sig.to_bytes())
    agg = pool.best_aggregate(5, root, NS)
    assert agg.sync_committee_bits.count() == 3
    # unknown root -> empty aggregate with infinity signature
    empty = pool.best_aggregate(5, b"\x44" * 32, NS)
    assert empty.sync_committee_bits.count() == 0
    assert bytes(empty.sync_committee_signature) == A.Signature.empty().to_bytes()


# ----------------------------------------------------------------- op pool


def test_operation_pool_dedup_and_pack():
    import dataclasses

    genesis = interop_genesis_state(16, CFG)
    pool = OperationPool(CFG)
    exit_ = NS.SignedVoluntaryExit(
        message=NS.VoluntaryExit(epoch=0, validator_index=3)
    )
    assert pool.insert_voluntary_exit(exit_)
    assert not pool.insert_voluntary_exit(exit_)  # dedup by validator
    # at genesis the exit is NOT includable (spec "exit: too young":
    # activation_epoch + shard_committee_period > current epoch) — pack
    # must exclude it or the produced block fails its own transition
    assert pool.pack(genesis)["voluntary_exits"] == []
    # with the age gate lifted the same exit packs
    young_ok = OperationPool(
        dataclasses.replace(CFG, shard_committee_period=0)
    )
    young_ok.insert_voluntary_exit(exit_)
    packed = young_ok.pack(genesis)
    assert len(packed["voluntary_exits"]) == 1
    # consumed on block application
    body = NS.BeaconBlockBody(voluntary_exits=[exit_])
    young_ok.on_block_applied(NS.BeaconBlock(body=body))
    assert young_ok.pack(genesis)["voluntary_exits"] == []


# ---------------------------------------------------- slashing protection


def test_slashing_protection_blocks():
    sp = SlashingProtection()
    pk = b"\xaa" * 48
    sp.check_and_insert_block(pk, 10)
    with pytest.raises(SlashingProtectionError):
        sp.check_and_insert_block(pk, 10)  # same slot
    with pytest.raises(SlashingProtectionError):
        sp.check_and_insert_block(pk, 9)   # rollback
    sp.check_and_insert_block(pk, 11)


def test_slashing_protection_attestations():
    sp = SlashingProtection()
    pk = b"\xbb" * 48
    sp.check_and_insert_attestation(pk, 0, 1)
    with pytest.raises(SlashingProtectionError, match="double vote"):
        sp.check_and_insert_attestation(pk, 0, 1)
    sp.check_and_insert_attestation(pk, 1, 2)
    with pytest.raises(SlashingProtectionError, match="surround"):
        sp.check_and_insert_attestation(pk, 0, 3)  # surrounds (1,2)
    sp.check_and_insert_attestation(pk, 2, 5)
    with pytest.raises(SlashingProtectionError, match="surrounded"):
        sp.check_and_insert_attestation(pk, 3, 4)  # surrounded by (2,5)
    with pytest.raises(SlashingProtectionError):
        sp.check_and_insert_attestation(pk, 5, 4)  # source > target


def test_slashing_protection_interchange_roundtrip(tmp_path):
    gvr = b"\x77" * 32
    sp = SlashingProtection(genesis_validators_root=gvr)
    pk = b"\xcc" * 48
    sp.check_and_insert_block(pk, 42)
    sp.check_and_insert_attestation(pk, 1, 2)
    blob = sp.export_interchange()
    assert blob["metadata"]["interchange_format_version"] == "5"

    sp2 = SlashingProtection(
        Database.persistent(str(tmp_path / "sp.sqlite")),
        genesis_validators_root=gvr,
    )
    sp2.import_interchange(blob)
    with pytest.raises(SlashingProtectionError):
        sp2.check_and_insert_block(pk, 42)
    with pytest.raises(SlashingProtectionError):
        sp2.check_and_insert_attestation(pk, 1, 2)
    # chain mismatch refused
    with pytest.raises(SlashingProtectionError):
        SlashingProtection(genesis_validators_root=b"\x01" * 32).import_interchange(
            blob
        )


# ---------------------------------------------------------------- liveness


def test_liveness_tracker():
    lt = LivenessTracker(8)
    lt.on_attestation(3, [1, 5])
    lt.on_block(3, 2)
    lt.on_sync_message(4, 7)
    assert lt.is_live(3, 1) and lt.is_live(3, 2) and lt.is_live(3, 5)
    assert not lt.is_live(3, 0)
    assert lt.is_live(4, 7)
    rows = lt.liveness(3, [0, 1])
    assert rows == [
        {"index": "0", "is_live": False},
        {"index": "1", "is_live": True},
    ]
    # old epochs roll off (keeps 2)
    lt.on_attestation(5, [0])
    lt.on_attestation(6, [0])
    assert not lt.is_live(3, 1)


# ----------------------------------------------------------------- metrics


def test_metrics_exposition():
    m = Metrics()
    m.fc_blocks_applied.inc()
    m.fc_blocks_applied.inc(2)
    m.head_slot.set(123)
    with m.block_processing_times.time():
        pass
    text = m.expose()
    assert "fc_blocks_applied_total 3.0" in text
    assert "head_slot 123.0" in text
    assert "block_processing_seconds_count 1" in text
    assert 'block_processing_seconds_bucket{le="+Inf"} 1' in text


# -------------------------------------------------------------------- eth1


def test_eth1_deposit_cache_to_block_flow():
    """Deposit logs -> cache -> proposer inclusion proofs -> state
    transition applies the new validator (the eth1/deposit_tree loop)."""
    from grandine_tpu.consensus import signing as sgn
    from grandine_tpu.eth1 import Eth1Cache, select_eth1_vote
    from grandine_tpu.transition.combined import untrusted_state_transition
    from grandine_tpu.transition.genesis import interop_genesis_state
    from grandine_tpu.validator.duties import produce_block

    genesis = interop_genesis_state(16, CFG)
    cache = Eth1Cache(CFG)
    # replay the genesis deposits into the cache (log order)
    for v in genesis.validators:
        dd = NS.DepositData(
            pubkey=bytes(v.pubkey),
            withdrawal_credentials=bytes(v.withdrawal_credentials),
            amount=P.MAX_EFFECTIVE_BALANCE,
        )
        cache.add_deposit(dd)
    # one new deposit arrives via the injected log fetcher
    new_sk = interop_secret_key(500)
    dd = NS.DepositData(
        pubkey=new_sk.public_key().to_bytes(),
        withdrawal_credentials=b"\x00" + b"\x09" * 31,
        amount=P.MAX_EFFECTIVE_BALANCE,
    )
    dd = dd.replace(
        signature=new_sk.sign(sgn.deposit_signing_root(dd, CFG)).to_bytes()
    )
    added = cache.follow(lambda next_index: [dd] if next_index == 16 else [])
    assert added == 1 and cache.deposit_count == 17

    # the chain adopts the cache's eth1 data, then the proposer must
    # include the pending deposit with a valid proof
    state = genesis.replace(eth1_data=cache.eth1_data(NS))
    deposits = cache.deposits_for_block(state, NS)
    assert len(deposits) == 1
    blk, post = produce_block(
        state, 1, CFG, deposits=deposits, full_sync_participation=False
    )
    v = untrusted_state_transition(state, blk, CFG)
    assert v.hash_tree_root() == post.hash_tree_root()
    assert len(post.validators) == 17
    assert bytes(post.validators[16].pubkey) == new_sk.public_key().to_bytes()

    # vote selection: majority among candidates
    vote = select_eth1_vote(post, [post.eth1_data], CFG)
    assert vote == post.eth1_data
