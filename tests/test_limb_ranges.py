"""Limb-range abstract interpreter (tools/ranges).

Covers the whole-program run (repo-wide findings = 0 after inline
suppressions), the certificate round-trip and staleness cycle, seeded
per-theorem violation fixtures driven through the actual transfer
functions, suppression scoping, the lint-rule registration, and the
ed25519-vs-BLS constants parametrization.
"""

import os

import numpy as np
import pytest
from fractions import Fraction

from tools.lint.core import Context, Finding
from tools import ranges
from tools.ranges.domain import Aff, LimbVal
from tools.ranges.fields import load_field_params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def run():
    ctx = Context(REPO)
    findings, analysis = ranges.analyze(ctx=ctx, check_cert=True)
    return ctx, findings, analysis


# --- whole-program run ------------------------------------------------------


def test_repo_is_clean(run):
    ctx, findings, analysis = run
    assert analysis.root_errors == []
    live = [f for f in findings if not ctx.suppressed(f)]
    assert live == [], [f.render() for f in live]


def test_suppressed_sites_are_the_documented_ones(run):
    """The inline `# lint: disable=limb-range` escape hatch is only used
    at the Fp2-chain sites the README documents — a new suppression has
    to be added here deliberately."""
    ctx, findings, _ = run
    suppressed = {
        (f.path, f.line) for f in findings if ctx.suppressed(f)
    }
    assert {p for p, _ in suppressed} == {
        "grandine_tpu/tpu/field.py",
        "grandine_tpu/tpu/curve.py",
        "grandine_tpu/tpu/pairing.py",
    }
    # every suppressed line carries the annotation in source
    for path, line in suppressed:
        src = ctx.source(path).splitlines()
        assert "lint: disable=limb-range" in src[line - 1]


def test_every_montmul_site_discharges_theorem_a(run):
    """Int32 digit/accumulator safety — the theorem overflow rides on —
    holds at EVERY recorded site, including the suppressed ones."""
    _, _, analysis = run
    assert analysis.rows, "no sites recorded"
    for r in analysis.rows:
        assert not any("theorem a" in v for v in r["violations"]), (
            r["sitekey"], r["violations"])
        if r["max_prod"]:
            assert r["max_prod"] < 1 << 31
        if r["prim"] == "montmul":
            assert 0 < r["max_acc"] < 1 << 22, r["sitekey"]


def test_both_planes_are_analyzed(run):
    _, _, analysis = run
    planes = {r["fp"] for r in analysis.rows}
    assert planes == {"bls", "ed25519"}
    ed_mont = [r for r in analysis.rows
               if r["fp"] == "ed25519" and r["prim"] == "montmul"]
    assert ed_mont, "no ed25519 montmul site recorded"


# --- certificate ------------------------------------------------------------


def test_cert_round_trip_and_determinism(run):
    ctx, _, analysis = run
    want = analysis.cert_text()
    assert want == analysis.cert_text()  # deterministic within a run
    assert ctx.source(ranges.CERT_PATH) == want
    assert "[headroom<=50%]" in want
    assert "[tightest]" in want
    assert "[no-relax-needed]" in want
    # site keys are line-number free: path:function:primitive#ordinal
    for r in analysis.rows:
        assert str(r["line"]) not in r["sitekey"].split(":")


def test_cert_staleness_cycle(run):
    ctx, _, _ = run
    have = ctx.source(ranges.CERT_PATH)

    stale = Context(REPO)
    stale._sources[ranges.CERT_PATH] = have + "# doctored\n"
    findings, _ = ranges.analyze(ctx=stale, check_cert=True)
    assert any(f.key.endswith(":stale") for f in findings)

    missing = Context(REPO)
    missing._sources[ranges.CERT_PATH] = None
    findings, _ = ranges.analyze(ctx=missing, check_cert=True)
    assert any(f.key.endswith(":missing") for f in findings)

    fresh = Context(REPO)
    findings, _ = ranges.analyze(ctx=fresh, check_cert=True)
    assert not any(":stale" in f.key or ":missing" in f.key
                   for f in findings)


# --- seeded per-theorem violations ------------------------------------------


@pytest.fixture()
def live_engine():
    """A live engine outside any root, mirroring ranges._run wiring, so
    transfer functions can be driven directly with seeded bad states."""
    from tools.ranges import engine as eng_mod
    from tools.ranges.engine import Engine
    from tools.ranges.primitives import Recorder, install_operators

    install_operators()
    fields = load_field_params(REPO)
    eng = Engine(REPO, fields, Recorder())
    eng.current_root = "fixture"
    prev = eng_mod.CURRENT
    eng_mod.CURRENT = eng
    yield eng, fields
    eng_mod.CURRENT = prev


def _limb(eng, fp, *, dmag, tmag, hull, canonical=False):
    lo, hi = Fraction(hull[0]), Fraction(hull[1])
    form = Aff.of_sym(eng.tab.fresh(lo, hi))
    return LimbVal(fp, (fp.nlimbs, 4), 0, dmag, tmag, False, canonical,
                   form)


def _violations(eng):
    return [
        v for s in eng.recorder.sites.values() for v in s["violations"]
    ]


def test_seeded_oversized_digit_product_theorem_a(live_engine):
    from tools.ranges.primitives import make_field_transfers

    eng, (bls, _) = live_engine
    t = make_field_transfers(bls)
    big = _limb(eng, bls, dmag=1 << 17, tmag=1 << 17, hull=(-1, 2))
    t["montmul"](big, big)
    viol = _violations(eng)
    assert any("2^31" in v and "theorem a" in v for v in viol), viol


def test_seeded_missing_relax_before_montmul_theorem_b(live_engine):
    from tools.ranges.primitives import make_field_transfers

    eng, (bls, _) = live_engine
    t = make_field_transfers(bls)
    hot = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-25, 25))
    ok = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-1, 2))
    t["montmul"](hot, ok)
    viol = _violations(eng)
    assert any("theorem b" in v for v in viol), viol
    # the in-range operand alone must NOT fire
    eng.recorder.sites.clear()
    t["montmul"](ok, ok)
    assert not _violations(eng)


def test_seeded_noncanonical_value_at_equality_fold_theorem_c(live_engine):
    from tools.ranges.primitives import make_field_transfers

    eng, (bls, _) = live_engine
    t = make_field_transfers(bls)
    wide = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-10, 10))
    t["is_zero_val"](wide)
    viol = _violations(eng)
    assert any("theorem c" in v for v in viol), viol

    eng.recorder.sites.clear()
    negative = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-1, 2))
    t["canonical_digits"](negative)
    viol = _violations(eng)
    assert any("not within [0, R)" in v for v in viol), viol

    eng.recorder.sites.clear()
    fine = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-3, 3))
    t["is_zero_val"](fine)
    assert not _violations(eng)


def test_montmul_output_contracts(live_engine):
    """For operands inside the 20p working bound the abstract Montgomery
    product contracts back under 2p — the fact the bound rides on."""
    from tools.ranges.primitives import make_field_transfers

    eng, (bls, _) = live_engine
    t = make_field_transfers(bls)
    a = _limb(eng, bls, dmag=bls.lmax, tmag=1 << 11, hull=(-19, 19))
    out = t["montmul"](a, a)
    lo, hi = out.val.hull(eng.tab)
    assert Fraction(-1) < lo and hi < Fraction(2)
    assert not _violations(eng)


# --- suppression scoping ----------------------------------------------------


def test_suppression_is_line_scoped(run):
    ctx, _, _ = run
    src = ctx.source("grandine_tpu/tpu/field.py").splitlines()
    annotated = next(
        i + 1 for i, l in enumerate(src)
        if "lint: disable=limb-range" in l
    )
    hit = Finding(ranges.RULE, "grandine_tpu/tpu/field.py", annotated,
                  "x", key="limb-range:test:x")
    assert ctx.suppressed(hit)
    # one line off: not suppressed
    miss = Finding(ranges.RULE, "grandine_tpu/tpu/field.py", annotated + 1,
                   "x", key="limb-range:test:y")
    assert not ctx.suppressed(miss)
    # a different rule at the same line: not suppressed
    other = Finding("host-sync", "grandine_tpu/tpu/field.py", annotated,
                    "x", key="host-sync:test:x")
    assert not ctx.suppressed(other)


# --- lint-rule integration --------------------------------------------------


def test_rule_registered_in_default_suite():
    from tools.lint.registry import all_rules

    rules = {r.name: r for r in all_rules()}
    assert "limb-range" in rules
    rule = rules["limb-range"]
    assert rule.kind == "ast"  # rides the default (and bench-preflight) run
    assert tuple(rule.default_paths) == tuple(ranges.DEFAULT_FILES)


def test_rule_findings_have_baseline_stable_keys(run):
    _, findings, _ = run
    for f in findings:
        assert f.key.startswith("limb-range:")
        assert str(f.line) not in f.key.split(":"), f.key


# --- constants parametrization ----------------------------------------------


def test_field_params_parsed_from_source():
    bls, ed = load_field_params(REPO)
    assert (bls.limb_bits, bls.nlimbs) == (15, 26)
    assert (ed.limb_bits, ed.nlimbs) == (15, 18)
    assert bls.p.bit_length() == 381
    assert ed.p == 2**255 - 19
    # R/p: the ed25519 plane contracts much harder (R = 2^270, p ~ 2^255)
    assert bls.r_over_p < 1 << 11
    assert ed.r_over_p > 1 << 14
    # parametrization witness: the same seeded digit bound is int32-safe
    # on the 18-limb plane but oversized on neither/both consistently
    sim_bls = bls.cios(bls.lmax, bls.lmax, bls.lmax)
    sim_ed = ed.cios(ed.lmax, ed.lmax, ed.lmax)
    assert sim_bls["max_prod"] == sim_ed["max_prod"] == bls.lmax**2
    assert sim_bls["max_acc"] > sim_ed["max_acc"]  # 26 vs 18 rows
    assert sim_bls["max_acc"] < 1 << 22
