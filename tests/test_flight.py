"""Flight recorder: ring wraparound and snapshot filters, SLO cause
attribution, bounded top-K origin table (space-saving eviction), fault
aggregation across retries, duty-cycle/occupancy integrals, concurrent
record/snapshot safety, the debug endpoint, and the ≤5% always-on
recording overhead guard.
"""

import hashlib
import json
import threading
import time

from grandine_tpu.http_api.routing import ApiContext, build_router
from grandine_tpu.metrics import Metrics
from grandine_tpu.runtime.flight import (
    BATCH,
    BREAKER,
    CANARY,
    FlightRecorder,
    OriginTable,
    SLO_CAUSES,
    bucket_of,
)


def _batch(fl, lane="block", kernel="multi_verify", items=10,
           device_s=0.0, queue_wait_s=0.0, verdict=True, **kw):
    bf = fl.begin_batch(lane, kernel, items, queue_wait_s=queue_wait_s,
                        breaker_state=kw.get("breaker_state", ""))
    if device_s:
        bf.note_device(device_s)
    if kw.get("host_s"):
        bf.note_host(kw["host_s"])
    if kw.get("bisect_s"):
        bf.note_bisect(kw["bisect_s"], kw.get("bisect_depth", 1))
    bf.finish(verdict)
    return bf.record


# ------------------------------------------------------- ring + snapshot


def test_ring_wraparound_keeps_newest():
    fl = FlightRecorder(capacity=16)
    for i in range(40):
        _batch(fl, items=i + 1)
    recs = fl.snapshot()
    assert len(recs) == 16
    assert [r.seq for r in recs] == list(range(24, 40))  # oldest-first
    s = fl.summary()
    assert s["records_total"] == 40 and s["records"] == 16
    assert s["batches"] == 40


def test_snapshot_filters_lane_kind_and_n():
    fl = FlightRecorder(capacity=64)
    for _ in range(4):
        _batch(fl, lane="block")
    for _ in range(3):
        _batch(fl, lane="attestation", kernel="fast_aggregate_verify")
    fl.record_canary("tpu", passed=True, duration_s=0.01)
    fl.record_breaker("tpu", "open")

    assert len(fl.snapshot(lane="block")) == 4
    assert len(fl.snapshot(lane="attestation")) == 3
    assert len(fl.snapshot(kind=BATCH)) == 7
    assert len(fl.snapshot(kind=CANARY)) == 1
    assert len(fl.snapshot(kind=BREAKER)) == 1
    # n truncates to the NEWEST n after filtering
    tail = fl.snapshot(kind=BATCH, n=2)
    assert [r.lane for r in tail] == ["attestation", "attestation"]
    assert fl.snapshot(n=0) == []
    assert len(fl.snapshot(lane="block", n=99)) == 4
    # health-plane rows share the timeline, ordered after the batches
    all_recs = fl.snapshot()
    assert [r.kind for r in all_recs[-2:]] == [CANARY, BREAKER]


def test_records_are_json_ready():
    fl = FlightRecorder()
    _batch(fl, items=5)
    row = fl.snapshot()[0].as_dict()
    json.dumps(row)  # must not raise
    assert row["bucket"] == 8 and row["fill"] == 0.625


# ------------------------------------------------------- SLO attribution


def test_slo_cause_attribution_all_four():
    fl = FlightRecorder(slo_budgets={"block": 0.01})
    # breaker open + no device time: the batch never had a chance
    r1 = _batch(fl, queue_wait_s=0.02, breaker_state="open",
                host_s=0.005, verdict=True)
    # bisection dominates both exec and queue wait
    r2 = _batch(fl, device_s=0.004, bisect_s=0.02, verdict=False)
    # device execute dominates
    r3 = _batch(fl, device_s=0.02, queue_wait_s=0.001)
    # queue wait dominates a tiny execute
    r4 = _batch(fl, device_s=0.001, queue_wait_s=0.02)
    causes = [r.slo_cause for r in (r1, r2, r3, r4)]
    assert causes == ["breaker_open", "bisection", "device", "queue_wait"]
    assert all(r.slo_miss for r in (r1, r2, r3, r4))
    assert set(causes) <= set(SLO_CAUSES)
    misses = fl.slo_misses()
    assert sum(misses["block"].values()) == 4


def test_slo_within_budget_is_not_a_miss():
    m = Metrics()
    fl = FlightRecorder(metrics=m, slo_budgets={"block": 1.0})
    rec = _batch(fl, device_s=0.001)
    assert not rec.slo_miss and rec.slo_cause is None
    assert fl.slo_misses() == {}
    fl2 = FlightRecorder(metrics=m, slo_budgets={"block": 0.0001})
    _batch(fl2, device_s=0.01)
    assert m.verify_slo_miss.value("block", "device") == 1


# ------------------------------------------------- fill / waste / faults


def test_bucket_fill_and_padding_waste():
    assert [bucket_of(n) for n in (1, 2, 3, 9, 64, 65)] == [
        1, 2, 4, 16, 64, 128,
    ]
    m = Metrics()
    fl = FlightRecorder(metrics=m)
    _batch(fl, items=5, kernel="multi_verify")   # bucket 8, waste 3
    _batch(fl, items=8, kernel="multi_verify")   # bucket 8, waste 0
    s = fl.summary()
    assert s["padding_waste"]["multi_verify"] == 3
    assert abs(s["fill_ratio"]["multi_verify"] - (0.625 + 1.0) / 2) < 1e-9
    assert m.verify_padding_waste.value("multi_verify") == 3


def test_note_fault_primary_and_secondary_both_counted():
    fl = FlightRecorder()
    bf = fl.begin_batch("block", "multi_verify", 4)
    bf.note_fault("settle")
    bf.note_retry()
    bf.note_fault("watchdog")  # lands on the retry: secondary
    bf.finish(True)
    rec = fl.snapshot()[0]
    assert rec.fault == "settle" and rec.note == "also_watchdog"
    assert rec.retries == 1
    assert fl.summary()["faults"] == {"settle": 1, "watchdog": 1}


# ----------------------------------------------------------- origin table


def test_origin_table_space_saving_eviction():
    t = OriginTable(capacity=2)
    for _ in range(3):
        t.note_failure("peer:A")
    t.note_failure("peer:B")
    # table full: a NEW origin evicts the minimum (B, count 1) and
    # inherits its count +1, with the floor recorded as error
    t.note_failure("peer:C")
    rows = t.snapshot()
    assert len(t) == 2 and len(rows) == 2
    assert rows[0] == {"origin": "peer:A", "failures": 3, "error": 0}
    assert rows[1] == {"origin": "peer:C", "failures": 2, "error": 1}


def test_origin_table_heavy_hitter_survives_churn():
    t = OriginTable(capacity=4)
    for _ in range(100):
        t.note_failure("peer:hot")
    for i in range(50):  # adversarial one-shot churn
        t.note_failure(f"peer:churn{i}")
    assert len(t) == 4
    rows = t.snapshot()
    assert rows[0]["origin"] == "peer:hot"
    assert rows[0]["failures"] >= 100


def test_batch_flight_threads_origin_into_table():
    fl = FlightRecorder()
    bf = fl.begin_batch("attestation", "fast_aggregate_verify", 64)
    bf.note_fault("verdict")
    bf.note_origin_failure("peer:9000")
    bf.finish(False)
    assert fl.snapshot()[0].origin == "peer:9000"
    assert fl.origins.snapshot()[0]["origin"] == "peer:9000"
    assert fl.summary()["failing_origins"][0]["failures"] == 1


# ------------------------------------------------------ duty / occupancy


def test_duty_cycle_and_occupancy_integrals():
    t = [0.0]
    fl = FlightRecorder(clock=lambda: t[0])
    fl.device_enter()          # depth 1 at t=0
    t[0] = 1.0
    fl.device_enter()          # depth 2 at t=1
    t[0] = 2.0
    fl.device_exit()           # depth 1 at t=2
    t[0] = 3.0
    fl.device_exit()           # idle at t=3
    t[0] = 4.0
    # busy 0..3 of 4s elapsed; occupancy integral 1+2+1 = 4 over 4s
    assert abs(fl.duty_cycle() - 0.75) < 1e-9
    assert abs(fl.occupancy() - 1.0) < 1e-9
    m = Metrics()
    fl2 = FlightRecorder(metrics=m, clock=lambda: t[0])
    fl2.device_enter()
    t[0] = 5.0
    fl2.device_exit()
    assert m.verify_device_duty_cycle.value == 1.0


# ------------------------------------------------------------ concurrency


def test_concurrent_record_and_snapshot():
    fl = FlightRecorder(capacity=64)
    stop = threading.Event()
    errors = []

    def writer(lane):
        try:
            while not stop.is_set():
                _batch(fl, lane=lane)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [
        threading.Thread(target=writer, args=(lane,), daemon=True)
        for lane in ("block", "attestation", "sync_message")
    ]
    for th in threads:
        th.start()
    deadline = time.monotonic() + 0.5
    snaps = 0
    while time.monotonic() < deadline:
        recs = fl.snapshot()
        seqs = [r.seq for r in recs]
        assert seqs == sorted(seqs), "snapshot must be ordered"
        assert len(seqs) == len(set(seqs)), "no duplicate slots"
        for r in fl.snapshot(lane="block", n=8):
            assert r.lane == "block"
        fl.summary()
        snaps += 1
    stop.set()
    for th in threads:
        th.join(2.0)
    assert not errors
    assert snaps > 10 and fl.summary()["batches"] > 10


# --------------------------------------------------------- debug endpoint


def _flight_ctx():
    fl = FlightRecorder(capacity=64)
    for _ in range(3):
        _batch(fl, lane="block")
    _batch(fl, lane="attestation", kernel="fast_aggregate_verify")
    fl.record_breaker("tpu", "open")
    return ApiContext(None, None, flight=fl), fl


def test_flight_endpoint_snapshot_and_filters():
    ctx, fl = _flight_ctx()
    router = build_router()
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight", None
    )
    assert status == 200
    data = payload["data"]
    assert len(data["records"]) == 5
    assert data["summary"]["batches"] == 4
    assert "slo" in data and "origins" in data
    json.dumps(payload)  # endpoint payload is JSON-ready

    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight", {"lane": "block"}
    )
    assert [r["lane"] for r in payload["data"]["records"]] == ["block"] * 3

    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight",
        {"kind": "breaker", "n": "10"},
    )
    rows = payload["data"]["records"]
    assert len(rows) == 1 and rows[0]["note"] == "breaker_open"

    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight", {"n": "2"}
    )
    assert len(payload["data"]["records"]) == 2


def test_flight_endpoint_rejects_bad_n_and_unwired():
    ctx, _fl = _flight_ctx()
    router = build_router()
    assert router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight", {"n": "nope"}
    )[0] == 400
    assert router.dispatch(
        ctx, "GET", "/eth/v1/debug/grandine/flight", {"n": "-1"}
    )[0] == 400
    bare = ApiContext(None, None)
    assert router.dispatch(
        bare, "GET", "/eth/v1/debug/grandine/flight", None
    )[0] == 503


# --------------------------------------------------------- overhead guard


def _recorded_workload(fl, rounds: int) -> float:
    """A batch-shaped CPU workload (16 batches of hashing) with the full
    per-batch recording sequence around each — the exact call pattern
    the scheduler's _flush/_complete path makes per batch — or bare
    when fl is None. Returns seconds."""
    payload = b"\x5a" * (1 << 17)
    t0 = time.perf_counter()
    for _ in range(rounds):
        for _b in range(16):
            if fl is not None:
                bf = fl.begin_batch("block", "multi_verify", 64,
                                    queue_wait_s=0.0001,
                                    breaker_state="closed")
                fl.device_enter()
            h = hashlib.sha256(payload).digest()
            for _ in range(8):
                h = hashlib.sha256(payload + h).digest()
            if fl is not None:
                fl.device_exit()
                bf.note_device(0.001)
                bf.finish(True)
    return time.perf_counter() - t0


def test_flight_recording_overhead_within_5_percent():
    """Recording is always-on (components build a private recorder when
    none is injected), so the per-batch record path must stay inside the
    same ≤5% envelope as the tracing/metrics instrumentation. Min-of-5
    each way with a small absolute epsilon against scheduler noise."""
    fl = FlightRecorder(capacity=4096, metrics=Metrics())
    _recorded_workload(fl, 1)     # warm both paths
    _recorded_workload(None, 1)
    t_off = min(_recorded_workload(None, 1) for _ in range(5))
    t_on = min(_recorded_workload(fl, 1) for _ in range(5))
    assert t_on <= t_off * 1.05 + 0.002, (
        f"recorded {t_on * 1e3:.2f}ms vs bare {t_off * 1e3:.2f}ms"
    )
    assert fl.summary()["batches"] >= 16 * 6
