"""Beacon API tests — handlers driven in-process through the router (the
reference's http_api context.rs pattern) plus one real-socket round trip.
"""

import json
import urllib.request

import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.http_api import ApiContext, serve
from grandine_tpu.http_api.routing import build_router
from grandine_tpu.metrics import Metrics
from grandine_tpu.pools import AttestationAggPool, OperationPool
from grandine_tpu.runtime import Controller
from grandine_tpu.runtime.liveness import LivenessTracker
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()


@pytest.fixture(scope="module")
def ctx():
    genesis = interop_genesis_state(16, CFG)
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    state = genesis
    for slot in (1, 2):
        blk, state = produce_block(state, slot, CFG, full_sync_participation=False)
        ctrl.on_tick(Tick(slot, TickKind.PROPOSE))
        ctrl.on_own_block(blk)
        ctrl.wait()
    liveness = LivenessTracker(16)
    liveness.on_attestation(0, [1, 2])
    context = ApiContext(
        ctrl,
        CFG,
        attestation_pool=AttestationAggPool(CFG),
        operation_pool=OperationPool(CFG),
        liveness=liveness,
        metrics=Metrics(),
    )
    yield context
    ctrl.stop()


@pytest.fixture(scope="module")
def router():
    return build_router()


def get(router, ctx, path, query=None):
    status, payload = router.dispatch(ctx, "GET", path, query)
    return status, payload


def test_node_endpoints(router, ctx):
    status, payload = get(router, ctx, "/eth/v1/node/version")
    assert status == 200 and payload["data"]["version"].startswith("grandine-tpu/")
    assert get(router, ctx, "/eth/v1/node/health")[0] == 200
    status, payload = get(router, ctx, "/eth/v1/node/syncing")
    assert status == 200 and payload["data"]["head_slot"] == "2"


def test_genesis_and_fork(router, ctx):
    status, payload = get(router, ctx, "/eth/v1/beacon/genesis")
    assert status == 200
    assert payload["data"]["genesis_validators_root"].startswith("0x")
    status, payload = get(router, ctx, "/eth/v1/beacon/states/head/fork")
    assert status == 200
    assert payload["data"]["current_version"] == "0x" + CFG.deneb_fork_version.hex()


def test_state_resolution(router, ctx):
    head_root = get(router, ctx, "/eth/v1/beacon/states/head/root")[1]["data"]["root"]
    by_slot = get(router, ctx, "/eth/v1/beacon/states/2/root")[1]["data"]["root"]
    assert head_root == by_slot
    by_root = get(router, ctx, f"/eth/v1/beacon/states/{head_root}/root")
    assert by_root[0] == 200
    assert get(router, ctx, "/eth/v1/beacon/states/99/root")[0] == 404
    assert get(router, ctx, "/eth/v1/beacon/states/bogus/root")[0] == 400


def test_validators_endpoint(router, ctx):
    status, payload = get(
        router, ctx, "/eth/v1/beacon/states/head/validators", {"id": "0,3"}
    )
    assert status == 200
    rows = payload["data"]
    assert [r["index"] for r in rows] == ["0", "3"]
    assert rows[0]["status"] == "active_ongoing"
    assert rows[0]["validator"]["pubkey"].startswith("0x")


def test_blocks_and_headers(router, ctx):
    status, payload = get(router, ctx, "/eth/v2/beacon/blocks/head")
    assert status == 200 and payload["version"] == "deneb"
    root = get(router, ctx, "/eth/v1/beacon/blocks/head/root")[1]["data"]["root"]
    status, payload = get(router, ctx, f"/eth/v2/beacon/blocks/{root}")
    assert status == 200 and payload["data"]["slot"] == "2"
    status, payload = get(router, ctx, "/eth/v1/beacon/headers")
    assert status == 200 and payload["data"][0]["canonical"]


def test_pool_attestation_submission(router, ctx):
    from grandine_tpu.types.containers import spec_types

    snap = ctx.snapshot()
    atts = produce_attestations(snap.head_state, CFG, slot=2)
    bits_typ = spec_types(CFG.preset).deneb.Attestation.FIELDS[0][1]
    body = [{
        "aggregation_bits": "0x"
        + bits_typ.serialize(atts[0].aggregation_bits).hex(),
        "data": {
            "slot": str(int(atts[0].data.slot)),
            "index": str(int(atts[0].data.index)),
            "beacon_block_root": "0x" + bytes(atts[0].data.beacon_block_root).hex(),
            "source": {"epoch": str(int(atts[0].data.source.epoch)),
                       "root": "0x" + bytes(atts[0].data.source.root).hex()},
            "target": {"epoch": str(int(atts[0].data.target.epoch)),
                       "root": "0x" + bytes(atts[0].data.target.root).hex()},
        },
        "signature": "0x" + bytes(atts[0].signature).hex(),
    }]
    status, payload = build_router().dispatch(
        ctx, "POST", "/eth/v1/beacon/pool/attestations", None, body
    )
    assert status == 200
    assert len(ctx.attestation_pool) == 1


def test_config_and_liveness(router, ctx):
    status, payload = get(router, ctx, "/eth/v1/config/spec")
    assert status == 200 and payload["data"]["PRESET_BASE"] == "minimal"
    status, payload = build_router().dispatch(
        ctx, "POST", "/eth/v1/validator/liveness/0", None, ["1", "5"]
    )
    assert status == 200
    assert payload["data"] == [
        {"index": "1", "is_live": True},
        {"index": "5", "is_live": False},
    ]


def test_metrics_endpoint(router, ctx):
    status, text = get(router, ctx, "/metrics")
    assert status == 200 and isinstance(text, str)
    assert "# TYPE head_slot gauge" in text


def test_unknown_route(router, ctx):
    assert get(router, ctx, "/eth/v1/nope")[0] == 404


def test_real_socket_roundtrip(ctx):
    server, _thread = serve(ctx, port=0)
    port = server.server_address[1]
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/eth/v1/node/version", timeout=5
        ) as resp:
            payload = json.loads(resp.read())
        assert payload["data"]["version"].startswith("grandine-tpu/")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            assert b"head_slot" in resp.read()
    finally:
        server.shutdown()


def test_duties_endpoints(router, ctx):
    # proposer duties for the current epoch: one duty per slot, and the
    # duty for an already-proposed slot matches the actual proposer
    status, payload = get(router, ctx, "/eth/v1/validator/duties/proposer/0")
    assert status == 200
    duties = payload["data"]
    assert len(duties) == CFG.preset.SLOTS_PER_EPOCH
    head = ctx.controller.store.blocks[ctx.snapshot().head_root]
    actual = int(head.signed_block.message.proposer_index)
    slot2 = next(d for d in duties if d["slot"] == "2")
    assert int(slot2["validator_index"]) == actual
    assert get(router, ctx, "/eth/v1/validator/duties/proposer/99")[0] == 400

    # attester duties: every validator appears exactly once per epoch
    status, payload = build_router().dispatch(
        ctx, "POST", "/eth/v1/validator/duties/attester/0", None, ["0", "5"]
    )
    assert status == 200
    rows = payload["data"]
    assert {r["validator_index"] for r in rows} == {"0", "5"}
    for r in rows:
        assert 0 <= int(r["slot"]) < CFG.preset.SLOTS_PER_EPOCH


def test_validators_bad_id_is_400(router, ctx):
    assert get(router, ctx, "/eth/v1/beacon/states/head/validators",
               {"id": "abc"})[0] == 400
    assert get(router, ctx, "/eth/v1/beacon/states/head/validators",
               {"id": "-1"})[0] == 400
