"""Dynamic twin of the limb-range static certifier (tools/ranges).

Seeded worst-case-digit fuzz: values driven to the documented envelope
edges (near 20p, digits pushed to ±LMAX by value-preserving borrow
perturbations) through the REAL kernels — montmul, relax, the Fp2/Fp12
tower — on CPU, checked against exact host anchors. Where the static
analysis proves an interval, this exercises the corners of it.
"""

import numpy as np

from grandine_tpu.crypto.constants import P
from grandine_tpu.crypto.fields import Fq2, Fq12
from grandine_tpu.tpu import ed25519 as E
from grandine_tpu.tpu import field as F
from grandine_tpu.tpu import limbs as L


def _perturb(digits, rng, lmax, nlimbs, rounds=64):
    """Value-preserving digit perturbation: d[i] += t·2^15, d[i+1] -= t
    leaves Σ d_i·2^(15i) unchanged while pushing digits toward ±LMAX —
    the adversarial representations the relaxed-digit bounds must
    absorb."""
    d = digits.astype(np.int64).copy()
    for _ in range(rounds):
        i = int(rng.integers(0, nlimbs - 1))
        t = int(rng.integers(-1, 2))
        if abs(d[i] + (t << 15)) <= lmax and abs(d[i + 1] - t) <= lmax:
            d[i] += t << 15
            d[i + 1] -= t
    return d.astype(np.int32)


def _worst_operand(rng, k_p, p, int_to_limbs, lmax, nlimbs):
    """A montmul operand with value u + k·p (near the envelope edge for
    k = 19) and digits fuzzed to the relaxed bound."""
    u = int.from_bytes(rng.bytes(48), "little") % p
    v = u + k_p * p
    return _perturb(int_to_limbs(v), rng, lmax, nlimbs), v


def test_montmul_at_20p_envelope_vs_anchor():
    rng = np.random.default_rng(0xB15)
    cols_a, cols_b, vals = [], [], []
    for trial in range(24):
        k_a = int(rng.integers(0, 20))
        k_b = 19 if trial % 3 == 0 else int(rng.integers(0, 20))
        da, va = _worst_operand(rng, k_a, P, L.int_to_limbs, L.LMAX,
                                L.NLIMBS)
        db, vb = _worst_operand(rng, k_b, P, L.int_to_limbs, L.LMAX,
                                L.NLIMBS)
        cols_a.append(da)
        cols_b.append(db)
        vals.append((va, vb))
    a = np.stack(cols_a, axis=1)
    b = np.stack(cols_b, axis=1)
    out = np.asarray(L.montmul(a, b))
    for i, (va, vb) in enumerate(vals):
        got = L.limbs_to_int(out[:, i])
        assert got % P == va * vb * L.R_INV % P
        # the documented output envelope for |v| < 20p operands
        assert -P < got < 2 * P
    assert int(np.abs(out).max()) <= L.LMAX


def test_relax_preserves_value_and_bounds_digits():
    rng = np.random.default_rng(0x5EED)
    for _ in range(16):
        da, va = _worst_operand(rng, int(rng.integers(0, 19)), P,
                                L.int_to_limbs, L.LMAX, L.NLIMBS)
        db, vb = _worst_operand(rng, int(rng.integers(0, 19)), P,
                                L.int_to_limbs, L.LMAX, L.NLIMBS)
        raw = da.astype(np.int64) + db.astype(np.int64)  # pre-relax sum
        assert np.abs(raw).max() < 1 << 31
        out = np.asarray(L.relax(raw.astype(np.int32)))
        assert L.limbs_to_int(out) == va + vb
        assert int(np.abs(out).max()) <= L.LMAX


def test_add_sub_chain_worst_digits_vs_anchor():
    rng = np.random.default_rng(0xADD)
    da, va = _worst_operand(rng, 3, P, L.int_to_limbs, L.LMAX, L.NLIMBS)
    db, vb = _worst_operand(rng, 2, P, L.int_to_limbs, L.LMAX, L.NLIMBS)
    s = np.asarray(L.add_mod(da, db))
    d = np.asarray(L.sub_mod(da, db))
    assert L.limbs_to_int(s) == va + vb  # value-preserving, no reduction
    assert L.limbs_to_int(d) == va - vb
    assert int(np.abs(s).max()) <= L.LMAX
    assert int(np.abs(d).max()) <= L.LMAX


def _rand_fq2(rng):
    return Fq2.from_ints(
        int.from_bytes(rng.bytes(48), "little") % P,
        int.from_bytes(rng.bytes(48), "little") % P,
    )


def _fq2_to_cols(x, rng):
    """Anchor → device Montgomery columns with fuzzed digits."""
    return tuple(
        _perturb(L.to_mont(c.n), rng, L.LMAX, L.NLIMBS)
        for c in (x.c0, x.c1)
    )


def test_fp2_mul_worst_digits_vs_anchor():
    rng = np.random.default_rng(0xF2)
    B = 4
    xs = [_rand_fq2(rng) for _ in range(B)]
    ys = [_rand_fq2(rng) for _ in range(B)]
    a0, a1 = zip(*[_fq2_to_cols(x, rng) for x in xs])
    b0, b1 = zip(*[_fq2_to_cols(y, rng) for y in ys])
    A = (np.stack(a0, 1), np.stack(a1, 1))
    Bv = (np.stack(b0, 1), np.stack(b1, 1))
    c0, c1 = F.fp2_mul(A, Bv)
    c0, c1 = np.asarray(c0), np.asarray(c1)
    for i in range(B):
        want = xs[i] * ys[i]
        assert L.from_mont(c0[:, i]) == want.c0.n
        assert L.from_mont(c1[:, i]) == want.c1.n


def _rand_fq12(rng):
    from grandine_tpu.crypto.fields import Fq6

    return Fq12(
        Fq6(_rand_fq2(rng), _rand_fq2(rng), _rand_fq2(rng)),
        Fq6(_rand_fq2(rng), _rand_fq2(rng), _rand_fq2(rng)),
    )


def test_fp12_tower_vs_anchor():
    rng = np.random.default_rng(0xF12)
    B = 2
    xs = [_rand_fq12(rng) for _ in range(B)]
    ys = [_rand_fq12(rng) for _ in range(B)]
    a = F.fp12_split(np.stack([F.fq12_to_dev(x) for x in xs]))
    b = F.fp12_split(np.stack([F.fq12_to_dev(y) for y in ys]))
    out = F.fp12_mul(a, b)
    merged = F.fp12_merge_np(
        tuple(
            tuple((np.asarray(c2[0]), np.asarray(c2[1])) for c2 in c6)
            for c6 in out
        )
    )
    for i in range(B):
        got = F.dev_to_fq12(merged[i])
        want = xs[i] * ys[i]
        assert got == want


def test_ed25519_plane_montmul_envelope_vs_anchor():
    rng = np.random.default_rng(0xED)
    lmax = (1 << 15) + 256
    cols_a, cols_b, vals = [], [], []
    for trial in range(16):
        k_a = 19 if trial % 4 == 0 else int(rng.integers(0, 20))
        da, va = _worst_operand(rng, k_a, E.P, E.int_to_limbs, lmax,
                                E.NLIMBS)
        db, vb = _worst_operand(rng, int(rng.integers(0, 20)), E.P,
                                E.int_to_limbs, lmax, E.NLIMBS)
        cols_a.append(da)
        cols_b.append(db)
        vals.append((va, vb))
    a = np.stack(cols_a, axis=1)
    b = np.stack(cols_b, axis=1)
    out = np.asarray(E.montmul(a, b))
    for i, (va, vb) in enumerate(vals):
        got = E.limbs_to_int(out[:, i])
        assert got % E.P == va * vb * E.R_INV % E.P
        assert -E.P < got < 2 * E.P
    assert int(np.abs(out).max()) <= lmax
