"""Differential tests: device batched G1/G2 decompression vs the
pure-Python anchor (`crypto.bls.g1_from_bytes` / `g2_from_bytes`).

The compressed-ingest plane's contract is BYTE-IDENTICAL verdicts: for
every blob the device masks must accept/reject exactly like the host
decoder, and accepted points must land on the same affine coordinates.
The edge corpus walks all three failure classes (non-canonical value
>= p, well-formed x with no curve point / non-residue, infinity flag
with a non-zero payload), the sign bit on both sqrt branches, and the
canonical infinity encoding.
"""

import random

import pytest

pytestmark = pytest.mark.kernel

import jax
import numpy as np

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.constants import P
from grandine_tpu.crypto.curves import G1, g1_infinity, g2_infinity
from grandine_tpu.crypto.fields import Fq2
from grandine_tpu.crypto.hash_to_curve import hash_to_g2
from grandine_tpu.tpu import curve as C
from grandine_tpu.tpu import limbs as L

rng = random.Random(0xDEC0)

# one compile per decompressor across the whole module — a fresh
# jax.jit per test would recompile the same ladder four times
_g1_jit = jax.jit(C.g1_decompress_dev)
_g2_jit = jax.jit(C.g2_decompress_dev)


def _host_verdict_g1(blob: bytes):
    try:
        p = A.g1_from_bytes(blob, subgroup_check=False)
        return True, p.is_infinity(), p
    except A.BlsError:
        return False, False, None


def _host_verdict_g2(blob: bytes):
    try:
        p = A.g2_from_bytes(blob, subgroup_check=False)
        return True, p.is_infinity(), p
    except A.BlsError:
        return False, False, None


def _g1_corpus():
    blobs = [A.g1_to_bytes(G1.mul(k)) for k in (1, 2, 3, 5, 1234567)]
    # opposite sqrt branch: same x, negated y — flips the sign bit
    flip = bytearray(blobs[0])
    flip[0] ^= C.SIGN_FLAG
    blobs.append(bytes(flip))
    blobs.append(A.g1_to_bytes(g1_infinity()))
    bad = []
    # compressed flag cleared
    b = bytearray(blobs[0])
    b[0] &= 0x7F
    bad.append(bytes(b))
    # non-canonical: x >= p
    enc = bytearray((P + 1).to_bytes(48, "big"))
    enc[0] |= C.COMPRESSED_FLAG
    bad.append(bytes(enc))
    # smallest non-residue x (x^3 + 4 has no sqrt): not on the curve
    x = 1
    while pow((x**3 + 4) % P, (P - 1) // 2, P) == 1:
        x += 1
    nr = bytearray(x.to_bytes(48, "big"))
    nr[0] |= C.COMPRESSED_FLAG
    bad.append(bytes(nr))
    # infinity flag on a non-zero payload
    ip = bytearray(blobs[0])
    ip[0] |= C.INFINITY_FLAG
    bad.append(bytes(ip))
    # infinity with the sign bit set (non-canonical infinity)
    isf = bytearray(48)
    isf[0] = C.COMPRESSED_FLAG | C.INFINITY_FLAG | C.SIGN_FLAG
    bad.append(bytes(isf))
    return blobs + bad


def _g2_corpus():
    blobs = [A.g2_to_bytes(hash_to_g2(b"corpus-%d" % i)) for i in range(4)]
    # opposite sqrt branch in Fq2
    flip = bytearray(blobs[0])
    flip[0] ^= C.SIGN_FLAG
    blobs.append(bytes(flip))
    blobs.append(A.g2_to_bytes(g2_infinity()))
    bad = []
    b = bytearray(blobs[0])
    b[0] &= 0x7F
    bad.append(bytes(b))
    # non-canonical c1 (leading half) and c0 (trailing half)
    c1_ge = bytearray(96)
    c1_ge[:48] = (P + 2).to_bytes(48, "big")
    c1_ge[0] |= C.COMPRESSED_FLAG
    bad.append(bytes(c1_ge))
    c0_ge = bytearray(96)
    c0_ge[48:] = (P + 2).to_bytes(48, "big")
    c0_ge[0] |= C.COMPRESSED_FLAG
    bad.append(bytes(c0_ge))
    # x whose rhs = x^3 + 4(1+i) is a non-residue in Fq2
    c0v = 0
    found = None
    while found is None:
        c0v += 1
        xx = Fq2.from_ints(c0v, 3)
        rhs = xx * xx * xx + Fq2.from_ints(4, 4)
        if rhs.sqrt() is None:
            found = xx
    nr = bytearray(
        found.c1.n.to_bytes(48, "big") + found.c0.n.to_bytes(48, "big")
    )
    nr[0] |= C.COMPRESSED_FLAG
    bad.append(bytes(nr))
    ip = bytearray(blobs[0])
    ip[0] |= C.INFINITY_FLAG
    bad.append(bytes(ip))
    return blobs + bad


def test_g1_decompress_matches_host_on_edge_corpus():
    blobs = _g1_corpus()
    rows = C.compressed_rows(blobs, 48)
    x_d, y_d, inf, ok, bad_enc, bad_curve, bad_inf = _g1_jit(rows)
    for i, blob in enumerate(blobs):
        h_ok, h_inf, hp = _host_verdict_g1(blob)
        assert bool(ok[i]) == h_ok, (i, "accept verdict diverged")
        assert bool(inf[i]) == h_inf, (i, "infinity verdict diverged")
        if h_ok and not h_inf:
            ax, ay = hp.to_affine()
            gx = L.from_mont(np.asarray(x_d[:, i])) % P
            gy = L.from_mont(np.asarray(y_d[:, i])) % P
            assert (gx, gy) == (ax.n, ay.n), (i, "coords diverged")
    # the three failure classes are each exercised and disjoint from ok
    assert int(np.asarray(bad_enc).sum()) >= 2  # flag cleared, x >= p
    assert int(np.asarray(bad_curve).sum()) >= 1  # non-residue x
    assert int(np.asarray(bad_inf).sum()) >= 2  # junk payload, sign bit
    assert not np.asarray(
        ok & (bad_enc | bad_curve | bad_inf)
    ).any(), "a row is both accepted and failed"


def test_g2_decompress_matches_host_on_edge_corpus():
    blobs = _g2_corpus()
    rows = C.compressed_rows(blobs, 96)
    x_d, y_d, inf, ok, bad_enc, bad_curve, bad_inf = _g2_jit(rows)
    for i, blob in enumerate(blobs):
        h_ok, h_inf, hp = _host_verdict_g2(blob)
        assert bool(ok[i]) == h_ok, (i, "accept verdict diverged")
        assert bool(inf[i]) == h_inf, (i, "infinity verdict diverged")
        if h_ok and not h_inf:
            ax, ay = hp.to_affine()
            for comp, host in (
                (x_d[0][:, i], ax.c0.n),
                (x_d[1][:, i], ax.c1.n),
                (y_d[0][:, i], ay.c0.n),
                (y_d[1][:, i], ay.c1.n),
            ):
                assert L.from_mont(np.asarray(comp)) % P == host, (
                    i, "coords diverged"
                )
    assert int(np.asarray(bad_enc).sum()) >= 3
    assert int(np.asarray(bad_curve).sum()) >= 1
    assert int(np.asarray(bad_inf).sum()) >= 1


def test_g1_roundtrip_property_fuzz():
    """compress -> device decompress -> recompress == identity over
    random scalar multiples (both sqrt branches land here: the sign bit
    is data-dependent on y's parity)."""
    pts = [G1.mul(rng.randrange(1, 1 << 64)) for _ in range(12)]
    blobs = [A.g1_to_bytes(p) for p in pts]
    rows = C.compressed_rows(blobs, 48)
    x_d, y_d, inf, ok, *_ = _g1_jit(rows)
    assert bool(np.asarray(ok).all()) and not np.asarray(inf).any()
    for i, p in enumerate(pts):
        ax, ay = p.to_affine()
        gx = L.from_mont(np.asarray(x_d[:, i])) % P
        gy = L.from_mont(np.asarray(y_d[:, i])) % P
        assert (gx, gy) == (ax.n, ay.n)
        # recompress from the device coordinates: byte-identical wire
        sign = 1 if gy > (P - 1) // 2 else 0
        enc = bytearray(gx.to_bytes(48, "big"))
        enc[0] |= C.COMPRESSED_FLAG | (C.SIGN_FLAG if sign else 0)
        assert bytes(enc) == blobs[i]


def test_g2_roundtrip_property_fuzz():
    pts = [hash_to_g2(b"fuzz-%d" % rng.getrandbits(32)) for _ in range(11)]
    blobs = [A.g2_to_bytes(p) for p in pts]
    rows = C.compressed_rows(blobs, 96)
    x_d, y_d, inf, ok, *_ = _g2_jit(rows)
    assert bool(np.asarray(ok).all()) and not np.asarray(inf).any()
    for i, p in enumerate(pts):
        ax, ay = p.to_affine()
        got = (
            L.from_mont(np.asarray(x_d[0][:, i])) % P,
            L.from_mont(np.asarray(x_d[1][:, i])) % P,
            L.from_mont(np.asarray(y_d[0][:, i])) % P,
            L.from_mont(np.asarray(y_d[1][:, i])) % P,
        )
        assert got == (ax.c0.n, ax.c1.n, ay.c0.n, ay.c1.n)


def test_compressed_rows_rejects_wire_length():
    with pytest.raises(ValueError):
        C.compressed_rows([b"\x80" * 47], 48)
    with pytest.raises(ValueError):
        C.compressed_rows([b"\x80" * 95], 96)
    flags = C.compressed_infinity_flags(
        C.compressed_rows([b"\xc0" + b"\x00" * 47], 48)
    )
    assert list(flags) == [True]
