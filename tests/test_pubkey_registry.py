"""Device-resident pubkey registry + pipelined verify plane.

Three tiers in one module:
  - host-only unit tests: `_bucket` padding, the bounded `_LruCache`,
    registry lifecycle bookkeeping, controller staleness wiring;
  - a pipeline-overlap test driving the real AttestationVerifier with a
    stub backend whose settle is slow — the span timeline must show batch
    N+1's host_prep starting inside batch N's readback window;
  - kernel-tier differential tests (marked `kernel`): the index-gather
    verify kernels must agree with the upload-path kernels on the same
    batch, including after an incremental registry append and after an
    invalidation/refresh, with the warm path uploading no pubkey bytes.
"""

import random
import time

import numpy as np
import pytest

from grandine_tpu.crypto import bls as A
from grandine_tpu.metrics import Metrics
from grandine_tpu.tpu.bls import (
    MAX_BUCKET,
    TpuBlsBackend,
    _JITTED,
    _LruCache,
    _bucket,
)
from grandine_tpu.tpu.registry import MIN_CAPACITY, DevicePubkeyRegistry

_seed_rng = random.Random(0x9E61)


def _rng_bytes(n: int) -> bytes:
    return bytes(_seed_rng.randrange(256) for _ in range(n))


class _Rng:
    """random.Random behind the secrets-style randbits interface the
    backend's RLC draw expects."""

    def __init__(self, seed: int) -> None:
        self._rng = random.Random(seed)

    def randbits(self, n: int) -> int:
        return self._rng.getrandbits(n)


# ------------------------------------------------------------- _bucket


def test_bucket_monotone_and_covers_range():
    prev = 0
    for n in range(1, 1025):
        b = _bucket(n)
        assert b >= n, "bucket must cover the batch"
        assert b >= prev, "buckets must be monotone in n"
        assert b & (b - 1) == 0, "buckets are powers of two"
        prev = b
    # lo floor and custom lo
    assert _bucket(1) == 4
    assert _bucket(1, lo=16) == 16


def test_bucket_covers_max_and_rejects_beyond():
    assert _bucket(MAX_BUCKET) == MAX_BUCKET
    assert _bucket(MAX_BUCKET - 1) == MAX_BUCKET
    with pytest.raises(ValueError):
        _bucket(MAX_BUCKET + 1)


# ------------------------------------------------------------ LRU cache


def test_lru_cache_bound_eviction_and_metrics():
    m = Metrics()
    c = _LruCache(3, "testcache", metrics=m)
    for i in range(5):
        c.put(i, i * 10)
    assert len(c) == 3
    ev = m.device_cache_events.value
    assert ev("testcache", "evict") == 2
    assert m.device_cache_size.value("testcache") == 3
    # oldest entries evicted, newest retained
    assert c.get(0) is None and c.get(1) is None
    assert c.get(4) == 40
    assert ev("testcache", "miss") == 2 and ev("testcache", "hit") == 1
    # LRU order: touching 2 protects it from the next eviction
    c.get(2)
    c.put(99, 990)
    assert c.get(2) == 20
    assert c.get(3) is None  # 3 was the least recent → evicted


def test_backend_h2c_cache_is_bounded():
    m = Metrics()
    backend = TpuBlsBackend(metrics=m)
    backend._h2c_cache.cap = 2  # shrink for the test
    for i in range(4):
        backend._hash_to_g2_dev(b"h2c-%d" % i, b"dst")
    assert len(backend._h2c_cache) == 2
    # repeat of the newest is a hit, no growth
    backend._hash_to_g2_dev(b"h2c-3", b"dst")
    assert len(backend._h2c_cache) == 2
    assert m.device_cache_events.value("hash_to_g2_dev", "hit") == 1
    assert m.device_cache_events.value("hash_to_g2_dev", "evict") == 2


# ------------------------------------------------- registry bookkeeping


def _fresh_keypairs(n: int):
    sks = [A.SecretKey.keygen(_rng_bytes(32)) for _ in range(n)]
    return sks, tuple(sk.public_key().to_bytes() for sk in sks)


def test_registry_lifecycle_hit_append_refresh():
    m = Metrics()
    reg = DevicePubkeyRegistry(metrics=m)
    _, pkb = _fresh_keypairs(5)
    assert not reg.ensure(())  # empty set: unusable
    first3 = pkb[:3]  # the SAME tuple object, as head-state columns are
    assert reg.ensure(first3)
    assert reg.count == 3 and reg.capacity == MIN_CAPACITY
    assert reg.stats["refreshes"] == 1
    # identity re-ensure is a free hit
    assert reg.ensure(first3)
    assert reg.stats["hits"] == 1
    # prefix growth appends without a refresh
    assert reg.ensure(pkb)
    assert reg.count == 5
    assert reg.stats["appends"] == 1 and reg.stats["refreshes"] == 1
    # same content under a NEW tuple object: miss, prefix-adopt, then hit
    clone = tuple(bytes(b) for b in pkb)
    assert clone is not pkb and reg.ensure(clone)
    assert reg.stats["appends"] == 1 and reg.stats["refreshes"] == 1
    assert reg.ensure(clone) and reg.stats["hits"] >= 2
    # mark_stale demotes the identity fast path exactly once
    reg.mark_stale()
    misses_before = reg.stats["misses"]
    assert reg.ensure(clone)
    assert reg.stats["misses"] == misses_before + 1
    assert reg.ensure(clone)
    assert reg.stats["misses"] == misses_before + 1  # hit again
    # a NON-prefix set forces a refresh
    _, other = _fresh_keypairs(2)
    assert reg.ensure(other)
    assert reg.count == 2 and reg.stats["refreshes"] == 2
    # invalidate drops everything
    reg.invalidate()
    assert reg.count == 0 and reg.capacity == 0
    assert m.pubkey_registry_events.value("invalidate") == 1
    assert m.pubkey_registry_size.value == 0


def test_registry_append_uploads_only_new_rows():
    m = Metrics()
    reg = DevicePubkeyRegistry(metrics=m)
    _, pkb = _fresh_keypairs(6)
    assert reg.ensure(pkb[:4])
    base = reg.stats["uploaded_bytes"]
    assert reg.ensure(pkb)  # +2 rows, within MIN_CAPACITY
    from grandine_tpu.tpu.registry import _next_pow2

    # compressed ingest: the append ships the RAW 48-byte rows (padded
    # to the decompress kernel's bucket), not decompressed limb planes
    assert reg.stats["uploaded_bytes"] - base == _next_pow2(2) * 48
    assert m.device_upload_bytes.value("pubkey_registry") == (
        reg.stats["uploaded_bytes"]
    )
    # host mirror serves the fallback path
    pks = reg.public_keys([5, 0])
    assert pks[0].to_bytes() == pkb[5] and pks[1].to_bytes() == pkb[0]


def test_registry_compressed_ingest_upload_ratio():
    """The compressed-ingest plane's traffic win, pinned: a registry
    build moves 48 B/row of wire bytes where the host-decompress path
    moved the 2 × NLIMBS × 4 B affine limb planes — a ≥ 3× (208/48 ≈
    4.3×) per-row drop in device_upload_bytes_total."""
    import grandine_tpu.tpu.limbs as L

    m = Metrics()
    reg = DevicePubkeyRegistry(metrics=m)
    _, pkb = _fresh_keypairs(6)
    assert reg.ensure(pkb)
    cap = reg.capacity
    raw_bytes = m.device_upload_bytes.value("pubkey_registry")
    assert raw_bytes == cap * 48  # one full raw upload at capacity
    limb_bytes = cap * 2 * L.NLIMBS * 4  # what the limb plane would move
    assert limb_bytes >= 3 * raw_bytes, (
        f"per-row upload {raw_bytes / cap:.0f} B is not a >=3x drop from "
        f"the {limb_bytes / cap:.0f} B limb plane"
    )


def test_verifier_wires_registry_staleness_hook():
    from grandine_tpu.consensus.verifier import NullVerifier
    from grandine_tpu.runtime import AttestationVerifier, Controller
    from grandine_tpu.transition.genesis import interop_genesis_state
    from grandine_tpu.types.config import Config

    cfg = Config.minimal()
    genesis = interop_genesis_state(32, cfg)
    ctrl = Controller(genesis, cfg, verifier_factory=NullVerifier)
    verifier = AttestationVerifier(ctrl, use_device=True, deadline_s=0.01)
    try:
        assert verifier.registry is not None
        assert ctrl.snapshot().validator_count == 32
        assert len(ctrl.on_validator_set_change) == 1
        _, pkb = _fresh_keypairs(2)
        assert verifier.registry.ensure(pkb)
        assert verifier.registry._stale is False
        # the controller-side hook demotes the next ensure to a recheck
        ctrl.on_validator_set_change[0](None, ctrl.snapshot())
        assert verifier.registry._stale is True
    finally:
        verifier.stop()
        ctrl.stop()


# ------------------------------------------------------ pipeline overlap


class _SlowSettleBackend:
    """Async-seam stub: dispatch returns instantly; settle sleeps inside a
    `readback` span, so overlap between one batch's readback and the next
    batch's host_prep is visible on the span timeline."""

    def __init__(self, tracer, settle_s: float = 0.25) -> None:
        self.tracer = tracer
        self.settle_s = settle_s
        self.dispatches = 0

    def g2_subgroup_check_batch_async(self, points):
        n = len(points)
        return lambda: np.ones((n,), bool)

    def fast_aggregate_verify_batch_async(self, messages, sigs, members):
        self.dispatches += 1

        def settle() -> bool:
            with self.tracer.span("readback", {"stub": True}):
                time.sleep(self.settle_s)
            return True

        return settle


def test_pipelined_dispatch_overlaps_prep_with_readback():
    """Acceptance: with max_active=1 (no task-level parallelism), batch
    N+1's host_prep must START before batch N's readback ENDS — only the
    two-deep dispatch queue makes that possible."""
    from grandine_tpu.consensus.verifier import NullVerifier
    from grandine_tpu.fork_choice.store import Tick, TickKind
    from grandine_tpu.runtime import AttestationVerifier, Controller
    from grandine_tpu.tracing import Tracer
    from grandine_tpu.transition.genesis import interop_genesis_state
    from grandine_tpu.types.config import Config
    from grandine_tpu.validator.duties import produce_attestations, produce_block

    cfg = Config.minimal()
    genesis = interop_genesis_state(32, cfg)
    tracer = Tracer()
    ctrl = Controller(genesis, cfg, verifier_factory=NullVerifier)
    stub = _SlowSettleBackend(tracer, settle_s=0.25)
    verifier = AttestationVerifier(
        ctrl,
        backend=stub,
        use_device=True,
        use_registry=False,
        max_batch=1,
        max_active=1,
        deadline_s=0.005,
        tracer=tracer,
    )
    try:
        blk, post = produce_block(
            genesis, 1, cfg, full_sync_participation=False
        )
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_own_block(blk)
        ctrl.wait()
        att = produce_attestations(post, cfg, slot=1)[0]
        # four copies → four single-item batches through the pipeline
        verifier.submit_many([att, att, att, att])
        verifier.flush(timeout=30.0)
        assert verifier.stats["accepted"] == 4
        assert stub.dispatches == 4
    finally:
        verifier.stop()
        ctrl.stop()

    spans = tracer.finished_spans()
    readbacks = [s for s in spans if s.name == "readback"]
    preps = [s for s in spans if s.name == "host_prep"]
    assert len(readbacks) == 4
    overlapped = any(
        h.trace_id != r.trace_id and r.start < h.start < r.end
        for r in readbacks
        for h in preps
    )
    assert overlapped, (
        "no host_prep span of a later batch started inside an earlier "
        "batch's readback window — the dispatch queue is not pipelining"
    )


# ----------------------------------------------------- kernel differential

kernel = pytest.mark.kernel


@pytest.fixture(scope="module")
def metrics():
    return Metrics()


@pytest.fixture(scope="module")
def backend(metrics):
    return TpuBlsBackend(metrics=metrics)


@pytest.fixture(scope="module")
def keyring():
    sks = [A.SecretKey.keygen(_rng_bytes(32)) for _ in range(6)]
    return sks, tuple(sk.public_key().to_bytes() for sk in sks)


@kernel
@pytest.mark.slow
def test_indexed_flat_verify_agrees_with_upload_path(
    backend, metrics, keyring
):
    sks, pkb = keyring
    pks = [sk.public_key() for sk in sks]
    reg = DevicePubkeyRegistry(metrics=metrics)
    assert reg.ensure(pkb[:4])

    msgs = [b"flat-%d" % i for i in range(3)]
    sigs = [sks[i].sign(msgs[i]) for i in range(3)]
    rng = _Rng(0xA1)
    assert backend.multi_verify_indexed(msgs, sigs, [0, 1, 2], reg, rng=rng)
    assert backend.multi_verify(msgs, sigs, pks[:3], rng=rng)
    # wrong signer index fails exactly like wrong key
    assert not backend.multi_verify_indexed(
        msgs, sigs, [1, 0, 2], reg, rng=rng
    )
    # an index the registry does not cover fails
    assert not backend.multi_verify_indexed(
        msgs, sigs, [0, 1, 5], reg, rng=rng
    )
    # after an incremental append the new rows verify
    assert reg.ensure(pkb)
    assert reg.stats["appends"] == 1
    msgs5 = [b"flat-append"]
    sigs5 = [sks[5].sign(msgs5[0])]
    assert backend.multi_verify_indexed(msgs5, sigs5, [5], reg, rng=rng)
    # after invalidation: unusable, then a refresh restores agreement
    reg.invalidate()
    assert not backend.multi_verify_indexed(msgs, sigs, [0, 1, 2], reg, rng=rng)
    assert reg.ensure(pkb)
    assert reg.stats["refreshes"] == 2
    assert backend.multi_verify_indexed(msgs, sigs, [0, 1, 2], reg, rng=rng)


def test_indexed_aggregate_edge_policies_without_device(backend, keyring):
    """Host-side edge policies of the indexed aggregate path — the
    fast tier-1 witness for the full differential below (slow tier):
    length mismatch and an empty committee are verification failures,
    the empty batch is vacuously true, all decided before any device
    work."""
    sks, pkb = keyring
    reg = DevicePubkeyRegistry()
    msg = b"edge"
    sig = A.Signature.aggregate([sks[0].sign(msg)])
    settle = backend.fast_aggregate_verify_batch_indexed_async(
        [msg], [sig], [[0], [1]], reg
    )
    assert settle() is False  # committees/messages length mismatch
    settle = backend.fast_aggregate_verify_batch_indexed_async(
        [msg], [sig], [[]], reg
    )
    assert settle() is False  # empty committee can't have signed
    settle = backend.fast_aggregate_verify_batch_indexed_async(
        [], [], [], reg
    )
    assert settle() is True  # vacuous batch


@kernel
@pytest.mark.slow
def test_indexed_aggregate_verify_agrees_and_skips_pubkey_upload(
    backend, metrics, keyring
):
    sks, pkb = keyring
    pks = [sk.public_key() for sk in sks]
    reg = DevicePubkeyRegistry(metrics=metrics)
    assert reg.ensure(pkb)

    committees = [[0, 1, 2], [3, 4], [5]]
    msgs = [b"agg-%d" % i for i in range(3)]
    aggs = [
        A.Signature.aggregate([sks[j].sign(msgs[i]) for j in committees[i]])
        for i in range(3)
    ]
    member_keys = [[pks[j] for j in c] for c in committees]
    rng = _Rng(0xB2)
    assert backend.fast_aggregate_verify_batch_indexed(
        msgs, aggs, committees, reg, rng=rng
    )
    assert backend.fast_aggregate_verify_batch(
        msgs, aggs, member_keys, rng=rng
    )
    # a committee missing a signer fails on both paths
    short = [c[:-1] or c for c in committees[:1]] + committees[1:]
    short[0] = [0, 1]  # signature includes sks[2]
    assert not backend.fast_aggregate_verify_batch_indexed(
        msgs, aggs, short, reg, rng=rng
    )
    assert not backend.fast_aggregate_verify_batch(
        msgs, aggs, [[pks[j] for j in c] for c in short], rng=rng
    )

    # WARM-PATH ACCOUNTING: the verifier's per-batch registry sync is an
    # ensure() on the same head-state tuple — an identity hit that uploads
    # zero registry bytes; the indexed verify then moves well under the
    # pubkey plane the upload path would carry.
    upload = metrics.device_upload_bytes.value
    hits_before = reg.stats["hits"]
    assert reg.ensure(pkb)  # what _sync_registry does on a warm batch
    assert reg.stats["hits"] == hits_before + 1
    reg_bytes = upload("pubkey_registry")
    idx_bytes = upload("agg_fast_verify_msm_idx")
    up_bytes = upload("agg_fast_verify_msm")
    assert backend.fast_aggregate_verify_batch_indexed(
        msgs, aggs, committees, reg, rng=rng
    )
    assert backend.fast_aggregate_verify_batch(
        msgs, aggs, member_keys, rng=rng
    )
    assert upload("pubkey_registry") == reg_bytes, (
        "warm verify re-uploaded registry bytes"
    )
    import grandine_tpu.tpu.limbs as L

    batch_bytes = upload("agg_fast_verify_msm_idx") - idx_bytes
    upload_path_bytes = upload("agg_fast_verify_msm") - up_bytes
    # the two arg tuples differ ONLY in mem_x+mem_y (the pubkey plane)
    # vs mem_idx (an int32 index plane) — the rest is shape-identical
    # per bucket, so the saving is exactly plane-minus-indices
    bm, bk = _bucket(3), _bucket(3, lo=4)
    pk_plane = bm * bk * 2 * L.NLIMBS * 4
    idx_plane = bm * bk * 4
    assert upload_path_bytes - batch_bytes == pk_plane - idx_plane, (
        f"warm indexed batch moved {batch_bytes} B vs upload path's "
        f"{upload_path_bytes} B; expected the {pk_plane} B pubkey plane "
        f"to be replaced by a {idx_plane} B index plane"
    )


@kernel
def test_one_compile_per_bucket(backend, keyring):
    """Varying batch sizes inside one padding bucket must NOT trigger new
    jit compiles: the padded shapes (and the data-independent MSM plan
    geometry) are identical, so each kernel compiles once per bucket."""
    sks, _ = keyring
    pks = [sk.public_key() for sk in sks]
    rng = _Rng(0xC3)

    def flat_verify(n: int) -> bool:
        msgs = [b"compile-%d" % i for i in range(n)]  # distinct → flat path
        sigs = [sks[i].sign(msgs[i]) for i in range(n)]
        return backend.multi_verify(msgs, sigs, pks[:n], rng=rng)

    def sizes(prefix: str) -> int:
        total = 0
        for key, fn in _JITTED.items():
            if key.startswith(prefix) and not key.startswith(prefix + "_idx"):
                total += int(fn._cache_size())
        return total

    assert flat_verify(3)  # bucket 4: compile happens here (or is cached)
    baseline = sizes("multi_verify_msm")
    assert baseline >= 1
    for n in (2, 4):  # both inside bucket 4
        assert flat_verify(n)
        assert sizes("multi_verify_msm") == baseline, (
            f"batch size {n} inside one bucket triggered a recompile"
        )


# --------------------------------------------- churn at registry scale


def _fake_decompress_dev(raw):
    """Synthetic device decompress keyed off the raw wire bytes — stands
    in for the G1 sqrt kernel so churn tests scale to mainnet row counts
    without compiling (or running) the real decompressor."""
    import jax.numpy as jnp

    import grandine_tpu.tpu.limbs as L

    ids = raw[:, -4:].astype(np.int64)
    ids = (ids[:, 0] << 24) | (ids[:, 1] << 16) | (ids[:, 2] << 8) | ids[:, 3]
    x = np.zeros((raw.shape[0], L.NLIMBS), np.int32)
    x[:, 0] = (ids & 0x7FFF_FFFF).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(x + 1)


def _synthetic_keys(n: int) -> tuple:
    """Wire-well-formed compressed pubkeys (flag byte 0x80, distinct
    payloads) — they pass `_raw_rows`'s flag screen; the fake device
    decompress above supplies the limb rows."""
    return tuple(b"\x80" + i.to_bytes(47, "big") for i in range(n))


def _churn(reg, keys_all, base_count, batch, batches):
    """Deposit-batch churn: `batches` prefix-appends of `batch` rows on
    top of `base_count`, returning (appended_rows, stats deltas)."""
    assert reg.ensure(keys_all[:base_count])
    cap0 = reg.capacity
    grows0 = reg.stats["host_grows"]
    up0 = reg.stats["uploaded_bytes"]
    refr0 = reg.stats["refreshes"]
    end = base_count
    for _ in range(batches):
        end += batch
        assert reg.ensure(keys_all[:end])
    return (
        end - base_count,
        cap0,
        reg.stats["host_grows"] - grows0,
        reg.stats["uploaded_bytes"] - up0,
        reg.stats["refreshes"] - refr0,
    )


def test_registry_churn_within_capacity_is_o_new(monkeypatch):
    """Fast witness for the mainnet churn invariant: prefix appends
    inside capacity upload exactly the new rows' raw bytes (bucketed to
    the decompress kernel's warm ladder), never regrow the host mirror,
    and never rebuild the device arrays."""
    from grandine_tpu.tpu.registry import _next_pow2

    m = Metrics()
    reg = DevicePubkeyRegistry(metrics=m)
    monkeypatch.setattr(reg, "_decompress_dev", _fake_decompress_dev)
    keys_all = _synthetic_keys(1024)
    appended, cap0, grows, uploaded, refreshes = _churn(
        reg, keys_all, base_count=1024 - 64, batch=8, batches=8
    )
    assert appended == 64
    assert reg.capacity == cap0 == 1024
    assert grows == 0, "within-capacity churn regrew the host mirror"
    assert refreshes == 0
    assert uploaded == 8 * _next_pow2(8) * 48, (
        "append upload is not O(new raw rows)"
    )
    assert m.pubkey_registry_host_bytes.value == reg._hraw.nbytes
    assert m.pubkey_registry_capacity.value == 1024


def test_registry_host_mirror_growth_is_geometric(monkeypatch):
    """Growing 4 → 4096 rows in 64-row appends must reallocate the host
    mirror O(log n) times, not O(appends)."""
    reg = DevicePubkeyRegistry()
    monkeypatch.setattr(reg, "_decompress_dev", _fake_decompress_dev)
    keys_all = _synthetic_keys(4096)
    assert reg.ensure(keys_all[:4])
    for end in range(64, 4097, 64):
        assert reg.ensure(keys_all[:end])
    assert reg.stats["host_grows"] <= 12  # log2(4096) = 12
    assert reg.count == 4096


@pytest.mark.slow
def test_registry_churn_at_mainnet_capacity(monkeypatch):
    """The 2^20 bucket itself: build the mainnet-size registry (synthetic
    limb rows), then run deposit-batch churn and hold the O(new)
    invariants at full scale. `test_registry_churn_within_capacity_is_
    o_new` is the fast witness for this path."""
    import grandine_tpu.tpu.limbs as L
    from grandine_tpu.tpu.registry import MAINNET_CAPACITY, _next_pow2

    m = Metrics()
    reg = DevicePubkeyRegistry(metrics=m)
    monkeypatch.setattr(reg, "_decompress_dev", _fake_decompress_dev)
    n = MAINNET_CAPACITY
    keys_all = _synthetic_keys(n)
    appended, cap0, grows, uploaded, refreshes = _churn(
        reg, keys_all, base_count=n - 512, batch=64, batches=8
    )
    assert appended == 512
    assert reg.capacity == cap0 == n
    assert grows == 0 and refreshes == 0
    assert uploaded == 8 * _next_pow2(64) * 48
    assert reg.count == n
    assert m.pubkey_registry_device_bytes.value == n * L.NLIMBS * 4 * 2
