"""Grouped (message-deduplicated) batch verification: the
grouped_multi_verify_kernel and the backend's automatic grouping path.

The grouping identity ∏ᵢ e(rᵢ·pkᵢ, H(mᵢ)) = ∏ⱼ e(Σᵢ∈ⱼ rᵢ·pkᵢ, H(mⱼ))
collapses Miller loops to the distinct-message count — this suite pins its
policy equivalence with the flat path / anchor."""

import numpy as np
import pytest

pytestmark = pytest.mark.kernel

from grandine_tpu.crypto import bls as A
from grandine_tpu.tpu.bls import TpuBlsBackend


@pytest.fixture(scope="module")
def backend():
    return TpuBlsBackend()


@pytest.fixture(scope="module")
def triples():
    msgs = [b"grouped-%d" % (i % 2) for i in range(8)]  # 2 distinct msgs
    sks = [A.SecretKey.keygen(bytes([40 + i]) * 32) for i in range(8)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]
    pks = [sk.public_key() for sk in sks]
    return msgs, sigs, pks


@pytest.mark.slow
def test_grouped_path_taken_and_accepts(backend, triples, monkeypatch):
    msgs, sigs, pks = triples
    called = {}
    orig = backend._grouped_multi_verify_async

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(backend, "_grouped_multi_verify_async", spy)
    assert backend.multi_verify(msgs, sigs, pks)
    assert called.get("yes"), "duplicate-message batch must take the grouped path"
    # anchor agreement
    assert A.multi_verify(msgs, sigs, pks)


@pytest.mark.slow
def test_grouped_rejects_bad_signature(backend, triples):
    msgs, sigs, pks = triples
    bad = list(sigs)
    bad[3] = sigs[4]  # same message group, wrong key's signature? ensure bad
    if msgs[3] == msgs[4]:
        bad[3] = A.SecretKey.keygen(b"\x99" * 32).sign(msgs[3])
    assert not backend.multi_verify(msgs, bad, pks)


@pytest.mark.slow
def test_grouped_rejects_cross_group_swap(backend, triples):
    msgs, sigs, pks = triples
    # swap two signatures across DIFFERENT message groups
    bad = list(sigs)
    bad[0], bad[1] = bad[1], bad[0]
    assert msgs[0] != msgs[1]
    assert not backend.multi_verify(msgs, bad, pks)


@pytest.mark.slow
def test_all_distinct_messages_stay_flat(backend, monkeypatch):
    """Slow tier: pays the flat-kernel compile to prove the verdict;
    the routing decision itself has the fast witness below."""
    msgs = [b"distinct-%d" % i for i in range(4)]
    sks = [A.SecretKey.keygen(bytes([60 + i]) * 32) for i in range(4)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]
    pks = [sk.public_key() for sk in sks]

    def boom(*a, **kw):  # must not be called
        raise AssertionError("grouped path taken for distinct messages")

    monkeypatch.setattr(backend, "_grouped_multi_verify_async", boom)
    assert backend.multi_verify(msgs, sigs, pks)


class _FlatDispatch(Exception):
    """Sentinel: the flat kernel was about to be built."""


def test_distinct_messages_route_flat_without_kernel(backend, monkeypatch):
    """Fast routing witness for the slow flat-verdict test above: with
    all messages distinct the backend must NOT take the grouped path —
    asserted by intercepting the flat path at its kernel-build seam, so
    no compile is paid."""
    msgs = [b"route-%d" % i for i in range(4)]
    sks = [A.SecretKey.keygen(bytes([70 + i]) * 32) for i in range(4)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]
    pks = [sk.public_key() for sk in sks]

    def boom(*a, **kw):
        raise AssertionError("grouped path taken for distinct messages")

    def flat_seam(*a, **kw):
        raise _FlatDispatch

    monkeypatch.setattr(backend, "_grouped_multi_verify_async", boom)
    monkeypatch.setattr(backend, "_jitted_msm", flat_seam)
    with pytest.raises(_FlatDispatch):
        backend.multi_verify(msgs, sigs, pks)


def test_duplicate_messages_route_grouped_without_kernel(
    backend, triples, monkeypatch
):
    """Fast routing witness for the slow grouped-verdict tests above:
    a duplicate-message batch must take the grouped path — asserted by
    intercepting the grouped seam before any kernel is built, so no
    compile is paid."""

    class _GroupedDispatch(Exception):
        pass

    def grouped_seam(*a, **kw):
        raise _GroupedDispatch

    msgs, sigs, pks = triples
    monkeypatch.setattr(
        backend, "_grouped_multi_verify_async", grouped_seam
    )
    with pytest.raises(_GroupedDispatch):
        backend.multi_verify(msgs, sigs, pks)
