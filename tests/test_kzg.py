"""KZG / EIP-4844 tests — reference shape: kzg_utils/src/spec_tests
(blob_to_kzg_commitment / compute_kzg_proof / verify_kzg_proof /
compute_blob_kzg_proof / verify_blob_kzg_proof[_batch] suites).

Official vectors are not vendorable offline, so correctness is anchored
three ways: (1) algebraic identities a KZG scheme must satisfy (constant
polynomials commit to [c]G1 with the zero proof, evaluations at roots equal
the blob entries), (2) full prove→verify round-trips incl. tamper
rejection, on an insecure known-tau dev setup where every value is
independently recomputable, (3) the barycentric evaluator cross-checked
against direct Lagrange interpolation.
"""

import numpy as np
import pytest

from grandine_tpu.crypto import bls as A
from grandine_tpu.crypto.curves import G1
from grandine_tpu.kzg import eip4844, fr
from grandine_tpu.kzg.setup import dev_setup

N = 64
SETUP = dev_setup(N)
R = fr.BLS_MODULUS


@pytest.fixture(autouse=True)
def host_msm(monkeypatch):
    """Unit tests use the host Pippenger; the device MSM has its own test."""
    monkeypatch.setattr(eip4844, "USE_DEVICE_MSM", False)


def blob_from_ints(values) -> bytes:
    assert len(values) == N
    return b"".join(int(v % R).to_bytes(32, "big") for v in values)


def rand_blob(seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    return blob_from_ints([int.from_bytes(rng.bytes(31), "big") for _ in range(N)])


# ---------------------------------------------------------------------- fr


def test_roots_of_unity():
    roots = fr.compute_roots_of_unity(N)
    w = roots[1]
    assert pow(w, N, R) == 1
    assert pow(w, N // 2, R) == R - 1  # primitive
    assert len(set(roots)) == N


def test_bit_reversal_permutation():
    vals = list(range(8))
    assert fr.bit_reversal_permutation(vals) == [0, 4, 2, 6, 1, 5, 3, 7]
    # involution
    twice = fr.bit_reversal_permutation(fr.bit_reversal_permutation(vals))
    assert twice == vals


def test_batch_inverse():
    vals = [3, 7, 0, 123456789]
    inv = fr.batch_inverse(vals)
    assert inv[2] == 0
    for v, i in zip(vals, inv):
        if v:
            assert v * i % R == 1


def test_barycentric_matches_direct_interpolation():
    rng = np.random.default_rng(7)
    evals = [int.from_bytes(rng.bytes(31), "big") % R for _ in range(N)]
    roots = SETUP.roots_brp
    z = 0xABCDEF123456789
    got = fr.evaluate_polynomial_in_evaluation_form(evals, z, roots)
    # direct Lagrange: sum f_i * prod_{j!=i} (z - w_j)/(w_i - w_j)
    expect = 0
    for i in range(N):
        num, den = 1, 1
        for j in range(N):
            if i == j:
                continue
            num = num * ((z - roots[j]) % R) % R
            den = den * ((roots[i] - roots[j]) % R) % R
        expect = (expect + evals[i] * num % R * pow(den, R - 2, R)) % R
    assert got == expect


def test_barycentric_at_root_returns_evaluation():
    evals = [(i * i + 5) % R for i in range(N)]
    assert (
        fr.evaluate_polynomial_in_evaluation_form(
            evals, SETUP.roots_brp[3], SETUP.roots_brp
        )
        == evals[3]
    )


# ------------------------------------------------------------- commitments


def test_constant_blob_commits_to_scaled_generator():
    """p(x) = c everywhere ⇒ commitment = [c]G1 and the proof at any z is
    the identity point."""
    c = 0x1234_5678
    blob = blob_from_ints([c] * N)
    commitment = eip4844.blob_to_kzg_commitment(blob, SETUP)
    assert commitment == A.g1_to_bytes(G1.mul(c))
    proof, y = eip4844.compute_kzg_proof(blob, (99).to_bytes(32, "big"), SETUP)
    assert int.from_bytes(y, "big") == c
    assert proof == eip4844.G1_POINT_AT_INFINITY
    assert eip4844.verify_kzg_proof(
        commitment, (99).to_bytes(32, "big"), y, proof, SETUP
    )


def test_prove_verify_roundtrip():
    blob = rand_blob(1)
    commitment = eip4844.blob_to_kzg_commitment(blob, SETUP)
    z = (0xDEADBEEF).to_bytes(32, "big")
    proof, y = eip4844.compute_kzg_proof(blob, z, SETUP)
    assert eip4844.verify_kzg_proof(commitment, z, y, proof, SETUP)
    # wrong claimed value rejected
    bad_y = ((int.from_bytes(y, "big") + 1) % R).to_bytes(32, "big")
    assert not eip4844.verify_kzg_proof(commitment, z, bad_y, proof, SETUP)
    # wrong commitment rejected
    other = eip4844.blob_to_kzg_commitment(rand_blob(2), SETUP)
    assert not eip4844.verify_kzg_proof(other, z, y, proof, SETUP)


def test_proof_at_root_of_unity():
    """z equal to an evaluation domain point exercises the special-row
    quotient construction."""
    blob = rand_blob(3)
    commitment = eip4844.blob_to_kzg_commitment(blob, SETUP)
    z_int = SETUP.roots_brp[5]
    z = z_int.to_bytes(32, "big")
    proof, y = eip4844.compute_kzg_proof(blob, z, SETUP)
    poly = [int.from_bytes(blob[i * 32 : (i + 1) * 32], "big") for i in range(N)]
    assert int.from_bytes(y, "big") == poly[5]
    assert eip4844.verify_kzg_proof(commitment, z, y, proof, SETUP)


def test_blob_proof_flow():
    blob = rand_blob(4)
    commitment = eip4844.blob_to_kzg_commitment(blob, SETUP)
    proof = eip4844.compute_blob_kzg_proof(blob, commitment, SETUP)
    assert eip4844.verify_blob_kzg_proof(blob, commitment, proof, SETUP)
    # tampered blob fails
    tampered = bytearray(blob)
    tampered[5] ^= 1
    assert not eip4844.verify_blob_kzg_proof(bytes(tampered), commitment, proof, SETUP)


def test_blob_batch_verification():
    blobs = [rand_blob(s) for s in (10, 11, 12)]
    commitments = [eip4844.blob_to_kzg_commitment(b, SETUP) for b in blobs]
    proofs = [
        eip4844.compute_blob_kzg_proof(b, c, SETUP)
        for b, c in zip(blobs, commitments)
    ]
    assert eip4844.verify_blob_kzg_proof_batch(blobs, commitments, proofs, SETUP)
    # one bad proof poisons the batch
    swapped = [proofs[1], proofs[0], proofs[2]]
    assert not eip4844.verify_blob_kzg_proof_batch(blobs, commitments, swapped, SETUP)
    assert eip4844.verify_blob_kzg_proof_batch([], [], [], SETUP)


def test_field_element_range_check():
    bad = bytearray(rand_blob(5))
    bad[0:32] = (R).to_bytes(32, "big")  # == modulus: out of range
    with pytest.raises(eip4844.KzgError):
        eip4844.blob_to_kzg_commitment(bytes(bad), SETUP)
    with pytest.raises(eip4844.KzgError):
        eip4844.blob_to_kzg_commitment(b"\x00" * 31, SETUP)  # wrong size


def test_invalid_commitment_encoding_rejected():
    blob = rand_blob(6)
    with pytest.raises(eip4844.KzgError):
        eip4844.verify_blob_kzg_proof(blob, b"\x00" * 48, b"\xc0" + b"\x00" * 47, SETUP)


# ------------------------------------------------------------- device MSM


def test_device_msm_matches_host():
    """The TPU MSM (one batched scalar-mul launch + sum tree) agrees with
    the host Pippenger on the dev setup."""
    blob = rand_blob(20)
    host = eip4844.blob_to_kzg_commitment(blob, SETUP)

    import grandine_tpu.kzg.eip4844 as mod

    old = mod.USE_DEVICE_MSM
    mod.USE_DEVICE_MSM = True
    try:
        poly = [
            int.from_bytes(blob[i * 32 : (i + 1) * 32], "big") for i in range(N)
        ]
        dev_point = mod._msm_device(SETUP, poly)
        assert A.g1_to_bytes(dev_point) == host
    finally:
        mod.USE_DEVICE_MSM = old
