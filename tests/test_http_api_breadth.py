"""Beacon API breadth tests: state/pool/validator/node route groups plus
block production + publish — reference: http_api/src/routing.rs:221-410.
"""

import json

import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice.store import Tick, TickKind
from grandine_tpu.http_api import ApiContext
from grandine_tpu.http_api.routing import build_router
from grandine_tpu.pools import AttestationAggPool, OperationPool
from grandine_tpu.pools.sync_committee_pool import SyncCommitteeAggPool
from grandine_tpu.runtime import Controller
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()


@pytest.fixture(scope="module")
def ctx():
    genesis = interop_genesis_state(16, CFG)
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    state = genesis
    blocks = []
    for slot in (1, 2):
        atts = (
            produce_attestations(state, CFG, slot=slot - 1)
            if slot > 1
            else []
        )
        blk, state = produce_block(
            state, slot, CFG, full_sync_participation=False,
            attestations=atts,
        )
        blocks.append(blk)
        ctrl.on_tick(Tick(slot, TickKind.PROPOSE))
        ctrl.on_own_block(blk)
        ctrl.wait()
    context = ApiContext(
        ctrl,
        CFG,
        attestation_pool=AttestationAggPool(CFG),
        operation_pool=OperationPool(CFG),
        sync_pool=SyncCommitteeAggPool(CFG),
    )
    context.test_blocks = blocks
    context.test_state = state
    yield context
    ctrl.stop()


@pytest.fixture(scope="module")
def router():
    return build_router()


# ------------------------------------------------------------ state group


def test_committees_route(router, ctx):
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/states/head/committees"
    )
    assert status == 200
    rows = payload["data"]
    assert rows and all(r["validators"] for r in rows)
    # filtered by slot: subset of the full listing
    slot = rows[0]["slot"]
    status, filtered = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/states/head/committees", {"slot": slot}
    )
    assert status == 200
    assert all(r["slot"] == slot for r in filtered["data"])


def test_sync_committees_route(router, ctx):
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/states/head/sync_committees"
    )
    assert status == 200
    data = payload["data"]
    assert len(data["validators"]) == CFG.preset.SYNC_COMMITTEE_SIZE
    assert len(data["validator_aggregates"]) == 4
    # epoch beyond both known periods is a 400
    status, _ = router.dispatch(
        ctx,
        "GET",
        "/eth/v1/beacon/states/head/sync_committees",
        {"epoch": "4096"},
    )
    assert status == 400


def test_validator_balances_route(router, ctx):
    status, payload = router.dispatch(
        ctx,
        "GET",
        "/eth/v1/beacon/states/head/validator_balances",
        {"id": "0,3"},
    )
    assert status == 200
    assert [r["index"] for r in payload["data"]] == ["0", "3"]
    assert all(int(r["balance"]) > 0 for r in payload["data"])


def test_single_validator_route(router, ctx):
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/states/head/validators/3"
    )
    assert status == 200
    pk = payload["data"]["validator"]["pubkey"]
    # lookup by pubkey resolves to the same row
    status, by_pk = router.dispatch(
        ctx, "GET", f"/eth/v1/beacon/states/head/validators/{pk}"
    )
    assert status == 200 and by_pk["data"]["index"] == "3"
    assert router.dispatch(
        ctx, "GET", "/eth/v1/beacon/states/head/validators/9999"
    )[0] == 404


def test_header_and_block_attestations(router, ctx):
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/headers/head"
    )
    assert status == 200
    assert payload["data"]["canonical"] is True
    assert payload["data"]["header"]["message"]["slot"] == "2"

    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/blocks/2/attestations"
    )
    assert status == 200
    atts = payload["data"]
    assert atts and atts[0]["data"]["slot"] == "1"
    assert atts[0]["aggregation_bits"].startswith("0x")


# ------------------------------------------------------------- pool group


def test_pool_proposer_slashing_roundtrip(router, ctx):
    header = {
        "message": {
            "slot": "1",
            "proposer_index": "5",
            "parent_root": "0x" + "11" * 32,
            "state_root": "0x" + "22" * 32,
            "body_root": "0x" + "33" * 32,
        },
        "signature": "0x" + "44" * 96,
    }
    header2 = json.loads(json.dumps(header))
    header2["message"]["body_root"] = "0x" + "55" * 32
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/proposer_slashings",
        body={"signed_header_1": header, "signed_header_2": header2},
    )
    assert status == 200
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/pool/proposer_slashings"
    )
    assert status == 200
    assert payload["data"][0]["signed_header_1"]["message"]["proposer_index"] == "5"


def test_pool_attester_slashing_roundtrip(router, ctx):
    data = {
        "slot": "1",
        "index": "0",
        "beacon_block_root": "0x" + "aa" * 32,
        "source": {"epoch": "0", "root": "0x" + "bb" * 32},
        "target": {"epoch": "1", "root": "0x" + "cc" * 32},
    }
    data2 = json.loads(json.dumps(data))
    data2["beacon_block_root"] = "0x" + "dd" * 32
    att = lambda d: {  # noqa: E731
        "attesting_indices": ["2", "4"],
        "data": d,
        "signature": "0x" + "ee" * 96,
    }
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/attester_slashings",
        body={"attestation_1": att(data), "attestation_2": att(data2)},
    )
    assert status == 200
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/pool/attester_slashings"
    )
    assert payload["data"][0]["attestation_1"]["attesting_indices"] == ["2", "4"]


def test_pool_exit_and_bls_change(router, ctx):
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/voluntary_exits",
        body={
            "message": {"epoch": "0", "validator_index": "7"},
            "signature": "0x" + "12" * 96,
        },
    )
    assert status == 200
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/pool/voluntary_exits"
    )
    assert any(
        e["message"]["validator_index"] == "7" for e in payload["data"]
    )

    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/bls_to_execution_changes",
        body=[{
            "message": {
                "validator_index": "6",
                "from_bls_pubkey": "0x" + "ab" * 48,
                "to_execution_address": "0x" + "cd" * 20,
            },
            "signature": "0x" + "ef" * 96,
        }],
    )
    assert status == 200
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/pool/bls_to_execution_changes"
    )
    assert payload["data"][0]["message"]["validator_index"] == "6"


def test_pool_sync_committee_messages(router, ctx):
    from grandine_tpu.validator.duties import _interop_keys

    state = ctx.snapshot().head_state
    # validator 0's real position(s); signature content is not verified
    # by the pool, but must be a valid G2 point to aggregate
    sig = _interop_keys(0).sign(b"\x01" * 32).to_bytes()
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/sync_committees",
        body=[{
            "slot": "2",
            "beacon_block_root": "0x" + "00" * 32,
            "validator_index": "0",
            "signature": "0x" + sig.hex(),
        }],
    )
    assert status == 200
    # unknown validator index -> 400 with failure detail
    status, payload = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/pool/sync_committees",
        body=[{
            "slot": "2",
            "beacon_block_root": "0x" + "00" * 32,
            "validator_index": "99999",
            "signature": "0x" + sig.hex(),
        }],
    )
    assert status == 400


def test_aggregate_and_proofs_and_lookup(router, ctx):
    # take a real attestation from block 2 and submit it as an aggregate
    status, payload = router.dispatch(
        ctx, "GET", "/eth/v1/beacon/blocks/2/attestations"
    )
    att_json = payload["data"][0]
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/validator/aggregate_and_proofs",
        body=[{
            "message": {
                "aggregator_index": "0",
                "aggregate": att_json,
                "selection_proof": "0x" + "00" * 96,
            },
            "signature": "0x" + "00" * 96,
        }],
    )
    assert status == 200
    # recover it through the aggregate_attestation lookup
    from grandine_tpu.types.combined import fork_namespace, state_phase_of

    state = ctx.snapshot().head_state
    ns = fork_namespace(CFG, state_phase_of(state, CFG))
    data = ns.AttestationData(
        slot=int(att_json["data"]["slot"]),
        index=int(att_json["data"]["index"]),
        beacon_block_root=bytes.fromhex(
            att_json["data"]["beacon_block_root"][2:]
        ),
        source=ns.Checkpoint(
            epoch=int(att_json["data"]["source"]["epoch"]),
            root=bytes.fromhex(att_json["data"]["source"]["root"][2:]),
        ),
        target=ns.Checkpoint(
            epoch=int(att_json["data"]["target"]["epoch"]),
            root=bytes.fromhex(att_json["data"]["target"]["root"][2:]),
        ),
    )
    status, payload = router.dispatch(
        ctx,
        "GET",
        "/eth/v1/validator/aggregate_attestation",
        {
            "slot": att_json["data"]["slot"],
            "attestation_data_root": "0x" + data.hash_tree_root().hex(),
        },
    )
    assert status == 200
    assert payload["data"]["data"]["slot"] == att_json["data"]["slot"]


# ------------------------------------------- production / publish group


def test_produce_and_publish_block(router, ctx):
    status, payload = router.dispatch(
        ctx,
        "GET",
        "/eth/v3/validator/blocks/3",
        {"randao_reveal": "0x" + "11" * 96},
    )
    assert status == 200
    assert payload["execution_payload_blinded"] is False
    assert payload["data"]["slot"] == "3"
    assert payload["data"]["ssz"].startswith("0x")

    # produce a SIGNED block with the duty engine and publish it
    signed, _post = produce_block(
        ctx.test_state, 3, CFG, full_sync_participation=False
    )
    ctx.controller.on_tick(Tick(3, TickKind.PROPOSE))
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/beacon/blocks",
        body={"ssz": "0x" + signed.serialize().hex()},
    )
    assert status == 200
    ctx.controller.wait()
    assert ctx.snapshot().head_root == signed.message.hash_tree_root()
    ctx.test_state = _post


def test_produce_block_requires_reveal_and_future_slot(router, ctx):
    assert router.dispatch(
        ctx, "GET", "/eth/v3/validator/blocks/9"
    )[0] == 400
    assert router.dispatch(
        ctx,
        "GET",
        "/eth/v3/validator/blocks/1",
        {"randao_reveal": "0x" + "11" * 96},
    )[0] == 400


def test_publish_malformed_block_is_400(router, ctx):
    assert router.dispatch(
        ctx, "POST", "/eth/v1/beacon/blocks", body={"ssz": "0x0102"}
    )[0] == 400
    assert router.dispatch(
        ctx, "POST", "/eth/v1/beacon/blocks", body=["nope"]
    )[0] == 400


# --------------------------------------------------- validator/node group


def test_sync_duties_route(router, ctx):
    status, payload = router.dispatch(
        ctx, "POST", "/eth/v1/validator/duties/sync/0",
        body=[str(i) for i in range(16)],
    )
    assert status == 200
    # minimal preset: every validator appears in the 32-wide committee
    assert payload["data"]
    row = payload["data"][0]
    assert row["validator_sync_committee_indices"]


def test_prepare_and_register(router, ctx):
    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/validator/prepare_beacon_proposer",
        body=[{
            "validator_index": "4",
            "fee_recipient": "0x" + "aa" * 20,
        }],
    )
    assert status == 200
    assert ctx.prepared_proposers[4] == "0x" + "aa" * 20

    status, _ = router.dispatch(
        ctx,
        "POST",
        "/eth/v1/validator/register_validator",
        body=[{
            "message": {
                "fee_recipient": "0x" + "bb" * 20,
                "gas_limit": "30000000",
                "timestamp": "0",
                "pubkey": "0x" + "cc" * 48,
            },
            "signature": "0x" + "dd" * 96,
        }],
    )
    assert status == 200
    assert "0x" + "cc" * 48 in ctx.validator_registrations


def test_subscriptions_require_subnet_service(router, ctx):
    assert router.dispatch(
        ctx,
        "POST",
        "/eth/v1/validator/beacon_committee_subscriptions",
        body=[],
    )[0] == 503


def test_node_identity_and_peers(router, ctx):
    status, payload = router.dispatch(ctx, "GET", "/eth/v1/node/identity")
    assert status == 200 and "peer_id" in payload["data"]
    status, payload = router.dispatch(ctx, "GET", "/eth/v1/node/peers")
    assert status == 200 and payload["meta"]["count"] == 0
    status, payload = router.dispatch(ctx, "GET", "/eth/v1/node/peer_count")
    assert status == 200 and payload["data"]["connected"] == "0"


def test_debug_routes(router, ctx):
    """/eth/v1/debug/fork_choice, /eth/v2/debug/beacon/heads, and the
    debug state dump (http_api/src/routing.rs:460-467)."""
    status, body = router.dispatch(ctx, "GET", "/eth/v1/debug/fork_choice")
    assert status == 200
    assert body["fork_choice_nodes"]
    n0 = body["fork_choice_nodes"][0]
    assert {"slot", "block_root", "weight", "validity"} <= set(n0)

    status, body = router.dispatch(ctx, "GET", "/eth/v2/debug/beacon/heads")
    assert status == 200
    assert body["data"][0]["root"].startswith("0x")

    status, body = router.dispatch(
        ctx, "GET", "/eth/v2/debug/beacon/states/head"
    )
    assert status == 200
    assert body["data"]["ssz"].startswith("0x")
