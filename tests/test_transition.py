"""State-transition tests: genesis, slot/epoch processing, block sanity,
operations, finality, fork upgrades.

Reference test parity: the consensus-spec-tests sanity/finality/operations
suites' *shapes* (transition_functions/src/*/block_processing.rs:550-605)
built from in-framework produced chains (no network, no eth1 — the §4.3
Null seams).
"""

import numpy as np
import pytest

from grandine_tpu.consensus import accessors
from grandine_tpu.consensus.verifier import (
    MultiVerifier,
    NullVerifier,
    SignatureInvalid,
)
from grandine_tpu.crypto import bls as A
from grandine_tpu.ssz.merkle import MerkleTree
from grandine_tpu.transition import combined
from grandine_tpu.transition.block import TransitionError
from grandine_tpu.transition.combined import (
    StateRootMismatch,
    untrusted_state_transition,
)
from grandine_tpu.transition.fork_upgrade import state_phase
from grandine_tpu.transition.genesis import interop_genesis_state, interop_secret_key
from grandine_tpu.transition.slots import process_slots
from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.types.primitives import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    FAR_FUTURE_EPOCH,
    Phase,
)
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()
P = CFG.preset

PHASE0_CFG = Config(
    config_name="phase0-test",
    preset_base="minimal",
    altair_fork_epoch=FAR_FUTURE_EPOCH,
    bellatrix_fork_epoch=FAR_FUTURE_EPOCH,
    capella_fork_epoch=FAR_FUTURE_EPOCH,
    deneb_fork_epoch=FAR_FUTURE_EPOCH,
)


@pytest.fixture(scope="module")
def genesis():
    return interop_genesis_state(32, CFG)


@pytest.fixture(scope="module")
def chain(genesis):
    """A 3-block deneb chain: genesis -> b1 -> b2 -> b3 with attestations."""
    states = [genesis]
    blocks = []
    prev = genesis
    for slot in (1, 2, 3):
        atts = produce_attestations(prev, CFG, slot=slot - 1) if slot > 1 else []
        blk, post = produce_block(
            prev, slot, CFG, attestations=atts, full_sync_participation=(slot == 2)
        )
        blocks.append(blk)
        states.append(post)
        prev = post
    return blocks, states


# ------------------------------------------------------------------ genesis


def test_genesis_invariants(genesis):
    assert int(genesis.slot) == 0
    assert state_phase(genesis, CFG) == Phase.DENEB
    assert (
        bytes(genesis.genesis_validators_root)
        == genesis.validators.hash_tree_root()
    )
    registry = {bytes(v.pubkey) for v in genesis.validators}
    for pk in genesis.current_sync_committee.pubkeys:
        assert bytes(pk) in registry
    # aggregate pubkey is the real aggregate
    agg = A.PublicKey.aggregate(
        [A.PublicKey.from_bytes(bytes(pk))
         for pk in genesis.current_sync_committee.pubkeys]
    )
    assert bytes(genesis.current_sync_committee.aggregate_pubkey) == agg.to_bytes()


# ------------------------------------------------------------------- slots


def test_process_slots_records_roots(genesis):
    s3 = process_slots(genesis, 3, CFG)
    assert int(s3.slot) == 3
    # slot-0 state root was cached, header state root backfilled
    assert bytes(s3.state_roots[0]) == genesis.hash_tree_root()
    assert bytes(s3.latest_block_header.state_root) == genesis.hash_tree_root()
    # the same block root repeats for empty slots
    assert bytes(s3.block_roots[0]) == bytes(s3.block_roots[2])
    with pytest.raises(ValueError):
        process_slots(s3, 1, CFG)  # backwards


# ------------------------------------------------------------ block sanity


def test_valid_chain_verifies(chain, genesis):
    blocks, states = chain
    state = genesis
    for blk, expected in zip(blocks, states[1:]):
        state = untrusted_state_transition(state, blk, CFG)
        assert state.hash_tree_root() == expected.hash_tree_root()


def test_bad_proposer_signature_rejected(chain, genesis):
    blocks, _ = chain
    bad = blocks[0].replace(signature=interop_secret_key(9).sign(b"x" * 32).to_bytes())
    with pytest.raises(SignatureInvalid):
        untrusted_state_transition(genesis, bad, CFG)


def test_wrong_state_root_rejected(chain, genesis):
    blocks, _ = chain
    msg = blocks[0].message.replace(state_root=b"\x13" * 32)
    proposer = interop_secret_key(int(msg.proposer_index))
    from grandine_tpu.consensus import signing

    pre = process_slots(genesis, 1, CFG)
    sig = proposer.sign(signing.block_signing_root(pre, msg, CFG)).to_bytes()
    bad = blocks[0].replace(message=msg, signature=sig)
    with pytest.raises(StateRootMismatch):
        untrusted_state_transition(genesis, bad, CFG)


def test_wrong_proposer_rejected(chain, genesis):
    blocks, _ = chain
    msg = blocks[0].message
    wrong = (int(msg.proposer_index) + 1) % 32
    msg = msg.replace(proposer_index=wrong)
    bad = blocks[0].replace(message=msg)
    with pytest.raises((TransitionError, SignatureInvalid)):
        untrusted_state_transition(genesis, bad, CFG)


def test_tampered_attestation_rejected(chain):
    blocks, states = chain
    blk3 = blocks[2]
    body = blk3.message.body
    atts = list(body.attestations)
    if not atts:
        pytest.skip("no attestations in block 3")
    # flip a participation bit without re-signing
    a0 = atts[0]
    flipped = a0.aggregation_bits.set(0, not a0.aggregation_bits[0])
    atts[0] = a0.replace(aggregation_bits=flipped)
    bad = blk3.replace(
        message=blk3.message.replace(body=body.replace(attestations=atts))
    )
    with pytest.raises((SignatureInvalid, TransitionError)):
        combined.verify_signatures(
            process_slots(states[2], 3, CFG), bad, MultiVerifier(), CFG
        )


# -------------------------------------------------------------- operations


def test_proposer_slashing(chain):
    _, states = chain
    state = states[-1]
    ns = spec_types(P).deneb
    from grandine_tpu.consensus import signing

    offender = 7
    sk = interop_secret_key(offender)
    h = ns.BeaconBlockHeader(
        slot=int(state.slot), proposer_index=offender,
        parent_root=b"\x01" * 32, state_root=b"\x02" * 32, body_root=b"\x03" * 32,
    )
    h2 = h.replace(body_root=b"\x04" * 32)
    pre = process_slots(state, int(state.slot) + 1, CFG)
    sh1 = ns.SignedBeaconBlockHeader(
        message=h, signature=sk.sign(signing.header_signing_root(pre, h, CFG)).to_bytes()
    )
    sh2 = ns.SignedBeaconBlockHeader(
        message=h2, signature=sk.sign(signing.header_signing_root(pre, h2, CFG)).to_bytes()
    )
    ps = ns.ProposerSlashing(signed_header_1=sh1, signed_header_2=sh2)
    blk, post = produce_block(
        state, int(state.slot) + 1, CFG, proposer_slashings=[ps],
        full_sync_participation=False,
    )
    v = untrusted_state_transition(state, blk, CFG)
    assert v.hash_tree_root() == post.hash_tree_root()
    assert bool(post.validators[offender].slashed)
    assert int(post.balances[offender]) < int(state.balances[offender])


def test_attester_slashing(chain):
    _, states = chain
    state = states[-1]
    ns = spec_types(P).deneb
    from grandine_tpu.consensus import signing

    offenders = [3, 11]
    cp = lambda e, r: ns.Checkpoint(epoch=e, root=r)  # noqa: E731
    root_a = b"\x0a" * 32
    root_b = b"\x0b" * 32
    d1 = ns.AttestationData(
        slot=int(state.slot), index=0, beacon_block_root=root_a,
        source=cp(0, b"\x00" * 32), target=cp(1, root_a),
    )
    d2 = d1.replace(beacon_block_root=root_b, target=cp(1, root_b))
    pre = process_slots(state, int(state.slot) + 1, CFG)

    def indexed(data):
        root = signing.attestation_signing_root(pre, data, CFG)
        sigs = [interop_secret_key(i).sign(root) for i in offenders]
        return ns.IndexedAttestation(
            attesting_indices=offenders, data=data,
            signature=A.Signature.aggregate(sigs).to_bytes(),
        )

    aslash = ns.AttesterSlashing(attestation_1=indexed(d1), attestation_2=indexed(d2))
    blk, post = produce_block(
        state, int(state.slot) + 1, CFG, attester_slashings=[aslash],
        full_sync_participation=False,
    )
    v = untrusted_state_transition(state, blk, CFG)
    assert v.hash_tree_root() == post.hash_tree_root()
    for i in offenders:
        assert bool(post.validators[i].slashed)


def test_deposit_flow(genesis):
    """New-validator deposit with a real merkle proof + a top-up."""
    ns = spec_types(P).deneb
    from grandine_tpu.consensus import signing as sgn

    new_sk = interop_secret_key(1000)
    amount = P.MAX_EFFECTIVE_BALANCE

    def deposit_data(sk, creds):
        dd = ns.DepositData(
            pubkey=sk.public_key().to_bytes(),
            withdrawal_credentials=creds,
            amount=amount,
        )
        sig = sk.sign(sgn.deposit_signing_root(dd, CFG))
        return dd.replace(signature=sig.to_bytes())

    dd_new = deposit_data(new_sk, b"\x00" + b"\x05" * 31)
    dd_topup = deposit_data(interop_secret_key(0), b"\x00" + b"\x06" * 31)

    # the deposit tree continues from the genesis deposits
    tree = MerkleTree(DEPOSIT_CONTRACT_TREE_DEPTH, track_leaves=True)
    for v in genesis.validators:
        dd = ns.DepositData(
            pubkey=bytes(v.pubkey),
            withdrawal_credentials=bytes(v.withdrawal_credentials),
            amount=P.MAX_EFFECTIVE_BALANCE,
        )
        tree.push(dd.hash_tree_root())  # placeholder leaves for prior slots

    deposits = []
    leaves = [dd_new, dd_topup]
    for dd in leaves:
        tree.push(dd.hash_tree_root())
    count = tree.count
    root = tree.root_with_length()
    for k, dd in enumerate(leaves):
        index = 32 + k
        proof = tree.proof(index) + [count.to_bytes(32, "little")]
        deposits.append(ns.Deposit(proof=proof, data=dd))

    state = genesis.replace(
        eth1_data=ns.Eth1Data(
            deposit_root=root, deposit_count=count,
            block_hash=bytes(genesis.eth1_data.block_hash),
        )
    )
    blk, post = produce_block(
        state, 1, CFG, deposits=deposits, full_sync_participation=False
    )
    v = untrusted_state_transition(state, blk, CFG)
    assert v.hash_tree_root() == post.hash_tree_root()
    assert len(post.validators) == 33
    assert bytes(post.validators[32].pubkey) == new_sk.public_key().to_bytes()
    # top-up landed (validator 0 may also pay a small sync-committee
    # non-participation penalty in the same block)
    assert int(post.balances[0]) >= int(state.balances[0]) + amount - 10**6
    assert len(post.previous_epoch_participation) == 33
    assert len(post.inactivity_scores) == 33


def test_voluntary_exit(genesis):
    cfg = Config.minimal()
    # shard_committee_period epochs must pass; shortcut with a fresh config
    import dataclasses

    cfg = dataclasses.replace(cfg, shard_committee_period=0)
    state = interop_genesis_state(32, cfg)
    ns = spec_types(P).deneb
    from grandine_tpu.consensus import signing as sgn

    exiting = 4
    exit_msg = ns.VoluntaryExit(epoch=0, validator_index=exiting)
    pre = process_slots(state, 1, cfg)
    sig = interop_secret_key(exiting).sign(
        sgn.voluntary_exit_signing_root(pre, exit_msg, cfg, Phase.DENEB)
    )
    signed = ns.SignedVoluntaryExit(message=exit_msg, signature=sig.to_bytes())
    blk, post = produce_block(
        state, 1, cfg, voluntary_exits=[signed], full_sync_participation=False
    )
    v = untrusted_state_transition(state, blk, cfg)
    assert v.hash_tree_root() == post.hash_tree_root()
    assert int(post.validators[exiting].exit_epoch) != FAR_FUTURE_EPOCH


def test_bls_to_execution_change(genesis):
    ns = spec_types(P).deneb
    from grandine_tpu.consensus import misc as m
    from grandine_tpu.consensus import signing as sgn

    index = 6
    sk = interop_secret_key(index)
    pk_bytes = sk.public_key().to_bytes()
    creds = b"\x00" + m.sha256(pk_bytes)[1:]
    vs = list(genesis.validators)
    vs[index] = vs[index].replace(withdrawal_credentials=creds)
    state = genesis.replace(validators=vs)

    change = ns.BLSToExecutionChange(
        validator_index=index,
        from_bls_pubkey=pk_bytes,
        to_execution_address=b"\xaa" * 20,
    )
    sig = sk.sign(sgn.bls_to_execution_change_signing_root(state, change, CFG))
    signed = ns.SignedBLSToExecutionChange(message=change, signature=sig.to_bytes())
    blk, post = produce_block(
        state, 1, CFG, bls_to_execution_changes=[signed],
        full_sync_participation=False,
    )
    v = untrusted_state_transition(state, blk, CFG)
    assert v.hash_tree_root() == post.hash_tree_root()
    new_creds = bytes(post.validators[index].withdrawal_credentials)
    assert new_creds[:1] == b"\x01"
    assert new_creds[12:] == b"\xaa" * 20


# ---------------------------------------------------------------- finality


def test_phase0_finality_two_epochs():
    state = interop_genesis_state(32, PHASE0_CFG)
    prev = state
    for slot in range(1, 33):
        atts = (
            produce_attestations(prev, PHASE0_CFG, slot=slot - 1)
            if slot > 1
            else []
        )
        _, prev = produce_block(prev, slot, PHASE0_CFG, attestations=atts)
    assert int(prev.current_justified_checkpoint.epoch) == 3
    assert int(prev.finalized_checkpoint.epoch) == 2
    assert state_phase(prev, PHASE0_CFG) == Phase.PHASE0


def test_no_attestations_no_finality():
    state = interop_genesis_state(32, PHASE0_CFG)
    prev = state
    for slot in range(1, 25):
        _, prev = produce_block(prev, slot, PHASE0_CFG)
    assert int(prev.current_justified_checkpoint.epoch) == 0
    assert int(prev.finalized_checkpoint.epoch) == 0


# ------------------------------------------------------------ fork upgrade


def test_fork_upgrade_boundary_smoke():
    """Fast witness for the full three-fork traversal below (slow
    tier): cross the single phase0→altair boundary with a small
    validator set and keep producing valid blocks on the far side."""
    cfg = Config(
        config_name="upgrade-smoke",
        preset_base="minimal",
        altair_fork_epoch=1,
        bellatrix_fork_epoch=FAR_FUTURE_EPOCH,
        capella_fork_epoch=FAR_FUTURE_EPOCH,
        deneb_fork_epoch=FAR_FUTURE_EPOCH,
        genesis_fork_version=bytes.fromhex("00000002"),
        altair_fork_version=bytes.fromhex("01000002"),
    )
    slots_per_epoch = cfg.preset.SLOTS_PER_EPOCH
    prev = interop_genesis_state(16, cfg)
    assert state_phase(prev, cfg) == Phase.PHASE0
    for slot in range(1, slots_per_epoch + 2):
        atts = produce_attestations(prev, cfg, slot=slot - 1) if slot > 1 else []
        _, prev = produce_block(prev, slot, cfg, attestations=atts)
        assert state_phase(prev, cfg) == cfg.phase_at_slot(slot)
    assert state_phase(prev, cfg) == Phase.ALTAIR


@pytest.mark.slow
def test_fork_upgrade_phase0_to_altair():
    cfg = Config(
        config_name="upgrade-test",
        preset_base="minimal",
        altair_fork_epoch=1,
        bellatrix_fork_epoch=2,
        capella_fork_epoch=3,
        deneb_fork_epoch=FAR_FUTURE_EPOCH,
        genesis_fork_version=bytes.fromhex("00000001"),
        altair_fork_version=bytes.fromhex("01000001"),
        bellatrix_fork_version=bytes.fromhex("02000001"),
        capella_fork_version=bytes.fromhex("03000001"),
        deneb_fork_version=bytes.fromhex("04000001"),
    )
    prev = interop_genesis_state(32, cfg)
    assert state_phase(prev, cfg) == Phase.PHASE0
    for slot in range(1, 25):
        atts = produce_attestations(prev, cfg, slot=slot - 1) if slot > 1 else []
        _, prev = produce_block(prev, slot, cfg, attestations=atts)
        expected_phase = cfg.phase_at_slot(slot)
        assert state_phase(prev, cfg) == expected_phase
    assert state_phase(prev, cfg) == Phase.CAPELLA
    # cross-fork participation accounting worked: epochs 1 and 2 (spanning
    # the altair/bellatrix/capella upgrades) are justified by slot 24
    # (finalization needs one more epoch than this chain runs)
    assert int(prev.current_justified_checkpoint.epoch) >= 2
