"""Blob-sidecar distribution plane tests: gossip topic round-trips,
BlobsByRange/Root + BlocksByRoot req/resp, received-sidecar dedup, and the
controller's delayed-until-blobs gate — reference p2p/src/network.rs
:15,104,221-222 and fork_choice_control/src/mutator.rs:84-104.

Blobs in these tests are all-zero, whose KZG commitment and proof are the
point at infinity — spec-valid and constant, so no multi-second host MSM
runs at test time.
"""

import time

import pytest

from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice import Tick, TickKind
from grandine_tpu.kzg.sidecar import make_blob_sidecars
from grandine_tpu.p2p.network import GossipTopics, InMemoryHub, Network
from grandine_tpu.runtime.controller import Controller
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.types.containers import spec_types
from grandine_tpu.validator.duties import produce_block

CFG = Config.minimal()
P = CFG.preset
NS = spec_types(P).deneb

ZERO_BLOB = b"\x00" * (P.FIELD_ELEMENTS_PER_BLOB * 32)
INF_G1 = b"\xc0" + b"\x00" * 47  # commitment AND proof of the zero blob


@pytest.fixture()
def genesis():
    return interop_genesis_state(16, CFG)


def blob_block(state, slot, n_blobs=1):
    """A signed deneb block committing to `n_blobs` zero blobs, plus its
    sidecars."""
    signed, post = produce_block(
        state, slot, CFG, full_sync_participation=False,
        blob_kzg_commitments=[INF_G1] * n_blobs,
    )
    sidecars = make_blob_sidecars(
        NS, P, signed, [ZERO_BLOB] * n_blobs, proofs=[INF_G1] * n_blobs
    )
    return signed, post, sidecars


def test_block_waits_for_sidecars_then_imports(genesis):
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        signed, _post, sidecars = blob_block(genesis, 1, n_blobs=2)
        root = signed.message.hash_tree_root()
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_gossip_block(signed)
        ctrl.wait()
        # delayed: not imported without its sidecars
        assert root not in ctrl.store.blocks
        assert root in ctrl._delayed_by_blobs

        ctrl.on_gossip_blob_sidecar(sidecars[0])
        ctrl.wait()
        assert root not in ctrl.store.blocks  # 1 of 2

        ctrl.on_gossip_blob_sidecar(sidecars[1])
        ctrl.wait()
        assert root in ctrl.store.blocks  # complete -> imported
        assert ctrl.snapshot().head_root == root
        assert ctrl.blob_sidecars_for(root)[0] is not None
    finally:
        ctrl.stop()


def test_sidecars_first_then_block_imports_immediately(genesis):
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        signed, _post, sidecars = blob_block(genesis, 1)
        for sc in sidecars:
            ctrl.on_gossip_blob_sidecar(sc)
        ctrl.wait()
        ctrl.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl.on_gossip_block(signed)
        ctrl.wait()
        assert signed.message.hash_tree_root() in ctrl.store.blocks
    finally:
        ctrl.stop()


def test_sidecar_dedup_and_invalid_rejection(genesis):
    ctrl = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        signed, _post, sidecars = blob_block(genesis, 1)
        root = signed.message.hash_tree_root()
        # duplicates collapse to one cache entry
        for _ in range(3):
            ctrl.on_gossip_blob_sidecar(sidecars[0])
        ctrl.wait()
        assert len(ctrl.blob_sidecars_for(root)) == 1

        # a sidecar with a broken inclusion proof never enters the cache
        bad = NS.BlobSidecar(
            index=1,
            blob=ZERO_BLOB,
            kzg_commitment=INF_G1,
            kzg_proof=INF_G1,
            signed_block_header=sidecars[0].signed_block_header,
            kzg_commitment_inclusion_proof=[b"\x11" * 32]
            * P.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH,
        )
        ctrl.on_gossip_blob_sidecar(bad)
        ctrl.wait()
        assert len(ctrl.blob_sidecars_for(root)) == 1
    finally:
        ctrl.stop()


def test_blob_gossip_topic_roundtrip_and_serving(genesis):
    """Hub-mesh: node A publishes sidecars then the block; node B imports
    only after its blob gate fills, and serves BlobsByRange/Root +
    BlocksByRoot back."""
    hub = InMemoryHub()
    ctrl_a = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_b = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        net_a = Network(hub.join("a"), ctrl_a, CFG)
        net_b = Network(hub.join("b"), ctrl_b, CFG)
        signed, _post, sidecars = blob_block(genesis, 1)
        root = signed.message.hash_tree_root()
        ctrl_a.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl_b.on_tick(Tick(1, TickKind.PROPOSE))
        for sc in sidecars:
            ctrl_a.on_gossip_blob_sidecar(sc)  # a's own cache (serving)
            net_a.publish_blob_sidecar(sc)
        net_a.publish_block(signed)
        ctrl_a.on_gossip_block(signed)
        ctrl_a.wait()
        ctrl_b.wait()
        assert root in ctrl_b.store.blocks
        assert net_b.stats["blob_sidecars_in"] == len(sidecars)

        # req/resp: B serves blobs and blocks by root/range
        raw = net_a.transport.request_blobs_by_range("b", 1, 1)
        assert len(raw) == len(sidecars)
        raw = net_a.transport.request_blobs_by_root("b", [(root, 0)])
        assert len(raw) == 1
        got = NS.BlobSidecar.deserialize(raw[0])
        assert bytes(got.kzg_commitment) == INF_G1
        raw = net_a.transport.request_blocks_by_root("b", [root])
        assert len(raw) == 1
    finally:
        ctrl_a.stop()
        ctrl_b.stop()


def test_unknown_parent_resolved_via_blocks_by_root(genesis):
    """A block whose parent never arrived by gossip is completed through
    BlocksByRoot instead of waiting for range sync."""
    from grandine_tpu.p2p.sync import BlockSyncService

    hub = InMemoryHub()
    ctrl_a = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_b = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        Network(hub.join("a"), ctrl_a, CFG)
        net_b = Network(hub.join("b"), ctrl_b, CFG)
        # A builds slots 1 and 2 (no blobs)
        b1, post1 = produce_block(genesis, 1, CFG,
                                  full_sync_participation=False)
        b2, _ = produce_block(post1, 2, CFG, full_sync_participation=False)
        ctrl_a.on_tick(Tick(2, TickKind.PROPOSE))
        ctrl_a.on_gossip_block(b1)
        ctrl_a.on_gossip_block(b2)
        ctrl_a.wait()

        sync_b = BlockSyncService(net_b.transport, ctrl_b, CFG)
        # B hears only block 2 -> unknown parent -> BlocksByRoot to A
        ctrl_b.on_tick(Tick(2, TickKind.PROPOSE))
        ctrl_b.on_gossip_block(b2)
        deadline = time.time() + 10
        while time.time() < deadline:
            ctrl_b.wait()
            if b2.message.hash_tree_root() in ctrl_b.store.blocks:
                break
            time.sleep(0.05)
        assert b1.message.hash_tree_root() in ctrl_b.store.blocks
        assert b2.message.hash_tree_root() in ctrl_b.store.blocks
        assert sync_b.stats["root_requests"] >= 1
    finally:
        ctrl_a.stop()
        ctrl_b.stop()


def test_breadth_topics_roundtrip(genesis):
    """Sync-committee message/contribution, slashing, and bls-change
    topics land in their pools on the receiving node — PROPERLY SIGNED;
    forged signatures are rejected at the gossip boundary."""
    from grandine_tpu.consensus import misc, signing
    from grandine_tpu.metrics import Metrics
    from grandine_tpu.pools.operation_pool import OperationPool
    from grandine_tpu.pools.sync_committee_pool import SyncCommitteeAggPool
    from grandine_tpu.validator.duties import _interop_keys

    hub = InMemoryHub()
    ctrl_a = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_b = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        net_a = Network(hub.join("a"), ctrl_a, CFG)
        sync_pool = SyncCommitteeAggPool(CFG)
        op_pool = OperationPool(CFG)
        net_b = Network(hub.join("b"), ctrl_b, CFG,
                        sync_pool=sync_pool, operation_pool=op_pool,
                        metrics=Metrics())

        # --- sync-committee message, signed by its validator ------------
        head_root = ctrl_a.snapshot().head_root
        vidx = 0
        key = _interop_keys(vidx)
        root = signing.sync_committee_message_signing_root(
            genesis, head_root, 0, CFG
        )
        msg = NS.SyncCommitteeMessage(
            slot=1, beacon_block_root=head_root, validator_index=vidx,
            signature=key.sign(root).to_bytes(),
        )
        net_a.publish_sync_committee_message(msg)
        assert net_b.stats["sync_messages_in"] == 1
        assert net_b.stats.get("sync_messages_rejected", 0) == 0
        assert sync_pool.best_aggregate(1, head_root, NS) is not None

        # forged signature: rejected, pool untouched
        forged = msg.replace(signature=b"\xc0" + b"\x00" * 95)
        net_a.publish_sync_committee_message(forged)
        assert net_b.stats["sync_messages_rejected"] == 1

        # --- contribution with a valid aggregate ------------------------
        sub_size = P.SYNC_COMMITTEE_SIZE // CFG.sync_committee_subnet_count
        # find a committee position in subcommittee 0 owned by vidx's key
        members = [bytes(pk) for pk in
                   genesis.current_sync_committee.pubkeys[:sub_size]]
        pos = members.index(key.public_key().to_bytes())
        bits = [False] * sub_size
        bits[pos] = True
        contribution = NS.SyncCommitteeContribution(
            slot=1, beacon_block_root=head_root, subcommittee_index=0,
            aggregation_bits=bits,
            signature=key.sign(root).to_bytes(),
        )
        # selection proof + outer signature are verified on gossip now
        proof_msg = NS.ContributionAndProof(
            aggregator_index=vidx, contribution=contribution,
            selection_proof=key.sign(
                signing.sync_selection_proof_signing_root(
                    genesis,
                    NS.SyncAggregatorSelectionData(
                        slot=1, subcommittee_index=0
                    ),
                    CFG,
                )
            ).to_bytes(),
        )
        signed_contrib = NS.SignedContributionAndProof(
            message=proof_msg,
            signature=key.sign(
                signing.contribution_and_proof_signing_root(
                    genesis, proof_msg, CFG
                )
            ).to_bytes(),
        )
        net_a.publish_sync_contribution(signed_contrib)
        assert net_b.stats["sync_contributions_in"] == 1
        assert net_b.stats.get("sync_contributions_rejected", 0) == 0

        # --- attester slashing: a REAL double vote ----------------------
        from grandine_tpu.consensus import accessors

        committee = accessors.get_beacon_committee(genesis, 0, 0, P)
        offenders = sorted(int(i) for i in committee)[:2]
        data1 = NS.AttestationData(
            slot=0, index=0, beacon_block_root=b"\x01" * 32,
            source=genesis.current_justified_checkpoint,
            target=NS.Checkpoint(epoch=0, root=b"\x01" * 32),
        )
        data2 = data1.replace(beacon_block_root=b"\x02" * 32,
                              target=NS.Checkpoint(epoch=0, root=b"\x02" * 32))

        def indexed(data):
            sroot = signing.attestation_signing_root(genesis, data, CFG)
            from grandine_tpu.crypto import bls as A

            sig = A.Signature.aggregate(
                [_interop_keys(i).sign(sroot) for i in offenders]
            )
            return NS.IndexedAttestation(
                attesting_indices=offenders, data=data,
                signature=sig.to_bytes(),
            )

        slashing = NS.AttesterSlashing(
            attestation_1=indexed(data1), attestation_2=indexed(data2)
        )
        net_a.publish_attester_slashing(slashing)
        ctrl_b.wait()
        assert net_b.stats.get("attester_slashings_rejected", 0) == 0
        assert op_pool.contents()["attester_slashings"]
        assert set(offenders) <= ctrl_b.store.equivocating

        # forged slashing (garbage signatures): rejected, no effect
        bad = NS.AttesterSlashing(
            attestation_1=slashing.attestation_1.replace(
                signature=b"\xc0" + b"\x00" * 95
            ),
            attestation_2=slashing.attestation_2,
        )
        before = len(ctrl_b.store.equivocating)
        net_a.publish_attester_slashing(bad)
        ctrl_b.wait()
        assert net_b.stats["attester_slashings_rejected"] == 1
        assert len(ctrl_b.store.equivocating) == before

        # --- proposer slashing: two conflicting headers, REALLY signed --
        pidx = 1
        pkey = _interop_keys(pidx)

        def signed_header(body_root):
            header = NS.BeaconBlockHeader(
                slot=0, proposer_index=pidx, parent_root=b"\x00" * 32,
                state_root=b"\x00" * 32, body_root=body_root,
            )
            sroot = signing.header_signing_root(genesis, header, CFG)
            return NS.SignedBeaconBlockHeader(
                message=header, signature=pkey.sign(sroot).to_bytes()
            )

        pslashing = NS.ProposerSlashing(
            signed_header_1=signed_header(b"\x01" * 32),
            signed_header_2=signed_header(b"\x02" * 32),
        )
        net_a.publish_proposer_slashing(pslashing)
        assert net_b.stats["proposer_slashings_in"] == 1
        assert net_b.stats.get("proposer_slashings_rejected", 0) == 0
        assert op_pool.contents()["proposer_slashings"]

        # forged header signature: rejected, pool unchanged
        bad_ps = pslashing.replace(
            signed_header_2=pslashing.signed_header_2.replace(
                signature=b"\xc0" + b"\x00" * 95
            )
        )
        net_a.publish_proposer_slashing(bad_ps)
        assert net_b.stats["proposer_slashings_rejected"] == 1
        assert len(op_pool.contents()["proposer_slashings"]) == 1

        # --- bls-to-execution-change, signed by the claimed BLS key -----
        ckey = _interop_keys(3)
        change_msg = NS.BLSToExecutionChange(
            validator_index=3,
            from_bls_pubkey=ckey.public_key().to_bytes(),
            to_execution_address=b"\x02" * 20,
        )
        croot = signing.bls_to_execution_change_signing_root(
            genesis, change_msg, CFG
        )
        change = NS.SignedBLSToExecutionChange(
            message=change_msg, signature=ckey.sign(croot).to_bytes(),
        )
        net_a.publish_bls_change(change)
        assert net_b.stats["bls_changes_in"] == 1
        assert net_b.stats.get("bls_changes_rejected", 0) == 0
        assert op_pool.contents()["bls_to_execution_changes"]

        # forged change signature: rejected at the gossip boundary
        forged_change = change.replace(signature=b"\xc0" + b"\x00" * 95)
        net_a.publish_bls_change(forged_change)
        assert net_b.stats["bls_changes_rejected"] == 1
        assert len(op_pool.contents()["bls_to_execution_changes"]) == 1

        # labeled gossip counters on node B saw accepts and rejects
        fam = net_b.metrics.gossip_messages
        assert fam.value("proposer_slashing", "accept") == 1
        assert fam.value("proposer_slashing", "reject") == 1
        assert fam.value("bls_to_execution_change", "accept") == 1
        assert fam.value("bls_to_execution_change", "reject") == 1
        assert fam.value("sync_committee", "reject") == 1
    finally:
        ctrl_a.stop()
        ctrl_b.stop()

def test_blob_distribution_over_tcp(genesis):
    """Wire-level (real sockets): node A publishes the sidecars and the
    block over TcpTransport gossip; node B's blob gate holds the deneb
    block until the sidecars land, then imports; BlobsByRange serves the
    cached sidecars back over the same connection."""
    from grandine_tpu.p2p.tcp import TcpTransport

    digest = GossipTopics.fork_digest(CFG, genesis)
    ta = TcpTransport("blob-a", digest)
    tb = TcpTransport("blob-b", digest)
    ctrl_a = Controller(genesis, CFG, verifier_factory=NullVerifier)
    ctrl_b = Controller(genesis, CFG, verifier_factory=NullVerifier)
    try:
        net_a = Network(ta, ctrl_a, CFG)
        net_b = Network(tb, ctrl_b, CFG)
        tb.connect("127.0.0.1", ta.port)
        signed, _post, sidecars = blob_block(genesis, 1)
        root = signed.message.hash_tree_root()
        ctrl_a.on_tick(Tick(1, TickKind.PROPOSE))
        ctrl_b.on_tick(Tick(1, TickKind.PROPOSE))

        # block first: B must delay it on missing blobs
        net_a.publish_block(signed)
        deadline = time.time() + 5
        while root not in ctrl_b._delayed_by_blobs and time.time() < deadline:
            time.sleep(0.02)
        ctrl_b.wait()
        assert root not in ctrl_b.store.blocks

        for sc in sidecars:
            ctrl_a.on_gossip_blob_sidecar(sc)
            net_a.publish_blob_sidecar(sc)
        ctrl_a.on_gossip_block(signed)  # A imports its own block (serving)
        deadline = time.time() + 15
        while root not in ctrl_b.store.blocks and time.time() < deadline:
            ctrl_b.wait()
            time.sleep(0.05)
        assert root in ctrl_b.store.blocks

        ctrl_a.wait()
        raw = tb.request_blobs_by_range("blob-a", 1, 1)
        assert len(raw) == len(sidecars)
    finally:
        ta.close()
        tb.close()
        ctrl_a.stop()
        ctrl_b.stop()
