"""Adversarial isolation plane tests (runtime/isolation.py).

Three surfaces, each against fast fakes so nothing here compiles:

- FaultLocalizer: differential verdict equivalence against the eager
  host path for forged-position patterns, the O(log n) device-pass
  bound, host work bounded by named-bad leaves, and every degradation
  edge (undecodable signature, subgroup-named-bad, device fault,
  breaker open, budget exhausted).
- ReputationTable: quarantine entry / consecutive-clean exit / time
  decay / bounded capacity, on a fake clock.
- AdmissionController: fair-share starvation resistance — a hostile
  origin at 10x the honest rate is clamped to its share while honest
  origins keep >=80% (in fact all) of theirs.

Scheduler integration (quarantine reroute, localizer delegation, the
quarantined flight flag) runs over the same truth-table stub the chaos
suite uses, with the host path monkeypatched onto the truth table —
fault-free expectations are exact.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from grandine_tpu.crypto import bls as A
from grandine_tpu.metrics import Metrics
from grandine_tpu.runtime import health as _health
from grandine_tpu.runtime import isolation as iso
from grandine_tpu.runtime import verify_scheduler as vs
from grandine_tpu.runtime.flight import BATCH, FlightRecorder
from grandine_tpu.testing.chaos import KnownAnswerBackend
from grandine_tpu.transition.genesis import interop_secret_key

# one REAL signature reused everywhere: the localizer's host pre-pass
# decompresses each item's signature bytes (and rejects infinity);
# verdicts come from truth tables, not the crypto
_SK = interop_secret_key(0)
_SIG_BYTES = _SK.sign(b"isolation-test").to_bytes()
_PK = _SK.public_key()


def _item(message: bytes) -> vs.VerifyItem:
    return vs.VerifyItem(message, _SIG_BYTES, public_keys=(_PK,))


def _truth_and_items(n: int, forged: "set[int]"):
    messages = [b"iso-%04d" % i + b"\x00" * 23 for i in range(n)]
    truth = {m: i not in forged for i, m in enumerate(messages)}
    return truth, [_item(m) for m in messages]


def _localizer_for(truth, counter: "list[int]" = None, **kw):
    def host_check(item):
        if counter is not None:
            counter[0] += 1
        return truth.get(bytes(item.message), False)

    return iso.FaultLocalizer(host_check=host_check, **kw)


# ---------------------------------------------------------- ladder math


def test_ladder_ends_per_item_and_is_monotone():
    for bucket in (4, 8, 16, 32, 64, 128, 1024):
        rungs = iso.ladder(bucket)
        assert rungs[-1] == bucket  # final rung is per-item
        assert rungs == sorted(set(rungs))
        assert all(bucket % g == 0 for g in rungs)  # groups divide bucket


@pytest.mark.parametrize("n", [1, 2, 4, 5, 8, 16, 100, 128, 1000, 16384])
def test_max_device_passes_within_log2_bound(n):
    bucket = iso._bucket(n)
    assert iso.max_device_passes(n) <= math.ceil(math.log2(bucket)) + 1


# ------------------------------------------- differential localization

#: forged-position patterns the acceptance gate names: first, last,
#: adjacent pairs, all-bad — plus a scattered set
_PATTERNS = [
    (5, {0}),
    (5, {4}),
    (8, {0}),
    (8, {7}),
    (8, {3, 4}),          # adjacent pair straddling a group boundary
    (13, {0, 1}),         # adjacent pair at the front
    (13, set(range(13))),  # all bad
    (16, {0, 15}),
    (32, {5}),
    (32, {7, 8, 30, 31}),
    (32, set(range(32))),
]


@pytest.mark.parametrize("n,forged", _PATTERNS)
def test_localize_matches_eager_host_path(n, forged):
    """Verdicts are byte-identical to what the eager host path would
    say for every item, for every forged-position pattern."""
    truth, items = _truth_and_items(n, forged)
    kab = KnownAnswerBackend(truth)
    loc = _localizer_for(truth)
    verdicts = loc.localize(kab, items)
    expected = [truth[bytes(it.message)] for it in items]
    assert verdicts == expected


@pytest.mark.parametrize("n,forged", _PATTERNS)
def test_localize_device_pass_bound_and_leaf_economy(n, forged):
    """No batch takes more device passes than the ceil(log2)+1 bound,
    and the host verifies EXACTLY the named-bad leaves — never a clean
    item."""
    truth, items = _truth_and_items(n, forged)
    kab = KnownAnswerBackend(truth)
    host_calls = [0]
    loc = _localizer_for(truth, counter=host_calls)
    loc.localize(kab, items)
    # 1 subgroup pass + the partition rungs actually dispatched
    device_passes = 1 + len(kab.partitions)
    assert device_passes <= iso.max_device_passes(n)
    # the fake backend's subgroup check passes everything, so host
    # leaves are exactly the per-item-rung named-bad set == the forgeries
    assert host_calls[0] == len(forged)
    # the descent never dispatches a wider group count than the bucket
    assert all(g <= iso._bucket(n) for _, g in kab.partitions)


def test_localize_clean_batch_single_partition_pass():
    """A batch the device wrongly called invalid (verdict fault) clears
    on the FIRST partition rung: one subgroup + one partition pass, no
    host work at all."""
    truth, items = _truth_and_items(16, set())
    kab = KnownAnswerBackend(truth)
    host_calls = [0]
    loc = _localizer_for(truth, counter=host_calls)
    assert loc.localize(kab, items) == [True] * 16
    assert len(kab.partitions) == 1  # first rung cleared every group
    assert host_calls[0] == 0


def test_localize_counts_passes_in_metrics():
    m = Metrics()
    truth, items = _truth_and_items(16, {3})
    kab = KnownAnswerBackend(truth)
    loc = iso.FaultLocalizer(
        metrics=m,
        host_check=lambda it: truth.get(bytes(it.message), False),
    )
    loc.localize(kab, items)
    assert m.verify_isolation_passes.value("g2_subgroup") == 1
    assert m.verify_isolation_passes.value("rlc_partition") == len(
        kab.partitions
    )
    assert m.verify_isolation_passes.value("host") == 0


# ----------------------------------------------------- degradation edges


def test_localize_undecodable_signature_is_a_host_leaf():
    """An item whose signature bytes cannot decompress never reaches
    the device — the eager host check is its verdict of record."""
    truth, items = _truth_and_items(6, {2})
    items[4] = vs.VerifyItem(
        items[4].message, b"\xff" * 96, public_keys=(_PK,)
    )
    truth[bytes(items[4].message)] = False  # host says no
    kab = KnownAnswerBackend(truth)
    loc = _localizer_for(truth)
    verdicts = loc.localize(kab, items)
    assert verdicts == [True, True, False, True, False, True]
    # the garbage item was excluded from every device dispatch
    assert all(n_items <= 5 for n_items, _ in kab.partitions)


def test_localize_subgroup_named_bad_is_a_host_leaf():
    """A per-item subgroup False becomes a host leaf (host verdict
    wins), and the partition descent runs over the remaining items."""
    truth, items = _truth_and_items(8, set())

    class SubgroupFlagged(KnownAnswerBackend):
        def g2_subgroup_check_batch_async(self, points):
            flags = np.ones((len(points),), dtype=bool)
            flags[1] = False
            return lambda: flags

    kab = SubgroupFlagged(truth)
    host_calls = [0]
    loc = _localizer_for(truth, counter=host_calls)
    verdicts = loc.localize(kab, items)
    assert verdicts == [True] * 8  # host overruled the device naming
    assert host_calls[0] == 1
    assert all(n_items == 7 for n_items, _ in kab.partitions)


def test_localize_device_fault_mid_descent_sweeps_on_host():
    """A partition dispatch that raises degrades to a host sweep of the
    still-suspect items — verdicts stay correct and the sweep is
    counted as a `host` pass."""
    m = Metrics()
    truth, items = _truth_and_items(12, {9})

    class Faulting(KnownAnswerBackend):
        def rlc_partition_verify_async(self, *a, **kw):
            raise RuntimeError("injected partition fault")

    loc = iso.FaultLocalizer(
        metrics=m,
        host_check=lambda it: truth.get(bytes(it.message), False),
    )
    verdicts = loc.localize(Faulting(truth), items)
    assert verdicts == [truth[bytes(it.message)] for it in items]
    assert m.verify_isolation_passes.value("host") == 1


class _Breaker:
    """allow_device stub with the supervisor surface localize touches."""

    settle_timeout_s = 0.2

    def __init__(self, allow: bool) -> None:
        self._allow = allow
        self.faults: "list[str]" = []

    def allow_device(self) -> bool:
        return self._allow

    def record_fault(self, kind: str) -> None:
        self.faults.append(kind)

    def record_success(self) -> None:
        pass

    def guard_settle(self, settle, timeout_s=None):
        try:
            return _health.SettleOutcome(_health.OK, value=settle())
        except Exception as e:
            return _health.SettleOutcome(_health.FAULT, error=e)


def test_localize_breaker_open_never_touches_device():
    truth, items = _truth_and_items(8, {1})
    kab = KnownAnswerBackend(truth)
    loc = _localizer_for(truth, health=_Breaker(allow=False))
    verdicts = loc.localize(kab, items)
    assert verdicts == [truth[bytes(it.message)] for it in items]
    assert kab.partitions == []  # zero device dispatches


def test_localize_expired_deadline_sweeps_on_host():
    truth, items = _truth_and_items(8, {6})
    kab = KnownAnswerBackend(truth)
    loc = _localizer_for(truth)
    import time as _time

    verdicts = loc.localize(kab, items, deadline=_time.monotonic() - 1.0)
    assert verdicts == [truth[bytes(it.message)] for it in items]
    assert kab.partitions == []


# --------------------------------------------------------- reputation


def _fake_clock(start: float = 0.0):
    t = [start]
    return t, (lambda: t[0])


def test_reputation_entry_consecutive_clean_exit():
    t, clock = _fake_clock()
    rep = iso.ReputationTable(exit_clean=3, decay_s=60.0, clock=clock)
    assert not rep.is_quarantined("peer:a")
    rep.note_failure("peer:a")
    assert rep.is_quarantined("peer:a")
    rep.note_clean_batch("peer:a")
    rep.note_clean_batch("peer:a")
    assert rep.is_quarantined("peer:a")  # 2 clean < exit_clean
    rep.note_clean_batch("peer:a")
    assert not rep.is_quarantined("peer:a")  # 3rd consecutive: out
    assert len(rep) == 0


def test_reputation_failure_resets_clean_streak():
    t, clock = _fake_clock()
    rep = iso.ReputationTable(exit_clean=2, clock=clock)
    rep.note_failure("peer:b")
    rep.note_clean_batch("peer:b")
    rep.note_failure("peer:b")  # streak back to zero
    rep.note_clean_batch("peer:b")
    assert rep.is_quarantined("peer:b")
    rep.note_clean_batch("peer:b")
    assert not rep.is_quarantined("peer:b")


def test_reputation_time_decay():
    t, clock = _fake_clock()
    rep = iso.ReputationTable(decay_s=60.0, clock=clock)
    rep.note_failure("peer:c")
    t[0] = 59.0
    assert rep.is_quarantined("peer:c")
    t[0] = 61.0
    assert not rep.is_quarantined("peer:c")
    assert len(rep) == 0  # decayed entries are dropped, not kept


def test_reputation_capacity_evicts_stalest():
    t, clock = _fake_clock()
    rep = iso.ReputationTable(capacity=2, clock=clock)
    rep.note_failure("peer:old")
    t[0] = 1.0
    rep.note_failure("peer:new")
    t[0] = 2.0
    rep.note_failure("peer:newest")  # at capacity: evicts peer:old
    assert len(rep) == 2
    assert not rep.is_quarantined("peer:old")
    assert rep.is_quarantined("peer:new")
    assert rep.is_quarantined("peer:newest")


def test_reputation_none_origin_is_noop():
    rep = iso.ReputationTable()
    rep.note_failure(None)
    rep.note_failure("")
    assert len(rep) == 0 and not rep.is_quarantined(None)


# ----------------------------------------------------------- admission


def test_admission_lone_origin_never_throttled():
    t, clock = _fake_clock()
    adm = iso.AdmissionController(min_quota=256, clock=clock)
    assert all(adm.admit("peer:solo", 8) for _ in range(32))  # == floor


def test_admission_hostile_origin_cannot_starve_honest():
    """Hostile origin at 10x the honest per-origin rate: honest origins
    keep >=80% of their submissions (here: all of them) while the
    hostile origin is clamped to roughly its fair share."""
    m = Metrics()
    t, clock = _fake_clock()
    adm = iso.AdmissionController(
        window_s=1.0, max_share=0.5, min_quota=8, metrics=m, clock=clock
    )
    honest = [f"peer:honest-{i}" for i in range(5)]
    admitted = {o: 0 for o in honest + ["peer:hostile"]}
    attempted = {o: 0 for o in admitted}
    for tick in range(40):  # 2s of 50ms ticks — one full window warmup
        t[0] = tick * 0.05
        for _ in range(10):  # 10x the honest rate
            attempted["peer:hostile"] += 1
            if adm.admit("peer:hostile", 1, lane="sync_message"):
                admitted["peer:hostile"] += 1
        for o in honest:
            attempted[o] += 1
            if adm.admit(o, 1, lane="sync_message"):
                admitted[o] += 1
    for o in honest:
        assert admitted[o] / attempted[o] >= 0.8, (o, admitted[o])
    # the hostile origin was actually clamped…
    assert admitted["peer:hostile"] < attempted["peer:hostile"] * 0.75
    # …to at most its fair share of the window (plus the floor's slack)
    assert adm.window_share("peer:hostile") <= 0.6
    rejected = m.verify_admission_rejected.value("sync_message")
    assert rejected == sum(attempted.values()) - sum(admitted.values())
    assert rejected > 0


def test_admission_unattributed_always_admitted():
    adm = iso.AdmissionController(min_quota=1)
    assert all(adm.admit(None, 10_000) for _ in range(10))


def test_admission_window_slides():
    t, clock = _fake_clock()
    adm = iso.AdmissionController(
        window_s=1.0, max_share=0.5, min_quota=4, clock=clock
    )
    assert adm.admit("peer:x", 4)
    assert not adm.admit("peer:x", 1)  # floor exhausted this window
    t[0] = 1.5  # window slid past the old entries
    assert adm.admit("peer:x", 4)


def test_admission_capacity_churn_cannot_evict_heavy_hitters():
    t, clock = _fake_clock()
    adm = iso.AdmissionController(
        window_s=10.0, max_share=0.5, min_quota=4, capacity=2, clock=clock
    )
    assert adm.admit("peer:tracked", 4)
    assert adm.admit("peer:other", 4)
    # sybil churn past capacity: admitted (under the floor) but the
    # tracked heavy hitter's clamp survives
    assert adm.admit("peer:sybil-1", 1)
    assert adm.admit("peer:sybil-2", 1)
    # global window = 10, quota = max(4, 5) = 5; tracked holds 4
    assert not adm.admit("peer:tracked", 2)


# ---------------------------------- reputation-fed admission quotas


def test_reputation_failure_rate_needs_observations():
    """Below TRUST_MIN_OBSERVED submitted jobs the rate is None — an
    origin must EARN trust (and distrust) with volume, so a burst of
    two clean jobs cannot unlock an unclamped firehose."""
    rep = iso.ReputationTable()
    rep.note_submitted("peer:new", jobs=iso.TRUST_MIN_OBSERVED - 1)
    assert rep.failure_rate("peer:new") is None
    rep.note_submitted("peer:new")
    assert rep.failure_rate("peer:new") == 0.0
    assert rep.failure_rate(None) is None
    assert rep.failure_rate("peer:never-seen") is None


def test_reputation_failure_rate_tracking():
    rep = iso.ReputationTable()
    rep.note_submitted("peer:mixed", jobs=90)
    for _ in range(10):
        rep.note_failure("peer:mixed")
    # note_failure counts toward failures only; denominator is submitted
    assert abs(rep.failure_rate("peer:mixed") - 10 / 90) < 1e-9


def test_admission_honest_high_rate_aggregator_not_clamped():
    """The ISSUE's scenario: a high-rate HONEST aggregator (big share of
    traffic, near-zero failures) must never be clamped by raw share —
    with reputation wired its clean record bypasses the share quota,
    while the same traffic without reputation is rejected."""
    rep = iso.ReputationTable()
    rep.note_submitted("peer:agg", jobs=1000)  # long clean history
    t, clock = _fake_clock()
    adm = iso.AdmissionController(
        window_s=10.0, max_share=0.25, min_quota=4, clock=clock,
        reputation=rep,
    )
    t2, clock2 = _fake_clock()
    plain = iso.AdmissionController(
        window_s=10.0, max_share=0.25, min_quota=4, clock=clock2,
    )
    # grow the global window so share quotas bind
    for i in range(8):
        adm.admit(f"peer:bg-{i}", 4)
        plain.admit(f"peer:bg-{i}", 4)
    for _ in range(6):  # way past 25% share
        assert adm.admit("peer:agg", 4)
        rep.note_submitted("peer:agg", jobs=4)
    assert not all(plain.admit("peer:agg", 4) for _ in range(6))


def test_admission_high_failure_origin_clamped_toward_floor():
    """A high-failure origin's quota scales DOWN by its failure rate:
    distrust earns a tighter clamp than raw share alone."""
    rep = iso.ReputationTable()
    rep.note_submitted("peer:bad", jobs=100)
    for _ in range(80):
        rep.note_failure("peer:bad")
    assert rep.failure_rate("peer:bad") == 0.8
    t, clock = _fake_clock()
    adm = iso.AdmissionController(
        window_s=10.0, max_share=0.5, min_quota=8, clock=clock,
        reputation=rep,
    )
    for i in range(10):
        assert adm.admit(f"peer:bg-{i}", 8)  # global window = 80
    # plain share quota would be ~40; 80% failures scale it to the floor
    got = 0
    for _ in range(40):
        if adm.admit("peer:bad", 1):
            got += 1
    assert got <= 8
    # an untracked origin at the same rate keeps the plain share quota
    got_plain = sum(1 for _ in range(40) if adm.admit("peer:plain", 1))
    assert got_plain > got


def test_reputation_traffic_counters_halve():
    """Rolling halving keeps the rate an EWMA-ish recent-window figure:
    an origin that stops failing recovers, instead of dragging a
    lifetime tally forever."""
    rep = iso.ReputationTable()
    rep.note_submitted("peer:x", jobs=iso._TRAFFIC_HALF_AT - 1)
    for _ in range(100):
        rep.note_failure("peer:x")
    before = rep.failure_rate("peer:x")
    rep.note_submitted("peer:x")  # crosses the halving threshold
    after = rep.failure_rate("peer:x")
    assert after is not None and abs(after - before) < 0.01  # rate kept
    # clean traffic now decays the rate twice as fast as pre-halving
    rep.note_submitted("peer:x", jobs=iso._TRAFFIC_HALF_AT // 2)
    assert rep.failure_rate("peer:x") < after / 1.5


# ------------------------------------------- scheduler integration


def _scheduler(truth, monkeypatch, metrics=None, flight=None,
               exit_clean=2):
    kab = KnownAnswerBackend(truth)
    sched = vs.VerifyScheduler(
        backend=kab, use_device=True, metrics=metrics, flight=flight,
        reputation=iso.ReputationTable(exit_clean=exit_clean),
    )
    monkeypatch.setattr(
        vs, "host_check_item",
        lambda item: truth.get(bytes(item.message), False),
    )
    return kab, sched


def test_scheduler_quarantine_roundtrip(monkeypatch):
    """A forged batch quarantines its origin; later sheddable traffic
    reroutes to the quarantine lane (HIGH lanes never reroute); clean
    quarantine batches step the origin back out."""
    m = Metrics()
    fl = FlightRecorder()
    good = b"good-msg" + b"\x00" * 24
    bad = b"bad-msg!" + b"\x00" * 24
    truth = {good: True, bad: False}
    kab, sched = _scheduler(truth, monkeypatch, metrics=m, flight=fl)
    try:
        t1 = sched.submit("sync_message", [_item(bad)], origin="peer:evil")
        sched.flush(30.0)
        assert t1.done() and t1.ok is False
        assert sched.reputation.is_quarantined("peer:evil")

        # sheddable traffic from the quarantined origin: rerouted
        t2 = sched.submit("sync_message", [_item(good)], origin="peer:evil")
        assert t2.lane == "quarantine"
        # HIGH lane from the same origin: never rerouted
        t3 = sched.submit("block", [_item(good)], origin="peer:evil")
        assert t3.lane == "block"
        sched.flush(30.0)
        assert t2.ok is True and t3.ok is True

        # second clean quarantine batch reaches exit_clean=2
        t4 = sched.submit("sync_message", [_item(good)], origin="peer:evil")
        assert t4.lane == "quarantine"
        sched.flush(30.0)
        assert t4.ok is True
        assert not sched.reputation.is_quarantined("peer:evil")
        t5 = sched.submit("sync_message", [_item(good)], origin="peer:evil")
        assert t5.lane == "sync_message"
        sched.flush(30.0)
    finally:
        sched.stop()

    assert m.verify_quarantine_batches.value == 2
    quarantined_recs = [
        r for r in fl.snapshot(kind=BATCH) if r.quarantined
    ]
    assert len(quarantined_recs) == 2
    assert all(r.lane == "quarantine" for r in quarantined_recs)


def test_scheduler_isolate_uses_localizer(monkeypatch):
    """A poisoned batch settles through the on-device localizer (the
    partition seam is dispatched, passes are counted) and every ticket
    gets the eager-host verdict for its own items."""
    m = Metrics()
    n = 12
    truth, items = _truth_and_items(n, {5})
    kab, sched = _scheduler(truth, monkeypatch, metrics=m)
    try:
        tickets = [
            sched.submit("sync_message", [it], origin=f"peer:{i}")
            for i, it in enumerate(items)
        ]
        sched.flush(30.0)
    finally:
        sched.stop()
    for i, tk in enumerate(tickets):
        assert tk.done() and tk.ok is (i != 5)
    assert kab.partitions, "localizer never dispatched the partition seam"
    assert m.verify_isolation_passes.value("g2_subgroup") >= 1
    assert m.verify_isolation_passes.value("rlc_partition") >= 1
    # only the forged item's origin was quarantined
    assert sched.reputation.is_quarantined("peer:5")
    assert not sched.reputation.is_quarantined("peer:4")


def test_scheduler_no_isolation_falls_back_to_bisection(monkeypatch):
    """--no-isolation: the legacy host bisection still settles poisoned
    batches correctly and never touches the partition seam."""
    good = b"fb-good!" + b"\x00" * 24
    bad = b"fb-bad!!" + b"\x00" * 24
    truth = {good: True, bad: False}
    kab = KnownAnswerBackend(truth)
    sched = vs.VerifyScheduler(
        backend=kab, use_device=True, use_isolation=False,
    )
    monkeypatch.setattr(
        vs, "host_check_item",
        lambda item: truth.get(bytes(item.message), False),
    )
    try:
        t_good = sched.submit("sync_message", [_item(good)])
        t_bad = sched.submit("sync_message", [_item(bad)])
        sched.flush(30.0)
        assert t_good.ok is True and t_bad.ok is False
    finally:
        sched.stop()
    assert kab.partitions == []
