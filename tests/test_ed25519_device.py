"""Ed25519 device plane: RFC 8032 differential tests for the batched
cofactored verify kernel (tpu/ed25519.py) against the host scalar twin
(crypto/ed25519.py), plus the scheduler's `ed25519` lane round-trip.

The host twin is COFACTORED ([8](SB - R - kA) == identity) to match the
device batch equation, so the two paths are byte-identical on every
input — including the small-torsion specimens where cofactored and
cofactorless verifiers legitimately disagree. Malleable encodings
(S >= L) are rejected in `prepare` before either equation runs.

Kernel-compiling cells are marked slow+kernel and keep every batch at
n <= 3 items (ladder rows m = 1 + 2n <= 7 -> one bucket-8 compile for
the whole module); the fast unmarked cells exercise the host twin, the
prepare statuses, and the scheduler lane's host degradation path.
"""

from __future__ import annotations

import numpy as np
import pytest

from grandine_tpu.crypto import ed25519 as HE

# RFC 8032 test-vector secret keys (TEST 1 / TEST 3)
SK1 = bytes.fromhex(
    "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
)
SK3 = bytes.fromhex(
    "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"
)
MSG3 = bytes.fromhex("af82")


class Item:
    """Scheduler-geometry item: ed25519 rides the (message, signature,
    public_keys) slots exactly like a BLS VerifyItem."""

    def __init__(self, pk: bytes, msg: bytes, sig: bytes) -> None:
        self.public_keys = (pk,)
        self.message = msg
        self.signature = sig


class FixedRng:
    """Deterministic stand-in for the backend's RLC-coefficient rng."""

    def __init__(self, seed: int = 7) -> None:
        self._r = np.random.default_rng(seed)

    def randbits(self, n: int) -> int:
        return int.from_bytes(self._r.bytes(n // 8), "little")


def _backend():
    from grandine_tpu.tpu.ed25519 import Ed25519Backend

    return Ed25519Backend(rng=FixedRng())


def _run_batch(items) -> bool:
    be = _backend()
    status, prep = be.prepare(items)
    assert status == "ok", status
    return be.verify_batch_async(prep)()


def _torsion_signature(sk: bytes, msg: bytes) -> "tuple[bytes, bytes]":
    """A signature whose R carries a 2-torsion component: accepted by
    cofactored verification, rejected cofactorless."""
    a, prefix = HE.secret_expand(sk)
    pk = HE.secret_to_public(sk)
    r = int.from_bytes(HE.sha512(prefix + msg), "little") % HE.L
    r_tor = HE.point_add(HE.point_mul(r, HE.BASE), HE.ORDER2)
    r_enc = HE.point_compress(r_tor)
    k = int.from_bytes(HE.sha512(r_enc + pk + msg), "little") % HE.L
    s = (r + k * a) % HE.L
    return pk, r_enc + s.to_bytes(32, "little")


# ------------------------------------------------- host twin (fast)


def test_host_twin_rfc8032_vectors():
    pk1 = HE.secret_to_public(SK1)
    assert pk1 == bytes.fromhex(
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )
    sig1 = HE.sign(SK1, b"")
    assert sig1 == bytes.fromhex(
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b"
    )
    assert HE.verify(pk1, b"", sig1) is True
    pk3 = HE.secret_to_public(SK3)
    sig3 = HE.sign(SK3, MSG3)
    assert HE.verify(pk3, MSG3, sig3) is True
    assert HE.verify(pk3, MSG3 + b"\x00", sig3) is False
    assert HE.verify(pk1, b"", sig3) is False


def test_host_twin_is_cofactored():
    pk, sig = _torsion_signature(SK1, b"torsion")
    assert HE.verify(pk, b"torsion", sig) is True


def test_host_twin_rejects_malleable_s():
    sig1 = HE.sign(SK1, b"")
    s_mall = int.from_bytes(sig1[32:], "little") + HE.L
    assert HE.verify(
        HE.secret_to_public(SK1), b"", sig1[:32] + s_mall.to_bytes(32, "little")
    ) is False


# --------------------------------------------- prepare statuses (fast)


def test_prepare_rejects_malleable_and_malformed():
    be = _backend()
    pk1 = HE.secret_to_public(SK1)
    sig1 = HE.sign(SK1, b"")
    s_mall = int.from_bytes(sig1[32:], "little") + HE.L
    mall = sig1[:32] + s_mall.to_bytes(32, "little")
    assert be.prepare([Item(pk1, b"", mall)])[0] == "invalid"
    assert be.prepare([Item(b"\xff" * 32, b"", sig1)])[0] == "invalid"
    assert be.prepare([Item(pk1, b"", sig1[:-1])])[0] == "invalid"


def test_prepare_oversize_and_empty():
    be = _backend()
    pk1 = HE.secret_to_public(SK1)
    sig1 = HE.sign(SK1, b"")
    assert be.prepare([Item(pk1, b"", sig1)] * 64)[0] == "oversize"
    status, prep = be.prepare([])
    assert status == "ok"
    # empty batch settles True without any kernel dispatch
    assert be.verify_batch_async(prep)() is True


# -------------------------------------------- field/point plane (fast)


def test_field_montmul_matches_host_ints():
    import jax.numpy as jnp

    from grandine_tpu.tpu import ed25519 as DE

    rng = np.random.default_rng(0)
    for _ in range(6):
        a = int(rng.integers(0, 2**63)) * int(rng.integers(0, 2**63)) % HE.P
        b = int(rng.integers(0, 2**63)) ** 3 % HE.P
        prod = DE.montmul(
            jnp.asarray(DE.to_mont(a)), jnp.asarray(DE.to_mont(b))
        )
        assert DE.from_mont(np.asarray(prod)) == a * b % HE.P
    z = DE.montmul(jnp.asarray(DE.to_mont(0)), jnp.asarray(DE.ONE_MONT))
    assert bool(DE.is_zero_val(z))
    nz = DE.montmul(jnp.asarray(DE.to_mont(5)), jnp.asarray(DE.ONE_MONT))
    assert not bool(DE.is_zero_val(nz))


def test_unified_add_matches_host_double():
    import jax.numpy as jnp

    from grandine_tpu.tpu import ed25519 as DE

    def to_dev(p):
        x, y, z, _t = p
        zinv = pow(z, HE.P - 2, HE.P)
        xa, ya = x * zinv % HE.P, y * zinv % HE.P
        return (
            jnp.asarray(DE.to_mont(xa)),
            jnp.asarray(DE.to_mont(ya)),
            jnp.asarray(DE.ONE_MONT),
            jnp.asarray(DE.to_mont(xa * ya % HE.P)),
        )

    got = DE.ed_add(to_dev(HE.BASE), to_dev(HE.BASE))
    x, y, z, _t = (DE.from_mont(np.asarray(c)) for c in got)
    zinv = pow(z, HE.P - 2, HE.P)
    b2 = HE.point_add(HE.BASE, HE.BASE)
    b2zinv = pow(b2[2], HE.P - 2, HE.P)
    assert (x * zinv % HE.P, y * zinv % HE.P) == (
        b2[0] * b2zinv % HE.P,
        b2[1] * b2zinv % HE.P,
    )


# -------------------------------------- device kernel (slow+kernel)


@pytest.mark.kernel
@pytest.mark.slow
def test_device_batch_differential():
    """Every verdict class through ONE bucket-8 kernel compile: valid
    RFC 8032 batch, forged message, forged S, the torsion specimen
    (cofactored twin and device must both accept), and a seeded random
    sweep where the batch verdict equals the AND of host verdicts."""
    pk1 = HE.secret_to_public(SK1)
    sig1 = HE.sign(SK1, b"")
    pk3 = HE.secret_to_public(SK3)
    sig3 = HE.sign(SK3, MSG3)

    assert _run_batch([Item(pk1, b"", sig1), Item(pk3, MSG3, sig3)]) is True
    assert _run_batch(
        [Item(pk1, b"", sig1), Item(pk3, b"\x00" + MSG3, sig3)]
    ) is False
    s_bad = (int.from_bytes(sig1[32:], "little") + 1) % HE.L
    assert _run_batch(
        [Item(pk1, b"", sig1[:32] + s_bad.to_bytes(32, "little"))]
    ) is False

    pk_t, sig_t = _torsion_signature(SK1, b"torsion")
    assert HE.verify(pk_t, b"torsion", sig_t) is True
    assert _run_batch([Item(pk_t, b"torsion", sig_t)]) is True

    rng = np.random.default_rng(42)
    for trial in range(4):
        items, expect = [], True
        for _ in range(int(rng.integers(1, 4))):  # n <= 3: same bucket
            sk = rng.bytes(32)
            pk = HE.secret_to_public(sk)
            msg = rng.bytes(int(rng.integers(0, 40)))
            sig = HE.sign(sk, msg)
            if rng.random() < 0.3:
                msg = msg + b"!"
            it = Item(pk, msg, sig)
            expect = expect and HE.check_item(it)
            items.append(it)
        assert _run_batch(items) == expect, trial


@pytest.mark.kernel
@pytest.mark.slow
def test_scheduler_ed25519_lane_device_roundtrip():
    """The `ed25519` lane end to end on the real device backend: a good
    batch accepts, a forged item fails its batch and bisection isolates
    it against the host twin — with zero device faults (rejection is a
    verdict, not a fault)."""
    from grandine_tpu.runtime import verify_scheduler as vs

    sched = vs.VerifyScheduler(use_device=True, settle_timeout_s=300.0)
    try:
        sks = [bytes([i]) * 32 for i in range(1, 4)]
        pks = [HE.secret_to_public(sk) for sk in sks]
        msgs = [b"msg-%d" % i for i in range(3)]
        sigs = [HE.sign(sk, m) for sk, m in zip(sks, msgs)]
        items = [
            vs.VerifyItem(m, s, public_keys=(pk,))
            for m, s, pk in zip(msgs, sigs, pks)
        ]
        assert sched.submit("ed25519", items).result(300.0) is True
        forged = vs.VerifyItem(b"other", sigs[0], public_keys=(pks[0],))
        assert sched.submit("ed25519", [items[0], forged]).result(
            300.0
        ) is False
        stats = dict(sched.stats.get("ed25519", {}))
        assert stats.get("device_faults", 0) == 0
    finally:
        sched.stop()


# ------------------------------------ scheduler host path (fast)


def test_scheduler_ed25519_lane_host_path():
    """use_device=False: the lane resolves verdicts through the host
    twin — no kernel, same byte-identical answers."""
    from grandine_tpu.runtime import verify_scheduler as vs

    sched = vs.VerifyScheduler(use_device=False)
    try:
        sk = bytes([9]) * 32
        pk = HE.secret_to_public(sk)
        sig = HE.sign(sk, b"host-path")
        good = vs.VerifyItem(b"host-path", sig, public_keys=(pk,))
        assert sched.submit("ed25519", [good]).result(60.0) is True
        bad = vs.VerifyItem(b"forged", sig, public_keys=(pk,))
        assert sched.submit("ed25519", [good, bad]).result(60.0) is False
    finally:
        sched.stop()
