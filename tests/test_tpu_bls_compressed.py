"""Differential tests: the compressed-ingest verify kernels vs the
uncompressed device path and the pure-Python anchor.

The compressed-entry kernels (`*_comp` in tpu/bls.py) take the raw
96-byte wire signatures as the operand and decompress inside the fused
program, so the per-item host `Fq2.sqrt` disappears from prep. The
contract: identical verdicts to the host-decompress twin on every input,
including per-row invalid encodings (which must fail the BATCH verdict
without poisoning the group math — invalid rows fold into the infinity
mask).

Everything here compiles pairing kernels (minutes each on the CPU
backend), so the module is slow-tier; the cheap wire-screen policies
live in test_schemes_scheduler-level tests and the decompress masks in
test_tpu_decompress.py.
"""

import random

import pytest

pytestmark = [pytest.mark.kernel, pytest.mark.slow]

from grandine_tpu.crypto import bls as A
from grandine_tpu.tpu.bls import TpuBlsBackend

rng = random.Random(0xC0DE)


def _rng_bytes(n: int) -> bytes:
    return bytes(rng.randrange(256) for _ in range(n))


@pytest.fixture(scope="module")
def backend():
    return TpuBlsBackend()


@pytest.fixture(scope="module")
def keys():
    return [A.SecretKey.keygen(_rng_bytes(32)) for _ in range(5)]


def test_multi_verify_compressed_matches_host_twin(backend, keys):
    pks = [sk.public_key() for sk in keys]
    msgs = [b"comp-%d" % i for i in range(5)]
    sigs = [sk.sign(m) for sk, m in zip(keys, msgs)]
    sig_bytes = [A.g2_to_bytes(s.point) for s in sigs]
    # (the uncompressed twin's verdicts are pinned by test_tpu_bls.py;
    # compiling it again here would double the slow-tier wall time)
    assert backend.multi_verify_compressed(msgs, sig_bytes, pks) is True

    # swapped signature: both paths reject
    bad = list(sig_bytes)
    bad[2] = sig_bytes[3]
    assert backend.multi_verify_compressed(msgs, bad, pks) is False

    # per-row invalid encodings fail the batch, never crash it
    mal = list(sig_bytes)
    b0 = bytearray(mal[1])
    b0[0] &= 0x7F  # compressed flag cleared
    mal[1] = bytes(b0)
    assert backend.multi_verify_compressed(msgs, mal, pks) is False

    wl = list(sig_bytes)
    wl[0] = wl[0][:95]  # wire length — host twin raises BlsError: False
    assert backend.multi_verify_compressed(msgs, wl, pks) is False

    nr = list(sig_bytes)
    z = bytearray(96)
    z[0] = 0x80
    z[95] = 1  # x = 1: rhs is a non-residue, no curve point
    nr[4] = bytes(z)
    assert backend.multi_verify_compressed(msgs, nr, pks) is False


def test_aggregate_compressed_matches_host_twin(backend, keys):
    pks = [sk.public_key() for sk in keys]
    msgs = [b"att-%d" % i for i in range(3)]
    committees = [[0, 1], [2, 3, 4], [1, 4]]
    aggs = [
        A.Signature.aggregate([keys[j].sign(m) for j in c])
        for m, c in zip(msgs, committees)
    ]
    agg_bytes = [A.g2_to_bytes(s.point) for s in aggs]
    member_keys = [[pks[j] for j in c] for c in committees]

    assert backend.fast_aggregate_verify_batch_compressed(
        msgs, agg_bytes, member_keys
    ) is True

    bad = list(agg_bytes)
    bad[1] = agg_bytes[0]
    assert backend.fast_aggregate_verify_batch_compressed(
        msgs, bad, member_keys
    ) is False


def test_aggregate_indexed_compressed_matches_registry_path(backend, keys):
    from grandine_tpu.tpu.registry import DevicePubkeyRegistry

    pkb = tuple(sk.public_key().to_bytes() for sk in keys)
    reg = DevicePubkeyRegistry()
    assert reg.ensure(pkb)

    msgs = [b"idx-%d" % i for i in range(2)]
    committees = [[0, 1, 2], [3, 4]]
    aggs = [
        A.Signature.aggregate([keys[j].sign(m) for j in c])
        for m, c in zip(msgs, committees)
    ]
    agg_bytes = [A.g2_to_bytes(s.point) for s in aggs]
    assert backend.fast_aggregate_verify_batch_indexed_compressed(
        msgs, agg_bytes, committees, reg
    ) is True
    # wrong committee fails like the uncompressed indexed path
    assert backend.fast_aggregate_verify_batch_indexed_compressed(
        msgs, agg_bytes, [committees[0][:2], committees[1]], reg
    ) is False


def test_compressed_subgroup_check_is_always_fused(backend, keys):
    """Security invariant: a compressed batch must reject a signature in
    the wrong subgroup even on a backend configured for the two-pass
    host fallback — the decompressed point never exists host-side, so
    the fused check is the ONLY subgroup gate on this path."""
    from grandine_tpu.crypto.hash_to_curve import (
        hash_to_field_fq2,
        map_to_curve_g2,
    )

    # an on-curve G2 point OUTSIDE the prime-order subgroup: passes
    # decompression's curve checks, must fail membership (same
    # construction as test_fused_verify's _nonsubgroup_sig)
    pt = map_to_curve_g2(hash_to_field_fq2(b"rogue", b"SGT", 1)[0])
    assert not pt.in_subgroup_slow()
    rogue = A.g2_to_bytes(pt)
    pks = [keys[0].public_key()]
    assert backend.multi_verify_compressed(
        [b"rogue"], [rogue], pks
    ) is False
