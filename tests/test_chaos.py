"""Seeded chaos soak + breaker lifecycle tests for the verify plane's
health supervisor (runtime/health.py, testing/chaos.py).

The device is a truth-table stub (`KnownAnswerBackend`) wrapped in a
seeded `ChaosBackend`, and the host path answers from the same truth
table — so the fault-free expectation for every ticket is exact, and
any verdict divergence under injected faults is a supervisor bug, not
test noise."""

from __future__ import annotations

import random
import threading
import time

import pytest

from grandine_tpu.crypto import bls as A
from grandine_tpu.metrics import Metrics
from grandine_tpu.runtime import health as _health
from grandine_tpu.runtime import verify_scheduler as vs
from grandine_tpu.testing.chaos import (
    ChaosBackend,
    FAULT_KINDS,
    FaultPlan,
    KnownAnswerBackend,
)
from grandine_tpu.transition.genesis import interop_secret_key

# one REAL signature reused everywhere: scheduler host prep decompresses
# each item's signature bytes (and rejects infinity); verdicts come from
# the truth table, not the crypto
_SK = interop_secret_key(0)
_SIG_BYTES = _SK.sign(b"chaos-test").to_bytes()
_PK = _SK.public_key()

_GOOD_CANARY = b"canary-good" + b"\x00" * 21
_BAD_CANARY = b"canary-bad" + b"\x00" * 22


def _canary_specimens():
    sig = A.Signature(A.g2_from_bytes(_SIG_BYTES, subgroup_check=False))
    return [
        _health.CanarySpecimen(_GOOD_CANARY, sig, [_PK], expected=True),
        _health.CanarySpecimen(_BAD_CANARY, sig, [_PK], expected=False),
    ]


def _make_plane(truth, plan, monkeypatch, metrics=None,
                settle_timeout_s=0.2, backoff_initial_s=0.05,
                backoff_max_s=0.2, window=16, flight=None):
    """ChaosBackend over a truth table + supervisor + scheduler, with
    the host path answering from the same truth table."""
    truth = dict(truth)
    truth[_GOOD_CANARY] = True  # _BAD_CANARY absent -> False
    chaos = ChaosBackend(KnownAnswerBackend(truth), plan, slow_s=0.02)
    sup = _health.BackendHealthSupervisor(
        metrics=metrics,
        settle_timeout_s=settle_timeout_s,
        probe=_health.make_canary_probe(
            chaos, _canary_specimens(), timeout_s=settle_timeout_s
        ),
        backoff_initial_s=backoff_initial_s,
        backoff_max_s=backoff_max_s,
        window=window,
        rng=random.Random(3),
        flight=flight,
    )
    sched = vs.VerifyScheduler(
        backend=chaos, use_device=True, health=sup, metrics=metrics,
        flight=flight,
    )
    monkeypatch.setattr(
        vs, "host_check_item",
        lambda item: truth.get(bytes(item.message), False),
    )
    return chaos, sup, sched


def _item(message: bytes) -> vs.VerifyItem:
    return vs.VerifyItem(message, _SIG_BYTES, public_keys=(_PK,))


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_chaos_soak_verdicts_match_fault_free(monkeypatch, seed):
    """Under a seeded mix of ALL five fault kinds, every ticket settles
    with exactly the verdict a fault-free run would produce, within the
    watchdog+host-pass latency bound, and no scheduler daemon dies.

    The truth table is all-valid: a `wrong_verdict` flip can then only
    turn valid→invalid, which host bisection corrects. (The converse —
    a silently-corrupt device validating a truly-invalid batch — is
    exactly the failure no per-batch check can catch; the canary test
    below shows the breaker quarantining such a device instead.)"""
    rng = random.Random(seed)
    messages = [b"soak-%03d" % i + b"\x00" * 23 for i in range(32)]
    truth = {m: True for m in messages}
    plan = FaultPlan(seed=seed, rates={k: 0.06 for k in FAULT_KINDS})
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch)

    tickets = []
    try:
        for _ in range(120):
            lane = "sync_message" if rng.random() < 0.7 else "block"
            msgs = [rng.choice(messages)
                    for _ in range(rng.randrange(1, 4))]
            expected = all(truth[m] for m in msgs)
            tickets.append(
                (sched.submit(lane, [_item(m) for m in msgs]), expected)
            )
        sched.flush(60.0)
        # no daemon thread died along the way
        assert sched._dispatcher.is_alive()
        assert sched._completion_thread.is_alive()
    finally:
        sched.stop()
        chaos.release_hangs()

    assert sum(plan.injected.values()) > 0, "soak injected nothing"
    for tk, expected in tickets:
        assert tk.done() and not tk.dropped
        assert tk.ok is expected, (
            f"verdict diverged from fault-free run (seed={seed})"
        )
        # watchdog bound: deadline (0.2s) + retry + host pass + slack —
        # never the unbounded hang the `hang` fault injects
        assert tk.settled_at - tk.enqueued_at < 10.0


def test_chaos_soak_preserves_rejections_without_verdict_faults(monkeypatch):
    """With invalid items in the mix and every fault kind EXCEPT
    wrong_verdict injected, rejections survive degradation exactly:
    raise/hang/slow faults only reroute to the host path, which shares
    the truth table."""
    rng = random.Random(5)
    truth = {}
    messages = []
    for i in range(24):
        m = b"rej-%03d" % i + b"\x00" * 24
        truth[m] = rng.random() >= 0.3  # ~30% invalid
        messages.append(m)
    plan = FaultPlan(seed=5, rates={
        "raise_dispatch": 0.08, "raise_settle": 0.08,
        "hang": 0.06, "slow_settle": 0.08,
    })
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch)

    tickets = []
    try:
        for i in range(80):
            msgs = [rng.choice(messages) for _ in range(rng.randrange(1, 3))]
            tickets.append((
                sched.submit("sync_message", [_item(m) for m in msgs]),
                all(truth[m] for m in msgs),
            ))
            if i % 4 == 3:  # cut batches: on-device localization needs
                sched.flush(30.0)  # so few seam calls that one big
                # coalesced batch would leave the plan nothing to hit
        sched.flush(60.0)
    finally:
        sched.stop()
        chaos.release_hangs()

    assert sum(plan.injected.values()) > 0
    assert any(not expected for _, expected in tickets)  # mix has rejects
    for tk, expected in tickets:
        assert tk.done() and not tk.dropped and tk.ok is expected


def test_breaker_full_traversal_closed_open_half_open_closed(monkeypatch):
    """Scripted settle faults walk the breaker CLOSED → OPEN; after the
    backoff a passing canary probe re-promotes HALF_OPEN → CLOSED. The
    labeled metrics record every transition."""
    m = Metrics()
    truth = {b"msg-a" + b"\x00" * 27: True}
    (msg,) = truth
    # batch1: dispatch(2 calls) faults, its retry(2 calls) faults;
    # batch2: dispatch(2 calls) faults -> 3rd consecutive -> OPEN
    # (its retry is breaker-blocked). Calls past the script are clean.
    plan = FaultPlan(script=["raise_settle"] * 6)
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch, metrics=m)

    try:
        assert sup.state == _health.CLOSED
        t1 = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t1.ok is True  # degraded to host, not dropped
        assert sup.state == _health.CLOSED  # 2 faults < threshold 3
        t2 = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t2.ok is True
        assert sup.state == _health.OPEN
        assert sup.breaker.stats["opens"] == 1
        assert sup.breaker.stats["faults"]["settle"] == 3
        assert sched.stats["block"]["retries"] == 1  # batch2's was blocked

        # while OPEN (inside backoff): zero device dispatch attempts
        before = chaos.dispatches
        t3 = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t3.ok is True
        assert chaos.dispatches == before
        assert sched.stats["block"]["breaker_skips"] >= 1

        # past the backoff: HALF_OPEN, canary passes (script exhausted),
        # breaker re-closes and the batch dispatches on-device again
        time.sleep(0.3)
        t4 = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t4.ok is True
        assert sup.state == _health.CLOSED
        assert chaos.dispatches > before  # probe + real dispatch
        br = sup.breaker.stats
        assert br["closes"] == 1 and br["probes_passed"] == 1
        assert m.verify_breaker_transitions.value("device", "open") == 1
        assert m.verify_breaker_transitions.value("device", "half_open") == 1
        assert m.verify_breaker_transitions.value("device", "closed") == 1
        assert m.verify_breaker_state.value("device") == 0
        assert m.verify_canary_probes.value("device", "pass") == 1
    finally:
        sched.stop()
        chaos.release_hangs()


def test_wrong_verdict_device_fails_canary_and_stays_open(monkeypatch):
    """A device that RAISES nothing but inverts verdicts: host bisection
    contradicts it (verdict faults open the breaker), and at re-promotion
    time the canary's known answers catch the inversion — the breaker
    stays OPEN and per-batch dispatch attempts stay at zero."""
    m = Metrics()
    truth = {b"msg-b" + b"\x00" * 27: True}
    (msg,) = truth
    plan = FaultPlan(seed=0, rates={"wrong_verdict": 1.0})
    # each batch records one settle SUCCESS (the inverted settle raises
    # nothing) then one verdict FAULT, so the consecutive counter never
    # reaches the threshold — the RATE path must open the breaker: with
    # window=4, two batches fill it at a 0.5 fault rate
    chaos, sup, sched = _make_plane(
        truth, plan, monkeypatch, metrics=m, window=4
    )

    try:
        # each single-item batch: device says False, host bisection says
        # True -> one "verdict" breaker fault
        tickets = [None] * 2
        for i in range(2):
            tickets[i] = sched.submit("block", [_item(msg)])
            sched.flush(30.0)
        assert all(t.ok is True for t in tickets)  # host verdict wins
        assert sup.state == _health.OPEN
        assert sup.breaker.stats["faults"]["verdict"] == 2

        # inside the backoff window: no probe, no dispatch
        before = chaos.dispatches
        t = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t.ok is True and chaos.dispatches == before
        assert sched.stats["block"]["breaker_skips"] >= 1

        # past the backoff: the canary probe runs — the inverted good
        # specimen fails it, so the device stays quarantined and the
        # batch itself never dispatches
        time.sleep(0.3)
        probe_calls_before = chaos.dispatches
        t = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t.ok is True
        assert sup.state == _health.OPEN
        assert sup.breaker.stats["probes_failed"] >= 1
        assert m.verify_canary_probes.value("device", "fail") >= 1
        # only the probe touched the seam (1 specimen call — run_canary
        # stops at the first wrong answer), never a batch dispatch
        assert chaos.dispatches - probe_calls_before <= 2
        assert m.verify_breaker_state.value("device") == 1
    finally:
        sched.stop()
        chaos.release_hangs()


# ------------------------------------------------------ flight timeline


#: what each injected fault kind must leave in the flight timeline;
#: slow_settle files no fault — it shows up as device time + SLO miss
_FLIGHT_FAULT_OF = {
    "raise_dispatch": "dispatch",
    "raise_settle": "settle",
    "hang": "watchdog",
    "wrong_verdict": "verdict",
    "slow_settle": None,
}


@pytest.mark.parametrize(
    "kind", sorted(set(FAULT_KINDS) - {"wrong_signature"})
)
def test_flight_timeline_attributes_each_fault_kind(monkeypatch, kind):
    """One scripted injection per fault kind, on a fresh plane each
    time: the batch still settles correctly AND the flight timeline
    carries a record attributing exactly that fault (or, for
    slow_settle, a fault-free record whose device time blew the lane
    budget with cause \"device\"). The script's leading None spends the
    subgroup-check seam call so the fault lands on the verify call.
    `wrong_signature` is sign-side only — it has its own cell below."""
    from grandine_tpu.runtime.flight import BATCH, FlightRecorder

    msg = b"flight-probe" + b"\x00" * 20
    truth = {msg: True}
    plan = FaultPlan(script=[None, kind])
    fl = FlightRecorder(slo_budgets={"block": 0.0005})
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch, flight=fl)
    try:
        tk = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert tk.ok is True, f"{kind}: verdict diverged"
    finally:
        sched.stop()
        chaos.release_hangs()

    assert plan.injected.get(kind, 0) == 1, f"{kind} was not injected"
    recs = fl.snapshot(kind=BATCH)
    assert recs, "no batch record reached the flight ring"
    want = _FLIGHT_FAULT_OF[kind]
    if want is not None:
        faulted = [r for r in recs if r.fault == want]
        assert faulted, (
            f"{kind}: no flight record with fault={want!r} "
            f"(got {[r.fault for r in recs]})"
        )
        assert fl.summary()["faults"].get(want, 0) >= 1
    else:
        (rec,) = recs
        assert rec.fault is None
        assert rec.device_s >= 0.018  # the injected slow settle
        assert rec.slo_miss and rec.slo_cause == "device"


def test_flight_timeline_attributes_wrong_signature():
    """`wrong_signature` fires on the chaos batch_sign seam: the
    signing plane's release gate catches the corrupted batch, the
    flight timeline attributes a verdict fault, and the released
    signature is still byte-identical to the host anchor."""
    from grandine_tpu.runtime.flight import BATCH, FlightRecorder
    from grandine_tpu.runtime.sign_plane import SignLaneConfig, SigningPlane
    from grandine_tpu.runtime.thread_pool import Priority

    root = b"\x5a" * 32
    anchor = _SK.sign(root).to_bytes()

    class _SignSeams(KnownAnswerBackend):
        """Truth-table sign seams: batch_sign is the host anchor (the
        chaos wrapper corrupts it), multi_verify is a known-answer
        release gate — no pairings, verdict plumbing is under test."""

        def batch_sign(self, messages, secret_keys):
            return [k.sign(bytes(m)) for k, m in zip(secret_keys, messages)]

        def multi_verify(self, messages, signatures, public_keys):
            return all(
                s.to_bytes() == _SK.sign(bytes(m)).to_bytes()
                for m, s in zip(messages, signatures)
            )

    plan = FaultPlan(script=["wrong_signature"])
    chaos = ChaosBackend(_SignSeams(), plan)
    fl = FlightRecorder()
    lanes = (
        SignLaneConfig("attestation", Priority.HIGH, 4, 0.002, 64,
                       shed=False),
    )
    plane = SigningPlane(backend=chaos, lanes=lanes, flight=fl,
                         settle_timeout_s=30.0)
    try:
        tk = plane.submit(root, _SK, duty_kind="attestation")
        assert tk.result(30.0) == anchor  # gate caught it: host bytes
    finally:
        plane.stop()
        chaos.release_hangs()

    assert plan.injected.get("wrong_signature", 0) == 1
    recs = fl.snapshot(kind=BATCH)
    assert any(r.fault == "verdict" for r in recs), (
        f"no verdict fault in timeline: {[r.fault for r in recs]}"
    )
    assert fl.summary()["faults"].get("verdict", 0) >= 1
    assert plane.stats()["attestation"]["gate_failures"] == 1


def test_flight_breaker_walk_and_canary_share_timeline(monkeypatch):
    """The scripted CLOSED→OPEN→HALF_OPEN→CLOSED traversal leaves an
    ordered breaker walk in the flight ring, with the provoking batch
    faults BEFORE the open and the passing canary probe BETWEEN
    half_open and re-close — one timeline tells the whole story."""
    from grandine_tpu.runtime.flight import (
        BATCH, BREAKER, CANARY, FlightRecorder,
    )

    msg = b"flight-brk" + b"\x00" * 22
    truth = {msg: True}
    plan = FaultPlan(script=["raise_settle"] * 6)
    fl = FlightRecorder()
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch, flight=fl)
    try:
        for _ in range(2):
            t = sched.submit("block", [_item(msg)])
            sched.flush(30.0)
            assert t.ok is True
        assert sup.state == _health.OPEN
        time.sleep(0.3)  # past the backoff: probe re-promotes
        t = sched.submit("block", [_item(msg)])
        sched.flush(30.0)
        assert t.ok is True and sup.state == _health.CLOSED
    finally:
        sched.stop()
        chaos.release_hangs()

    walk = [r.breaker_state for r in fl.snapshot(kind=BREAKER)]
    assert walk == ["open", "half_open", "closed"]
    probes = fl.snapshot(kind=CANARY)
    assert len(probes) == 1 and probes[0].verdict is True
    assert probes[0].note == "probe_pass"
    # ordering: the first faulted batch precedes the open (the SECOND
    # faulted batch's record commits at finish — after the open its
    # third fault triggered mid-batch), then open < half_open < probe
    # < re-close
    seq_of = {r.note: r.seq for r in fl.snapshot(kind=BREAKER)}
    fault_seqs = [r.seq for r in fl.snapshot(kind=BATCH)
                  if r.fault == "settle"]
    assert len(fault_seqs) == 2  # both batches carry a settle fault
    assert min(fault_seqs) < seq_of["breaker_open"]
    assert (seq_of["breaker_open"] < seq_of["breaker_half_open"]
            < probes[0].seq < seq_of["breaker_closed"])
    assert fl.summary()["faults"]["settle"] == 3


def test_flight_soak_causes_stay_in_enum(monkeypatch):
    """Under a seeded all-kinds soak every recorded SLO cause is a
    member of the closed enum and the recorder's aggregate counts match
    a walk of the ring it retains."""
    from grandine_tpu.runtime.flight import BATCH, FlightRecorder, SLO_CAUSES

    rng = random.Random(11)
    messages = [b"enum-%03d" % i + b"\x00" * 23 for i in range(16)]
    truth = {m: True for m in messages}
    plan = FaultPlan(seed=11, rates={k: 0.08 for k in FAULT_KINDS})
    fl = FlightRecorder(capacity=4096,
                        slo_budgets={"sync_message": 0.0005,
                                     "block": 0.0005})
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch, flight=fl)
    try:
        for i in range(60):
            lane = "sync_message" if rng.random() < 0.7 else "block"
            msgs = [rng.choice(messages) for _ in range(rng.randrange(1, 4))]
            sched.submit(lane, [_item(m) for m in msgs])
            if i % 3 == 2:  # cut batches: a burst this fast would
                sched.flush(30.0)  # otherwise coalesce into one batch
        sched.flush(60.0)
    finally:
        sched.stop()
        chaos.release_hangs()

    assert sum(plan.injected.values()) > 0
    recs = fl.snapshot(kind=BATCH)
    assert recs
    missed = [r for r in recs if r.slo_miss]
    assert missed, "a 5ms budget under chaos must record misses"
    assert all(r.slo_cause in SLO_CAUSES for r in missed)
    assert all(r.slo_cause is None for r in recs if not r.slo_miss)
    # aggregate == ring walk (nothing wrapped at this capacity)
    walked: dict = {}
    for r in missed:
        walked.setdefault(r.lane, {}).setdefault(r.slo_cause, 0)
        walked[r.lane][r.slo_cause] += 1
    assert fl.slo_misses() == walked


def test_fault_plan_is_deterministic():
    """Same seed, same fault schedule — the soak is reproducible."""
    a = FaultPlan(seed=9, rates={k: 0.1 for k in FAULT_KINDS})
    b = FaultPlan(seed=9, rates={k: 0.1 for k in FAULT_KINDS})
    seq_a = [a.next_fault() for _ in range(200)]
    seq_b = [b.next_fault() for _ in range(200)]
    assert seq_a == seq_b
    assert a.injected == b.injected


def test_scripted_plan_and_unknown_rate_validation():
    plan = FaultPlan(script=["hang", None, "raise_dispatch"])
    assert [plan.next_fault() for _ in range(5)] == [
        "hang", None, "raise_dispatch", None, None,
    ]
    with pytest.raises(ValueError):
        FaultPlan(rates={"nonsense": 0.5})


def test_watchdog_abandons_hung_settle():
    """run_with_deadline returns TIMEOUT promptly and leaves the hung
    settle on an expendable daemon thread."""
    release = threading.Event()

    def hung():
        release.wait()
        return True

    t0 = time.monotonic()
    outcome = _health.run_with_deadline(hung, 0.1, "test-watchdog")
    assert outcome.status == _health.TIMEOUT
    assert time.monotonic() - t0 < 2.0
    release.set()


# --------------------------------------------------- non-BLS lane chaos


def _patch_nonbls_host_twin(monkeypatch, truth):
    """Both non-BLS schemes answer host checks from the truth table, so
    fault-free expectations stay exact on the ed25519/blob_kzg lanes."""
    from grandine_tpu.tpu import schemes as _schemes

    for name in ("ed25519", "blob_kzg"):
        monkeypatch.setattr(
            _schemes.get(name), "host_check",
            lambda item, _t=truth: _t.get(bytes(item.message), False),
        )


@pytest.mark.parametrize("lane", ["ed25519", "blob_kzg"])
def test_wrong_verdict_on_nonbls_lane_host_twin_corrects(monkeypatch, lane):
    """A silently-corrupt device on the ed25519/blob_kzg lanes: the
    scripted wrong_verdict flips the batch verdict, bisection descends
    to the scheme's OWN host twin at the leaf, and the ticket settles
    with the fault-free verdict while the breaker books a verdict
    fault."""
    msg = b"nonbls-valid" + b"\x00" * 20
    truth = {msg: True}
    plan = FaultPlan(script=["wrong_verdict"])
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch)
    _patch_nonbls_host_twin(monkeypatch, truth)
    try:
        tk = sched.submit(lane, [_item(msg)])
        sched.flush(30.0)
        assert tk.done() and not tk.dropped
        assert tk.ok is True, (
            "host twin must correct the inverted device verdict"
        )
        assert plan.injected["wrong_verdict"] == 1
        assert sched.stats[lane]["accepted"] == 1
    finally:
        sched.stop()
        chaos.release_hangs()


@pytest.mark.parametrize("lane", ["ed25519", "blob_kzg"])
def test_nonbls_lane_failures_quarantine_origin(monkeypatch, lane):
    """Per-lane origin quarantine: an origin whose ed25519/blob_kzg
    submissions fail is attributed through the shared reputation table,
    and its NEXT sheddable submission reroutes into the quarantine
    lane (never sharing a batch with clean traffic again)."""
    bad = b"nonbls-forged" + b"\x00" * 19
    truth = {}  # bad absent -> host twin says False
    plan = FaultPlan(script=[])  # no injected faults: real rejections
    chaos, sup, sched = _make_plane(truth, plan, monkeypatch)
    _patch_nonbls_host_twin(monkeypatch, truth)
    try:
        tk = sched.submit(lane, [_item(bad)], origin="peer-evil")
        sched.flush(30.0)
        assert tk.done() and tk.ok is False
        assert sched.reputation.is_quarantined("peer-evil")
        tk2 = sched.submit(lane, [_item(bad)], origin="peer-evil")
        sched.flush(30.0)
        assert tk2.done() and tk2.ok is False
        assert sched.stats["quarantine"]["submitted"] >= 1, (
            "quarantined origin's traffic must reroute to the "
            "quarantine lane"
        )
    finally:
        sched.stop()
        chaos.release_hangs()
