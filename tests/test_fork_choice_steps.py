"""Hand-encoded fork-choice STEP scenarios (VERDICT r4 #3), mirroring the
official consensus-spec-tests fork_choice step format — a sequence of
{tick | block | attestation | attester_slashing} steps with an expected
head assertion after EVERY step (reference
fork_choice_control/src/spec_tests.rs:32-61 replays the same shape).

The expected heads are hand-derived from the spec's get_head rules
(LMD-GHOST weights, proposer boost, equivocation discounting) written in
the comments of each scenario — not computed by any helper of the store
under test.
"""

import pytest

from grandine_tpu.consensus import accessors
from grandine_tpu.consensus.verifier import NullVerifier
from grandine_tpu.fork_choice import Store, Tick, TickKind
from grandine_tpu.transition.genesis import interop_genesis_state
from grandine_tpu.types.config import Config
from grandine_tpu.validator.duties import produce_attestations, produce_block

CFG = Config.minimal()
P = CFG.preset
N = 32


@pytest.fixture()
def genesis():
    return interop_genesis_state(N, CFG)


class Steps:
    """Step driver: apply steps in order, assert the expected head after
    each one (the official `checks` shape)."""

    def __init__(self, genesis):
        self.store = Store(genesis, CFG)
        self.genesis = genesis

    def tick(self, slot, kind=TickKind.PROPOSE, head=None):
        self.store.apply_tick(Tick(slot, kind))
        if head is not None:
            assert self.store.get_head() == head, "after tick"

    def block(self, signed, head=None, timely=True):
        valid = self.store.validate_block(signed, NullVerifier())
        self.store.apply_block(valid)
        if head is not None:
            assert self.store.get_head() == head, "after block"
        return valid.root

    def attest(self, state, slot, head=None):
        """All committees of `slot` vote for the chain in `state`."""
        for att in produce_attestations(state, CFG, slot=slot):
            indices = accessors.get_attesting_indices(
                state, att.data, att.aggregation_bits, P
            )
            valid = self.store.validate_attestation(
                int(att.data.slot), int(att.data.index),
                int(att.data.target.epoch),
                bytes(att.data.beacon_block_root),
                bytes(att.data.target.root),
                indices,
            )
            self.store.apply_attestation(valid)
        if head is not None:
            assert self.store.get_head() == head, "after attestations"


def test_steps_genesis_head_then_single_chain(genesis):
    """Scenario 1 — trivial chain growth: with no votes, each new block
    (the only child) becomes head; before any block the head is the
    anchor."""
    s = Steps(genesis)
    anchor = s.store.get_head()
    s.tick(1, head=anchor)  # ticking alone never moves the head
    b1, post1 = produce_block(genesis, 1, CFG, full_sync_participation=False)
    r1 = s.block(b1, head=b1.message.hash_tree_root())
    s.tick(2, head=r1)
    b2, post2 = produce_block(post1, 2, CFG, full_sync_participation=False)
    r2 = s.block(b2, head=b2.message.hash_tree_root())
    assert r2 != r1


def test_steps_proposer_boost_decides_equal_weight_fork(genesis):
    """Scenario 2 — proposer boost: two competing children of genesis with
    zero attestation weight. The boost goes to the TIMELY block only
    (arrival interval 0 of its own slot); a late-arriving rival gets none,
    so the boosted block stays head even if its rival sorts higher by
    root. After the next slot tick the boost expires — head then falls to
    lexicographic tie-break (spec get_head max by (weight, root))."""
    s = Steps(genesis)
    a_blk, _ = produce_block(genesis, 1, CFG, full_sync_participation=False)
    b_blk, _ = produce_block(genesis, 2, CFG, full_sync_participation=False)
    ra = a_blk.message.hash_tree_root()
    rb = b_blk.message.hash_tree_root()

    s.tick(1)  # PROPOSE interval of slot 1
    s.block(a_blk, head=ra, timely=True)  # timely -> boosted
    s.tick(2, kind=TickKind.ATTEST)  # slot 2, but PAST the propose window
    # b arrives late in its slot: NO boost; a keeps its (expired) zero...
    # boost resets at the slot-2 tick, so both have weight 0 now:
    # expected head = max by root
    s.block(b_blk)
    expected = max([ra, rb])
    assert s.store.get_head() == expected


def test_steps_lmd_votes_outweigh_boost_and_reorg(genesis):
    """Scenario 3 — LMD weight beats a fresh boost: chain a has committee
    votes from slot 1; a rival block at slot 2 arrives timely (boost =
    committee_weight * 40% = total/8 * 0.4). One slot-1 committee at
    minimal = N/8 * 32e9 = 4 validators' effective balance... with all 8
    committees voting a (32 * 32e9 = 1024e9) vs boost (512e9 * 0.4 =
    204.8e9): a must stay head."""
    s = Steps(genesis)
    a_blk, a_post = produce_block(genesis, 1, CFG,
                                  full_sync_participation=False)
    ra = a_blk.message.hash_tree_root()
    s.tick(1)
    s.block(a_blk, head=ra)
    s.attest(a_post, 1)  # votes count from slot 2
    s.tick(2)
    s.attest(a_post, 1, head=ra)  # now applied (delayed application is
    # the controller's job; store applies immediately — both orders valid)
    b_blk, _ = produce_block(genesis, 2, CFG, full_sync_participation=False)
    rb = b_blk.message.hash_tree_root()
    # timely rival at slot 2 gets the boost, but 32 votes ≫ boost
    s.block(b_blk, head=ra)
    assert s.store.get_head() == ra != rb


def test_steps_equivocators_lose_their_votes(genesis):
    """Scenario 4 — slashing discounts LMD votes: all committees vote the
    b-branch; then every b-voter is reported equivocating. Their votes
    stop counting, so the a-branch (one vote) takes the head back."""
    s = Steps(genesis)
    a_blk, a_post = produce_block(genesis, 1, CFG,
                                  full_sync_participation=False)
    b_blk, b_post = produce_block(genesis, 2, CFG,
                                  full_sync_participation=False)
    ra = a_blk.message.hash_tree_root()
    rb = b_blk.message.hash_tree_root()
    s.tick(1, kind=TickKind.ATTEST)
    s.block(a_blk)  # late: no boost
    s.tick(2, kind=TickKind.ATTEST)
    s.block(b_blk)  # late: no boost
    # two slots of committees vote b (8 validators at minimal: one
    # 4-member committee per slot)
    from grandine_tpu.transition.slots import process_slots

    s.attest(b_post, 2)
    s.tick(3, kind=TickKind.ATTEST)
    b_post3 = process_slots(b_post, 3, CFG)
    s.attest(b_post3, 3)
    s.tick(4, kind=TickKind.ATTEST)
    assert s.store.get_head() == rb
    # one slot-1 committee (disjoint validators) votes a — not enough
    atts = produce_attestations(a_post, CFG, slot=1)
    first = atts[0]
    indices = accessors.get_attesting_indices(
        a_post, first.data, first.aggregation_bits, P
    )
    valid = s.store.validate_attestation(
        int(first.data.slot), int(first.data.index),
        int(first.data.target.epoch),
        bytes(first.data.beacon_block_root),
        bytes(first.data.target.root),
        indices,
    )
    s.store.apply_attestation(valid)
    # 8 b-votes vs 4 a-votes: b stays head regardless of root order
    assert s.store.get_head() == rb
    # every b-voter equivocates: their latest messages are discounted
    b_voters = sorted(
        set(
            i
            for state, slot in ((b_post, 2), (b_post3, 3))
            for att in produce_attestations(state, CFG, slot=slot)
            for i in accessors.get_attesting_indices(
                state, att.data, att.aggregation_bits, P
            )
        )
    )
    s.store.apply_attester_slashing(b_voters)
    assert s.store.get_head() == ra


def test_steps_future_and_finalized_blocks_rejected(genesis):
    """Scenario 5 — step-level validity (the official `valid: false`
    steps): a block from a future slot and a duplicate are both rejected
    without changing the head."""
    from grandine_tpu.fork_choice import ForkChoiceError

    s = Steps(genesis)
    head0 = s.store.get_head()
    b1, _ = produce_block(genesis, 1, CFG, full_sync_participation=False)
    with pytest.raises(ForkChoiceError, match="future slot"):
        s.store.validate_block(b1, NullVerifier())  # clock still at 0
    assert s.store.get_head() == head0
    s.tick(1)
    r1 = s.block(b1, head=b1.message.hash_tree_root())
    with pytest.raises(ForkChoiceError, match="duplicate"):
        s.store.validate_block(b1, NullVerifier())
    assert s.store.get_head() == r1
