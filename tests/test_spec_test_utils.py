"""Case-loader + snappy codec tests (spec_test_utils parity) and the
fork-combined decode dispatch."""

import os

import pytest

from grandine_tpu.spec_tests import Case, frame_compress, frame_decompress, iter_cases
from grandine_tpu.spec_tests.snappy import raw_decompress
from grandine_tpu.types.combined import (
    decode_signed_block,
    decode_state,
    state_phase_of,
)
from grandine_tpu.types.config import Config
from grandine_tpu.types.primitives import Phase


def test_snappy_roundtrip_uncompressed_frames():
    for payload in (b"", b"x", b"hello world" * 1000, os.urandom(200_000)):
        assert frame_decompress(frame_compress(payload)) == payload


def test_snappy_raw_block_decode():
    # literal + copy: "abcabcabc" = literal "abc" + copy(offset=3, len=6)
    # varint length 9, literal tag (3-1)<<2, then copy1: len 6 offset 3
    block = bytes([9, (3 - 1) << 2]) + b"abc" + bytes([((6 - 4) << 2) | 1, 3])
    assert raw_decompress(block) == b"abcabcabc"


def test_snappy_checksum_rejected():
    good = bytearray(frame_compress(b"payload"))
    # layout: 10-byte stream id, 4-byte chunk header, then the 4-byte CRC
    good[14] ^= 0xFF  # corrupt the CRC itself
    with pytest.raises(ValueError, match="checksum mismatch"):
        frame_decompress(bytes(good))
    # and corrupting the payload (after the CRC) must also be caught
    bad = bytearray(frame_compress(b"payload"))
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum mismatch"):
        frame_decompress(bytes(bad))


def test_case_loader(tmp_path):
    d = tmp_path / "suite" / "case_0"
    d.mkdir(parents=True)
    (d / "meta.yaml").write_text("bls_setting: 1\n")
    (d / "value.ssz_snappy").write_bytes(frame_compress(b"\x2a" + b"\x00" * 7))
    found = list(iter_cases(str(tmp_path / "suite" / "*")))
    assert len(found) == 1
    case = found[0]
    assert case.name == "case_0"
    assert case.meta() == {"bls_setting": 1}
    from grandine_tpu.ssz import uint64

    assert case.ssz("value.ssz_snappy", uint64) == 42


def test_combined_decode_dispatch():
    """A serialized state/block of any fork decodes through the combined
    dispatch (types/src/combined.rs round-trip at a fork boundary)."""
    from grandine_tpu.transition.genesis import interop_genesis_state
    from grandine_tpu.validator.duties import produce_block

    cfg = Config.minimal()  # all forks at genesis -> deneb
    state = interop_genesis_state(16, cfg)
    assert state_phase_of(state, cfg) == Phase.DENEB
    data = state.serialize()
    back = decode_state(data, cfg)
    assert back.hash_tree_root() == state.hash_tree_root()

    blk, _ = produce_block(state, 1, cfg, full_sync_participation=False)
    raw = blk.serialize()
    back_blk = decode_signed_block(raw, cfg)
    assert back_blk.message.hash_tree_root() == blk.message.hash_tree_root()
    assert decode_signed_block(raw, cfg, slot=1).message.hash_tree_root() == \
        blk.message.hash_tree_root()
