"""thread-affinity rule tests: each violation class fires on a seeded
fixture (and ONLY its own finding), the four sharing classes stay
quiet, annotations are class-scoped and demand justifications, the
suppression/baseline mechanics compose, and the repo itself is clean.
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PREAMBLE = "import threading\n\n"


def _run(tmp_path, source: str, capsys):
    """One fixture through the real CLI; returns (exit_code, FAIL
    lines) so tests can assert EXACTLY the expected finding fired."""
    from tools.lint.__main__ import main

    fixture = tmp_path / "fixture.py"
    fixture.write_text(_PREAMBLE + source)
    capsys.readouterr()
    code = main([
        "fixture.py", "--rules", "thread-affinity", "--no-baseline",
        "--root", str(tmp_path),
    ])
    err = capsys.readouterr().err
    fails = [l for l in err.splitlines() if l.startswith("FAIL:")]
    return code, fails


# --------------------------------------------------- violation classes


def test_cross_thread_unguarded_write(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.last = None
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.last = "tick"

    def poll(self):
        return self.last
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "Pump.last" in fails[0] and "data race" in fails[0]


def test_inconsistent_lock_coverage(tmp_path, capsys):
    """Written under the lock in the thread, read bare by callers: not
    consistently-lock-protected, so still a race."""
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        with self._lock:
            self.n = 1

    def poll(self):
        return self.n
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "Pump.n" in fails[0]


def test_rmw_flagged_even_when_annotated(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        # lint: atomic=n: single conceptual writer, torn reads benign
        self.n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.n += 1

    def poll(self):
        return self.n
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "read-modify-write" in fails[0]


def test_publication_before_init_escape(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
        self.ready = True

    def _run(self):
        with self._lock:
            pass
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "half-constructed" in fails[0] and "ready" in fails[0]


def test_bare_acquire_outside_with(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()

    def grab(self):
        self._lock.acquire()
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "outside a `with`" in fails[0]


def test_annotation_requires_justification(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        # lint: atomic=n:
        self.n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.n = 1

    def poll(self):
        return self.n
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "no justification" in fails[0]


# -------------------------------------------------- the sharing classes


def test_clean_sharing_classes_stay_quiet(tmp_path, capsys):
    """All four legal classes in one fixture: lock-protected,
    immutable-after-init, single-thread-owned, and annotated benign."""
    code, fails = _run(tmp_path, """
class Clean:
    def __init__(self):
        self._lock = threading.Lock()
        self.config = "immutable"
        self.guarded = 0
        self.owned = 0
        # lint: atomic=flag: write-once bool; readers tolerate staleness
        self.flag = False
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.owned += 1
        with self._lock:
            self.guarded += 1
        self.flag = True

    def poll(self):
        with self._lock:
            return self.guarded

    def peek(self):
        return self.config, self.flag
""", capsys)
    assert code == 0, fails


def test_plain_data_class_skipped(tmp_path, capsys):
    """No locks, no thread roots, no annotations: no concurrency
    contract to enforce."""
    code, fails = _run(tmp_path, """
class Record:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
""", capsys)
    assert code == 0, fails


def test_pool_spawn_counts_as_thread_root(tmp_path, capsys):
    """spawn/submit targets run on pool threads — a bare shared write
    from one is a race even with no threading.Thread in sight."""
    code, fails = _run(tmp_path, """
class Feeder:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self.seen = 0
        pool.spawn(self._work)

    def _work(self):
        self.seen = 1

    def poll(self):
        return self.seen
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "Feeder.seen" in fails[0]


# ------------------------------------------- annotation + suppression


def test_annotation_is_class_scoped(tmp_path, capsys):
    """An atomic= annotation inside class A must not excuse the same
    attribute name in class B."""
    code, fails = _run(tmp_path, """
class A:
    def __init__(self):
        self._lock = threading.Lock()
        # lint: atomic=n: event-gated, readers see settled value
        self.n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.n = 1

    def poll(self):
        return self.n


class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.n = 1

    def poll(self):
        return self.n
""", capsys)
    assert code == 1
    assert len(fails) == 1
    assert "B.n" in fails[0]


def test_line_suppression_works(tmp_path, capsys):
    code, fails = _run(tmp_path, """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()

    def grab(self):
        self._lock.acquire()  # lint: disable=thread-affinity
""", capsys)
    assert code == 0, fails


def test_baseline_cycle(tmp_path, capsys):
    from tools.lint import core
    from tools.lint.__main__ import main

    fixture = tmp_path / "fixture.py"
    fixture.write_text(_PREAMBLE + """
class Pump:
    def __init__(self):
        self._lock = threading.Lock()

    def grab(self):
        self._lock.acquire()
""")
    baseline = tmp_path / "baseline.txt"
    argv = ["fixture.py", "--rules", "thread-affinity",
            "--baseline", str(baseline), "--root", str(tmp_path)]

    assert main(argv) == 1                      # new finding fails
    assert main(argv + ["--write-baseline"]) == 0
    assert main(argv) == 0                      # grandfathered
    reasons = core.load_baseline(core.Context(str(tmp_path)), str(baseline))
    assert len(reasons) == 1

    fixture.write_text("x = 1\n")               # fixed -> stale entry
    capsys.readouterr()
    assert main(argv) == 0
    assert "stale baseline entry" in capsys.readouterr().err


# ------------------------------------------------------------ the repo


def test_repo_is_clean_under_thread_affinity():
    """Zero unannotated findings on the runtime sources: every shared
    attribute is lock-protected, owned, immutable, or annotated with a
    schedule-fuzz-backed justification."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--rules", "thread-affinity"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    assert "findings=0" in proc.stdout
